"""Overlap-aware collectives (ISSUE 11): bucketed in-backward gradient
sync, one-layer-ahead weight prefetch, ICI+DCN striping, and the
comm-exposed-time accounting.

The load-bearing assertions:

- jaxpr interleaving: with overlap ON the backward scan body contains
  one quantized reduce-scatter per bucket (≥2 buckets on the test
  model) AND the stage-3 gather, instead of a single fused tail
  collective — and the forward scan carries the gathered weights
  (double-buffered prefetch);
- parity: the overlap step's loss trajectory matches the PR 7 quantized
  step within PR 7's established tolerances, and toggling overlap alone
  (prefetch pinned) is BIT-identical;
- exposed-time algebra is exact on synthetic interval sets (nested,
  overlapping, back-to-back).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu import flags as pt_flags
from paddle_tpu import optimizer as optim
from paddle_tpu import stats
from paddle_tpu.distributed import compression as C
from paddle_tpu.distributed import overlap as OV
from paddle_tpu.distributed import planner
from paddle_tpu.distributed.sharding import (
    attach_comm_ef, build_group_sharded_step, init_group_sharded_state)
from paddle_tpu.observability import comm as obs_comm


@pytest.fixture
def fsdp_mesh():
    topo = dist.init_mesh(fsdp=4, devices=jax.devices()[:4],
                          set_global=False)
    yield topo
    from paddle_tpu.distributed import mesh as mesh_lib
    mesh_lib.set_topology(None)


def _batch(seed=0, b=16, d=16, k=8):
    rs = np.random.RandomState(seed)
    return (jnp.asarray(rs.randn(b, d), jnp.float32),
            jnp.asarray(rs.randn(b, k), jnp.float32))


def _run(mesh, steps=5, **kw):
    params, stacked, emb, blk, lf = OV.mlp_block_model(n_layers=3)
    x, y = _batch()
    kw.setdefault("bucket_mb", 1e-4)   # tiny budget → one bucket per leaf
    sp, st, step = OV.overlap_parallel(
        dict(params), emb, blk, lf, optim.SGD(learning_rate=0.05),
        mesh, stacked, **kw)
    losses = []
    for _ in range(steps):
        sp, st, loss = step(sp, st, x, y)
        losses.append(float(loss))
    return sp, st, losses


# -- bucket partitioning (engine-free) ---------------------------------------

def test_partition_buckets_reverse_layer_order():
    leaves = [("l0", 100), ("l1", 100), ("l2", 100)]
    buckets = OV.partition_buckets(leaves, bucket_mb=1e-5)  # ~10 bytes
    assert buckets == [["l2"], ["l1"], ["l0"]]
    # forward order opt-out
    assert OV.partition_buckets(leaves, bucket_mb=1e-5, reverse=False) \
        == [["l0"], ["l1"], ["l2"]]


def test_partition_buckets_mb_budget_accumulates_tiny_leaves():
    mb = 2.0 ** 20
    leaves = [("a", mb // 4), ("b", mb // 4), ("c", mb // 4),
              ("d", mb // 4), ("e", mb // 4)]
    buckets = OV.partition_buckets(leaves, bucket_mb=1.0)
    # reverse order, four quarter-MB leaves fill the 1MB budget
    assert buckets == [["e", "d", "c", "b"], ["a"]]


def test_partition_buckets_oversized_leaf_clamps_to_own_bucket():
    """A leaf bigger than the whole budget forms its own bucket rather
    than splitting — the bucket clamps to the leaf (PR 7's tiny-leaf
    block clamp, in the other direction)."""
    mb = 2.0 ** 20
    leaves = [("small", 64), ("huge", 8 * mb), ("tail", 64)]
    buckets = OV.partition_buckets(leaves, bucket_mb=1.0)
    assert buckets == [["tail"], ["huge"], ["small"]]


def test_partition_buckets_single_bucket_under_budget():
    leaves = [("a", 10), ("b", 10)]
    assert OV.partition_buckets(leaves, bucket_mb=64) == [["b", "a"]]


# -- exposed-time accounting --------------------------------------------------

def test_exposed_time_exact_uncovered_measure():
    # comm [0,4], compute [1,2] and [3,3.5] → exposed 1 + 1 + 0.5
    assert obs_comm.exposed_time([(0, 4)], [(1, 2), (3, 3.5)]) \
        == pytest.approx(1.0 + 1.0 + 0.5)
    # fully covered → 0
    assert obs_comm.exposed_time([(1, 2)], [(0, 4)]) == pytest.approx(0.0)
    # no compute at all → everything exposed
    assert obs_comm.exposed_time([(0, 1), (2, 3)], []) == pytest.approx(2.0)


def test_exposed_time_nested_and_back_to_back_spans():
    # nested comm spans must union, not double-count: [0,4] contains [1,2]
    comm = [(0, 4), (1, 2)]
    assert obs_comm.exposed_time(comm, []) == pytest.approx(4.0)
    # back-to-back compute [0,1][1,2] covers comm [0.5,1.5] completely
    assert obs_comm.exposed_time([(0.5, 1.5)], [(0, 1), (1, 2)]) \
        == pytest.approx(0.0)
    # nested compute spans (parent [0,10], child [2,3]) cover once
    assert obs_comm.exposed_time([(1, 4)], [(0, 10), (2, 3)]) \
        == pytest.approx(0.0)
    # overlapping comm spans against partial compute
    assert obs_comm.exposed_time([(0, 2), (1, 3)], [(0, 1)]) \
        == pytest.approx(2.0)


def test_overlap_fraction_and_step_overlap_from_events():
    # synthetic trace events: (name, t0_ns, dur_ns, tid, sid, parent, attrs)
    ev = [
        ("compute/step", int(0e9), int(2e9), 1, 1, 0, None),
        ("collective/all_to_all", int(1e9), int(2e9), 1, 2, 1, None),
        ("collective/all_gather", int(3e9), int(1e9), 1, 3, 1, None),
        ("serve/other", int(0e9), int(9e9), 1, 4, 0, None),
    ]
    e, frac, busy = obs_comm.step_overlap(events=ev)
    # comm busy [1,3]∪[3,4] = 3s; compute [0,2] covers [1,2] → exposed 2
    assert busy == pytest.approx(3.0)
    assert e == pytest.approx(2.0)
    assert frac == pytest.approx(1.0 - 2.0 / 3.0)
    # no comm → fraction 1.0 (nothing exposed)
    e0, f0, b0 = obs_comm.step_overlap(events=[ev[0]])
    assert (e0, f0, b0) == (0.0, 1.0, 0.0)


def test_record_step_overlap_ticks_stats():
    stats.reset("comm/")
    ev = [("compute/step", 0, int(1e9), 1, 1, 0, None),
          ("collective/psum", 0, int(2e9), 1, 2, 0, None)]
    e, frac, busy = obs_comm.record_step_overlap(events=ev)
    assert e == pytest.approx(1.0)
    assert stats.get("comm/overlap_frac") == pytest.approx(0.5)
    snap = stats.snapshot()
    assert any("comm/exposed_s" in k for k in snap), snap.keys()


# -- the bucket codec ---------------------------------------------------------

def test_bucket_rs_matches_per_leaf_rs(fsdp_mesh):
    """One concatenated bucket exchange computes the same mean (within
    block-scaling tolerance of the per-leaf codec — block boundaries
    shift inside the concatenation) and the same error-feedback algebra:
    v = mean + … with ef = v − own-dequant."""
    rs = np.random.RandomState(1)
    g1 = jnp.asarray(rs.randn(16, 8), jnp.float32)
    g2 = jnp.asarray(rs.randn(32,), jnp.float32)

    def body(a, b):
        sh, ef, ok = C.quantized_bucket_reduce_scatter(
            {"a": a, "b": b}, {"a": jnp.zeros_like(a),
                               "b": jnp.zeros_like(b)},
            "fsdp", "int8", block=64, dims={"a": 0, "b": 0})
        return sh["a"], sh["b"], ef["a"], ef["b"], ok

    sm = shard_map(body, mesh=fsdp_mesh.mesh, in_specs=(P(), P()),
                   out_specs=(P("fsdp"), P("fsdp"), P(), P(), P()),
                   check_vma=False)
    sa, sb, ea, eb, ok = jax.jit(sm)(g1, g2)
    assert bool(ok)
    # every rank fed the same g → the "mean" is g itself ± quant error.
    # Inside a bucket, quantization blocks span leaf boundaries, so the
    # half-step bound uses the BUCKET's amax, not each leaf's own.
    bound = max(float(jnp.max(jnp.abs(g1))),
                float(jnp.max(jnp.abs(g2)))) * (0.5 / 127) + 1e-6
    assert float(jnp.max(jnp.abs(sa - g1))) <= bound
    assert float(jnp.max(jnp.abs(sb - g2))) <= bound
    # ef = v − own-dequant, bounded by the same half-step
    assert float(jnp.max(jnp.abs(ea))) <= bound
    assert float(jnp.max(jnp.abs(eb))) <= bound
    assert float(jnp.max(jnp.abs(ea))) > 0.0


def test_bucket_rs_fp32_exact_zero_ef(fsdp_mesh):
    """method=None: the bucket exchange is exact and the residual is
    identically zero — the scheduling A/B baseline changes no math."""
    rs = np.random.RandomState(2)
    g = jnp.asarray(rs.randn(4, 16, 8), jnp.float32)  # per-rank rows

    def body(gl):
        sh, ef, ok = C.quantized_bucket_reduce_scatter(
            {"w": gl[0]}, {"w": jnp.zeros((16, 8), jnp.float32)},
            "fsdp", None, dims={"w": 0})
        return sh["w"], ef["w"], ok

    sm = shard_map(body, mesh=fsdp_mesh.mesh,
                   in_specs=(P("fsdp"),),
                   out_specs=(P("fsdp"), P(), P()), check_vma=False)
    sh, ef, ok = jax.jit(sm)(g)
    assert bool(ok)
    np.testing.assert_array_equal(np.asarray(ef), 0.0)
    np.testing.assert_allclose(np.asarray(sh),
                               np.asarray(g).mean(0), rtol=1e-6)


def test_bucket_rs_striped_concurrent_wire(fsdp_mesh):
    """stripe=0.5: the lowered exchange carries BOTH an fp32 stripe and
    an int8 stripe (concurrent collectives on the two link classes),
    the stripe byte counters tick, and the result still reconstructs
    the mean within the quantized stripe's tolerance."""
    stats.reset("comm/")
    rs = np.random.RandomState(3)
    g = jnp.asarray(rs.randn(64, 16), jnp.float32)

    def body(gl):
        sh, ef, ok = C.quantized_bucket_reduce_scatter(
            {"w": gl}, {"w": jnp.zeros_like(gl)}, "fsdp", "int8",
            block=64, dims={"w": 0}, stripe=0.5, stripe_min=1)
        return sh["w"], ok

    sm = shard_map(body, mesh=fsdp_mesh.mesh, in_specs=(P(),),
                   out_specs=(P("fsdp"), P()), check_vma=False)
    jitted = jax.jit(sm)
    jx = jax.make_jaxpr(sm)(g)
    a2a = [(n, a) for n, a in _collective_eqns(jx) if n == "all_to_all"]
    # the int8 stripe AND a tensor-sized fp32 stripe (scales are tiny)
    assert any(a and a[0].dtype == jnp.int8 for _, a in a2a), a2a
    assert any(a and a[0].dtype == jnp.float32 and a[0].size > 16
               for _, a in a2a), a2a
    sh, ok = jitted(g)
    assert bool(ok)
    assert stats.get("comm/stripe_bytes_ici") > 0
    assert stats.get("comm/stripe_bytes_dcn") > 0
    bound = float(jnp.max(jnp.abs(g))) * (0.5 / 127) + 1e-6
    assert float(jnp.max(jnp.abs(sh - g))) <= bound


def test_bucket_rs_fp32_striped_two_concurrent_launches(fsdp_mesh):
    """Regression (review finding): striping on an fp32 wire must split
    into two CONCURRENT full-precision launches — it used to fall into
    the quantized branch and crash at trace time on method=None."""
    stats.reset("comm/")
    rs = np.random.RandomState(5)
    g = jnp.asarray(rs.randn(64, 16), jnp.float32)

    def body(gl):
        sh, ef, ok = C.quantized_bucket_reduce_scatter(
            {"w": gl}, {"w": jnp.zeros_like(gl)}, "fsdp", None,
            dims={"w": 0}, stripe=0.5, stripe_min=1)
        return sh["w"], ef["w"], ok

    sm = shard_map(body, mesh=fsdp_mesh.mesh, in_specs=(P(),),
                   out_specs=(P("fsdp"), P(), P()), check_vma=False)
    jx = jax.make_jaxpr(sm)(g)
    a2a = [(n, a) for n, a in _collective_eqns(jx) if n == "all_to_all"]
    assert len(a2a) == 2 and all(a[0].dtype == jnp.float32
                                 for _, a in a2a), a2a
    sh, ef, ok = jax.jit(sm)(g)
    assert bool(ok)
    np.testing.assert_array_equal(np.asarray(ef), 0.0)   # exact wire
    np.testing.assert_allclose(np.asarray(sh), np.asarray(g), rtol=1e-6)
    assert stats.get("comm/stripe_bytes_ici") > 0
    assert stats.get("comm/stripe_bytes_dcn") > 0


def test_stripe_plan_and_resolve(monkeypatch):
    from paddle_tpu.cost_model import CostModel
    cm = CostModel(device_kind="v5")
    degrees = {"dp": 4, "fsdp": 2, "tp": 2}
    # single host → no second link class → no striping anywhere
    assert planner.stripe_plan(degrees, n_hosts=1, cost_model=cm) == {
        "dp": None, "fsdp": None}
    pol = planner.stripe_plan(degrees, n_hosts=4, cost_model=cm)
    # dp crosses hosts: fraction = q·B_dcn/(q·B_dcn + B_ici) ∈ (0,1)
    assert pol["fsdp"] is None
    assert 0.0 < pol["dp"] < 1.0
    eff = 3.94 * cm.dcn_bw
    assert pol["dp"] == pytest.approx(eff / (eff + cm.ici_bw), abs=1e-3)
    # knob resolution
    assert OV.resolve_stripe(0.3, "dp") == pytest.approx(0.3)
    assert OV.resolve_stripe("0", "dp") is None
    assert OV.resolve_stripe(1.5, "dp") is None     # out of range → off
    monkeypatch.delenv("PT_COMM_STRIPE", raising=False)
    assert OV.resolve_stripe(None, "dp") is None    # env default off
    # auto fraction tracks the RESOLVED wire format's compression
    monkeypatch.setenv("PT_COMM_STRIPE", "auto")
    monkeypatch.setenv("PT_NNODES", "2")
    degrees8 = {"fsdp": 8}

    class _M:  # duck-typed mesh: resolve_stripe only reads .shape
        shape = degrees8

    f_int8 = OV.resolve_stripe(None, "fsdp", _M, method="int8")
    f_fp32 = OV.resolve_stripe(None, "fsdp", _M, method=None)
    f_bf16 = OV.resolve_stripe(None, "fsdp", _M, method="bf16")
    assert f_int8 > f_bf16 > f_fp32 > 0, (f_int8, f_bf16, f_fp32)


# -- the overlap step ---------------------------------------------------------

def _collective_eqns(jaxpr):
    out = []

    def walk(jx):
        jx = getattr(jx, "jaxpr", jx)
        for eqn in jx.eqns:
            if eqn.primitive.name in ("all_gather", "all_to_all", "psum",
                                      "psum_scatter", "ppermute", "pmax"):
                out.append((eqn.primitive.name,
                            [v.aval for v in eqn.invars
                             if hasattr(v, "aval")]))
            for v in eqn.params.values():
                for cand in (v if isinstance(v, (list, tuple)) else [v]):
                    if hasattr(cand, "eqns") or hasattr(cand, "jaxpr"):
                        walk(cand)

    walk(jaxpr.jaxpr)
    return out


def _scan_bodies(jaxpr):
    """Body jaxprs of every scan, recursing through shard_map/pjit."""
    out = []

    def walk(jx):
        jx = getattr(jx, "jaxpr", jx)
        for eqn in jx.eqns:
            if eqn.primitive.name == "scan":
                out.append(eqn.params["jaxpr"])
            for v in eqn.params.values():
                for cand in (v if isinstance(v, (list, tuple)) else [v]):
                    if hasattr(cand, "eqns") or hasattr(cand, "jaxpr"):
                        walk(cand)

    walk(jaxpr.jaxpr)
    return out


def _body_stats(body):
    dots = a2a_q = ag_q = 0

    def walk(jx):
        nonlocal dots, a2a_q, ag_q
        jx = getattr(jx, "jaxpr", jx)
        for eqn in jx.eqns:
            n = eqn.primitive.name
            avals = [v.aval for v in eqn.invars if hasattr(v, "aval")]
            if n == "dot_general":
                dots += 1
            if n == "all_to_all" and avals and avals[0].dtype == jnp.int8:
                a2a_q += 1
            if n == "all_gather" and avals and avals[0].dtype == jnp.int8:
                ag_q += 1
            for v in eqn.params.values():
                for cand in (v if isinstance(v, (list, tuple)) else [v]):
                    if hasattr(cand, "eqns") or hasattr(cand, "jaxpr"):
                        walk(cand)

    walk(body)
    return dots, a2a_q, ag_q


def _make_step(mesh, **kw):
    params, stacked, emb, blk, lf = OV.mlp_block_model(n_layers=3)
    kw.setdefault("bucket_mb", 1e-4)
    return OV.overlap_parallel(
        dict(params), emb, blk, lf, optim.SGD(learning_rate=0.05),
        mesh, stacked, **kw)


def test_jaxpr_overlap_interleaves_collectives_into_backward(fsdp_mesh):
    """ACCEPTANCE: with overlap on, backward lowers to one quantized
    reduce-scatter per bucket (≥2 buckets on this model — the tiny
    budget gives one bucket per leaf, 3 total) INSIDE the scan body
    that also does the layer compute, and the stage-3 gather for layer
    l+1 is issued inside layer l's scan body (the forward scan carries
    the gathered full weights in its carry)."""
    x, y = _batch()
    sp, st, step = _make_step(fsdp_mesh.mesh, comm_quant="int8",
                              overlap=True)
    jx = jax.make_jaxpr(lambda p, s, a, b: step(p, s, a, b))(sp, st, x, y)
    bodies = _scan_bodies(jx)
    assert len(bodies) == 2, f"expected fwd+bwd scans, got {len(bodies)}"
    per_body = [_body_stats(b) for b in bodies]
    # the backward body: compute (dots) AND >=2 per-bucket quantized
    # reduce-scatters (all-to-all wire) in the SAME body
    bwd = [s for s in per_body if s[0] > 0 and s[1] >= 2]
    assert bwd, f"no scan body interleaves compute with bucket RS: " \
                f"{per_body}"
    # every scan body that computes also gathers (stage-3 prefetch path)
    for dots, _, ag in per_body:
        if dots:
            assert ag >= 1, per_body
    # the forward scan carries the gathered FULL weights (double
    # buffer): some scan body has a carry operand of a full per-layer
    # weight shape (d=16 × hidden=32) — the non-prefetch form only ever
    # carries activations
    carries = [tuple(v.aval.shape) for b in bodies
               for v in b.jaxpr.invars if hasattr(v, "aval")]
    assert (16, 32) in carries, carries


def test_jaxpr_overlap_off_keeps_tail_collective(fsdp_mesh):
    """The baseline lowers the OPPOSITE way: no scan body mixes layer
    compute with the bucket reduce-scatter — the collectives live in a
    separate tail scan (the fused-tail formulation)."""
    x, y = _batch()
    sp, st, step = _make_step(fsdp_mesh.mesh, comm_quant="int8",
                              overlap=False)
    jx = jax.make_jaxpr(lambda p, s, a, b: step(p, s, a, b))(sp, st, x, y)
    per_body = [_body_stats(b) for b in _scan_bodies(jx)]
    assert not any(s[0] > 0 and s[1] > 0 for s in per_body), per_body
    # the tail scan exists and carries the buckets
    assert any(s[0] == 0 and s[1] >= 2 for s in per_body), per_body


def test_overlap_toggle_bit_identical(fsdp_mesh):
    """Toggling overlap alone (prefetch pinned) is a scheduling-only
    change: parameters after 4 steps are BIT-identical."""
    x, y = _batch()
    out = {}
    for on in (True, False):
        sp, st, step = _make_step(fsdp_mesh.mesh, comm_quant="int8",
                                  overlap=on, prefetch=False)
        for _ in range(4):
            sp, st, loss = step(sp, st, x, y)
        out[on] = jax.device_get(sp)
    for k in out[True]:
        np.testing.assert_array_equal(np.asarray(out[True][k]),
                                      np.asarray(out[False][k]),
                                      err_msg=k)


def test_prefetch_toggle_ulp_parity(fsdp_mesh):
    """The double-buffered weight carry changes buffer layouts (and so
    the matmuls' FMA order) — parity there is float-ulp-level, not
    bitwise; pin the envelope."""
    x, y = _batch()
    out = {}
    for pf in (True, False):
        sp, st, step = _make_step(fsdp_mesh.mesh, comm_quant="int8",
                                  overlap=True, prefetch=pf)
        for _ in range(4):
            sp, st, loss = step(sp, st, x, y)
        out[pf] = jax.device_get(sp)
    for k in out[True]:
        a, b = np.asarray(out[True][k]), np.asarray(out[False][k])
        assert float(np.max(np.abs(a - b))) <= 1e-6, k


@pytest.mark.parametrize("method", [None, "bf16", "int8"])
def test_overlap_step_converges(fsdp_mesh, method):
    _, st, losses = _run(fsdp_mesh.mesh, steps=30, comm_quant=method)
    assert losses[-1] < 0.2 * losses[0], losses
    if method == "int8":
        ef_mag = max(float(jnp.max(jnp.abs(v)))
                     for v in st["comm_ef"].values())
        assert ef_mag > 0.0, "error feedback never engaged"


def test_loss_trajectory_parity_vs_quantized_step(fsdp_mesh):
    """ACCEPTANCE: the bucketed+prefetched step is loss-trajectory
    matched (PR 7 tolerances) with the established PR 7 quantized step
    on the SAME specs and the same flat loss — fp32 and int8."""
    params, stacked, emb, blk, lf = OV.mlp_block_model(n_layers=3)
    x, y = _batch()
    specs = OV.overlap_group_specs(dict(params), fsdp_mesh.mesh, stacked)

    def flat_loss(p, xb, yb):
        h = emb(p, xb, yb)
        for l in range(3):
            h = blk({k: p[k][l] for k in stacked}, h)
        return lf(p, h, xb, yb)

    def run_ref(method):
        opt = optim.SGD(learning_rate=0.05)
        sp, st = init_group_sharded_state(dict(params), opt, specs)
        if method:
            st = attach_comm_ef(dict(params), st, specs)
        step = build_group_sharded_step(flat_loss, opt, specs,
                                        comm_quant=method)
        losses = []
        for _ in range(40):
            sp, st, loss = step(sp, st, x, y)
            losses.append(float(loss))
        return losses

    for method in (None, "int8"):
        ref = run_ref(method)
        _, _, ov = _run(fsdp_mesh.mesh, steps=40, comm_quant=method)
        # PR 7's established convergence-parity tolerance
        assert ov[-1] <= ref[-1] * 1.5 + 1e-3, (method, ov[-1], ref[-1])
        assert ref[-1] <= ov[-1] * 1.5 + 1e-3, (method, ov[-1], ref[-1])
        # trajectories track each other step for step, not just at the end
        deltas = [abs(a - b) for a, b in zip(ov, ref)]
        assert max(deltas) <= 0.05 * ov[0] + 1e-3, (method, max(deltas))


def test_striped_step_trajectory_matches_unstriped(fsdp_mesh):
    _, _, base = _run(fsdp_mesh.mesh, steps=20, comm_quant="int8")
    _, _, striped = _run(fsdp_mesh.mesh, steps=20, comm_quant="int8",
                         stripe=0.5, stripe_min=1)
    assert striped[-1] <= base[-1] * 1.5 + 1e-3, (striped[-1], base[-1])


def test_overlap_group_specs_layer_dim_never_sharded(fsdp_mesh):
    params, stacked, *_ = OV.mlp_block_model(n_layers=4)
    specs = OV.overlap_group_specs(dict(params), fsdp_mesh.mesh, stacked)
    for k in stacked:
        for tree in (specs.param, specs.grad, specs.opt_slot):
            entry = tuple(tree[k])
            assert entry and entry[0] is None, (k, entry)
            assert any(e == "fsdp" or (isinstance(e, tuple) and
                                       "fsdp" in e)
                       for e in entry[1:]), (k, entry)


def test_os_g_level_runs_and_converges(fsdp_mesh):
    _, _, losses = _run(fsdp_mesh.mesh, steps=20, comm_quant="int8",
                        level="os_g")
    assert losses[-1] < 0.3 * losses[0], losses


def test_overlap_env_contract_declared():
    for name in ("PT_COMM_BUCKET_MB", "PT_COMM_OVERLAP",
                 "PT_COMM_STRIPE"):
        assert pt_flags.env_declared(name), name
