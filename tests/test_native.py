"""Native C++ runtime tests: shm ring buffer, TCP store, DataLoader shm
transport (≙ reference C++ unit tests for mmap_allocator / tcp_store and
the multiprocess DataLoader suites)."""

import multiprocessing as mp
import os

import numpy as np
import pytest

from paddle_tpu import native

pytestmark = pytest.mark.skipif(not native.is_available(),
                                reason="native toolchain unavailable")


def test_ring_roundtrip_and_order():
    rb = native.ShmRingBuffer(f"/ptt_{os.getpid()}_a", nslots=4,
                              slot_size=1 << 16)
    try:
        for i in range(10):  # wraps the 4-slot ring
            rb.push(f"msg{i}".encode())
            assert rb.pop() == f"msg{i}".encode()
        rb.push(b"x" * 100)
        rb.push(b"y" * 200)
        assert len(rb) == 2
        assert rb.pop() == b"x" * 100
        assert rb.pop() == b"y" * 200
    finally:
        rb.close()


def test_ring_rejects_oversize():
    rb = native.ShmRingBuffer(f"/ptt_{os.getpid()}_b", nslots=2,
                              slot_size=64)
    try:
        with pytest.raises(ValueError, match="slot"):
            rb.push(b"z" * 100)
    finally:
        rb.close()


def _producer(name, n):
    rb = native.ShmRingBuffer(name, create=False)
    for i in range(n):
        rb.push(np.full((100,), i, np.int64).tobytes())
    rb.close_producer()


def test_ring_cross_process():
    name = f"/ptt_{os.getpid()}_c"
    rb = native.ShmRingBuffer(name, nslots=4, slot_size=1 << 13)
    try:
        p = mp.get_context("fork").Process(target=_producer, args=(name, 20))
        p.start()
        got = []
        while True:
            try:
                data = rb.pop(timeout=10.0)
            except EOFError:
                break
            got.append(int(np.frombuffer(data, np.int64)[0]))
        p.join()
        assert got == list(range(20))
    finally:
        rb.close()


def test_tcp_store_rendezvous():
    master = native.TCPStore(is_master=True)
    try:
        c1 = native.TCPStore(port=master.port)
        c2 = native.TCPStore(port=master.port)
        # barrier via add
        assert c1.add("arrived", 1) == 1
        assert c2.add("arrived", 1) == 2
        c1.set("rank0/addr", b"10.0.0.1:1234")
        assert c2.get("rank0/addr") == b"10.0.0.1:1234"
        with pytest.raises(TimeoutError):
            c2.get("never", timeout=0.3)
        c2.delete_key("rank0/addr")
        with pytest.raises(TimeoutError):
            c1.get("rank0/addr", timeout=0.3)
        c1.close()
        c2.close()
    finally:
        master.close()


def _store_rank(port, rank, results):
    s = native.TCPStore(port=port)
    s.set(f"rank{rank}", str(rank).encode())
    # wait for the other rank's key (cross-process blocking get)
    other = 1 - rank
    results.put((rank, s.get(f"rank{other}", timeout=10.0)))
    s.close()


def test_tcp_store_cross_process_wait():
    master = native.TCPStore(is_master=True)
    try:
        ctx = mp.get_context("fork")
        q = ctx.Queue()
        ps = [ctx.Process(target=_store_rank, args=(master.port, r, q))
              for r in range(2)]
        for p in ps:
            p.start()
        got = dict(q.get(timeout=15) for _ in range(2))
        for p in ps:
            p.join()
        assert got == {0: b"1", 1: b"0"}
    finally:
        master.close()


def test_dataloader_shm_transport_matches_queue():
    from paddle_tpu.io import DataLoader
    from paddle_tpu.io.dataset import Dataset

    class Ds(Dataset):
        def __len__(self):
            return 37

        def __getitem__(self, i):
            return (np.full((4, 5), i, np.float32), np.int64(i % 7))

    def collect(**kw):
        dl = DataLoader(Ds(), batch_size=5, num_workers=2, shuffle=False,
                        **kw)
        return [(x.copy(), y.copy()) for x, y in dl]

    shm = collect(use_shared_memory=True)
    q = collect(use_shared_memory=False)
    assert len(shm) == len(q) == 8
    for (xa, ya), (xb, yb) in zip(shm, q):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)


def test_dataloader_shm_oversize_batch_errors_cleanly():
    from paddle_tpu.io import DataLoader
    from paddle_tpu.io.dataset import Dataset

    class Big(Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            return np.zeros((1 << 16,), np.float32)  # 256KB each

    dl = DataLoader(Big(), batch_size=4, num_workers=1,
                    use_shared_memory=True, shm_slot_bytes=1 << 16)
    with pytest.raises(RuntimeError, match="shm slot"):
        list(dl)


def test_shm_transport_codec():
    from paddle_tpu.io.shm_transport import decode_msg, encode_msg
    payload = {"x": np.arange(12).reshape(3, 4),
               "y": [np.ones(3, np.float16), "label"]}
    bid, out, err = decode_msg(encode_msg(7, payload))
    assert bid == 7 and err is None
    np.testing.assert_array_equal(out["x"], payload["x"])
    np.testing.assert_array_equal(out["y"][0], payload["y"][0])
    assert out["y"][1] == "label"
