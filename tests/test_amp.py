"""AMP O1/O2 + GradScaler (≙ paddle.amp auto_cast/decorate/GradScaler;
VERDICT r1 item 4: O1 must actually cast white-listed op inputs inside the
trace, fp16+scaler training must converge and skip steps on injected inf)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.amp.auto_cast import auto_cast, amp_cast
from paddle_tpu.amp.grad_scaler import GradScaler
from paddle_tpu.nn import functional as F


def test_o1_casts_matmul_inputs_inside_trace():
    """Assert the dtype the matmul actually sees under O1 — captured from
    inside a traced function."""
    seen = {}

    def probe(x, w):
        xc = amp_cast(x)
        seen["dtype"] = xc.dtype
        return F.linear(x, w)

    x = jnp.ones((2, 4), jnp.float32)
    w = jnp.ones((4, 3), jnp.float32)
    with auto_cast(dtype="bfloat16"):
        out = jax.jit(probe)(x, w)
    assert seen["dtype"] == jnp.bfloat16
    assert out.dtype == jnp.bfloat16
    # outside the region: no cast
    out2 = jax.jit(lambda x, w: F.linear(x, w))(x, w)
    assert out2.dtype == jnp.float32


def test_o1_black_ops_stay_fp32():
    x = jnp.ones((2, 8), jnp.float32)
    with auto_cast(dtype="bfloat16"):
        assert amp_cast(x, op_class="black").dtype == jnp.float32
        assert jnp.mean(x).dtype == jnp.float32


def test_conv_and_attention_consult_amp():
    x = jnp.ones((1, 3, 8, 8), jnp.float32)
    w = jnp.ones((4, 3, 3, 3), jnp.float32)
    with auto_cast(dtype="bfloat16"):
        assert F.conv2d(x, w).dtype == jnp.bfloat16
    q = jnp.ones((1, 4, 2, 8), jnp.float32)
    with auto_cast(dtype="bfloat16"):
        out = F.scaled_dot_product_attention(q, q, q)
    assert out.dtype == jnp.bfloat16


class _Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 32)
        self.fc2 = nn.Linear(32, 2)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def _data(n=64, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 8).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    return x, y


def test_fp16_scaler_training_converges():
    from paddle_tpu.hapi import Model
    net = _Net()
    m = Model(net)
    m.prepare(pt.optimizer.Adam(learning_rate=1e-2),
              nn.CrossEntropyLoss(),
              amp_configs={"level": "O1", "dtype": "float16",
                           "init_loss_scaling": 2.0 ** 10})
    x, y = _data()
    losses = [m.train_batch([x], [y]) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.7, losses[::10]
    assert np.isfinite(losses[-1])
    # scaler state is live and finite
    assert float(m._scaler_state["scale"]) > 0


def test_scaler_skips_step_on_injected_inf():
    scaler = GradScaler(init_loss_scaling=2.0 ** 4,
                        decr_every_n_nan_or_inf=1)
    state = scaler.init_state()
    params = {"w": jnp.ones((3,))}
    opt_state = {"step": jnp.zeros((), jnp.int32)}
    grads = {"w": jnp.asarray([1.0, jnp.inf, 0.0])}
    grads, found = scaler.unscale_and_check(grads, state)
    assert bool(found)
    new_p = {"w": params["w"] - 0.1}
    sel_p, sel_s = scaler.apply_or_skip(new_p, opt_state, params, opt_state,
                                        found)
    np.testing.assert_array_equal(np.asarray(sel_p["w"]),
                                  np.asarray(params["w"]))
    new_state = scaler.update_state(state, found)
    assert float(new_state["scale"]) < float(state["scale"])

    # finite grads: step applies, scale eventually grows
    grads2, found2 = scaler.unscale_and_check(
        {"w": jnp.ones((3,))}, new_state)
    assert not bool(found2)
    sel_p2, _ = scaler.apply_or_skip(new_p, opt_state, params, opt_state,
                                     found2)
    np.testing.assert_array_equal(np.asarray(sel_p2["w"]),
                                  np.asarray(new_p["w"]))


def test_o2_casts_params():
    from paddle_tpu.hapi import Model
    net = _Net()
    m = Model(net)
    m.prepare(pt.optimizer.Adam(learning_rate=1e-2), nn.CrossEntropyLoss(),
              amp_configs={"level": "O2", "dtype": "bfloat16"})
    assert all(v.dtype == jnp.bfloat16 for v in m._params.values()
               if jnp.issubdtype(v.dtype, jnp.floating))
    x, y = _data(32, 1)
    loss0 = m.train_batch([x], [y])
    loss1 = m.train_batch([x], [y])
    assert np.isfinite(loss0) and np.isfinite(loss1)
