"""Control-plane fault-tolerance acceptance (ISSUE 17 tentpole), real
processes end to end:

1. SIGKILL the router process mid-Poisson-traffic. A successor
   generation (same --journal, same --endpoint-file) recovers the
   intake, the replicas reconnect through the endpoint file and
   republish their retained results, and the client sees EVERY
   request id with streams byte-identical to an undisturbed control
   fleet.
2. SIGSTOP the router (store unreachable > the replicas' retry
   budget): both replicas degrade to partition mode — buffered
   results, missed heartbeats — and NEITHER dies; on SIGCONT the
   fleet heals and finishes the workload.
3. ``store.partition`` chaos dropped into a disaggregated prefill
   replica's control-plane ops mid-handoff: zero replica deaths, zero
   request-id loss (at-least-once re-placement covers lost handoffs),
   and the KV blobs ride the replica-to-replica SOCKET plane — the
   TCPStore byte counter for KV stays at zero.

Marked slow: these spawn real replica fleets (the ~1-minute CI
variant is ``tools/ci.sh ha``). The fast fake-store unit layer lives
in tests/test_fleet_ha.py.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu import native
from paddle_tpu.serving.router import read_endpoint_file

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROUTER_WORKER = os.path.join(REPO, "tests", "_router_worker.py")
SERVE_WORKER = os.path.join(REPO, "tests", "_serve_worker.py")
DISAGG_WORKER = os.path.join(REPO, "tests", "_disagg_worker.py")

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(not native.is_available(),
                       reason="native TCPStore unavailable"),
]


def _free_port():
    """A launch-master port nothing else is listening on RIGHT NOW.

    Fixed port ladders collide with orphans from earlier (failed)
    runs — the serve workers outlive a killed launch parent — and a
    replica that cannot bind its rendezvous port looks exactly like a
    partition-death, poisoning the one assertion this file is for.
    """
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_router(ep_file, journal, results, workload, seed=0,
                  extra=()):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, ROUTER_WORKER,
         "--endpoint-file", ep_file, "--journal", journal,
         "--results", results, "--workload", str(workload),
         "--seed", str(seed), *extra],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        start_new_session=True)


def _spawn_replica(store_port, rid, ep_file,
                   worker=SERVE_WORKER, role=None, extra_env=None):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               PT_ROUTER_ENDPOINT_FILE=ep_file)
    env.update(extra_env or {})
    argv = [sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nproc_per_node", "1",
            "--master", f"127.0.0.1:{_free_port()}",
            worker, str(store_port), rid]
    if role is not None:
        argv.append(role)
    # own process group: cleanup must reach the serve-worker
    # grandchildren, not just the launch parent
    return subprocess.Popen(argv, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE,
                            start_new_session=True)


def _wait_file(path, timeout, what="file"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return
        time.sleep(0.05)
    raise TimeoutError(f"{what} {path} absent after {timeout}s")


def _journal_counts(path):
    """(submits, results) recorded in the journal so far."""
    s = r = 0
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                if '"kind": "submit"' in line:
                    s += 1
                elif '"kind": "result"' in line:
                    r += 1
    except OSError:
        pass
    return s, r


def _read_results(path):
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def _kill_group(p):
    """SIGKILL the worker's whole process group (launch + children)."""
    try:
        os.killpg(p.pid, signal.SIGKILL)
    except (OSError, ProcessLookupError):
        try:
            p.kill()
        except OSError:
            pass


def _reap(procs, timeout=40):
    errs = []
    for p in procs:
        try:
            p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            _kill_group(p)
            try:
                _, err = p.communicate(timeout=10)
            except Exception:
                err = b""
            errs.append(err)
    return errs


def _kill_all(procs):
    for p in procs:
        if p.poll() is None:
            _kill_group(p)


def _run_fleet(tmp_path, tag, workload, seed):
    """One undisturbed control fleet run → its results dict."""
    ep = str(tmp_path / f"{tag}.ep")
    journal = str(tmp_path / f"{tag}.jsonl")
    res = str(tmp_path / f"{tag}.results.json")
    router = _spawn_router(ep, journal, res, workload, seed=seed)
    procs = []
    try:
        _wait_file(ep, 60, "endpoint file")
        port = read_endpoint_file(ep)["port"]
        procs = [_spawn_replica(port, f"{tag}-r0", ep),
                 _spawn_replica(port, f"{tag}-r1", ep)]
        _wait_file(res, 240, "results file")
        _reap([router] + procs)
        return _read_results(res)
    except BaseException:
        _kill_all([router] + procs)
        raise


def test_router_sigkill_failover_zero_loss_bit_identical(tmp_path):
    n = 12
    control = _run_fleet(tmp_path, "ctrl", n, seed=0)
    all_ids = {f"rq-{i:06d}" for i in range(1, n + 1)}
    assert set(control["results"]) == all_ids

    ep = str(tmp_path / "ha.ep")
    journal = str(tmp_path / "ha.jsonl")
    res = str(tmp_path / "ha.results.json")
    gen1 = _spawn_router(ep, journal, res, n, seed=0,
                         extra=["--interval-ms", "30"])
    procs, gen2 = [], None
    try:
        _wait_file(ep, 60, "endpoint file")
        port = read_endpoint_file(ep)["port"]
        procs = [_spawn_replica(port, "ha-r0", ep),
                 _spawn_replica(port, "ha-r1", ep)]
        # let the traffic reach mid-flight, then murder the router at
        # an instant with journaled-but-unanswered requests — so the
        # successor PROVABLY has outstanding work to recover
        deadline = time.monotonic() + 120
        while True:
            s, r = _journal_counts(journal)
            if s >= n // 2 and s > r:
                break
            assert time.monotonic() < deadline, \
                "gen-1 router never reached mid-traffic"
            assert gen1.poll() is None, "gen-1 router died on its own"
            time.sleep(0.02)
        os.kill(gen1.pid, signal.SIGKILL)
        gen1.wait(timeout=10)
        # successor generation: same journal, same endpoint file
        gen2 = _spawn_router(ep, journal, res, n, seed=0)
        _wait_file(res, 240, "failover results file")
        out = _read_results(res)
        assert out["generation"] == 2
        assert out["recovered"] >= 1, \
            "journal replay found nothing outstanding"
        # acceptance: zero request-id loss...
        assert set(out["results"]) == all_ids
        assert all(r["status"] == "done"
                   for r in out["results"].values())
        # ...and byte-identical streams vs the undisturbed control
        for q in sorted(all_ids):
            assert out["results"][q]["tokens"] \
                == control["results"][q]["tokens"], \
                f"{q} diverged across the failover"
        _reap([gen2] + procs)
        assert all(p.returncode == 0 for p in procs)
    except BaseException:
        _kill_all([gen1, *procs] + ([gen2] if gen2 else []))
        raise


def test_router_sigstop_partition_heals_without_death(tmp_path):
    n = 8
    ep = str(tmp_path / "stall.ep")
    journal = str(tmp_path / "stall.jsonl")
    res = str(tmp_path / "stall.results.json")
    router = _spawn_router(ep, journal, res, n, seed=1,
                           extra=["--interval-ms", "150"])
    procs = []
    try:
        _wait_file(ep, 60, "endpoint file")
        port = read_endpoint_file(ep)["port"]
        procs = [_spawn_replica(port, "st-r0", ep),
                 _spawn_replica(port, "st-r1", ep)]
        deadline = time.monotonic() + 120
        while _journal_counts(journal)[0] < 3:
            assert time.monotonic() < deadline
            assert router.poll() is None
            time.sleep(0.05)
        # freeze the router LONGER than the replicas' store retry
        # budget (PT_STORE_RETRY_S default 2s): every replica store op
        # exhausts its deadline and the links flip partitioned
        os.kill(router.pid, signal.SIGSTOP)
        time.sleep(3.0)
        for p in procs:
            assert p.poll() is None, \
                "replica died during a store partition"
        os.kill(router.pid, signal.SIGCONT)
        _wait_file(res, 240, "results file")
        out = _read_results(res)
        assert set(out["results"]) \
            == {f"rq-{i:06d}" for i in range(1, n + 1)}
        assert all(r["status"] == "done"
                   for r in out["results"].values())
        for p in procs:
            assert p.poll() is None, "replica died after healing"
        _reap([router] + procs)
    except BaseException:
        _kill_all([router] + procs)
        raise


def test_disagg_handoff_survives_store_chaos_on_socket_plane(tmp_path):
    """store.partition chaos inside the prefill replica while KV
    handoffs stream over the socket plane: no replica dies, every id
    completes, and the TCPStore carries ZERO KV payload bytes."""
    from paddle_tpu.serving import Router
    router = Router(port=0, dead_after=15.0)
    # the drop window must outlast the worker's retry budget so at
    # least one op gives up and actually enters partition mode
    chaos = {"PT_STORE_RETRY_S": "0.5",
             "PT_FAULTS": "store.partition:drop:after=25,count=20"}
    procs = [_spawn_replica(router.store.port, "pf0",
                            ep_file="", worker=DISAGG_WORKER,
                            role="prefill", extra_env=chaos),
             _spawn_replica(router.store.port, "dc0",
                            ep_file="", worker=DISAGG_WORKER,
                            role="decode")]
    try:
        router.wait_replicas(2, timeout=90)
        rs = np.random.RandomState(7)
        prompts = [list(rs.randint(0, 96, size=m))
                   for m in (9, 40, 140, 200, 60, 150)]
        ids = [router.submit(p, max_new_tokens=6) for p in prompts]
        results = router.drain(timeout=240)
        assert sorted(results) == sorted(ids)
        assert all(results[q]["status"] == "done" for q in ids)
        for p in procs:
            assert p.poll() is None, \
                "replica died under store.partition chaos"
        # acceptance: KV handoff blobs bypassed the TCPStore
        socket_b = store_b = 0
        for rid in ("pf0", "dc0"):
            exp = json.loads(
                router.store.get(f"serve/stats/{rid}", timeout=5.0))
            socket_b += exp["counters"].get(
                "serve/kv_transport_bytes_socket", 0)
            store_b += exp["counters"].get(
                "serve/kv_transport_bytes_store", 0)
        assert socket_b > 0, "no KV bytes moved over the socket plane"
        assert store_b == 0, \
            f"{store_b} KV bytes still transited the TCPStore"
    finally:
        router.shutdown()
        _reap(procs)
        router.close()
