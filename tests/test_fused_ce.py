"""Fused blockwise softmax-CE kernel vs the materializing oracle
(≙ c_softmax_with_cross_entropy_op.cu:38-192; SURVEY §4 OpTest style —
forward AND both gradients checked against jax.grad of the naive form)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.fused_ce import fused_softmax_cross_entropy


def _oracle(x, w, labels):
    logits = jnp.einsum("nd,vd->nv", x, w).astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, jnp.clip(labels, 0)[:, None], axis=-1)[:, 0]
    return jnp.where(labels >= 0, lse - picked, 0.0)


def _mk(n, v, d, dtype=jnp.float32, seed=0):
    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(n, d).astype(np.float32), dtype)
    w = jnp.asarray(0.1 * rs.randn(v, d).astype(np.float32), dtype)
    labels = jnp.asarray(rs.randint(0, v, n), jnp.int32)
    return x, w, labels


def test_forward_matches_oracle():
    x, w, labels = _mk(256, 768, 64)
    got = fused_softmax_cross_entropy(x, w, labels)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_oracle(x, w, labels)),
                               atol=1e-5, rtol=1e-5)


def test_row_padding_and_ignored_labels():
    # N=77 pads to 128; two rows explicitly ignored
    x, w, labels = _mk(77, 384, 32)
    labels = labels.at[3].set(-1).at[60].set(-1)
    got = np.asarray(fused_softmax_cross_entropy(x, w, labels))
    want = np.asarray(_oracle(x, w, labels))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)
    assert got[3] == 0.0 and got[60] == 0.0


def test_gradients_match_oracle():
    x, w, labels = _mk(128, 768, 64, seed=1)
    labels = labels.at[7].set(-1)

    def fused_mean(x, w):
        per = fused_softmax_cross_entropy(x, w, labels)
        return jnp.sum(per) / jnp.sum(labels >= 0)

    def oracle_mean(x, w):
        per = _oracle(x, w, labels)
        return jnp.sum(per) / jnp.sum(labels >= 0)

    gx_f, gw_f = jax.grad(fused_mean, argnums=(0, 1))(x, w)
    gx_o, gw_o = jax.grad(oracle_mean, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_o),
                               atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_o),
                               atol=1e-5, rtol=1e-4)


def test_bfloat16_inputs():
    x, w, labels = _mk(128, 512, 64, dtype=jnp.bfloat16, seed=2)
    got = fused_softmax_cross_entropy(x, w, labels)
    want = _oracle(x, w, labels)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               atol=5e-2, rtol=5e-2)
    # grads flow and are finite in bf16
    g = jax.grad(lambda x, w: jnp.sum(
        fused_softmax_cross_entropy(x, w, labels)), argnums=(0, 1))(x, w)
    assert all(np.isfinite(np.asarray(t, np.float32)).all() for t in g)


def test_vocab_without_divisor_raises():
    x, w, labels = _mk(8, 130, 16)
    with pytest.raises(ValueError):
        fused_softmax_cross_entropy(x, w, labels)


def test_gpt_train_loss_parity():
    """The GPT train step's fused-CE path must match forward()+lm_loss
    in value and gradients (small dense model, V divisible by 384)."""
    from paddle_tpu.models import gpt

    cfg = gpt.GPTConfig(vocab_size=768, max_seq_len=32, d_model=64,
                        n_layers=2, n_heads=4, dtype=jnp.float32)
    model = gpt.GPT(cfg, seed=0)
    params, _ = model.split_params()
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 768, (2, 32)), jnp.int32)

    def loss_fused(p):
        m = model.merge_params(p)
        return gpt.fused_lm_loss(m, tokens, force=True)

    def loss_ref(p):
        m = model.merge_params(p)
        return gpt.lm_loss(m(tokens), tokens)

    lf, gf = jax.value_and_grad(loss_fused)(params)
    lr, gr = jax.value_and_grad(loss_ref)(params)
    np.testing.assert_allclose(float(lf), float(lr), atol=1e-5, rtol=1e-5)
    flat_f = jax.tree_util.tree_leaves(gf)
    flat_r = jax.tree_util.tree_leaves(gr)
    for a, b in zip(flat_f, flat_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-4)
