"""SLO-driven serving autoscaler: the actuation half of the fleet
story (ISSUE 14 tentpole; docs/elastic.md).

PR 13's ``FleetStats`` *detects* — SLO burn, stalled replicas, pool
exhaustion — but nothing *acted* on the signals. The
:class:`FleetController` closes the loop over a :class:`Router`:

- **sense**: ``FleetStats.signals(role)`` condenses the heartbeat load
  gauges + SLO watch per serving tier (prefill / decode / both — a
  disaggregated fleet's tiers scale independently);
- **decide**: a pluggable :class:`~paddle_tpu.fleet.policy.ScalePolicy`
  per tier (default target-occupancy band with hysteresis), wrapped in
  controller-level min/max clamps and a cooldown between actions;
- **actuate**: scale-up spawns replica processes through the
  ``distributed/launch.py`` machinery (``launch_spawn`` builds the
  canonical one-launcher-per-replica command); scale-down retires via
  the graceful **drain protocol** — ``Router.mark_draining`` flips the
  directory state so no new placement lands, the replica finishes its
  in-flight requests, publishes ``drained``, and exits; a replica that
  overstays ``drain_grace_s`` is SIGKILLed and the router's death
  sweep redistributes whatever it still held (at-least-once, first
  result wins — zero request-id loss either way).

Healing is scale-up's degenerate case: a SIGKILLed/dead replica drops
out of the alive count, the tier falls below its floor, and the
controller spawns a replacement on the next step — no policy or
cooldown consultation, a hole in the fleet is never "in band".

Every action is written to the flight recorder under the synthetic
request id ``"fleet"`` (``scale-up`` / ``drain-start`` /
``drain-complete`` / ``kill`` events with the policy's reason), so a
postmortem dump answers WHY the fleet changed shape, and counted under
``fleet/controller_*`` (docs/observability.md).
"""

import os
import signal as _signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from paddle_tpu.fleet.policy import ScalePolicy, TargetOccupancyPolicy

__all__ = ["FleetController", "TierSpec", "RouterSupervisor",
           "launch_spawn", "router_standby_enabled",
           "fleet_min_replicas", "fleet_max_replicas",
           "fleet_cooldown_s", "fleet_drain_grace_s"]


def fleet_min_replicas() -> int:
    """``PT_FLEET_MIN_REPLICAS`` (default 1): the per-tier floor the
    controller heals back up to, bypassing policy and cooldown."""
    return int(os.environ.get("PT_FLEET_MIN_REPLICAS", "1"))


def fleet_max_replicas() -> int:
    """``PT_FLEET_MAX_REPLICAS`` (default 8): the per-tier ceiling —
    scale-up clamps here no matter how hard the SLO burns."""
    return int(os.environ.get("PT_FLEET_MAX_REPLICAS", "8"))


def fleet_cooldown_s() -> float:
    """``PT_FLEET_COOLDOWN_S`` (default 5): seconds after any
    policy-driven action before the next one — actuation latency
    (spawn→announce, drain→exit) must not be mistaken for an
    unanswered signal. Healing below the floor ignores it."""
    return float(os.environ.get("PT_FLEET_COOLDOWN_S", "5"))


def router_standby_enabled() -> bool:
    """``PT_ROUTER_STANDBY`` (default off): the `RouterSupervisor`
    keeps a WARM standby router process (already imported, waiting on
    its start token) so failover skips interpreter+import startup —
    recovery time becomes store-bind + journal-replay. Off, the
    supervisor cold-spawns the successor on demand."""
    return os.environ.get("PT_ROUTER_STANDBY", "0") != "0"


def fleet_drain_grace_s() -> float:
    """``PT_FLEET_DRAIN_GRACE_S`` (default 10): how long a draining
    replica may take to finish its in-flight work before the
    controller SIGKILLs it (the death sweep then redistributes)."""
    return float(os.environ.get("PT_FLEET_DRAIN_GRACE_S", "10"))


@dataclass
class TierSpec:
    """One serving tier's autoscaling envelope. ``role`` matches the
    replicas' heartbeat ``role`` load field (``both`` for symmetric
    ``serve_replica`` fleets, ``prefill``/``decode`` for the
    disaggregated loops)."""
    role: str = "both"
    min_replicas: int = field(default_factory=fleet_min_replicas)
    max_replicas: int = field(default_factory=fleet_max_replicas)
    policy: Optional[ScalePolicy] = None

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"tier {self.role!r}: need 1 <= min <= max, got "
                f"{self.min_replicas}..{self.max_replicas}")
        if self.policy is None:
            self.policy = TargetOccupancyPolicy()


_spawn_seq = [0]


def launch_spawn(script: str, store_port: int, extra_args=(),
                 extra_env: Optional[dict] = None,
                 pass_role: bool = True) -> Callable:
    """Build the controller's ``spawn(role, rid)`` callable over the
    ``distributed/launch.py`` CLI — one launcher per replica
    (``--nproc_per_node 1``), so killing one replica can never take a
    peer's launcher down with it. ``script`` is a replica worker whose
    argv contract is ``STORE_PORT REPLICA_ID [ROLE] ...`` (the
    tests/_serve_worker.py / _disagg_worker.py shape)."""
    def spawn(role: str, rid: str):
        _spawn_seq[0] += 1
        # the master port is inert for nproc=1 serving workers (they
        # never init jax.distributed) but must be unique per launcher
        port = 8700 + (os.getpid() + _spawn_seq[0] * 7) % 1000
        env = dict(os.environ)
        env.update(extra_env or {})
        argv = [sys.executable, "-m", "paddle_tpu.distributed.launch",
                "--nproc_per_node", "1",
                "--master", f"127.0.0.1:{port}",
                script, str(store_port), rid]
        if pass_role:
            argv.append(role)
        argv.extend(extra_args)
        return subprocess.Popen(argv, env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
    return spawn


class FleetController:
    """Close the sense→decide→actuate loop over one :class:`Router`.

        router = Router(...)
        fleet = router.enable_fleet_stats(...)
        ctl = FleetController(router, spawn=launch_spawn(worker, port),
                              tiers=[TierSpec("both", 2, 4)])
        while serving:
            router.poll(); router.check_replicas(); ctl.step()

    ``spawn(role, rid)`` must start a replica process that announces
    exactly ``rid`` on the router's directory; the returned handle is
    kept in :attr:`procs` (the caller owns reaping at shutdown —
    the controller only ``poll()``s Popen-like handles to avoid
    zombies). A spawned replica counts toward its tier until it
    announces or ``spawn_timeout_s`` passes, so one heal never
    double-spawns.
    """

    def __init__(self, router, spawn: Callable,
                 tiers: Optional[List[TierSpec]] = None,
                 fleet_stats=None,
                 cooldown_s: Optional[float] = None,
                 drain_grace_s: Optional[float] = None,
                 spawn_timeout_s: float = 60.0,
                 fleet_poll_s: float = 1.0):
        self.router = router
        self.spawn = spawn
        self.tiers = list(tiers) if tiers else [TierSpec()]
        roles = [t.role for t in self.tiers]
        if len(set(roles)) != len(roles):
            raise ValueError(f"duplicate tier roles: {roles}")
        if fleet_stats is None:
            from paddle_tpu.observability.fleet import FleetStats
            fleet_stats = (router.fleet_stats
                           or FleetStats(router.directory,
                                         dead_after=router.dead_after))
        self.fleet = fleet_stats
        self.cooldown_s = (fleet_cooldown_s() if cooldown_s is None
                           else float(cooldown_s))
        self.drain_grace_s = (fleet_drain_grace_s()
                              if drain_grace_s is None
                              else float(drain_grace_s))
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.fleet_poll_s = float(fleet_poll_s)
        self._fleet_at: Optional[float] = None
        self.procs: List[object] = []            # every spawned handle
        self._pending: Dict[str, tuple] = {}     # rid -> (role, t, h)
        self._draining: Dict[str, float] = {}    # rid -> drain start
        self._killed: set = set()
        self._cooldown_until: Dict[str, float] = {}  # per tier role
        self._spawn_fails: Dict[str, int] = {}       # consecutive, per role
        self._heal_backoff: Dict[str, float] = {}    # per tier role
        self._seq = 0
        # the controller's timeline must outlive request churn in the
        # flight ring — it is THE postmortem of why the fleet changed
        from paddle_tpu.observability import flight
        flight.pin("fleet")

    # -- sensing ------------------------------------------------------------

    def _members(self) -> Dict[str, dict]:
        return self.router.directory.members()

    def _tier_view(self, tier: TierSpec, members: Dict[str, dict],
                   now: float):
        """(routable alive rids, pending spawn count, draining rids)
        for one tier."""
        d = self.router.directory
        alive = []
        draining = set()
        for rid, meta in members.items():
            if meta.get("role", "both") != tier.role:
                continue
            if not d.alive(rid, self.router.dead_after):
                continue
            # lifecycle through the Router's TTL cache (mark_draining
            # updates it synchronously) — not a raw store read per
            # replica per controller step
            if (rid in self._draining
                    or self.router._replica_state(rid) != "up"):
                draining.add(rid)
                continue
            alive.append(rid)
            if self._pending.pop(rid, None):  # announced: spawn landed
                self._spawn_fails[tier.role] = 0
        pending = [rid for rid, (role, t, _h) in self._pending.items()
                   if role == tier.role]
        for rid in pending:
            _role, t0, h = self._pending[rid]
            # a spawn whose process exited before announcing is a
            # failed spawn NOW — waiting out spawn_timeout_s would
            # hold the tier below its floor with nothing coming
            poll = getattr(h, "poll", None)
            died = callable(poll) and poll() is not None
            if died or now - t0 > self.spawn_timeout_s:
                from paddle_tpu import stats
                stats.add("fleet/controller_spawn_timeouts")
                self._pending.pop(rid)
                # exponential heal backoff: a worker that crashes on
                # startup (bad argv, import error) otherwise gets
                # re-spawned every controller step, forever
                fails = self._spawn_fails.get(tier.role, 0) + 1
                self._spawn_fails[tier.role] = fails
                self._heal_backoff[tier.role] = now + min(
                    30.0, 0.5 * (2 ** min(fails, 6)))
        pending = [rid for rid, (role, _t, _h) in self._pending.items()
                   if role == tier.role]
        return sorted(alive), len(pending), draining

    # -- actuation ----------------------------------------------------------

    def _scale_up(self, tier: TierSpec, n: int, reason: str,
                  now: float):
        from paddle_tpu import stats
        from paddle_tpu.observability import flight
        for _ in range(n):
            self._seq += 1
            rid = f"ctl-{tier.role}-{self._seq}"
            handle = self.spawn(tier.role, rid)
            self.procs.append(handle)
            self._pending[rid] = (tier.role, now, handle)
            stats.add("fleet/controller_scale_ups")
            flight.record("fleet", "scale-up", role=tier.role,
                          replica=rid, reason=reason)
            print(f"[fleet] scale-up {tier.role}: spawn {rid} "
                  f"({reason})", file=sys.stderr, flush=True)

    def _pick_victim(self, alive: List[str]):
        """Drain the emptiest replica: least busy slots by its own load
        gauge, then least router-outstanding — minimizes the work that
        must finish (or redistribute) before the drain completes."""
        d = self.router.directory

        def key(rid):
            load = d.load(rid) or {}
            return (load.get("busy_slots", 0) + load.get("queued", 0),
                    self.router._outstanding.get(rid, 0), rid)
        return min(alive, key=key)

    def _drain(self, tier: TierSpec, alive: List[str], n: int,
               reason: str, now: float):
        from paddle_tpu import stats
        from paddle_tpu.observability import flight
        pool = list(alive)
        for _ in range(min(n, len(pool))):
            victim = self._pick_victim(pool)
            pool.remove(victim)
            self.router.mark_draining(victim)
            self._draining[victim] = now
            stats.add("fleet/controller_scale_downs")
            flight.record("fleet", "drain-start", role=tier.role,
                          replica=victim, reason=reason)
            print(f"[fleet] drain-start {victim} ({reason})",
                  file=sys.stderr, flush=True)

    def _watch_drains(self, members: Dict[str, dict], now: float):
        """Advance every in-progress drain: ``drained`` (or death)
        completes it; overstaying the grace window earns a SIGKILL —
        the router's death sweep then redistributes whatever the
        replica still held (at-least-once; the drain protocol loses no
        request id either way)."""
        from paddle_tpu import stats
        from paddle_tpu.observability import flight
        d = self.router.directory
        for rid in list(self._draining):
            t0 = self._draining[rid]
            state = d.state(rid)
            alive = d.alive(rid, self.router.dead_after)
            if state == "drained" or not alive:
                self._draining.pop(rid)
                self._killed.discard(rid)
                stats.add("fleet/controller_drains_completed")
                # drain latency hist: with PT_DRAIN_MIGRATE this is
                # bounded by migration time, not the longest in-flight
                # request (the bench_fleet_churn acceptance axis)
                stats.observe("fleet/drain_latency_s", now - t0)
                flight.record("fleet", "drain-complete", replica=rid,
                              graceful=(state == "drained"),
                              elapsed_s=round(now - t0, 3))
                continue
            if now - t0 > self.drain_grace_s and rid not in self._killed:
                pid = (members.get(rid) or {}).get("pid")
                if pid:
                    try:
                        os.kill(int(pid), _signal.SIGKILL)
                    except (OSError, ProcessLookupError):
                        pass
                self._killed.add(rid)
                stats.add("fleet/controller_kills")
                flight.record("fleet", "kill", replica=rid,
                              after_s=round(now - t0, 3))
                print(f"[fleet] drain grace exceeded: SIGKILL {rid}",
                      file=sys.stderr, flush=True)

    # -- the loop -----------------------------------------------------------

    def step(self, now: Optional[float] = None) -> dict:
        """One sense→decide→actuate pass. Returns a per-tier summary
        (alive/pending counts and the action taken) for callers that
        log or assert on it."""
        from paddle_tpu import stats
        now = time.monotonic() if now is None else now
        # throttle the full FleetStats pump (refresh + merge + watch
        # deserializes every replica's export) to its own cadence —
        # the controller's per-step needs (alive/pending/draining) read
        # the directory directly, and signals() rides the last refresh.
        # Mirrors Router.poll's fleet-stats throttle.
        if self._fleet_at is None or \
                now - self._fleet_at >= self.fleet_poll_s:
            self._fleet_at = now
            self.fleet.poll(now=now)
        self._reap()
        members = self._members()
        self._watch_drains(members, now)
        out = {}
        for tier in self.tiers:
            alive, pending, draining = self._tier_view(tier, members,
                                                       now)
            effective = len(alive) + pending
            action = "hold"
            # draining replicas still heartbeat but are not routable —
            # counting their slots dilutes occupancy and suppresses a
            # needed scale-up on the saturated routable remainder
            sig = self.fleet.signals(tier.role, exclude=draining)
            if effective < tier.min_replicas:
                # healing: a hole in the fleet is never "in band" —
                # no policy, no cooldown (but failed-spawn backoff
                # applies: see _tier_view)
                if now >= self._heal_backoff.get(tier.role, 0.0):
                    self._scale_up(tier,
                                   tier.min_replicas - effective,
                                   f"below floor: {effective}/"
                                   f"{tier.min_replicas} replicas",
                                   now)
                    action = "heal"
            elif effective > tier.max_replicas:
                # same floor guard as the policy path: pending spawns
                # are not routable, so the drain count is capped by
                # what the ALIVE fleet can give up above the floor
                room = len(alive) - tier.min_replicas
                if room > 0:
                    self._drain(tier,
                                alive,
                                min(effective - tier.max_replicas,
                                    room),
                                f"above ceiling: {effective}/"
                                f"{tier.max_replicas}", now)
                    action = "drain"
            elif now >= self._cooldown_until.get(tier.role, 0.0):
                delta, reason = tier.policy.decide(sig, now=now)
                if delta > 0 and effective < tier.max_replicas:
                    # at the ceiling the vote is a silent no-op: no
                    # cooldown, no policy reset — the summary must not
                    # claim an actuation that never happened
                    self._scale_up(
                        tier, min(delta,
                                  tier.max_replicas - effective),
                        reason, now)
                    tier.policy.reset()
                    self._cooldown_until[tier.role] = \
                        now + self.cooldown_s
                    action = "scale-up"
                elif delta < 0 and len(alive) > 0:
                    # headroom counts only ALIVE replicas: a pending
                    # spawn is not routable yet, and draining against
                    # it would put the serving fleet below the floor
                    # for the whole engine-build window
                    room = len(alive) - tier.min_replicas
                    if room > 0:
                        self._drain(tier, alive, min(-delta, room),
                                    reason, now)
                        tier.policy.reset()
                        self._cooldown_until[tier.role] = \
                            now + self.cooldown_s
                        action = "scale-down"
            stats.set_value(f"fleet/controller_alive_{tier.role}",
                            len(alive))
            out[tier.role] = {"alive": len(alive), "pending": pending,
                              "draining": len(self._draining),
                              "action": action,
                              "occupancy": sig["occupancy"]}
        return out

    def _reap(self):
        """Opportunistically collect exited spawn handles and drop
        them from :attr:`procs` (a long-lived controller on a churny
        fleet otherwise accumulates dead handles without bound;
        opaque handles without ``poll`` are kept for shutdown)."""
        live = []
        for h in self.procs:
            poll = getattr(h, "poll", None)
            rc = None
            if callable(poll):
                try:
                    rc = poll()
                except Exception:
                    rc = None
            if rc is None:
                live.append(h)
        self.procs[:] = live

    def pump(self, duration_s: float, interval_s: float = 0.25,
             extra: Optional[Callable] = None):
        """Convenience loop for smokes/tests: poll the router, run the
        death sweep, step the controller — every ``interval_s`` for
        ``duration_s`` (``extra()`` is called each tick)."""
        deadline = time.monotonic() + duration_s
        while time.monotonic() < deadline:
            self.router.poll()
            self.router.check_replicas()
            self.step()
            if extra is not None:
                extra()
            time.sleep(interval_s)

    def shutdown(self, timeout: float = 30.0):
        """Reap every spawned handle (call after ``router.shutdown()``
        has asked the serve loops to exit)."""
        deadline = time.monotonic() + timeout
        for h in self.procs:
            wait = getattr(h, "wait", None)
            if not callable(wait):
                continue
            try:
                h.wait(timeout=max(0.1, deadline - time.monotonic()))
            except Exception:
                kill = getattr(h, "kill", None)
                if callable(kill):
                    kill()
                    try:
                        h.wait(timeout=5)
                    except Exception:
                        pass


class RouterSupervisor:
    """Keep exactly one live router generation (docs/fleet-ha.md).

    The router process is the control plane's single point of failure:
    it hosts the TCPStore and the placement state. The failover design
    (ISSUE 17 tentpole) makes that state RECONSTRUCTIBLE — the request
    journal holds the intake, the replicas hold results and membership
    — so the supervisor's whole job is to notice the router died and
    start the next generation. The successor binds a fresh store,
    writes the endpoint file at ``gen+1`` (``Router.__init__``),
    replays the journal (``Router.recover``), and the replicas'
    `RouterLink`s reconnect and republish.

    ``spawn_router(token_file)`` must start a router-hosting process:
    with ``token_file=None`` it starts serving immediately; with a path
    it must WAIT for that file to appear first (the warm-standby
    contract, ``PT_ROUTER_STANDBY``: the interpreter and imports are
    already paid for, so promotion costs store-bind + journal-replay
    only — the supervisor promotes by creating the token file).

        sup = RouterSupervisor(spawn_router, handle=first_router_proc)
        while serving:
            sup.step()          # respawns/promotes if the router died
    """

    def __init__(self, spawn_router: Callable, handle=None,
                 standby: Optional[bool] = None,
                 restart_backoff_s: float = 0.5,
                 max_restarts: int = 8,
                 token_dir: Optional[str] = None):
        import tempfile
        self.spawn_router = spawn_router
        self.handle = handle if handle is not None \
            else spawn_router(None)
        self.standby_enabled = router_standby_enabled() \
            if standby is None else bool(standby)
        self.restart_backoff_s = float(restart_backoff_s)
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self._token_dir = token_dir or tempfile.mkdtemp(
            prefix="pt-router-standby-")
        self._standby = None            # (handle, token_path)
        self._next_restart_ok = 0.0
        from paddle_tpu.observability import flight
        flight.pin("fleet")

    def _arm_standby(self):
        token = os.path.join(self._token_dir,
                             f"standby-{self.restarts}.go")
        self._standby = (self.spawn_router(token), token)

    def _router_dead(self) -> bool:
        poll = getattr(self.handle, "poll", None)
        return callable(poll) and poll() is not None

    def step(self, now: Optional[float] = None) -> bool:
        """Returns True when a failover was performed this step."""
        from paddle_tpu import stats
        from paddle_tpu.observability import flight
        now = time.monotonic() if now is None else now
        if self.standby_enabled and self._standby is None:
            self._arm_standby()
        if not self._router_dead():
            return False
        if now < self._next_restart_ok:
            return False            # backoff between rapid deaths
        if self.restarts >= self.max_restarts:
            raise RuntimeError(
                f"router died {self.restarts} times — refusing to "
                f"restart again (crash loop)")
        self.restarts += 1
        self._next_restart_ok = now + self.restart_backoff_s
        stats.add("fleet/router_restarts")
        if self._standby is not None:
            handle, token = self._standby
            self._standby = None
            # promotion = creating the token file the standby waits on
            with open(token, "w", encoding="utf-8") as f:
                f.write("go\n")
            self.handle = handle
            flight.record("fleet", "router-promote",
                          generation=self.restarts + 1)
            print(f"[fleet] router died: promoted warm standby "
                  f"(restart {self.restarts})", file=sys.stderr,
                  flush=True)
        else:
            self.handle = self.spawn_router(None)
            flight.record("fleet", "router-respawn",
                          generation=self.restarts + 1)
            print(f"[fleet] router died: cold-spawned successor "
                  f"(restart {self.restarts})", file=sys.stderr,
                  flush=True)
        return True

    def shutdown(self, timeout: float = 10.0):
        """Kill the live router and any armed standby."""
        for h in [self.handle] + (
                [self._standby[0]] if self._standby else []):
            kill = getattr(h, "kill", None)
            if callable(kill):
                try:
                    kill()
                    h.wait(timeout=timeout)
                except Exception:
                    pass
        self._standby = None
