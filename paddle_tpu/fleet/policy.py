"""Pluggable autoscaling policy for the fleet controller.

The controller (``fleet/controller.py``) separates *sensing* (the
``FleetStats.signals`` condensed view of the heartbeat load gauges +
SLO watch), *deciding* (this module), and *actuating* (spawn / drain).
A policy sees one tier's signals per step and answers "how many
replicas do you want added (+n) or retired (-n) right now" — min/max
clamping, cooldown between actions, and dead-replica healing are the
controller's job, so policies stay small pure-ish state machines.

:class:`TargetOccupancyPolicy` is the default: a target-occupancy band
with hysteresis. Scale-up pressure is any of: occupancy above the
band, sustained queue age, a burning TTFT SLO window, or an exhausted
page pool with work waiting. Scale-down requires the opposite to hold
*continuously* for ``sustain_s`` (idle occupancy, empty queues) — a
momentary lull between Poisson bursts must not flap the fleet.
"""

import time
from dataclasses import dataclass
from typing import Optional, Tuple

__all__ = ["FleetSignals", "ScalePolicy", "TargetOccupancyPolicy"]


@dataclass
class FleetSignals:
    """Typed view of ``FleetStats.signals()`` for policy authors who
    prefer attributes over dict keys (``from_dict`` accepts either)."""
    n_alive: int = 0
    queued: int = 0
    busy_slots: int = 0
    total_slots: int = 0
    occupancy: float = 0.0
    queue_age_s: float = 0.0
    free_pages: int = 0
    total_pages: int = 0
    ttft_burn: float = 0.0
    goodput: float = 0.0

    @classmethod
    def from_dict(cls, d) -> "FleetSignals":
        if isinstance(d, cls):
            return d
        fields = cls.__dataclass_fields__
        return cls(**{k: v for k, v in d.items() if k in fields})


class ScalePolicy:
    """Base policy: ``decide(signals, now)`` returns ``(delta,
    reason)`` — +n to add replicas, -n to retire, 0 to hold. The
    reason string lands in the controller's flight-recorder event so a
    postmortem dump says WHY the fleet changed shape."""

    def decide(self, sig, now: Optional[float] = None
               ) -> Tuple[int, str]:
        raise NotImplementedError

    def reset(self):
        """Forget sustained-condition anchors (controller calls this
        after it actuates, so the next decision re-observes from
        scratch instead of double-firing on the same stretch)."""


class TargetOccupancyPolicy(ScalePolicy):
    """Occupancy band with hysteresis + sustained-condition anchors.

    Scale UP (+step) when, continuously for ``up_sustain_s``:
      - slot occupancy > ``high``, or
      - the oldest queued request is older than ``queue_age_s``, or
      - the fleet TTFT window burns (``ttft_burn`` > ``burn_high``), or
      - a paged tier has zero free pages with work queued.

    Scale DOWN (-step) when, continuously for ``down_sustain_s``:
      - occupancy < ``low`` AND nothing is queued anywhere.

    Inside the band (or with mixed signals) the policy holds — that IS
    the hysteresis: the band's width, not a single threshold, decides,
    so a fleet hovering near one edge never flaps.
    """

    def __init__(self, low: float = 0.25, high: float = 0.85,
                 queue_age_s: float = 5.0, burn_high: float = 1.0,
                 up_sustain_s: float = 1.0, down_sustain_s: float = 5.0,
                 step: int = 1):
        if not 0.0 <= low < high <= 1.0:
            raise ValueError(f"need 0 <= low < high <= 1, "
                             f"got low={low} high={high}")
        self.low = float(low)
        self.high = float(high)
        self.queue_age_s = float(queue_age_s)
        self.burn_high = float(burn_high)
        self.up_sustain_s = float(up_sustain_s)
        self.down_sustain_s = float(down_sustain_s)
        self.step = int(step)
        self._up_since: Optional[float] = None
        self._down_since: Optional[float] = None

    def _pressure(self, s: FleetSignals) -> Optional[str]:
        if s.total_slots and s.occupancy > self.high:
            return (f"occupancy {s.occupancy:.2f} > {self.high:.2f}"
                    f" band")
        if s.queue_age_s > self.queue_age_s:
            return (f"queue age {s.queue_age_s:.1f}s > "
                    f"{self.queue_age_s:.0f}s")
        if s.ttft_burn > self.burn_high:
            return f"TTFT SLO burn {s.ttft_burn:.2f} > 1"
        if s.total_pages > 0 and s.free_pages <= 0 and s.queued > 0:
            return f"page pool exhausted with {s.queued} queued"
        return None

    def decide(self, sig, now: Optional[float] = None
               ) -> Tuple[int, str]:
        now = time.monotonic() if now is None else now
        s = FleetSignals.from_dict(sig)
        up_reason = self._pressure(s)
        idle = (s.total_slots > 0 and s.occupancy < self.low
                and s.queued == 0 and s.queue_age_s == 0.0
                and s.ttft_burn <= self.burn_high)
        if up_reason is None:
            self._up_since = None
        elif self._up_since is None:
            self._up_since = now
        if not idle:
            self._down_since = None
        elif self._down_since is None:
            self._down_since = now
        if (self._up_since is not None
                and now - self._up_since >= self.up_sustain_s):
            return self.step, up_reason
        if (self._down_since is not None
                and now - self._down_since >= self.down_sustain_s):
            return -self.step, (f"idle: occupancy {s.occupancy:.2f} < "
                                f"{self.low:.2f} with empty queue for "
                                f"{now - self._down_since:.1f}s")
        return 0, ""

    def reset(self):
        self._up_since = None
        self._down_since = None
