"""Elastic fleet controller (ISSUE 14, ROADMAP item 3): SLO-driven
autoscaling of serving replicas and preemption-tolerant training that
reshapes its mesh.

Two control loops close the fleet story on top of the detection planes
that already exist:

- **Serving autoscaler** (``controller.FleetController`` +
  ``policy.TargetOccupancyPolicy``): consumes the router-side
  ``FleetStats`` merged signals (queue depth/age, occupancy, windowed
  TTFT burn, goodput floor, pool pages), spawns replicas through the
  ``distributed/launch.py`` machinery (prefill/decode tiers scale
  independently), heals SIGKILLed replicas, and retires surplus ones
  via the graceful drain protocol (``ReplicaDirectory`` lifecycle
  states — no request loss).
- **Preemption-tolerant training** (``elastic_train.ElasticTrainer``):
  membership change means *reshape and continue*, not crash — the
  launcher re-forms at the surviving world size and
  ``AutoCheckpoint.restore_resharded`` restores the newest VERIFIED
  epoch onto the new topology's re-planned mesh.

See docs/elastic.md for the drain state machine, the reshape
sequence, and the chaos-run howto.
"""

from paddle_tpu.fleet.policy import (FleetSignals, ScalePolicy,
                                     TargetOccupancyPolicy)
from paddle_tpu.fleet.controller import (FleetController, TierSpec,
                                         launch_spawn)
from paddle_tpu.fleet.elastic_train import ElasticTrainer, plan_topology

__all__ = ["FleetSignals", "ScalePolicy", "TargetOccupancyPolicy",
           "FleetController", "TierSpec", "launch_spawn",
           "ElasticTrainer", "plan_topology"]
