"""Preemption-tolerant training: membership change = reshape and
continue, never crash-and-restart-at-the-old-size (ISSUE 14 tentpole,
training half; docs/elastic.md).

The pieces existed separately: PR 2's launcher auto-resumes, PR 8's
``load_resharded`` moves a VERIFIED checkpoint between arbitrary
meshes and layouts, PR 12 hardened the sharded step. This module wires
them into ONE harness:

- :func:`plan_topology` re-plans the fsdp×tp mesh for the CURRENT
  device count (``planner.suggest_mesh`` when a model is given) — the
  surviving topology gets a fresh plan, not the old mesh minus holes;
- :class:`ElasticTrainer` builds fresh sharded state on that mesh,
  ``AutoCheckpoint.restore_resharded``-resumes from the newest
  VERIFIED epoch (checkpoint-coordinated across ranks by
  ``last_verified_epoch``'s broadcast), runs the deterministic
  per-epoch step loop, and — when an ``ElasticManager`` membership
  callback reports a dead peer — exits with ``ELASTIC_EXIT_CODE`` at
  the next epoch boundary so the launcher re-forms the store table at
  the new world size. The relaunched generation replans, restores,
  and continues: the loss trajectory is parity-pinned against an
  uninterrupted run (same per-epoch data and rng).

The launcher side of the contract: ``--elastic`` re-forms multi-node
membership via the TCPStore registry; single-node ``--max_restarts``
relaunches shrink to the surviving local worker count when
``PT_ELASTIC_RESHAPE=1`` (distributed/launch.py), exporting the new
``PT_NUM_PROCESSES``/``PT_PROCESS_ID`` to every worker.
"""

import json
import os
import sys
import time
from typing import Callable, List, Optional

__all__ = ["ElasticTrainer", "plan_topology", "synthetic_data"]


def plan_topology(model=None, n_devices: Optional[int] = None,
                  max_tp: int = 8):
    """An fsdp×tp topology re-planned for the CURRENT device count —
    the reshape half of an elastic resume. With ``model``, the planner
    picks (dp, fsdp, tp) degrees by its memory/cost model
    (``planner.suggest_mesh``); without, the whole device set becomes
    one fsdp axis (parameter sharding with mesh-independent loss
    semantics). Returns the initialized topology
    (``distributed.init_mesh``)."""
    import jax
    from paddle_tpu import distributed as dist
    devices = jax.devices()
    n = len(devices) if n_devices is None else int(n_devices)
    devices = devices[:n]     # a reshape to fewer-than-all devices
    # carves the leading prefix (the virtual-device test topology)
    if model is not None:
        from paddle_tpu.distributed import planner
        degrees = planner.suggest_mesh(model, n, max_tp=max_tp)
        degrees = {k: int(v) for k, v in degrees.items()
                   if k in ("dp", "fsdp", "tp", "pp") and int(v) > 1}
        if degrees:
            return dist.init_mesh(devices=devices, **degrees)
    return dist.init_mesh(fsdp=n, devices=devices)


def synthetic_data(vocab_size: int, batch: int, seq_len: int):
    """Deterministic per-epoch batch generator: epoch e's tokens and
    rng depend only on ``e`` — every world size sees the SAME data, so
    a reshaped trajectory stays comparable to an uninterrupted one."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def data_fn(epoch: int):
        tokens = jnp.asarray(
            np.random.RandomState(1000 + epoch).randint(
                0, vocab_size, (batch, seq_len)), jnp.int32)
        return tokens, jax.random.PRNGKey(epoch)
    return data_fn


class ElasticTrainer:
    """Reshape-on-membership-change training loop.

        trainer = ElasticTrainer(model, opt, ckpt_root,
                                 data_fn=synthetic_data(V, B, T),
                                 n_epochs=8)
        records = trainer.run()     # resumes+reshapes transparently

    Every epoch: ``step(params, opt_state, tokens, rng)`` then an
    AutoCheckpoint save. On (re)start the harness replans the mesh for
    the current device count, restores the newest VERIFIED epoch via
    ``restore_resharded`` (surviving a mesh AND block-layout change),
    and continues at ``epoch+1``. ``elastic_store`` wires an
    ``ElasticManager`` peer watch: a dead peer requests a reshape,
    honored at the next epoch boundary with ``ELASTIC_EXIT_CODE`` so
    the launcher re-forms at the new world size (state is already on
    disk — the save IS the coordination point).

    ``init_fn(model, opt, mesh)`` / ``step_fn(model, opt, mesh)``
    default to the GPT training factory; any model family with the
    same (params, opt_state, tokens, rng) step contract plugs in.
    """

    def __init__(self, model, opt, ckpt_root: str, *, job_id="job",
                 n_epochs: int = 8, keep: int = 3,
                 data_fn: Optional[Callable] = None,
                 mesh=None, init_fn: Optional[Callable] = None,
                 step_fn: Optional[Callable] = None,
                 on_epoch: Optional[Callable] = None,
                 log_path: Optional[str] = None,
                 elastic_store=None, rank: int = 0,
                 world_size: int = 1, ttl: float = 10.0):
        self.model = model
        self.opt = opt
        self.ckpt_root = ckpt_root
        self.job_id = job_id
        self.n_epochs = int(n_epochs)
        self.keep = int(keep)
        self.data_fn = data_fn
        self.mesh = mesh
        self.init_fn = init_fn
        self.step_fn = step_fn
        self.on_epoch = on_epoch
        self.log_path = log_path
        self.elastic_store = elastic_store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.ttl = float(ttl)
        self._manager = None
        self._dead_peers: Optional[list] = None
        self._reshape_to: Optional[int] = None

    # -- in-HBM reshape (ISSUE 16 tentpole) ---------------------------------

    def request_reshape(self, n_devices: int):
        """Ask the step loop to reshape THIS process's topology to
        ``n_devices`` at the next epoch boundary (the virtual-device
        idiom — elasticity within one process's device set). Honored
        in HBM when ``PT_RESHARD_INPLACE`` allows (O(collective), no
        disk round-trip), else via the save → ``restore_resharded``
        checkpoint path; both resume the SAME trajectory (the epoch
        just saved is the coordination point either way). Callable
        from an ``on_epoch`` hook or another thread."""
        self._reshape_to = int(n_devices)

    def _reshape_inplace(self, target, mesh_obj, params, opt_state,
                         init_fn, step_fn, ck, epoch):
        """Execute a requested same-process reshape: re-plan the mesh,
        redistribute the live state in HBM (fallback: restore the
        epoch just committed), rebuild the step. Returns the new
        (mesh, params, opt_state, step, n_dev)."""
        import jax.numpy as jnp
        from paddle_tpu import stats
        from paddle_tpu.distributed import redistribute as redist
        from paddle_tpu.observability import flight
        n_dev = int(mesh_obj.size)
        new_mesh = plan_topology(self.model, n_devices=target).mesh
        p_t, s_t = init_fn(self.model, self.opt, new_mesh)
        moved = None
        if os.environ.get("PT_RESHARD_INPLACE", "1") != "0":
            t0 = time.perf_counter()
            try:
                moved = redist.redistribute(
                    {"params": params, "opt": opt_state},
                    {"params": p_t, "opt": s_t}, mesh=new_mesh)
                dt = time.perf_counter() - t0
                stats.observe("fleet/reshard_inplace_s", dt)
                flight.record("fleet", "reshard", phase="inplace",
                              from_devices=n_dev, to_devices=target,
                              epoch=epoch, seconds=round(dt, 4))
                print(f"[elastic_train] in-HBM reshard {n_dev}->"
                      f"{target} devices in {dt:.3f}s (epoch {epoch})",
                      file=sys.stderr, flush=True)
            except Exception as e:
                # ANY redistribute failure — unprovable plan, injected
                # chaos, digest mismatch — degrades to the verified
                # checkpoint path, loudly
                stats.add("fleet/reshard_fallbacks")
                flight.record("fleet", "reshard", phase="fallback",
                              from_devices=n_dev, to_devices=target,
                              epoch=epoch, error=f"{type(e).__name__}: "
                                                 f"{e}"[:200])
                print(f"[elastic_train] in-HBM reshard failed "
                      f"({type(e).__name__}: {e}); falling back to "
                      f"checkpoint restore", file=sys.stderr,
                      flush=True)
                moved = None
        if moved is not None:
            params, opt_state = moved["params"], moved["opt"]
        else:
            fresh = {"params": p_t, "opt": s_t,
                     "epoch": jnp.zeros((), jnp.int32),
                     "world": jnp.asarray(target, jnp.int32)}
            state = ck.restore_resharded(fresh, mesh=new_mesh)
            if state is None:
                raise RuntimeError(
                    "reshape fallback found no verified checkpoint "
                    "(the epoch-boundary save should have committed "
                    "one)")
            params, opt_state = state["params"], state["opt"]
            flight.record("fleet", "reshard", phase="restore",
                          from_devices=n_dev, to_devices=target,
                          epoch=epoch)
            print(f"[elastic_train] reshard {n_dev}->{target} via "
                  f"checkpoint restore (epoch {epoch})",
                  file=sys.stderr, flush=True)
        step = step_fn(self.model, self.opt, new_mesh)
        return new_mesh, params, opt_state, step, target

    # -- membership ---------------------------------------------------------

    def _on_membership_change(self, dead_ranks):
        """ElasticManager callback (watcher thread): note the change;
        the step loop honors it at the next epoch boundary — a reshape
        exit mid-save would orphan the .tmp epoch dir for GC instead
        of committing it."""
        self._dead_peers = list(dead_ranks)

    def _maybe_reshape_exit(self, epoch: int):
        if self._dead_peers is None:
            return
        from paddle_tpu import stats
        from paddle_tpu.distributed.launch import ELASTIC_EXIT_CODE
        from paddle_tpu.observability import flight
        stats.add("fleet/reshape_exits")
        flight.record("fleet", "reshape", phase="exit",
                      dead_peers=self._dead_peers, epoch=epoch,
                      world=self.world_size)
        print(f"[elastic_train] peers {self._dead_peers} died; "
              f"exiting {ELASTIC_EXIT_CODE} for re-form after epoch "
              f"{epoch}", file=sys.stderr, flush=True)
        if self._manager is not None:
            self._manager.stop()
        raise SystemExit(ELASTIC_EXIT_CODE)

    # -- the loop -----------------------------------------------------------

    def run(self) -> List[dict]:
        import jax.numpy as jnp
        from paddle_tpu import stats
        from paddle_tpu.distributed.checkpoint import AutoCheckpoint
        from paddle_tpu.models import gpt
        from paddle_tpu.observability import flight

        mesh_obj = self.mesh
        if mesh_obj is None:
            mesh_obj = plan_topology(self.model).mesh
        elif hasattr(mesh_obj, "mesh"):      # a Topology was passed
            mesh_obj = mesh_obj.mesh
        init_fn = self.init_fn or gpt.init_train_state
        step_fn = self.step_fn or gpt.build_train_step
        params, opt_state = init_fn(self.model, self.opt, mesh_obj)
        step = step_fn(self.model, self.opt, mesh_obj)
        if self.data_fn is None:
            cfg = getattr(self.model, "config", None)
            if cfg is None:
                raise ValueError("pass data_fn= (no model.config to "
                                 "derive a synthetic batch from)")
            self.data_fn = synthetic_data(cfg.vocab_size, 8,
                                          cfg.max_seq_len)

        world = int(os.environ.get("PT_NUM_PROCESSES", "1"))
        # the topology's own device count (a reshape to fewer devices
        # than the process exposes — the virtual-device test idiom —
        # must still register as a reshape)
        n_dev = int(mesh_obj.size)
        ck = AutoCheckpoint(self.ckpt_root, job_id=self.job_id,
                            keep=self.keep)
        fresh = {"params": params, "opt": opt_state,
                 "epoch": jnp.zeros((), jnp.int32),
                 "world": jnp.asarray(n_dev, jnp.int32)}
        state = ck.restore_resharded(fresh, mesh=mesh_obj)
        if state is not None:
            params, opt_state = state["params"], state["opt"]
            start = int(state["epoch"]) + 1
            saved_world = int(state.get("world", n_dev))
            if saved_world != n_dev:
                # the reshape landed: a checkpoint saved under another
                # topology restored onto this one
                stats.add("fleet/reshape_resumes")
                flight.record("fleet", "reshape", phase="resume",
                              from_devices=saved_world,
                              to_devices=n_dev, epoch=start)
                print(f"[elastic_train] reshaped {saved_world}->"
                      f"{n_dev} devices; resuming at epoch {start}",
                      file=sys.stderr, flush=True)
        else:
            start = 0
        stats.set_value("fleet/train_world", world)

        if self.elastic_store is not None and self.world_size > 1:
            from paddle_tpu.distributed.elastic import ElasticManager
            self._manager = ElasticManager(
                self.elastic_store, self.rank, self.world_size,
                ttl=self.ttl,
                on_change=self._on_membership_change).start()

        records: List[dict] = []
        from paddle_tpu.testing import faults
        try:
            for epoch in range(start, self.n_epochs):
                # the documented training-loop fault site: the chaos
                # gate kills a trainer mid-step with
                # PT_FAULTS="train.step:kill:after=N" and asserts the
                # reshape path resumes it
                faults.fire("train.step")
                tokens, rng = self.data_fn(epoch)
                t0 = time.perf_counter()
                params, opt_state, loss = step(params, opt_state,
                                               tokens, rng)
                loss = float(loss)
                stats.observe("fleet/train_epoch_s",
                              time.perf_counter() - t0)
                rec = {"epoch": epoch, "loss": loss, "world": world,
                       "devices": n_dev}
                records.append(rec)
                if self.log_path:
                    with open(self.log_path, "a") as f:
                        f.write(json.dumps(rec) + "\n")
                ck.save({"params": params, "opt": opt_state,
                         "epoch": jnp.asarray(epoch, jnp.int32),
                         "world": jnp.asarray(n_dev, jnp.int32)},
                        epoch)
                if self.on_epoch is not None:
                    self.on_epoch(rec)
                # same-process reshape request: redistribute in HBM
                # AFTER the save (the committed epoch is the fallback's
                # restore point) and continue the loop on the new mesh
                if self._reshape_to is not None and \
                        int(self._reshape_to) != n_dev:
                    target = int(self._reshape_to)
                    self._reshape_to = None
                    (mesh_obj, params, opt_state, step,
                     n_dev) = self._reshape_inplace(
                        target, mesh_obj, params, opt_state,
                        init_fn, step_fn, ck, epoch)
                else:
                    self._reshape_to = None
                # reshape request (dead peer): exit AFTER the save —
                # the committed epoch is the coordination point the
                # surviving generation restores from
                self._maybe_reshape_exit(epoch)
        finally:
            if self._manager is not None:
                self._manager.stop()
        return records
