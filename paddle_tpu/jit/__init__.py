"""paddle_tpu.jit — dynamic-to-static (ref: @paddle.jit.to_static,
python/paddle/fluid/dygraph/dygraph_to_static/ ~20 AST transformers +
program_translator cache + partial_program run_program_op).

None of that machinery is needed on TPU: Python *is* the tracer. ``to_static``
is jax.jit with InputSpec-driven AOT lowering; ``save``/``load`` export
StableHLO via jax.export (≙ save_inference_model + C++ jit::Layer,
paddle/fluid/jit/layer.h:44). The module itself is callable:
``paddle_tpu.jit(fn)`` == ``to_static(fn)``.
"""

import os
import pickle
import sys
import types

import jax
import jax.numpy as jnp

__all__ = ["to_static", "save", "load", "not_to_static", "TranslatedLayer"]


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """ref: paddle.jit.to_static (dygraph/jit.py). Returns a compiled
    callable; with input_spec, lowering happens eagerly (AOT)."""
    def decorate(fn):
        target = fn.forward if hasattr(fn, "forward") else fn
        jitted = jax.jit(target)
        if input_spec is not None:
            from paddle_tpu.static import InputSpec
            structs = [s.to_shape_struct() if isinstance(s, InputSpec) else s
                       for s in input_spec]
            jitted.lower(*structs)  # warm the AOT cache
        return jitted
    if function is None:
        return decorate
    return decorate(function)


def not_to_static(fn):
    return fn


class TranslatedLayer:
    """Loaded exported model (≙ C++ jit::Layer / TranslatedLayer)."""

    def __init__(self, exported, extra=None):
        self._exported = exported
        self.extra = extra or {}

    def __call__(self, *args):
        return self._exported.call(*[jnp.asarray(a) for a in args])

    @property
    def stablehlo(self):
        return self._exported.mlir_module()


# .ptexport format version (VERDICT r4 item 10; ≙ the reference's per-op
# semantic versions, paddle/fluid/framework/op_version_registry.h:397 +
# phi/api/yaml/op_version.yaml — old programs must load correctly or fail
# loudly, never silently misbehave). Bump when the bundle layout or the
# semantics of exported programs change; widen MIN_READABLE only with a
# migration path.
FORMAT_VERSION = 1
MIN_READABLE_FORMAT = 1


def _op_registry_hash() -> str:
    """Fingerprint of the op registry the artifact was exported under —
    diagnostic provenance for drift reports (not a load gate: StableHLO
    programs carry their own ops)."""
    import hashlib
    from paddle_tpu.ops.registry import all_ops
    names = ",".join(sorted(spec.name for spec in all_ops()))
    return hashlib.md5(names.encode()).hexdigest()[:16]


def save(layer, path, input_spec=None, **configs):
    """Export a function/Module to .ptexport (serialized StableHLO +
    {format_version, package_version, op-registry hash} metadata).
    ref: paddle.jit.save → __model__ + params files."""
    from jax import export as jax_export
    from paddle_tpu.static import InputSpec

    fn = layer.forward if hasattr(layer, "forward") else layer
    if input_spec is None:
        raise ValueError("input_spec is required for AOT export")
    # None/-1 dims export as SYMBOLIC dimensions (shape polymorphism), so
    # the loaded model accepts any batch — the reference's -1 batch dim in
    # save_inference_model
    scope = jax_export.SymbolicScope()
    structs = []
    for i, s in enumerate(input_spec):
        if not isinstance(s, InputSpec):
            structs.append(s)
            continue
        dims = []
        for j, d in enumerate(s.shape):
            if d is None or d == -1:
                dims.append(jax_export.symbolic_shape(
                    f"d{i}_{j}", scope=scope)[0])
            else:
                dims.append(int(d))
        structs.append(jax.ShapeDtypeStruct(tuple(dims), s.dtype))
    exported = jax_export.export(jax.jit(fn))(*structs)
    blob = exported.serialize()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    from paddle_tpu.version import __version__ as pkg_version
    with open(path + ".ptexport", "wb") as f:
        pickle.dump({"stablehlo": bytes(blob),
                     "format_version": FORMAT_VERSION,
                     "package_version": pkg_version,
                     "op_registry_hash": _op_registry_hash()}, f)
    # params saved separately when layer is a Module
    if hasattr(layer, "state_dict"):
        from paddle_tpu.framework.io import save as obj_save
        obj_save(layer.state_dict(), path + ".pdparams")
    return path + ".ptexport"


def load(path, **configs):
    """Load a .ptexport bundle, gating on its format version: an artifact
    outside [MIN_READABLE_FORMAT, FORMAT_VERSION] fails with a clear
    error instead of deserializing a layout this build cannot interpret
    (≙ op_version_registry.h:397's load-time version checks)."""
    import warnings
    from jax import export as jax_export
    p = path if path.endswith(".ptexport") else path + ".ptexport"
    with open(p, "rb") as f:
        data = pickle.load(f)
    if "format_version" not in data:
        # pre-versioning bundles have the identical {"stablehlo": ...}
        # layout — load them, but flag the missing provenance
        warnings.warn(
            f"{p} predates .ptexport version stamping (no format_version)"
            "; loading as legacy — re-export to stamp provenance",
            stacklevel=2)
    else:
        fmt = data["format_version"]
        if not (MIN_READABLE_FORMAT <= fmt <= FORMAT_VERSION):
            raise ValueError(
                f"{p} has .ptexport format version {fmt} (saved by "
                f"paddle_tpu {data.get('package_version', '<unknown>')});"
                f" this build reads versions {MIN_READABLE_FORMAT}.."
                f"{FORMAT_VERSION} — re-export the artifact")
    saved_hash = data.get("op_registry_hash")
    if saved_hash:
        current = _op_registry_hash()
        if saved_hash != current:
            warnings.warn(
                f"{p} was exported under a different op registry "
                f"({saved_hash} vs {current}); the StableHLO program is "
                "self-contained, but re-export to refresh provenance",
                stacklevel=2)
    exported = jax_export.deserialize(bytearray(data["stablehlo"]))
    return TranslatedLayer(exported)


class _CallableModule(types.ModuleType):
    def __call__(self, fn=None, **kwargs):
        return to_static(fn, **kwargs)


sys.modules[__name__].__class__ = _CallableModule


class ProgramTranslator:
    """ref: dygraph_to_static ProgramTranslator singleton — enables or
    disables dy2static globally. Tracing IS the translator here; the
    flag only gates whether to_static wraps with jit."""

    _instance = None
    _enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static: bool = True):
        type(self)._enabled = bool(enable_to_static)

    @property
    def enable_to_static(self):
        return type(self)._enabled


def set_code_level(level=100, also_to_stdout=False):
    """ref: jit.set_code_level — the reference dumps transformed AST
    stages; there is no AST pipeline here, so this records the level for
    introspection only."""
    from paddle_tpu import stats
    stats.set_value("jit/code_level", level)


def set_verbosity(level=0, also_to_stdout=False):
    """ref: jit.set_verbosity (dy2static logging)."""
    from paddle_tpu import stats
    stats.set_value("jit/verbosity", level)


class TracedLayer:
    """ref: fluid.dygraph.TracedLayer — trace a layer into a static
    callable. Here tracing is jit: ``TracedLayer.trace(layer, inputs)``
    returns (eager outputs, a TracedLayer whose __call__ is the jitted
    forward and whose save_inference_model exports StableHLO)."""

    def __init__(self, layer, jitted, specs):
        self._layer = layer
        self._jitted = jitted
        self._specs = specs

    @staticmethod
    def trace(layer, inputs):
        import jax as _jax
        import jax.numpy as _jnp
        from paddle_tpu.static import InputSpec
        inputs = list(inputs) if isinstance(inputs, (list, tuple)) \
            else [inputs]
        specs = [InputSpec(shape=_jnp.shape(a),
                           dtype=_jnp.asarray(a).dtype) for a in inputs]
        jitted = _jax.jit(lambda *a: layer(*a))
        out = jitted(*inputs)
        return out, TracedLayer(layer, jitted, specs)

    def __call__(self, *inputs):
        return self._jitted(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None, **kw):
        from paddle_tpu.jit import save as _save
        kw.setdefault("input_spec", self._specs)
        return _save(lambda *a: self._layer(*a), path, **kw)


__all__ += ["ProgramTranslator", "TracedLayer", "set_code_level",
            "set_verbosity"]
