"""paddle_tpu.jit — dynamic-to-static (ref: @paddle.jit.to_static,
python/paddle/fluid/dygraph/dygraph_to_static/ ~20 AST transformers +
program_translator cache + partial_program run_program_op).

None of that machinery is needed on TPU: Python *is* the tracer. ``to_static``
is jax.jit with InputSpec-driven AOT lowering; ``save``/``load`` export
StableHLO via jax.export (≙ save_inference_model + C++ jit::Layer,
paddle/fluid/jit/layer.h:44). The module itself is callable:
``paddle_tpu.jit(fn)`` == ``to_static(fn)``.
"""

import os
import pickle
import sys
import types

import jax
import jax.numpy as jnp

__all__ = ["to_static", "save", "load", "not_to_static", "TranslatedLayer"]


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """ref: paddle.jit.to_static (dygraph/jit.py). Returns a compiled
    callable; with input_spec, lowering happens eagerly (AOT)."""
    def decorate(fn):
        target = fn.forward if hasattr(fn, "forward") else fn
        jitted = jax.jit(target)
        if input_spec is not None:
            from paddle_tpu.static import InputSpec
            structs = [s.to_shape_struct() if isinstance(s, InputSpec) else s
                       for s in input_spec]
            jitted.lower(*structs)  # warm the AOT cache
        return jitted
    if function is None:
        return decorate
    return decorate(function)


def not_to_static(fn):
    return fn


class TranslatedLayer:
    """Loaded exported model (≙ C++ jit::Layer / TranslatedLayer)."""

    def __init__(self, exported, extra=None):
        self._exported = exported
        self.extra = extra or {}

    def __call__(self, *args):
        return self._exported.call(*[jnp.asarray(a) for a in args])

    @property
    def stablehlo(self):
        return self._exported.mlir_module()


def save(layer, path, input_spec=None, **configs):
    """Export a function/Module to .ptexport (serialized StableHLO +
    metadata). ref: paddle.jit.save → __model__ + params files."""
    from jax import export as jax_export
    from paddle_tpu.static import InputSpec

    fn = layer.forward if hasattr(layer, "forward") else layer
    if input_spec is None:
        raise ValueError("input_spec is required for AOT export")
    # None/-1 dims export as SYMBOLIC dimensions (shape polymorphism), so
    # the loaded model accepts any batch — the reference's -1 batch dim in
    # save_inference_model
    scope = jax_export.SymbolicScope()
    structs = []
    for i, s in enumerate(input_spec):
        if not isinstance(s, InputSpec):
            structs.append(s)
            continue
        dims = []
        for j, d in enumerate(s.shape):
            if d is None or d == -1:
                dims.append(jax_export.symbolic_shape(
                    f"d{i}_{j}", scope=scope)[0])
            else:
                dims.append(int(d))
        structs.append(jax.ShapeDtypeStruct(tuple(dims), s.dtype))
    exported = jax_export.export(jax.jit(fn))(*structs)
    blob = exported.serialize()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".ptexport", "wb") as f:
        pickle.dump({"stablehlo": bytes(blob)}, f)
    # params saved separately when layer is a Module
    if hasattr(layer, "state_dict"):
        from paddle_tpu.framework.io import save as obj_save
        obj_save(layer.state_dict(), path + ".pdparams")
    return path + ".ptexport"


def load(path, **configs):
    from jax import export as jax_export
    p = path if path.endswith(".ptexport") else path + ".ptexport"
    with open(p, "rb") as f:
        data = pickle.load(f)
    exported = jax_export.deserialize(bytearray(data["stablehlo"]))
    return TranslatedLayer(exported)


class _CallableModule(types.ModuleType):
    def __call__(self, fn=None, **kwargs):
        return to_static(fn, **kwargs)


sys.modules[__name__].__class__ = _CallableModule
