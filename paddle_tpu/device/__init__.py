"""Device management (ref: python/paddle/device/__init__.py —
set_device:141/get_device:201, is_compiled_with_cuda, synchronize;
device/cuda/ memory_allocated, Stream/Event).

TPU-native mapping: PJRT owns devices; "set_device" selects the default
jax device for subsequent placements, "synchronize" drains dispatched
work via a scalar fetch barrier, and the cuda.* memory accessors forward
to the PJRT allocator stats (profiler/memory.py). Streams/events dissolve
— XLA's async dispatch IS the stream; Event becomes a completion fence."""

import jax

__all__ = ["set_device", "get_device", "get_all_device_type",
           "get_all_custom_device_type", "is_compiled_with_cuda",
           "is_compiled_with_rocm", "is_compiled_with_npu",
           "is_compiled_with_xpu", "is_compiled_with_tpu",
           "device_count", "synchronize", "cuda", "Stream", "Event"]

_current = None


def set_device(device: str):
    """'tpu', 'tpu:0', 'cpu' — pins the default placement device
    (≙ set_device:141). Returns the jax device."""
    global _current
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    name = {"gpu": "tpu"}.get(name, name)  # reference scripts say gpu
    devs = [d for d in jax.devices() if d.platform == name]
    if not devs and name != jax.default_backend():
        try:
            devs = jax.devices(name)
        except RuntimeError:
            devs = []
    if not devs:
        raise ValueError(
            f"no {name!r} devices; available: "
            f"{sorted({d.platform for d in jax.devices()})}")
    _current = devs[idx]
    jax.config.update("jax_default_device", _current)
    return _current


def get_device() -> str:
    """(≙ get_device:201) e.g. 'tpu:0'."""
    d = _current if _current is not None else jax.devices()[0]
    return f"{d.platform}:{d.id}"


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return [p for p in get_all_device_type() if p not in ("cpu", "tpu")]


def is_compiled_with_cuda() -> bool:
    return False  # TPU build


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def device_count() -> int:
    return len(jax.devices())


def synchronize(device=None):
    """Block until dispatched work completes (≙ cuda.synchronize). A
    scalar fetch is the reliable barrier on the tunneled PJRT backend."""
    import jax.numpy as jnp
    float(jnp.zeros(()) + 0.0)


class Event:
    """Completion fence (≙ device.cuda.Event). record() captures the
    async-dispatch frontier; synchronize()/query() resolve it."""

    def __init__(self, enable_timing=False):
        self.enable_timing = enable_timing
        self._time = None

    def record(self, stream=None):
        import time
        synchronize()
        self._time = time.perf_counter()

    def query(self) -> bool:
        return True

    def synchronize(self):
        synchronize()

    def elapsed_time(self, end: "Event") -> float:
        if self._time is None or end._time is None:
            raise RuntimeError("record() both events first")
        return (end._time - self._time) * 1e3


class Stream:
    """API-parity stream (≙ device.cuda.Stream). XLA's async dispatch is
    the one stream; this object scopes nothing but keeps ported code
    running."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        event.synchronize()

    def wait_stream(self, stream):
        stream.synchronize()

    def record_event(self, event=None):
        ev = event or Event()
        ev.record()
        return ev

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _CudaNamespace:
    """paddle.device.cuda parity surface, forwarding to PJRT stats."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def memory_allocated(device=None):
        from paddle_tpu.profiler.memory import memory_allocated
        return memory_allocated(device)

    @staticmethod
    def max_memory_allocated(device=None):
        from paddle_tpu.profiler.memory import max_memory_allocated
        return max_memory_allocated(device)

    @staticmethod
    def memory_reserved(device=None):
        from paddle_tpu.profiler.memory import device_memory_stats
        return device_memory_stats(device).get("bytes_reserved", 0)

    @staticmethod
    def max_memory_reserved(device=None):
        from paddle_tpu.profiler.memory import device_memory_stats
        return device_memory_stats(device).get("peak_bytes_reserved", 0)

    @staticmethod
    def synchronize(device=None):
        synchronize(device)

    @staticmethod
    def empty_cache():
        pass  # PJRT owns the allocator; no cache to flush

    @staticmethod
    def current_stream(device=None):
        return Stream(device)

    @staticmethod
    def stream_guard(stream):
        return stream


cuda = _CudaNamespace()
