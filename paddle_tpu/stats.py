"""Named-counter observability surface (StatRegistry).

Reference analog: paddle/fluid/platform/monitor.h — ``StatRegistry`` with
``STAT_ADD``/``STAT_RESET`` macros exposing named int64 stats that tools
scrape (plus the per-module monitors fluid registers, e.g. the dataloader
and RPC byte counters). Here: one process-wide registry of counters,
gauges, and timers; framework subsystems record into it (hapi fit loop,
profiler Benchmark, DataLoader workers can), and users read it as a dict
or a formatted table.

    from paddle_tpu import stats
    stats.add("my/steps", 1)
    with stats.timer("my/io"):
        ...
    print(stats.table())

Resilience counter namespace (docs/resilience.md) — every retry, timeout,
fallback, and degradation event in the fault-tolerant runtime lands here
so operators can tell a healthy job from one limping through failures:

    resilience/retries[, /<op>/retries]   guarded-op retries (RetryPolicy)
    resilience/retries_exhausted          gave up after max_attempts
    resilience/deadline_exceeded          absolute deadline overruns
    resilience/watchdog_syncs             guarded collectives that synced
    resilience/watchdog_stalls            stalled collectives detected
    ckpt/verify_failures                  checkpoint dirs failing verify
    ckpt/restore_fallbacks                restores skipping a bad epoch
    ckpt/tmp_gc                           orphaned .tmp_epoch_* collected
    p2p/recv_timeouts, p2p/dropped_sends  p2p degradation events
    serve/deadline_evictions              requests evicted past deadline
    serve/nonfinite_evictions             poisoned-logit requests evicted
    launch/restarts                       launcher worker-group restarts

``snapshot("resilience/")`` / ``table("ckpt/")`` filter by prefix.
"""

import threading
import time
from typing import Dict, Optional

__all__ = ["StatRegistry", "default_registry", "add", "set_value", "get",
           "timer", "snapshot", "table", "reset"]


class _Timer:
    __slots__ = ("total_s", "count", "max_s")

    def __init__(self):
        self.total_s = 0.0
        self.count = 0
        self.max_s = 0.0

    def record(self, seconds: float):
        self.total_s += seconds
        self.count += 1
        self.max_s = max(self.max_s, seconds)

    @property
    def mean_s(self):
        return self.total_s / self.count if self.count else 0.0


class StatRegistry:
    """Thread-safe named counters/gauges/timers (≙ monitor.h
    StatRegistry::Instance)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._timers: Dict[str, _Timer] = {}

    # -- counters (monotonic; STAT_ADD) -------------------------------------
    def add(self, name: str, value: float = 1) -> float:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value
            return self._counters[name]

    # -- gauges (last-value-wins) --------------------------------------------
    def set_value(self, name: str, value: float):
        with self._lock:
            self._gauges[name] = value

    def get(self, name: str, default=0):
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            if name in self._gauges:
                return self._gauges[name]
            if name in self._timers:
                return self._timers[name].total_s
            return default

    # -- timers ---------------------------------------------------------------
    def record_time(self, name: str, seconds: float):
        with self._lock:
            self._timers.setdefault(name, _Timer()).record(seconds)

    def timer(self, name: str):
        """Context manager accumulating wall time under ``name``."""
        registry = self

        class _Ctx:
            def __enter__(self):
                self._t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                registry.record_time(name,
                                     time.perf_counter() - self._t0)
                return False

        return _Ctx()

    # -- export ---------------------------------------------------------------
    def snapshot(self, prefix: Optional[str] = None) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            out.update(self._gauges)
            for name, t in self._timers.items():
                out[f"{name}.total_s"] = t.total_s
                out[f"{name}.count"] = t.count
                out[f"{name}.mean_s"] = t.mean_s
                out[f"{name}.max_s"] = t.max_s
        if prefix is not None:
            out = {k: v for k, v in out.items() if k.startswith(prefix)}
        return out

    def table(self, prefix: Optional[str] = None) -> str:
        snap = self.snapshot(prefix)
        if not snap:
            return "(no stats recorded)"
        width = max(len(k) for k in snap)
        lines = [f"{'stat':<{width}}  value", "-" * (width + 12)]
        for k in sorted(snap):
            v = snap[k]
            vs = f"{v:.6g}" if isinstance(v, float) else str(v)
            lines.append(f"{k:<{width}}  {vs}")
        return "\n".join(lines)

    def reset(self, prefix: Optional[str] = None):
        with self._lock:
            for d in (self._counters, self._gauges, self._timers):
                if prefix is None:
                    d.clear()
                else:
                    for k in [k for k in d if k.startswith(prefix)]:
                        del d[k]


_DEFAULT = StatRegistry()


def default_registry() -> StatRegistry:
    return _DEFAULT


def add(name: str, value: float = 1) -> float:
    return _DEFAULT.add(name, value)


def set_value(name: str, value: float):
    _DEFAULT.set_value(name, value)


def get(name: str, default=0):
    return _DEFAULT.get(name, default)


def timer(name: str):
    return _DEFAULT.timer(name)


def snapshot(prefix: Optional[str] = None) -> Dict[str, float]:
    return _DEFAULT.snapshot(prefix)


def table(prefix: Optional[str] = None) -> str:
    return _DEFAULT.table(prefix)


def reset(prefix: Optional[str] = None):
    _DEFAULT.reset(prefix)


def _dump_at_exit():
    """Flag-gated exit dump (PT_FLAGS_STATS_AT_EXIT=1): lets operators
    scrape the counters of short-lived CLI processes — launch agents,
    elastic workers — the way the reference's monitor stats are dumped
    by tools (platform/monitor.h:35-139, §5.5)."""
    import sys
    try:
        from paddle_tpu import flags as _flags
        if not _flags.get_flag("stats_at_exit"):
            return
    except Exception:
        return
    snap = _DEFAULT.snapshot()
    if snap:
        print("[paddle_tpu.stats]\n" + _DEFAULT.table(), file=sys.stderr,
              flush=True)


import atexit as _atexit  # noqa: E402

_atexit.register(_dump_at_exit)
