"""Named-metric observability surface (StatRegistry).

Reference analog: paddle/fluid/platform/monitor.h — ``StatRegistry`` with
``STAT_ADD``/``STAT_RESET`` macros exposing named int64 stats that tools
scrape (plus the per-module monitors fluid registers, e.g. the dataloader
and RPC byte counters). Here: one process-wide registry of counters,
gauges, timers, and log-bucketed histograms; framework subsystems record
into it (hapi fit loop, the decode engines, p2p, checkpointing, the
profiler Benchmark), and users read it as a dict or a formatted table.

    from paddle_tpu import stats
    stats.add("my/steps", 1)
    with stats.timer("my/io"):
        ...
    stats.observe("my/latency_s", 0.012)    # histogram sample
    print(stats.table())                    # incl. p50/p90/p99

Metric namespace catalogue: docs/observability.md. The resilience
counters (docs/resilience.md) — retries, deadline overruns, watchdog
stalls, checkpoint fallbacks, p2p degradation, serve evictions, launch
restarts — land here too, so operators can tell a healthy job from one
limping through failures. ``snapshot("resilience/")`` / ``table("ckpt/")``
filter by prefix.

Derived names: a timer ``t`` exports ``t.total_s/.count/.mean_s/.max_s``;
a histogram ``h`` exports ``h.p50/.p90/.p99/.count/.sum/.max``. These
exist only in ``snapshot()``/``table()`` — the registry stores the BASE
name, and ``reset(prefix)`` matches prefixes against both the base name
and its derived names (so ``reset("p2p/send.")`` clears the ``p2p/send``
timer instead of silently no-opping).

Cross-rank aggregation: ``export(rank=...)`` returns a structured,
JSON-able snapshot tagged with the rank; ``merge(exports)`` folds many
worker exports into one registry — counters sum, timers and histograms
merge, gauges get a ``rank{r}/`` prefix (per-worker values must not
last-write-wins collide). ``snapshot(tag_rank=True)`` applies the same
``rank{r}/`` prefix to a flat snapshot.
"""

import math
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["StatRegistry", "default_registry", "add", "set_value", "get",
           "timer", "observe", "snapshot", "table", "reset", "export",
           "merge"]

_TIMER_SUFFIXES = (".total_s", ".count", ".mean_s", ".max_s")
_HIST_SUFFIXES = (".p50", ".p90", ".p99", ".count", ".sum", ".max")


def _env_rank() -> int:
    try:
        return int(os.environ.get("PT_PROCESS_ID", 0))
    except ValueError:
        return 0


class _Timer:
    __slots__ = ("total_s", "count", "max_s")

    def __init__(self):
        self.total_s = 0.0
        self.count = 0
        self.max_s = 0.0

    def record(self, seconds: float):
        self.total_s += seconds
        self.count += 1
        self.max_s = max(self.max_s, seconds)

    @property
    def mean_s(self):
        return self.total_s / self.count if self.count else 0.0

    def merge(self, other: "_Timer"):
        self.total_s += other.total_s
        self.count += other.count
        self.max_s = max(self.max_s, other.max_s)


class _Histogram:
    """Log-bucketed histogram (DDSketch-style): bucket i covers
    [MIN·G^i, MIN·G^(i+1)) with growth G = 2^(1/4) — ≤ ~9% relative
    error on any quantile estimate (half a bucket), over 1e-9..1e12
    with ~175 sparse buckets. Sparse dict storage: only touched buckets
    cost memory, and two histograms merge bucket-wise exactly."""

    __slots__ = ("count", "sum", "min", "max", "buckets")

    GROWTH = 2.0 ** 0.25
    _LOG_G = math.log(GROWTH)
    MIN = 1e-9

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets: Dict[int, int] = {}

    def record(self, value: float):
        v = float(value)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if v <= self.MIN:
            i = 0
        else:
            i = int(math.log(v / self.MIN) / self._LOG_G) + 1
        self.buckets[i] = self.buckets.get(i, 0) + 1

    def percentile(self, q: float) -> float:
        """q in [0, 100]. Bucket-representative = geometric midpoint of
        the bucket's edges, clamped into the exact [min, max] seen."""
        if not self.count:
            return 0.0
        target = max(1, math.ceil(self.count * q / 100.0))
        cum = 0
        for i in sorted(self.buckets):
            cum += self.buckets[i]
            if cum >= target:
                if i == 0:
                    rep = self.MIN
                else:
                    rep = self.MIN * self.GROWTH ** (i - 0.5)
                return min(max(rep, self.min), self.max)
        return self.max

    def merge(self, other: "_Histogram"):
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        for i, c in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0) + c

    def to_dict(self):
        return {"count": self.count, "sum": self.sum,
                "min": (None if self.count == 0 else self.min),
                "max": (None if self.count == 0 else self.max),
                "buckets": {str(i): c for i, c in self.buckets.items()}}

    @classmethod
    def from_dict(cls, d):
        h = cls()
        h.count = int(d.get("count", 0))
        h.sum = float(d.get("sum", 0.0))
        h.min = math.inf if d.get("min") is None else float(d["min"])
        h.max = -math.inf if d.get("max") is None else float(d["max"])
        h.buckets = {int(i): int(c)
                     for i, c in d.get("buckets", {}).items()}
        return h


def _prefix_hits(base: str, suffixes, prefix: str) -> bool:
    """Does ``prefix`` select metric ``base``? Matches the base name OR
    any of its derived export names (reset("p2p/send.") must clear the
    p2p/send timer even though only p2p/send.total_s appears in
    snapshot())."""
    if base.startswith(prefix):
        return True
    return any((base + s).startswith(prefix) for s in suffixes)


class StatRegistry:
    """Thread-safe named counters/gauges/timers/histograms (≙ monitor.h
    StatRegistry::Instance)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._timers: Dict[str, _Timer] = {}
        self._hists: Dict[str, _Histogram] = {}

    # -- counters (monotonic; STAT_ADD) -------------------------------------
    def add(self, name: str, value: float = 1) -> float:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value
            return self._counters[name]

    # -- gauges (last-value-wins) --------------------------------------------
    def set_value(self, name: str, value: float):
        with self._lock:
            self._gauges[name] = value

    # compat alias (≙ monitor.h int64 set; profiler.stat_registry.set)
    def set(self, name: str, value):  # noqa: A003 (reference name)
        self.set_value(name, value)

    def get(self, name: str, default=0):
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            if name in self._gauges:
                return self._gauges[name]
            if name in self._timers:
                return self._timers[name].total_s
            if name in self._hists:
                return self._hists[name].count
            return default

    def stats(self) -> Dict[str, float]:
        """Counters + gauges as one flat dict (the monitor.h scrape
        shape the profiler's stat_registry historically returned)."""
        with self._lock:
            return {**self._counters, **self._gauges}

    # -- timers ---------------------------------------------------------------
    def record_time(self, name: str, seconds: float):
        with self._lock:
            self._timers.setdefault(name, _Timer()).record(seconds)

    def timer(self, name: str):
        """Context manager accumulating wall time under ``name``."""
        registry = self

        class _Ctx:
            def __enter__(self):
                self._t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                registry.record_time(name,
                                     time.perf_counter() - self._t0)
                return False

        return _Ctx()

    # -- histograms -----------------------------------------------------------
    def observe(self, name: str, value: float):
        """Record one sample into the log-bucketed histogram ``name``
        (p50/p90/p99 + count/sum surface in snapshot()/table())."""
        with self._lock:
            self._hists.setdefault(name, _Histogram()).record(value)

    def histogram(self, name: str) -> Optional[_Histogram]:
        with self._lock:
            return self._hists.get(name)

    # -- export ---------------------------------------------------------------
    def snapshot(self, prefix: Optional[str] = None,
                 tag_rank: bool = False) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            out.update(self._gauges)
            for name, t in self._timers.items():
                out[f"{name}.total_s"] = t.total_s
                out[f"{name}.count"] = t.count
                out[f"{name}.mean_s"] = t.mean_s
                out[f"{name}.max_s"] = t.max_s
            for name, h in self._hists.items():
                out[f"{name}.p50"] = h.percentile(50)
                out[f"{name}.p90"] = h.percentile(90)
                out[f"{name}.p99"] = h.percentile(99)
                out[f"{name}.count"] = h.count
                out[f"{name}.sum"] = h.sum
                out[f"{name}.max"] = (0.0 if h.count == 0 else h.max)
        if prefix is not None:
            out = {k: v for k, v in out.items() if k.startswith(prefix)}
        if tag_rank:
            r = _env_rank()
            out = {f"rank{r}/{k}": v for k, v in out.items()}
        return out

    def table(self, prefix: Optional[str] = None) -> str:
        snap = self.snapshot(prefix)
        if not snap:
            return "(no stats recorded)"
        width = max(len(k) for k in snap)
        lines = [f"{'stat':<{width}}  value", "-" * (width + 12)]
        for k in sorted(snap):
            v = snap[k]
            vs = f"{v:.6g}" if isinstance(v, float) else str(v)
            lines.append(f"{k:<{width}}  {vs}")
        return "\n".join(lines)

    def reset(self, prefix: Optional[str] = None):
        """Clear everything, or every metric selected by ``prefix``.
        Matching runs on BASE metric names and their derived export
        names alike — ``reset("p2p/send.")`` clears the ``p2p/send``
        timer/histogram even though only derived dotted names appear in
        ``snapshot()``."""
        with self._lock:
            for d, suffixes in ((self._counters, ()), (self._gauges, ()),
                                (self._timers, _TIMER_SUFFIXES),
                                (self._hists, _HIST_SUFFIXES)):
                if prefix is None:
                    d.clear()
                else:
                    for k in [k for k in d
                              if _prefix_hits(k, suffixes, prefix)]:
                        del d[k]

    # -- structured export / cross-rank merge --------------------------------
    def export(self, rank: Optional[int] = None) -> dict:
        """JSON-able structured snapshot tagged with ``rank`` (default:
        PT_PROCESS_ID). The statsz endpoint serves this form; launch-side
        aggregation feeds a list of them to ``merge()``."""
        with self._lock:
            return {
                "rank": _env_rank() if rank is None else int(rank),
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": {k: {"total_s": t.total_s, "count": t.count,
                               "max_s": t.max_s}
                           for k, t in self._timers.items()},
                "histograms": {k: h.to_dict()
                               for k, h in self._hists.items()},
            }

    def load_export(self, exp: dict, gauge_prefix: str = ""):
        """Fold one ``export()`` dict into this registry: counters sum,
        timers/histograms merge, gauges land under ``gauge_prefix``."""
        with self._lock:
            for k, v in exp.get("counters", {}).items():
                self._counters[k] = self._counters.get(k, 0) + v
            for k, v in exp.get("gauges", {}).items():
                self._gauges[gauge_prefix + k] = v
            for k, d in exp.get("timers", {}).items():
                t = self._timers.setdefault(k, _Timer())
                t.total_s += float(d.get("total_s", 0.0))
                t.count += int(d.get("count", 0))
                t.max_s = max(t.max_s, float(d.get("max_s", 0.0)))
            for k, d in exp.get("histograms", {}).items():
                h = self._hists.setdefault(k, _Histogram())
                h.merge(_Histogram.from_dict(d))


def merge(exports: List[dict]) -> StatRegistry:
    """Aggregate worker ``export()`` snapshots into one registry:
    counters sum across ranks, timers and histograms merge exactly
    (bucket-wise), and gauges — per-worker values with no meaningful
    cross-rank sum — are namespaced ``rank{r}/name`` so nothing
    collides. ``merge(...).table()`` is the multi-host job view."""
    out = StatRegistry()
    for exp in exports:
        out.load_export(exp, gauge_prefix=f"rank{exp.get('rank', 0)}/")
    return out


_DEFAULT = StatRegistry()


def default_registry() -> StatRegistry:
    return _DEFAULT


def add(name: str, value: float = 1) -> float:
    return _DEFAULT.add(name, value)


def set_value(name: str, value: float):
    _DEFAULT.set_value(name, value)


def get(name: str, default=0):
    return _DEFAULT.get(name, default)


def timer(name: str):
    return _DEFAULT.timer(name)


def observe(name: str, value: float):
    _DEFAULT.observe(name, value)


def snapshot(prefix: Optional[str] = None,
             tag_rank: bool = False) -> Dict[str, float]:
    return _DEFAULT.snapshot(prefix, tag_rank=tag_rank)


def table(prefix: Optional[str] = None) -> str:
    return _DEFAULT.table(prefix)


def reset(prefix: Optional[str] = None):
    _DEFAULT.reset(prefix)


def export(rank: Optional[int] = None) -> dict:
    return _DEFAULT.export(rank)


def _dump_at_exit():
    """Flag-gated exit dump (PT_FLAGS_STATS_AT_EXIT=1): lets operators
    scrape the counters of short-lived CLI processes — launch agents,
    elastic workers — the way the reference's monitor stats are dumped
    by tools (platform/monitor.h:35-139, §5.5)."""
    import sys
    try:
        from paddle_tpu import flags as _flags
        if not _flags.get_flag("stats_at_exit"):
            return
    except Exception:
        return
    snap = _DEFAULT.snapshot()
    if snap:
        print("[paddle_tpu.stats]\n" + _DEFAULT.table(), file=sys.stderr,
              flush=True)


import atexit as _atexit  # noqa: E402

_atexit.register(_dump_at_exit)
