"""Object save/load (ref: paddle.save/load, python/paddle/framework/io.py:640
/870 — pickle of state_dict structures; phi save/load kernels for static
tensors).

Format: a msgpack-free, numpy-based pickle with jax arrays converted to host
numpy on save and restored as jnp arrays on load. Distributed/sharded
checkpointing (per-shard save + resharding on load) lives in
paddle_tpu.distributed.checkpoint.
"""

import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["save", "load"]

_MAGIC = b"PTPU1\n"


def _to_host(obj):
    def cvt(x):
        if isinstance(x, jax.Array):
            return np.asarray(jax.device_get(x))
        return x
    return jax.tree_util.tree_map(cvt, obj)


def _to_device(obj):
    def cvt(x):
        if isinstance(x, np.ndarray):
            return jnp.asarray(x)
        return x
    return jax.tree_util.tree_map(cvt, obj)


def save(obj, path, protocol=4):
    """ref: paddle.save. Saves any pytree (state_dicts, opt state, ...)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = _to_host(obj)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        pickle.dump(payload, f, protocol=protocol)


def load(path, return_numpy=False):
    """ref: paddle.load."""
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            f.seek(0)
        obj = pickle.load(f)
    if return_numpy:
        return obj
    return _to_device(obj)
