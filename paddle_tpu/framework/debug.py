"""Numeric debugging: NaN/Inf sweeps (SURVEY §5.2).

Reference analog: FLAGS_check_nan_inf sweeping op outputs —
framework/details/nan_inf_utils_detail.{cc,cu} (static graph) and
eager/nan_inf_utils.cc (eager). Under XLA there is no per-op boundary to
hook, so the sweep runs at the program boundary (loss/grads/params after a
step): enable with ``pt.set_flags({"check_nan_inf": True})`` — hapi's
train_batch sweeps automatically — or call ``check_nan_inf`` directly.
"""

from typing import Any, Dict

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["nan_inf_stats", "check_nan_inf", "enabled"]


def enabled() -> bool:
    from paddle_tpu import flags
    return bool(flags.get_flag("check_nan_inf"))


def _named_leaves(tree, prefix=""):
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _named_leaves(tree[k], f"{prefix}{k}.")
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _named_leaves(v, f"{prefix}{i}.")
    elif tree is None:
        return
    else:
        yield prefix.rstrip("."), tree


def nan_inf_stats(tree) -> Dict[str, Any]:
    """jit-safe: {leaf name: count of non-finite values} (all names; zeros
    mean clean). Floating leaves only."""
    out = {}
    for name, leaf in _named_leaves(tree):
        x = jnp.asarray(leaf)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            continue
        out[name] = jnp.sum(~jnp.isfinite(x.astype(jnp.float32)))
    return out


def check_nan_inf(tree, label: str = "tensors"):
    """Eager sweep; raises FloatingPointError naming the offending leaves
    (≙ the reference's enforce on first NaN/Inf op output)."""
    stats = jax.device_get(nan_inf_stats(tree))
    bad = {k: int(v) for k, v in stats.items() if v > 0}
    if bad:
        detail = ", ".join(f"{k} ({v} non-finite)"
                           for k, v in sorted(bad.items())[:8])
        more = f" and {len(bad) - 8} more" if len(bad) > 8 else ""
        raise FloatingPointError(
            f"NaN/Inf detected in {label}: {detail}{more}")
    return tree
