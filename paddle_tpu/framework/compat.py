"""Top-level API-parity compatibility surface.

Reference analog: the grab-bag of names paddle exports at top level from
fluid/framework.py and fluid/core — Places, ParamAttr, static-mode
toggles, grad toggles, rng-state accessors. On TPU most are
single-implementation trivia (PJRT owns devices, jax owns RNG keys), but
reference scripts import them, so they exist with honest semantics."""

import contextlib
import threading

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "NPUPlace",
           "TPUPlace", "ParamAttr", "LazyGuard", "DataParallel",
           "enable_static", "disable_static", "in_dynamic_mode",
           "is_grad_enabled", "set_grad_enabled", "check_shape",
           "disable_signal_handler", "get_cuda_rng_state",
           "set_cuda_rng_state", "create_parameter", "iinfo", "reverse"]


class _Place:
    """≙ fluid.core Place family. One real backend (PJRT); the CUDA/NPU
    places exist so reference scripts parse, and all map to the default
    device."""

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))


class CPUPlace(_Place):
    pass


class CUDAPlace(_Place):
    pass


class CUDAPinnedPlace(_Place):
    pass


class NPUPlace(_Place):
    pass


class TPUPlace(_Place):
    pass


class ParamAttr:
    """≙ paddle.ParamAttr — parameter configuration carried into layer
    constructors (initializer / trainable / name / regularizer)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    def __call__(self, shape, dtype=None):
        """Materialize: layers may call a ParamAttr like an initializer."""
        from paddle_tpu.dtypes import get_default_dtype
        from paddle_tpu.nn import initializer as I
        init = self.initializer or I.XavierUniform()
        return init(shape, dtype or get_default_dtype())


@contextlib.contextmanager
def LazyGuard():  # noqa: N802 (reference name)
    """≙ paddle.LazyGuard — delays parameter materialization in the
    reference; here parameters are cheap jax arrays, so it scopes
    nothing but keeps lazy-init scripts running."""
    yield


class DataParallel:
    """≙ paddle.DataParallel(model): in the reference this installs NCCL
    gradient all-reduce hooks. Under SPMD the compiler inserts the
    gradient psum from shardings, so this wrapper only carries the model
    (and the no-op sync entry points scripts call)."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False):
        self._layers = layers

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss  # pmean over dp is the train step's job

    def apply_collective_grads(self):
        return None


_mode = threading.local()


def enable_static():
    """≙ paddle.enable_static — scripts then build via paddle.static."""
    _mode.static = True


def disable_static():
    _mode.static = False


def in_dynamic_mode() -> bool:
    return not getattr(_mode, "static", False)


_grad = threading.local()


def is_grad_enabled() -> bool:
    """≙ paddle.is_grad_enabled. Informational under jax: gradients are
    computed by explicit transforms, not a tape; paddle.no_grad /
    set_grad_enabled flip this flag for script parity."""
    return getattr(_grad, "enabled", True)


def set_grad_enabled(enabled: bool):
    class _Ctx:
        def __enter__(self):
            self._prev = is_grad_enabled()
            _grad.enabled = bool(enabled)
            return self

        def __exit__(self, *exc):
            _grad.enabled = self._prev
            return False

    return _Ctx()


def check_shape(shape):
    """≙ fluid check_shape: validate a creation-op shape argument."""
    if isinstance(shape, (list, tuple)):
        for d in shape:
            if not isinstance(d, (int, np.integer)) and not hasattr(
                    d, "dtype"):
                raise TypeError(f"shape entries must be ints, got {d!r}")
    return shape


def disable_signal_handler():
    """≙ paddle.disable_signal_handler — the reference unhooks its C++
    fault handlers; this runtime installs none."""
    return None


def get_cuda_rng_state():
    """≙ paddle.get_cuda_rng_state — maps to the framework RNG state (one
    RNG plane here; 'cuda' is a name, the state is the jax key)."""
    from paddle_tpu import random as pt_random
    return pt_random.get_rng_state()


def set_cuda_rng_state(state):
    from paddle_tpu import random as pt_random
    return pt_random.set_rng_state(state)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """≙ paddle.create_parameter (top level): a fresh initialized array."""
    from paddle_tpu.dtypes import to_dtype
    from paddle_tpu.nn import initializer as I
    init = default_initializer or (
        attr.initializer if isinstance(attr, ParamAttr) and attr.initializer
        else (I.Constant(0.0) if is_bias else I.XavierUniform()))
    return init(tuple(shape), to_dtype(dtype))


def iinfo(dtype):
    """≙ paddle.iinfo."""
    from paddle_tpu.dtypes import to_dtype
    return jnp.iinfo(to_dtype(dtype))


def reverse(x, axis):
    """≙ paddle.reverse (legacy name for flip)."""
    from paddle_tpu.tensor.manipulation import flip
    return flip(x, axis)
