"""Core framework surface: Tensor type, jit, autodiff entry points, devices.

Reference analog: python/paddle/framework/ + the dygraph/static dichotomy.
Here there is one execution model — trace to XLA — so `jit` is jax.jit with
framework policy applied, and eager execution is jax op-by-op dispatch (which
is itself compiled per-op, ref contrast: per-op KernelFactory dispatch,
paddle/phi/core/kernel_factory.h:268).
"""

import contextlib
import functools

import jax
import jax.numpy as jnp

from paddle_tpu.tensor.creation import to_tensor
from paddle_tpu.tensor.logic import is_tensor

Tensor = jax.Array


def jit(fn=None, *, static_argnums=None, static_argnames=None, donate_argnums=None,
        **kwargs):
    """Compile a function to a single TPU executable (ref ambition:
    static-graph mode / @to_static, python/paddle/fluid/dygraph/jit.py).
    Thin policy wrapper over jax.jit."""
    if fn is None:
        return functools.partial(jit, static_argnums=static_argnums,
                                 static_argnames=static_argnames,
                                 donate_argnums=donate_argnums, **kwargs)
    return jax.jit(fn, static_argnums=static_argnums,
                   static_argnames=static_argnames,
                   donate_argnums=donate_argnums, **kwargs)


def stop_gradient(x):
    """ref: Tensor.stop_gradient attribute; functional here."""
    return jax.lax.stop_gradient(x)


@contextlib.contextmanager
def no_grad():
    """API-parity context (ref: paddle.no_grad). In a functional-AD framework
    gradients only flow where jax.grad is applied, so this is a no-op marker
    kept so reference code ports cleanly."""
    yield


def grad(fn, argnums=0, has_aux=False):
    """Functional gradient (ref: paddle.grad / eager Backward,
    paddle/fluid/eager/backward.cc:393 — replaced by tracing-based AD)."""
    return jax.grad(fn, argnums=argnums, has_aux=has_aux)


def value_and_grad(fn, argnums=0, has_aux=False):
    return jax.value_and_grad(fn, argnums=argnums, has_aux=has_aux)


def devices():
    return jax.devices()


def device_count():
    return jax.device_count()


_current_device = None


def set_device(device):
    """ref: paddle.set_device. Accepts 'tpu', 'cpu', 'tpu:0' etc."""
    global _current_device
    name = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    plat = {"gpu": "tpu", "xpu": "tpu", "npu": "tpu"}.get(name, name)
    _current_device = jax.devices(plat)[idx]
    return _current_device


def get_device():
    if _current_device is not None:
        d = _current_device
    else:
        d = jax.devices()[0]
    return f"{d.platform}:{getattr(d, 'id', 0)}"


def default_device():
    return _current_device or jax.devices()[0]
