"""Signal ops (ref: python/paddle/signal.py — frame, overlap_add, stft,
istft)."""

import jax
import jax.numpy as jnp

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1):
    x = jnp.asarray(x)
    assert axis in (-1, x.ndim - 1), "frame: axis must be last"
    n = x.shape[-1]
    num_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(num_frames)[:, None])
    out = x[..., idx]  # (..., num_frames, frame_length)
    return jnp.swapaxes(out, -1, -2)


def overlap_add(x, hop_length, axis=-1):
    x = jnp.asarray(x)
    # (..., frame_length, num_frames)
    frame_length = x.shape[-2]
    num_frames = x.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    out = jnp.zeros(x.shape[:-2] + (out_len,), x.dtype)
    for i in range(num_frames):
        out = out.at[..., i * hop_length:i * hop_length + frame_length].add(
            x[..., i])
    return out


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True):
    x = jnp.asarray(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones((win_length,))
    window = jnp.asarray(window)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        window = jnp.pad(window, (lpad, n_fft - win_length - lpad))
    if center:
        pads = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pads, mode=pad_mode)
    frames = frame(x, n_fft, hop_length)  # (..., n_fft, num_frames)
    frames = frames * window[:, None]
    spec = jnp.fft.fft(frames, axis=-2)
    if onesided:
        spec = spec[..., :n_fft // 2 + 1, :]
    if normalized:
        spec = spec / jnp.sqrt(n_fft)
    return spec


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False):
    x = jnp.asarray(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones((win_length,))
    window = jnp.asarray(window)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        window = jnp.pad(window, (lpad, n_fft - win_length - lpad))
    if normalized:
        x = x * jnp.sqrt(n_fft)
    if onesided:
        full = jnp.concatenate(
            [x, jnp.conj(jnp.flip(x[..., 1:-1, :], axis=-2))], axis=-2)
    else:
        full = x
    frames = jnp.fft.ifft(full, axis=-2).real  # (..., n_fft, num_frames)
    frames = frames * window[:, None]
    out = overlap_add(frames, hop_length)
    wsq = overlap_add(
        jnp.broadcast_to((window ** 2)[:, None],
                         (n_fft, x.shape[-1])), hop_length)
    out = out / jnp.maximum(wsq, 1e-11)
    if center:
        out = out[..., n_fft // 2:-(n_fft // 2)]
    if length is not None:
        out = out[..., :length]
    return out


# -- registry + oracles ------------------------------------------------------
# Hand-written numpy references (the reference checks stft against librosa,
# test_signal.py:stft_np; here numpy primitives play that role).

import numpy as np  # noqa: E402

from paddle_tpu.ops.registry import register_op  # noqa: E402


def _frame_np(x, frame_length, hop_length):
    n = x.shape[-1]
    num_frames = 1 + (n - frame_length) // hop_length
    idx = (np.arange(frame_length)[None, :]
           + hop_length * np.arange(num_frames)[:, None])
    return np.swapaxes(x[..., idx], -1, -2)


def _overlap_add_np(x, hop_length):
    frame_length, num_frames = x.shape[-2], x.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    out = np.zeros(x.shape[:-2] + (out_len,), x.dtype)
    for i in range(num_frames):
        out[..., i * hop_length:i * hop_length + frame_length] += x[..., i]
    return out


def _stft_np(x, n_fft, hop_length):
    x = np.pad(x, [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)],
               mode="reflect")
    frames = _frame_np(x, n_fft, hop_length)
    spec = np.fft.fft(frames, axis=-2)
    return spec[..., :n_fft // 2 + 1, :]


def _istft_np(x, n_fft, hop_length):
    full = np.concatenate(
        [x, np.conj(np.flip(x[..., 1:-1, :], axis=-2))], axis=-2)
    frames = np.fft.ifft(full, axis=-2).real
    out = _overlap_add_np(frames, hop_length)
    wsq = _overlap_add_np(
        np.broadcast_to(np.ones((n_fft, 1)), (n_fft, x.shape[-1])).copy(),
        hop_length)
    out = out / np.maximum(wsq, 1e-11)
    return out[..., n_fft // 2:-(n_fft // 2)]


_R = np.random.RandomState(20260731)
_sig = _R.randn(2, 64).astype(np.float32)
_spec = (_R.randn(2, 9, 13) + 1j * _R.randn(2, 9, 13)).astype(np.complex64)
_frames = _R.randn(2, 16, 13).astype(np.float32)

register_op("frame", frame, "fft",
            np_ref=lambda x: _frame_np(x, 16, 4),
            sample_args=lambda: ((_sig,), {"frame_length": 16,
                                           "hop_length": 4}),
            ref="python/paddle/signal.py:frame", differentiable=True)
register_op("overlap_add", overlap_add, "fft",
            np_ref=lambda x: _overlap_add_np(x, 4),
            sample_args=lambda: ((_frames,), {"hop_length": 4}),
            ref="python/paddle/signal.py:overlap_add", differentiable=True)
register_op("stft", stft, "fft",
            np_ref=lambda x: _stft_np(x, 16, 4),
            sample_args=lambda: ((_sig,), {"n_fft": 16, "hop_length": 4}),
            ref="python/paddle/signal.py:stft", differentiable=False)
register_op("istft", istft, "fft",
            np_ref=lambda x: _istft_np(x, 16, 4),
            sample_args=lambda: ((_spec,), {"n_fft": 16, "hop_length": 4}),
            ref="python/paddle/signal.py:istft", differentiable=False)
