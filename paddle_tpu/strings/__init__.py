"""String tensors, TPU-form (ref: paddle/phi/kernels/strings/ — StringTensor
with empty/copy/lower/upper kernels, unicode case tables in
phi/kernels/strings/unicode.h).

The reference's ``StringTensor`` is a host-resident array of byte strings;
its only device kernels are case conversion over UTF-8 code points via
precomputed tables.  The TPU-native form makes the SAME data a dense pair

    codepoints : (B, T) int32, one Unicode code point per slot
    lengths    : (B,)  int32

so case conversion, comparison, and length are ordinary jit-safe array ops
(a table gather IS how the reference kernel works — unicode.h:ToLower/
ToUpper flag arrays), and the padded-ids layout feeds tokenizer output
straight into embedding lookups with no host round-trip.

One-to-one case mappings only, like the reference kernel: code points whose
case form expands (e.g. ß→SS) map to themselves; points beyond the table
(astral planes) pass through unchanged.
"""

import numpy as np

import jax.numpy as jnp

__all__ = ["StringTensor", "to_string_tensor", "to_strings", "lower",
           "upper", "length", "equal", "empty", "empty_like"]

# case tables over the full Basic Multilingual Plane, built once from
# Python's own Unicode database — the analog of the reference's generated
# unicode.h arrays (256 KB per table; every 1:1 BMP case pair covered)
_TABLE_SIZE = 0x10000


def _case_tables():
    lo = np.arange(_TABLE_SIZE, dtype=np.int32)
    up = np.arange(_TABLE_SIZE, dtype=np.int32)
    for cp in range(_TABLE_SIZE):
        c = chr(cp)
        l, u = c.lower(), c.upper()
        if len(l) == 1:
            lo[cp] = ord(l)
        if len(u) == 1:
            up[cp] = ord(u)
    return lo, up


_LOWER_NP, _UPPER_NP = _case_tables()


class StringTensor:
    """Dense (codepoints, lengths) pair; rows are Unicode strings."""

    def __init__(self, codepoints, lengths):
        self.codepoints = jnp.asarray(codepoints, jnp.int32)
        self.lengths = jnp.asarray(lengths, jnp.int32)

    @property
    def shape(self):
        return (self.codepoints.shape[0],)

    def to_strings(self):
        cp = np.asarray(self.codepoints)
        ln = np.asarray(self.lengths)
        return ["".join(chr(int(c)) for c in cp[b, :ln[b]])
                for b in range(cp.shape[0])]

    def __repr__(self):
        return f"StringTensor({self.to_strings()!r})"


def to_string_tensor(strings, maxlen=None):
    """Host-boundary converter: list[str] → StringTensor (≙ the pybind
    py::list → StringTensor path, phi/kernels/strings/strings_copy_kernel)."""
    rows = [[ord(ch) for ch in s] for s in strings]
    T = int(maxlen) if maxlen is not None else max(
        (len(r) for r in rows), default=0)
    out = np.zeros((len(rows), T), np.int32)
    lens = np.zeros((len(rows),), np.int32)
    for b, r in enumerate(rows):
        out[b, :min(len(r), T)] = r[:T]
        lens[b] = min(len(r), T)
    return StringTensor(out, lens)


def to_strings(st):
    return st.to_strings()


def _map_case(st, table_np):
    table = jnp.asarray(table_np)
    cp = st.codepoints
    mapped = jnp.where(cp < _TABLE_SIZE,
                       table[jnp.clip(cp, 0, _TABLE_SIZE - 1)], cp)
    valid = jnp.arange(cp.shape[1])[None, :] < st.lengths[:, None]
    return StringTensor(jnp.where(valid, mapped, cp), st.lengths)


def lower(st):
    """ref: strings_lower_upper_kernel.h StringLowerKernel — per-codepoint
    table map, jit-safe."""
    return _map_case(st, _LOWER_NP)


def upper(st):
    """ref: strings_lower_upper_kernel.h StringUpperKernel."""
    return _map_case(st, _UPPER_NP)


def length(st):
    """Per-row character counts (code points, not bytes)."""
    return st.lengths


def equal(a, b):
    """Row-wise string equality → (B,) bool, jit-safe."""
    if a.codepoints.shape[1] != b.codepoints.shape[1]:
        T = max(a.codepoints.shape[1], b.codepoints.shape[1])
        pad = lambda s: jnp.pad(  # noqa: E731
            s.codepoints, ((0, 0), (0, T - s.codepoints.shape[1])))
        acp, bcp = pad(a), pad(b)
    else:
        acp, bcp = a.codepoints, b.codepoints
    t = jnp.arange(acp.shape[1])[None, :]
    av = jnp.where(t < a.lengths[:, None], acp, -1)
    bv = jnp.where(t < b.lengths[:, None], bcp, -1)
    return jnp.all(av == bv, axis=1) & (a.lengths == b.lengths)


def empty(shape, maxlen=0):
    """ref: strings_empty_kernel.cc — uninitialized (here: zero) strings."""
    n = int(np.prod(shape)) if not isinstance(shape, int) else shape
    return StringTensor(np.zeros((n, maxlen), np.int32),
                        np.zeros((n,), np.int32))


def empty_like(st):
    return empty(st.shape[0], int(st.codepoints.shape[1]))
