"""Probability distributions (ref: python/paddle/distribution/ — Normal,
Uniform, Beta, Categorical, Dirichlet, …, kl_divergence). Built on
jax.random + jax.scipy.stats."""

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from paddle_tpu import random as pt_random

__all__ = ["Distribution", "ExponentialFamily", "register_kl",
           "Normal", "Uniform", "Categorical", "Beta",
           "Dirichlet", "Exponential", "Gamma", "Laplace", "Bernoulli",
           "Gumbel", "LogNormal", "Multinomial", "kl_divergence",
           "Independent", "TransformedDistribution", "Transform",
           "AffineTransform", "ExpTransform", "SigmoidTransform",
           "TanhTransform", "SoftmaxTransform", "PowerTransform",
           "AbsTransform", "ChainTransform", "StackTransform",
           "StickBreakingTransform", "ReshapeTransform",
           "IndependentTransform", "transform"]


def _key(key):
    return key if key is not None else pt_random.next_key()


class Distribution:
    def sample(self, shape=(), key=None):
        raise NotImplementedError

    def rsample(self, shape=(), key=None):
        return self.sample(shape, key)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return self.loc + self.scale * jax.random.normal(_key(key), shape)

    def log_prob(self, value):
        v = jnp.asarray(value)
        var = self.scale ** 2
        return -((v - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) \
            - 0.5 * math.log(2 * math.pi)

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)

    def kl_divergence(self, other):
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale ** 2


class LogNormal(Normal):
    def sample(self, shape=(), key=None):
        return jnp.exp(super().sample(shape, key))

    def log_prob(self, value):
        v = jnp.asarray(value)
        return super().log_prob(jnp.log(v)) - jnp.log(v)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = jnp.asarray(low, jnp.float32)
        self.high = jnp.asarray(high, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        return jax.random.uniform(_key(key), shape) * (
            self.high - self.low) + self.low

    def log_prob(self, value):
        v = jnp.asarray(value)
        inside = (v >= self.low) & (v < self.high)
        return jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)

    def entropy(self):
        return jnp.log(self.high - self.low)

    @property
    def mean(self):
        return (self.low + self.high) / 2

    @property
    def variance(self):
        return (self.high - self.low) ** 2 / 12


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if probs is not None:
            self.probs = jnp.asarray(probs, jnp.float32)
        else:
            self.probs = jax.nn.sigmoid(jnp.asarray(logits, jnp.float32))

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + self.probs.shape
        return jax.random.bernoulli(_key(key), self.probs,
                                    shape).astype(jnp.float32)

    def log_prob(self, value):
        v = jnp.asarray(value)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self.probs * (1 - self.probs)


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None:
            self.logits = jnp.asarray(logits, jnp.float32)
        else:
            self.logits = jnp.log(jnp.asarray(probs, jnp.float32) + 1e-12)

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def sample(self, shape=(), key=None):
        return jax.random.categorical(_key(key), self.logits,
                                      shape=tuple(shape)
                                      + self.logits.shape[:-1])

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        idx = jnp.asarray(value, jnp.int32)
        return jnp.take_along_axis(logp, idx[..., None], axis=-1)[..., 0]

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = jnp.asarray(alpha, jnp.float32)
        self.beta = jnp.asarray(beta, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.alpha.shape,
                                                    self.beta.shape)
        return jax.random.beta(_key(key), self.alpha, self.beta, shape)

    def log_prob(self, value):
        v = jnp.asarray(value)
        return ((self.alpha - 1) * jnp.log(v)
                + (self.beta - 1) * jnp.log1p(-v)
                - (jsp.gammaln(self.alpha) + jsp.gammaln(self.beta)
                   - jsp.gammaln(self.alpha + self.beta)))

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s ** 2 * (s + 1))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = jnp.asarray(concentration, jnp.float32)

    def sample(self, shape=(), key=None):
        return jax.random.dirichlet(_key(key), self.concentration,
                                    tuple(shape))

    def log_prob(self, value):
        v = jnp.asarray(value)
        a = self.concentration
        return (jnp.sum((a - 1) * jnp.log(v), axis=-1)
                + jsp.gammaln(jnp.sum(a, -1))
                - jnp.sum(jsp.gammaln(a), axis=-1))

    @property
    def mean(self):
        return self.concentration / jnp.sum(self.concentration, -1,
                                            keepdims=True)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = jnp.asarray(rate, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + self.rate.shape
        return jax.random.exponential(_key(key), shape) / self.rate

    def log_prob(self, value):
        v = jnp.asarray(value)
        return jnp.log(self.rate) - self.rate * v

    @property
    def mean(self):
        return 1.0 / self.rate

    @property
    def variance(self):
        return 1.0 / self.rate ** 2


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = jnp.asarray(concentration, jnp.float32)
        self.rate = jnp.asarray(rate, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.concentration.shape, self.rate.shape)
        return jax.random.gamma(_key(key), self.concentration,
                                shape) / self.rate

    def log_prob(self, value):
        v = jnp.asarray(value)
        a, b = self.concentration, self.rate
        return a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v - jsp.gammaln(a)

    @property
    def mean(self):
        return self.concentration / self.rate


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return self.loc + self.scale * jax.random.laplace(_key(key), shape)

    def log_prob(self, value):
        v = jnp.asarray(value)
        return -jnp.abs(v - self.loc) / self.scale - jnp.log(2 * self.scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2 * self.scale ** 2


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return self.loc + self.scale * jax.random.gumbel(_key(key), shape)

    def log_prob(self, value):
        z = (jnp.asarray(value) - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)

    @property
    def mean(self):
        return self.loc + self.scale * 0.5772156649015329


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = total_count
        self.probs = jnp.asarray(probs, jnp.float32)

    def sample(self, shape=(), key=None):
        logits = jnp.log(self.probs + 1e-12)
        draws = jax.random.categorical(
            _key(key), logits,
            shape=tuple(shape) + (self.total_count,)
            + self.probs.shape[:-1])
        n = self.probs.shape[-1]
        onehot = jax.nn.one_hot(draws, n)
        return jnp.sum(onehot, axis=len(shape))

    def log_prob(self, value):
        v = jnp.asarray(value)
        return (jsp.gammaln(jnp.asarray(self.total_count + 1.0))
                - jnp.sum(jsp.gammaln(v + 1.0), -1)
                + jnp.sum(v * jnp.log(self.probs + 1e-12), -1))


_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """ref: paddle.distribution.register_kl — decorator registering a KL
    rule for a (type(p), type(q)) pair; kl_divergence dispatches through
    the registry before its built-ins (most-derived match wins)."""
    def decorator(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return decorator


class ExponentialFamily(object):
    """ref: paddle.distribution.ExponentialFamily — base class for
    natural-parameter families; subclasses expose _natural_parameters
    and _log_normalizer, from which entropy follows by Bregman identity
    (entropy = logZ - <natural, E[T]> with E[T] = dlogZ/dnat, via jax
    autodiff instead of the reference's hand-derived per-family code)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0  # families with a nonzero carrier override

    def entropy(self):
        nat = self._natural_parameters
        logz, grads = jax.value_and_grad(
            self._log_normalizer, argnums=tuple(range(len(nat))))(*nat)
        ent = -self._mean_carrier_measure + logz
        for n, g in zip(nat, grads):
            ent = ent - n * g
        return ent


def kl_divergence(p, q):
    """ref: paddle.distribution.kl_divergence (kl.py registry)."""
    best = None
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            if best is None or (issubclass(cp, best[0][0])
                                and issubclass(cq, best[0][1])):
                best = ((cp, cq), fn)
    if best is not None:
        return best[1](p, q)
    if isinstance(p, Normal) and isinstance(q, Normal):
        return p.kl_divergence(q)
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        lp = jax.nn.log_softmax(p.logits, -1)
        lq = jax.nn.log_softmax(q.logits, -1)
        return jnp.sum(jnp.exp(lp) * (lp - lq), -1)
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        pp = jnp.clip(p.probs, 1e-7, 1 - 1e-7)
        qq = jnp.clip(q.probs, 1e-7, 1 - 1e-7)
        return pp * jnp.log(pp / qq) + (1 - pp) * jnp.log((1 - pp) / (1 - qq))
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        return jnp.log((q.high - q.low) / (p.high - p.low))
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")


from paddle_tpu.distribution import transform  # noqa: E402
from paddle_tpu.distribution.transform import (  # noqa: E402
    Transform, AffineTransform, ExpTransform, SigmoidTransform,
    TanhTransform, SoftmaxTransform, PowerTransform, AbsTransform,
    ChainTransform, StackTransform, StickBreakingTransform,
    ReshapeTransform, IndependentTransform, TransformedDistribution,
    Independent)
