"""Bijective transforms + TransformedDistribution + Independent
(ref: python/paddle/distribution/transform.py — Transform, AffineTransform,
ExpTransform, SigmoidTransform, TanhTransform, SoftmaxTransform,
PowerTransform, AbsTransform, ChainTransform, StackTransform,
StickBreakingTransform, ReshapeTransform; transformed_distribution.py;
independent.py).

Each Transform supplies forward / inverse / log|det J| as pure jnp — the
change-of-variables machinery is then three lines, and everything traces
under jit."""

import jax
import jax.numpy as jnp

__all__ = ["Transform", "AffineTransform", "ExpTransform",
           "SigmoidTransform", "TanhTransform", "SoftmaxTransform",
           "PowerTransform", "AbsTransform", "ChainTransform",
           "StackTransform", "StickBreakingTransform", "ReshapeTransform",
           "IndependentTransform", "TransformedDistribution", "Independent"]


class Transform:
    """Base bijector (≙ transform.py Transform: forward/inverse/
    forward_log_det_jacobian)."""

    _event_dims = 0  # dims consumed by one application

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def forward(self, x):
        return self.loc + self.scale * jnp.asarray(x)

    def inverse(self, y):
        return (jnp.asarray(y) - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)),
                                jnp.asarray(x).shape)


class ExpTransform(Transform):
    def forward(self, x):
        return jnp.exp(jnp.asarray(x))

    def inverse(self, y):
        return jnp.log(jnp.asarray(y))

    def forward_log_det_jacobian(self, x):
        return jnp.asarray(x)


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = jnp.asarray(power, jnp.float32)

    def forward(self, x):
        return jnp.power(jnp.asarray(x), self.power)

    def inverse(self, y):
        return jnp.power(jnp.asarray(y), 1.0 / self.power)

    def forward_log_det_jacobian(self, x):
        x = jnp.asarray(x)
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def forward(self, x):
        return jax.nn.sigmoid(jnp.asarray(x))

    def inverse(self, y):
        y = jnp.asarray(y)
        return jnp.log(y) - jnp.log1p(-y)

    def forward_log_det_jacobian(self, x):
        x = jnp.asarray(x)
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def forward(self, x):
        return jnp.tanh(jnp.asarray(x))

    def inverse(self, y):
        return jnp.arctanh(jnp.asarray(y))

    def forward_log_det_jacobian(self, x):
        x = jnp.asarray(x)
        # log(1 - tanh^2 x) = 2(log2 - x - softplus(-2x)), stable form
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class AbsTransform(Transform):
    """Non-bijective |x| (≙ AbsTransform: inverse returns the positive
    branch)."""

    def forward(self, x):
        return jnp.abs(jnp.asarray(x))

    def inverse(self, y):
        return jnp.asarray(y)

    def forward_log_det_jacobian(self, x):
        return jnp.zeros_like(jnp.asarray(x))


class SoftmaxTransform(Transform):
    """y = softmax(x); not volume-preserving — log-det undefined, used
    for reparameterized simplex values (≙ SoftmaxTransform)."""

    _event_dims = 1

    def forward(self, x):
        return jax.nn.softmax(jnp.asarray(x), -1)

    def inverse(self, y):
        y = jnp.asarray(y)
        return jnp.log(y) - jnp.log(y[..., -1:])

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError("SoftmaxTransform is not bijective")


class StickBreakingTransform(Transform):
    """R^{n} → open simplex Δ^{n} via stick breaking (≙
    StickBreakingTransform)."""

    _event_dims = 1

    def forward(self, x):
        x = jnp.asarray(x)
        n = x.shape[-1]
        offset = jnp.log(jnp.arange(n, 0, -1, dtype=jnp.float32))
        z = jax.nn.sigmoid(x - offset)
        zpad = jnp.concatenate([z, jnp.ones(z.shape[:-1] + (1,))], -1)
        one_minus = jnp.concatenate(
            [jnp.ones(z.shape[:-1] + (1,)), jnp.cumprod(1 - z, -1)], -1)
        return zpad * one_minus

    def inverse(self, y):
        y = jnp.asarray(y)
        n = y.shape[-1] - 1
        cum = jnp.concatenate(
            [jnp.zeros(y.shape[:-1] + (1,)), jnp.cumsum(y[..., :-1], -1)],
            -1)[..., :-1]
        z = y[..., :-1] / (1 - cum)
        offset = jnp.log(jnp.arange(n, 0, -1, dtype=jnp.float32))
        return jnp.log(z) - jnp.log1p(-z) + offset

    def forward_log_det_jacobian(self, x):
        x = jnp.asarray(x)
        n = x.shape[-1]
        offset = jnp.log(jnp.arange(n, 0, -1, dtype=jnp.float32))
        t = x - offset
        z = jax.nn.sigmoid(t)
        one_minus = jnp.concatenate(
            [jnp.ones(z.shape[:-1] + (1,)), jnp.cumprod(1 - z, -1)[..., :-1]],
            -1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(one_minus), -1)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def forward(self, x):
        x = jnp.asarray(x)
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(lead + self.out_event_shape)

    def inverse(self, y):
        y = jnp.asarray(y)
        lead = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(lead + self.in_event_shape)

    def forward_log_det_jacobian(self, x):
        x = jnp.asarray(x)
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(lead)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t.forward_log_det_jacobian(x)
            x = t.forward(x)
        return total


class StackTransform(Transform):
    """Apply transforms[i] along slice i of ``axis`` (≙ StackTransform)."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _map(self, method, x):
        x = jnp.asarray(x)
        parts = [getattr(t, method)(jnp.take(x, i, self.axis))
                 for i, t in enumerate(self.transforms)]
        return jnp.stack(parts, self.axis)

    def forward(self, x):
        return self._map("forward", x)

    def inverse(self, y):
        return self._map("inverse", y)

    def forward_log_det_jacobian(self, x):
        return self._map("forward_log_det_jacobian", x)


class IndependentTransform(Transform):
    def __init__(self, base, reinterpreted_batch_ndims):
        self.base = base
        self.n = reinterpreted_batch_ndims

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        ld = self.base.forward_log_det_jacobian(x)
        return jnp.sum(ld, axis=tuple(range(-self.n, 0)))


class TransformedDistribution:
    """base distribution pushed through a transform chain
    (≙ transformed_distribution.py): sample = T(base.sample);
    log_prob(y) = base.log_prob(T^-1(y)) - log|det J(T^-1(y))|."""

    def __init__(self, base, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transform = ChainTransform(list(transforms)) \
            if len(transforms) != 1 else transforms[0]

    def sample(self, shape=(), key=None):
        return self.transform.forward(self.base.sample(shape, key=key))

    def rsample(self, shape=(), key=None):
        return self.transform.forward(self.base.rsample(shape, key=key))

    def log_prob(self, value):
        x = self.transform.inverse(value)
        ld = self.transform.forward_log_det_jacobian(x)
        base_lp = self.base.log_prob(x)
        # per-element transforms broadcast against an event-shaped base lp
        if hasattr(ld, "shape") and base_lp.shape != getattr(
                ld, "shape", ()):
            extra = ld.ndim - base_lp.ndim
            if extra > 0:
                ld = jnp.sum(ld, axis=tuple(range(-extra, 0)))
        return base_lp - ld

    def prob(self, value):
        return jnp.exp(self.log_prob(value))


class Independent:
    """Reinterpret batch dims as event dims (≙ independent.py):
    log_prob sums over the reinterpreted trailing dims."""

    def __init__(self, base, reinterpreted_batch_ndims):
        self.base = base
        self.n = int(reinterpreted_batch_ndims)

    def sample(self, shape=(), key=None):
        return self.base.sample(shape, key=key)

    def rsample(self, shape=(), key=None):
        return self.base.rsample(shape, key=key)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        return jnp.sum(lp, axis=tuple(range(-self.n, 0)))

    def prob(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        ent = self.base.entropy()
        return jnp.sum(ent, axis=tuple(range(-self.n, 0)))

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance
