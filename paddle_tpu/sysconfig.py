"""ref: python/paddle/sysconfig.py — get_include/get_lib for building
extensions against the framework. Here: the package dir (headers are the
jax/XLA ones; the native runtime ships prebuilt in paddle_tpu/native)."""

import os

__all__ = ["get_include", "get_lib"]


def get_include():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "include")


def get_lib():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "native")
