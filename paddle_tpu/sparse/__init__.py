"""Sparse tensors (ref: paddle/phi/core/sparse_coo_tensor.h +
python/paddle/sparse/). XLA:TPU has no native sparse kernels; SparseCooTensor
is a (indices, values, shape) triple with dense bridging — the pattern that
matters for TPU (embedding-style scatter/gather) is expressed densely via
segment_sum, which tiles well on the MXU/VPU."""

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["SparseCooTensor", "sparse_coo_tensor", "sparse_csr_tensor",
           "to_dense", "to_sparse_coo", "add", "matmul", "masked_matmul"]


class SparseCooTensor:
    def __init__(self, indices, values, shape):
        self.indices = jnp.asarray(indices)  # (ndim, nnz)
        self.values = jnp.asarray(values)    # (nnz, ...)
        self.shape = tuple(shape)

    def to_dense(self):
        out = jnp.zeros(self.shape + self.values.shape[1:],
                        self.values.dtype)
        return out.at[tuple(self.indices)].add(self.values)

    def nnz(self):
        return self.values.shape[0]

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.values.dtype})")


def sparse_coo_tensor(indices, values, shape=None):
    indices = jnp.asarray(indices)
    values = jnp.asarray(values)
    if shape is None:
        shape = tuple(int(i) for i in np.asarray(indices).max(axis=1) + 1)
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape):
    crows = np.asarray(crows)
    cols = np.asarray(cols)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    return SparseCooTensor(np.stack([rows, cols]), values, shape)


def to_dense(x):
    return x.to_dense() if isinstance(x, SparseCooTensor) else jnp.asarray(x)


def to_sparse_coo(x, sparse_dim=None):
    arr = np.asarray(jax.device_get(x))
    idx = np.stack(np.nonzero(arr))
    vals = arr[tuple(idx)]
    return SparseCooTensor(idx, vals, arr.shape)


def add(a, b):
    return sparse_coo_tensor(
        jnp.concatenate([a.indices, b.indices], axis=1),
        jnp.concatenate([a.values, b.values]), a.shape)


def matmul(a, b):
    """SpMM as gather + segment-sum (dense-friendly on TPU)."""
    b = jnp.asarray(b)
    rows, cols = a.indices[0], a.indices[1]
    contrib = a.values[:, None] * b[cols]
    return jax.ops.segment_sum(contrib, rows, num_segments=a.shape[0])


def masked_matmul(x, y, mask: "SparseCooTensor"):
    """Compute (x@y) only at mask positions."""
    rows, cols = mask.indices[0], mask.indices[1]
    vals = jnp.sum(jnp.asarray(x)[rows] * jnp.asarray(y).T[cols], axis=-1)
    return SparseCooTensor(mask.indices, vals,
                           (x.shape[0], jnp.asarray(y).shape[1]))
