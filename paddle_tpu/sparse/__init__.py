"""Sparse tensors (ref: python/paddle/sparse/__init__.py surface;
paddle/phi/core/sparse_coo_tensor.h, sparse_csr_tensor.h; kernels
paddle/phi/kernels/sparse/). XLA:TPU has no native sparse kernels, so the
TPU-native design keeps values/indices as dense arrays and expresses
every op as gather / scatter-add / segment_sum — the forms that tile onto
the VPU/MXU — with host-side numpy only where the reference also runs on
host (rulebook/coalesce index plumbing).

Surface parity: creation (coo/csr), the full unary value-op list
(sin..expm1, cast, neg, pow, abs), coalesce/transpose/reshape, binary
(add/subtract/multiply/divide/mv/matmul/masked_matmul/is_same_shape),
multiary (addmm), and ``sparse.nn`` (activations, BatchNorm, Conv3D,
SubmConv3D, MaxPool3D) in sparse/nn.py.
"""

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor", "to_dense", "to_sparse_coo",
           "is_same_shape", "coalesce", "transpose", "reshape", "cast",
           "add", "subtract", "multiply", "divide", "matmul",
           "masked_matmul", "mv", "addmm", "nn",
           # unary value ops
           "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh",
           "sqrt", "square", "log1p", "abs", "pow", "neg", "expm1",
           "deg2rad", "rad2deg"]


class SparseCooTensor:
    def __init__(self, indices, values, shape):
        self.indices = jnp.asarray(indices)  # (ndim, nnz)
        self.values = jnp.asarray(values)    # (nnz, ...)
        self.shape = tuple(shape)

    def to_dense(self):
        out = jnp.zeros(self.shape + self.values.shape[1:],
                        self.values.dtype)
        return out.at[tuple(self.indices)].add(self.values)

    def nnz(self):
        return self.values.shape[0]

    @property
    def dtype(self):
        return self.values.dtype

    def with_values(self, values):
        return SparseCooTensor(self.indices, values, self.shape)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.values.dtype})")


class SparseCsrTensor:
    """CSR (≙ sparse_csr_tensor.h): 2-D, row-pointer layout."""

    def __init__(self, crows, cols, values, shape):
        crows = jnp.asarray(crows)
        cols = jnp.asarray(cols)
        # default to int32 for float/py-list inputs; preserve an explicit
        # integer dtype (cast(index_dtype=...) round-trips through here)
        self.crows = crows if jnp.issubdtype(crows.dtype, jnp.integer) \
            else crows.astype(jnp.int32)
        self.cols = cols if jnp.issubdtype(cols.dtype, jnp.integer) \
            else cols.astype(jnp.int32)
        self.values = jnp.asarray(values)
        self.shape = tuple(shape)

    def nnz(self):
        return self.values.shape[0]

    @property
    def dtype(self):
        return self.values.dtype

    def to_coo(self):
        crows = np.asarray(self.crows)
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        return SparseCooTensor(np.stack([rows, np.asarray(self.cols)]),
                               self.values, self.shape)

    def to_dense(self):
        return self.to_coo().to_dense()

    def with_values(self, values):
        return SparseCsrTensor(self.crows, self.cols, values, self.shape)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.values.dtype})")


def sparse_coo_tensor(indices, values, shape=None):
    indices = jnp.asarray(indices)
    values = jnp.asarray(values)
    if shape is None:
        shape = tuple(int(i) for i in np.asarray(indices).max(axis=1) + 1)
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape):
    return SparseCsrTensor(crows, cols, values, shape)


def to_dense(x):
    return x.to_dense() if hasattr(x, "to_dense") else jnp.asarray(x)


def to_sparse_coo(x, sparse_dim=None):
    arr = np.asarray(jax.device_get(x))
    idx = np.stack(np.nonzero(arr))
    vals = arr[tuple(idx)]
    return SparseCooTensor(idx, vals, arr.shape)


def is_same_shape(a, b):
    return tuple(a.shape) == tuple(b.shape)


def coalesce(x: "SparseCooTensor"):
    """Sort indices lexicographically and sum duplicates (≙ phi
    sparse coalesce kernel — index plumbing on host, value segment_sum on
    device)."""
    idx = np.asarray(jax.device_get(x.indices))
    flat = np.ravel_multi_index(idx, x.shape[:idx.shape[0]])
    uniq, inv = np.unique(flat, return_inverse=True)
    vals = jax.ops.segment_sum(x.values, jnp.asarray(inv),
                               num_segments=len(uniq))
    new_idx = np.stack(np.unravel_index(uniq, x.shape[:idx.shape[0]]))
    return SparseCooTensor(new_idx, vals, x.shape)


def transpose(x: "SparseCooTensor", perm):
    idx = x.indices[jnp.asarray(perm)]
    shape = tuple(x.shape[p] for p in perm)
    return SparseCooTensor(idx, x.values, shape)


def reshape(x: "SparseCooTensor", new_shape):
    idx = np.asarray(jax.device_get(x.indices))
    flat = np.ravel_multi_index(idx, x.shape)
    new_idx = np.stack(np.unravel_index(flat, tuple(new_shape)))
    return SparseCooTensor(new_idx, x.values, tuple(new_shape))


def cast(x, index_dtype=None, value_dtype=None):
    vals = x.values if value_dtype is None else x.values.astype(value_dtype)
    if isinstance(x, SparseCsrTensor):
        crows = x.crows if index_dtype is None \
            else x.crows.astype(index_dtype)
        cols = x.cols if index_dtype is None else x.cols.astype(index_dtype)
        return SparseCsrTensor(crows, cols, vals, x.shape)
    idx = x.indices if index_dtype is None else x.indices.astype(index_dtype)
    return SparseCooTensor(idx, vals, x.shape)


# -- unary (value-wise; sparsity-preserving functions only) ------------------

def _unary(fn):
    def op(x, *args):
        return x.with_values(fn(x.values, *args))
    return op


sin = _unary(jnp.sin)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
abs = _unary(jnp.abs)  # noqa: A001 (reference name)
pow = _unary(jnp.power)  # noqa: A001
neg = _unary(jnp.negative)
expm1 = _unary(jnp.expm1)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)


# -- binary ------------------------------------------------------------------

def add(a, b):
    """COO + COO: concatenate and coalesce (union of patterns)."""
    merged = SparseCooTensor(
        jnp.concatenate([a.indices, b.indices], axis=1),
        jnp.concatenate([a.values, b.values]), a.shape)
    return coalesce(merged)


def subtract(a, b):
    return add(a, b.with_values(-b.values))


def _aligned(a, b):
    """Coalesce both onto the union pattern → aligned value vectors."""
    ca = coalesce(a)
    cb = coalesce(b)
    ia = np.asarray(jax.device_get(ca.indices))
    ib = np.asarray(jax.device_get(cb.indices))
    fa = np.ravel_multi_index(ia, a.shape)
    fb = np.ravel_multi_index(ib, b.shape)
    uniq = np.union1d(fa, fb)
    pos_a = np.searchsorted(uniq, fa)
    pos_b = np.searchsorted(uniq, fb)
    va = jnp.zeros((len(uniq),) + ca.values.shape[1:], ca.values.dtype
                   ).at[jnp.asarray(pos_a)].set(ca.values)
    vb = jnp.zeros((len(uniq),) + cb.values.shape[1:], cb.values.dtype
                   ).at[jnp.asarray(pos_b)].set(cb.values)
    idx = np.stack(np.unravel_index(uniq, a.shape))
    return idx, va, vb


def multiply(a, b):
    idx, va, vb = _aligned(a, b)
    return SparseCooTensor(idx, va * vb, a.shape)


def divide(a, b):
    idx, va, vb = _aligned(a, b)
    return SparseCooTensor(idx, va / vb, a.shape)


def matmul(a, b):
    """SpMM as gather + segment-sum (dense-friendly on TPU)."""
    if isinstance(a, SparseCsrTensor):
        a = a.to_coo()
    b = jnp.asarray(b)
    rows, cols = a.indices[0], a.indices[1]
    contrib = a.values[:, None] * b[cols]
    return jax.ops.segment_sum(contrib, rows, num_segments=a.shape[0])


def mv(a, x):
    """Sparse matrix × dense vector (≙ sparse.mv)."""
    if isinstance(a, SparseCsrTensor):
        a = a.to_coo()
    x = jnp.asarray(x)
    rows, cols = a.indices[0], a.indices[1]
    return jax.ops.segment_sum(a.values * x[cols], rows,
                               num_segments=a.shape[0])


def masked_matmul(x, y, mask):
    """Compute (x@y) only at mask positions (≙ sparse.masked_matmul —
    the SDDMM kernel)."""
    if isinstance(mask, SparseCsrTensor):
        coo = mask.to_coo()
        rows, cols = coo.indices[0], coo.indices[1]
        vals = jnp.sum(jnp.asarray(x)[rows] * jnp.asarray(y).T[cols],
                       axis=-1)
        return mask.with_values(vals)
    rows, cols = mask.indices[0], mask.indices[1]
    vals = jnp.sum(jnp.asarray(x)[rows] * jnp.asarray(y).T[cols], axis=-1)
    return SparseCooTensor(mask.indices, vals,
                           (x.shape[0], jnp.asarray(y).shape[1]))


def addmm(input, x, y, beta=1.0, alpha=1.0):  # noqa: A002
    """beta*input + alpha*(x@y) with sparse x (≙ sparse.addmm)."""
    return beta * jnp.asarray(to_dense(input)) + alpha * matmul(x, y)


from paddle_tpu.sparse import nn  # noqa: E402  (public submodule)
