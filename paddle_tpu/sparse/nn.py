"""sparse.nn (ref: python/paddle/sparse/nn/__init__.py — ReLU/ReLU6/
LeakyReLU/Softmax/BatchNorm/SyncBatchNorm/Conv3D/SubmConv3D/MaxPool3D;
functional conv.py, transformer.py attention; CUDA kernels
paddle/phi/kernels/sparse/gpu/conv_kernel.cu + the host rulebook in
cpu/conv_kernel.cc).

TPU-native design: the rulebook (which active input site contributes to
which output site under each kernel offset) is built host-side with numpy
— exactly where the reference builds it — and the value math is all
gather → matmul → scatter-add on device, shapes static per rulebook, so
the MXU sees one (nnz_o, Cin)×(Cin, Cout) matmul per kernel offset.
"""

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.nn.module import Module, Parameter

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SyncBatchNorm", "Conv3D", "SubmConv3D", "MaxPool3D",
           "functional"]


def _coo(x):
    from paddle_tpu import sparse as S
    return x.to_coo() if isinstance(x, S.SparseCsrTensor) else x


# ---------------------------------------------------------------------------
# functional
# ---------------------------------------------------------------------------

class functional:
    """sparse.nn.functional (≙ reference module of the same path)."""

    @staticmethod
    def relu(x):
        return x.with_values(jnp.maximum(x.values, 0.0))

    @staticmethod
    def relu6(x):
        return x.with_values(jnp.clip(x.values, 0.0, 6.0))

    @staticmethod
    def leaky_relu(x, negative_slope=0.01):
        v = x.values
        return x.with_values(jnp.where(v >= 0, v, negative_slope * v))

    @staticmethod
    def softmax(x, axis=-1):
        """Row-wise softmax over the stored pattern (≙ sparse softmax
        kernel: softmax across the nnz of each row, zeros stay zero).
        For a batched COO (ndim > 2) every leading sparse dim joins the
        segment id, so rows in different batches normalize separately."""
        from paddle_tpu import sparse as S
        if axis not in (-1,):
            raise NotImplementedError(
                "sparse softmax supports axis=-1 only (the stored-pattern "
                "row direction)")
        coo = _coo(x)
        # segment id = flattened index over ALL dims but the softmaxed one
        seg = coo.indices[0] * 0
        n_seg = 1
        for d in range(coo.indices.shape[0] - 1):
            seg = seg * coo.shape[d] + coo.indices[d]
            n_seg *= coo.shape[d]
        v = coo.values.astype(jnp.float32)
        seg_max = jax.ops.segment_max(v, seg, num_segments=n_seg)
        e = jnp.exp(v - seg_max[seg])
        denom = jax.ops.segment_sum(e, seg, num_segments=n_seg)
        out = (e / denom[seg]).astype(x.values.dtype)
        if isinstance(x, S.SparseCsrTensor):
            return x.with_values(out)
        return coo.with_values(out)

    @staticmethod
    def attention(query, key, value, sparse_mask, key_padding_mask=None,
                  attn_mask=None):
        """Sparse-pattern attention (≙ sparse.nn.functional.attention →
        fused_attention_kernel.cu): scores only at mask nnz (SDDMM),
        sparse row softmax, then SpMM. q/k/v: (B, H, S, D); sparse_mask:
        a 2-D (S, S) COO/CSR pattern shared across batch×heads."""
        coo = _coo(sparse_mask)
        rows, cols = coo.indices[0], coo.indices[1]
        q = jnp.asarray(query)
        k = jnp.asarray(key)
        v = jnp.asarray(value)
        d = q.shape[-1]
        s = q.shape[-2]
        # (B, H, nnz): score at each stored (row, col)
        scores = jnp.einsum("bhnd,bhnd->bhn", q[..., rows, :],
                            k[..., cols, :]) / jnp.sqrt(float(d))
        seg_max = jax.vmap(jax.vmap(
            lambda sc: jax.ops.segment_max(sc, rows, num_segments=s)))(
            scores)
        e = jnp.exp(scores - seg_max[..., rows])
        denom = jax.vmap(jax.vmap(
            lambda ee: jax.ops.segment_sum(ee, rows, num_segments=s)))(e)
        probs = e / denom[..., rows]
        # out[r] = Σ_{c in row r} p(r,c) · v[c]
        return jax.vmap(jax.vmap(
            lambda p, vv: jax.ops.segment_sum(p[:, None] * vv[cols],
                                              rows, num_segments=s)))(
            probs, v)

    # -- 3-D sparse convolution --------------------------------------------

    @staticmethod
    def _rulebook(idx_np, spatial, ksize, stride, padding, subm):
        """Host-side rulebook (≙ cpu/conv_kernel.cc ProductRuleBook).
        idx_np: (4, nnz) rows (batch, z, y, x). Returns
        (out_indices (4, n_out), [(offset_id, in_idx, out_idx), ...],
        out_spatial)."""
        kz, ky, kx = ksize
        sz, sy, sx = stride
        pz, py, px = padding
        coords = idx_np.T  # (nnz, 4)
        if subm:
            out_spatial = spatial
            site = {tuple(c): i for i, c in enumerate(coords)}
            out_idx_map = site
            out_coords = coords
        else:
            out_spatial = tuple(
                (spatial[i] + 2 * (pz, py, px)[i] - ksize[i])
                // (sz, sy, sx)[i] + 1 for i in range(3))
            out_site = {}
            out_list = []
            for c in coords:
                b, z, y, x = c
                for oz in range(kz):
                    for oy in range(ky):
                        for ox in range(kx):
                            zz, rz = divmod(z + pz - oz, sz)
                            yy, ry = divmod(y + py - oy, sy)
                            xx, rx = divmod(x + px - ox, sx)
                            if rz or ry or rx:
                                continue
                            if not (0 <= zz < out_spatial[0]
                                    and 0 <= yy < out_spatial[1]
                                    and 0 <= xx < out_spatial[2]):
                                continue
                            t = (b, zz, yy, xx)
                            if t not in out_site:
                                out_site[t] = len(out_list)
                                out_list.append(t)
            out_idx_map = out_site
            out_coords = np.asarray(out_list, np.int64).reshape(-1, 4)
        rules = []
        for oz in range(kz):
            for oy in range(ky):
                for ox in range(kx):
                    oid = (oz * ky + oy) * kx + ox
                    ins, outs = [], []
                    for i, c in enumerate(coords):
                        b, z, y, x = c
                        zz, rz = divmod(z + pz - oz, sz)
                        yy, ry = divmod(y + py - oy, sy)
                        xx, rx = divmod(x + px - ox, sx)
                        if rz or ry or rx:
                            continue
                        t = (b, zz, yy, xx)
                        j = out_idx_map.get(t)
                        if j is not None:
                            ins.append(i)
                            outs.append(j)
                    if ins:
                        rules.append((oid, np.asarray(ins),
                                      np.asarray(outs)))
        return out_coords.T, rules, out_spatial

    @staticmethod
    def _conv3d(x, weight, bias, stride, padding, subm):
        from paddle_tpu import sparse as S
        stride = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
        padding = (padding,) * 3 if isinstance(padding, int) \
            else tuple(padding)
        w = jnp.asarray(weight)  # (kz, ky, kx, Cin, Cout)
        kz, ky, kx, cin, cout = w.shape
        wf = w.reshape(kz * ky * kx, cin, cout)
        idx_np = np.asarray(jax.device_get(x.indices))
        spatial = x.shape[1:4]
        out_idx, rules, out_spatial = functional._rulebook(
            idx_np, spatial, (kz, ky, kx), stride, padding, subm)
        n_out = out_idx.shape[1]
        out_vals = jnp.zeros((n_out, cout), x.values.dtype)
        for oid, ins, outs in rules:
            contrib = x.values[jnp.asarray(ins)] @ wf[oid]
            out_vals = out_vals.at[jnp.asarray(outs)].add(contrib)
        if bias is not None:
            out_vals = out_vals + jnp.asarray(bias)
        # hybrid COO: sparse dims in .shape, channel is the values' dense
        # trailing dim (≙ SparseCooTensor dense_dim=1)
        return S.SparseCooTensor(out_idx, out_vals,
                                 (x.shape[0],) + out_spatial)

    @staticmethod
    def _check_unsupported(dilation, groups):
        if dilation not in (1, (1, 1, 1), [1, 1, 1]):
            raise NotImplementedError("sparse conv3d: dilation != 1")
        if groups != 1:
            raise NotImplementedError("sparse conv3d: groups != 1")

    @staticmethod
    def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
               groups=1, data_format="NDHWC"):
        functional._check_unsupported(dilation, groups)
        return functional._conv3d(x, weight, bias, stride, padding,
                                  subm=False)

    @staticmethod
    def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                    groups=1, data_format="NDHWC"):
        """Submanifold conv: output sites == input sites (stride must be
        1) — the sparsity never dilates (≙ subm_conv3d)."""
        functional._check_unsupported(dilation, groups)
        return functional._conv3d(x, weight, bias, (1, 1, 1),
                                  padding, subm=True)

    @staticmethod
    def max_pool3d(x, kernel_size, stride=None, padding=0,
                   data_format="NDHWC"):
        from paddle_tpu import sparse as S
        k = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        stride = stride if stride is not None else k
        stride = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
        padding = (padding,) * 3 if isinstance(padding, int) \
            else tuple(padding)
        idx_np = np.asarray(jax.device_get(x.indices))
        out_idx, rules, out_spatial = functional._rulebook(
            idx_np, x.shape[1:4], k, stride, padding, subm=False)
        n_out = out_idx.shape[1]
        c = x.values.shape[-1]
        out_vals = jnp.full((n_out, c), -jnp.inf, x.values.dtype)
        for _, ins, outs in rules:
            out_vals = out_vals.at[jnp.asarray(outs)].max(
                x.values[jnp.asarray(ins)])
        return S.SparseCooTensor(out_idx, out_vals,
                                 (x.shape[0],) + out_spatial)


# ---------------------------------------------------------------------------
# layers
# ---------------------------------------------------------------------------

class ReLU(Module):
    def forward(self, x):
        return functional.relu(x)


class ReLU6(Module):
    def forward(self, x):
        return functional.relu6(x)


class LeakyReLU(Module):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return functional.leaky_relu(x, self.negative_slope)


class Softmax(Module):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return functional.softmax(x, self.axis)


class BatchNorm(Module):
    """BatchNorm over active sites (≙ sparse BatchNorm: the values
    (nnz, C) are normalized per channel; zeros don't participate)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 data_format="NDHWC"):
        super().__init__()
        from paddle_tpu.nn.module import Buffer
        self.epsilon = epsilon
        self.momentum = momentum
        self.weight = Parameter(jnp.ones((num_features,)))
        self.bias = Parameter(jnp.zeros((num_features,)))
        self.running_mean = Buffer(jnp.zeros((num_features,)))
        self.running_var = Buffer(jnp.ones((num_features,)))

    def forward(self, x):
        v = x.values.astype(jnp.float32)
        if self.training:
            mean = jnp.mean(v, axis=0)
            var = jnp.var(v, axis=0)
            # running stats ride the stateful Context like the dense
            # _BatchNormBase (nn/layer/norm.py:56-63)
            from paddle_tpu.nn.module import current_context
            ctx = current_context()
            if ctx is not None:
                m = self.momentum
                tag = getattr(self, "_stat_tag", None)
                if tag is None:
                    tag = f"id{id(self) % 10**9}"  # untagged: tag_paths()
                prefix = f"{tag}." if tag else ""
                ctx.record_update(
                    f"{prefix}running_mean",
                    (m * jnp.asarray(self.running_mean)
                     + (1 - m) * mean))
                n = v.shape[0]
                unbiased = var * n / max(n - 1, 1)
                ctx.record_update(
                    f"{prefix}running_var",
                    (m * jnp.asarray(self.running_var)
                     + (1 - m) * unbiased))
        else:
            mean = jnp.asarray(self.running_mean)
            var = jnp.asarray(self.running_var)
        out = (v - mean) * jax.lax.rsqrt(var + self.epsilon)
        out = out * jnp.asarray(self.weight) + jnp.asarray(self.bias)
        return x.with_values(out.astype(x.values.dtype))


class SyncBatchNorm(BatchNorm):
    """Cross-replica stats ride the mesh collectives under pjit (GSPMD
    inserts the psum) — same class body, parity name (≙ sparse
    SyncBatchNorm)."""


class _ConvBase(Module):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None, seed=0):
        super().__init__()
        k = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        rs = np.random.RandomState(seed)
        fan_in = in_channels * k[0] * k[1] * k[2]
        bound = float(np.sqrt(1.0 / fan_in))
        self.weight = Parameter(jnp.asarray(
            rs.uniform(-bound, bound, k + (in_channels, out_channels)),
            jnp.float32))
        self.bias = (None if bias_attr is False else Parameter(
            jnp.zeros((out_channels,), jnp.float32)))
        self.stride = stride
        self.padding = padding


class Conv3D(_ConvBase):
    def forward(self, x):
        return functional.conv3d(x, self.weight, self.bias, self.stride,
                                 self.padding)


class SubmConv3D(_ConvBase):
    def forward(self, x):
        return functional.subm_conv3d(x, self.weight, self.bias, 1,
                                      self.padding)


class MaxPool3D(Module):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC"):
        super().__init__()
        self.args = (kernel_size, stride, padding)

    def forward(self, x):
        return functional.max_pool3d(x, *self.args)
