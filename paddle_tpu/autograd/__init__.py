"""Autograd surface (ref: python/paddle/autograd/ — paddle.grad, PyLayer,
functional vjp/jvp; C++ engine paddle/fluid/eager/backward.cc:393).

On TPU, AD is tracing-based: there is no tape, no GradNode graph, no
TensorWrapper saved-activation machinery — jax traces the function and
transposes it. What remains framework-level:

- ``PyLayer``: user-defined forward/backward → jax.custom_vjp wrapper;
- ``grad``/``vjp``/``jvp``/``hessian``/``jacobian`` functional API;
- ``saved_tensors_hooks`` analog is subsumed by jax.checkpoint policies
  (see paddle_tpu.distributed.recompute).
"""

import jax
import jax.numpy as jnp

__all__ = ["grad", "value_and_grad", "vjp", "jvp", "jacobian", "hessian",
           "PyLayer", "PyLayerContext", "no_grad", "backward"]

grad = jax.grad
value_and_grad = jax.value_and_grad


def vjp(func, xs, v=None):
    """ref: paddle.incubate.autograd.vjp."""
    out, pullback = jax.vjp(func, *(xs if isinstance(xs, (list, tuple))
                                    else (xs,)))
    if v is None:
        v = jnp.ones_like(out)
    return out, pullback(v)


def jvp(func, xs, v=None):
    xs = xs if isinstance(xs, (list, tuple)) else (xs,)
    if v is None:
        v = tuple(jnp.ones_like(x) for x in xs)
    v = v if isinstance(v, (list, tuple)) else (v,)
    return jax.jvp(func, tuple(xs), tuple(v))


def jacobian(func, xs, create_graph=False):
    return jax.jacrev(func)(xs)


def hessian(func, xs, create_graph=False):
    return jax.hessian(func)(xs)


class PyLayerContext:
    """ref: paddle.autograd.PyLayerContext — save_for_backward surface."""

    def __init__(self):
        self._saved = ()
        self.extra = {}

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    saved_tensors = saved_tensor


class PyLayer:
    """User-defined differentiable function (ref: paddle.autograd.PyLayer,
    C++ pylayer/ in eager/). Implemented over jax.custom_vjp::

        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x ** 3
            @staticmethod
            def backward(ctx, dy):
                x, = ctx.saved_tensor
                return 3 * x ** 2 * dy

        y = Cube.apply(x)
    """

    @classmethod
    def apply(cls, *args, **kwargs):
        if not hasattr(cls, "_jax_fn"):
            @jax.custom_vjp
            def fn(*fargs):
                ctx = PyLayerContext()
                return cls.forward(ctx, *fargs)

            def fwd(*fargs):
                ctx = PyLayerContext()
                out = cls.forward(ctx, *fargs)
                return out, (ctx, fargs)

            def bwd(res, g):
                ctx, fargs = res
                grads = cls.backward(ctx, g)
                if not isinstance(grads, tuple):
                    grads = (grads,)
                # pad Nones for non-differentiable args
                out = []
                gi = iter(grads)
                for a in fargs:
                    try:
                        out.append(next(gi))
                    except StopIteration:
                        out.append(jnp.zeros_like(a))
                return tuple(
                    jnp.zeros_like(a) if g is None else g
                    for g, a in zip(out, fargs))

            fn.defvjp(fwd, bwd)
            cls._jax_fn = fn
        return cls._jax_fn(*args, **kwargs)


from paddle_tpu.framework import no_grad  # noqa: E402


def backward(tensors, grad_tensors=None, retain_graph=False):
    raise RuntimeError(
        "paddle_tpu is functional: use paddle_tpu.grad/value_and_grad on a "
        "loss function instead of tensor.backward() "
        "(ref eager Backward, paddle/fluid/eager/backward.cc:393 — replaced "
        "by tracing-based AD).")
