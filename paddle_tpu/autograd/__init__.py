"""Autograd surface (ref: python/paddle/autograd/ — paddle.grad, PyLayer,
functional vjp/jvp; C++ engine paddle/fluid/eager/backward.cc:393).

On TPU, AD is tracing-based: there is no tape, no GradNode graph, no
TensorWrapper saved-activation machinery — jax traces the function and
transposes it. What remains framework-level:

- ``PyLayer``: user-defined forward/backward → jax.custom_vjp wrapper;
- ``grad``/``vjp``/``jvp``/``hessian``/``jacobian`` functional API;
- ``saved_tensors_hooks`` analog is subsumed by jax.checkpoint policies
  (see paddle_tpu.distributed.recompute).
"""

import jax
import jax.numpy as jnp

__all__ = ["grad", "value_and_grad", "vjp", "jvp", "jacobian", "hessian",
           "PyLayer", "PyLayerContext", "no_grad", "backward",
           "saved_tensors_hooks"]

grad = jax.grad
value_and_grad = jax.value_and_grad


def vjp(func, xs, v=None):
    """ref: paddle.incubate.autograd.vjp."""
    out, pullback = jax.vjp(func, *(xs if isinstance(xs, (list, tuple))
                                    else (xs,)))
    if v is None:
        v = jnp.ones_like(out)
    return out, pullback(v)


def jvp(func, xs, v=None):
    xs = xs if isinstance(xs, (list, tuple)) else (xs,)
    if v is None:
        v = tuple(jnp.ones_like(x) for x in xs)
    v = v if isinstance(v, (list, tuple)) else (v,)
    return jax.jvp(func, tuple(xs), tuple(v))


def jacobian(func, xs, create_graph=False):
    return jax.jacrev(func)(xs)


def hessian(func, xs, create_graph=False):
    return jax.hessian(func)(xs)


class PyLayerContext:
    """ref: paddle.autograd.PyLayerContext — save_for_backward surface."""

    def __init__(self):
        self._saved = ()
        self.extra = {}

    def save_for_backward(self, *tensors):
        hooks = saved_tensors_hooks._active
        if hooks is not None:
            tensors = tuple(hooks.pack_hook(t) for t in tensors)
            self._hooks = hooks
        self._saved = tensors

    @property
    def saved_tensor(self):
        hooks = getattr(self, "_hooks", None)
        if hooks is not None:
            return tuple(hooks.unpack_hook(t) for t in self._saved)
        return self._saved

    saved_tensors = saved_tensor


class PyLayer:
    """User-defined differentiable function (ref: paddle.autograd.PyLayer,
    C++ pylayer/ in eager/). Implemented over jax.custom_vjp::

        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x ** 3
            @staticmethod
            def backward(ctx, dy):
                x, = ctx.saved_tensor
                return 3 * x ** 2 * dy

        y = Cube.apply(x)
    """

    @classmethod
    def apply(cls, *args, **kwargs):
        if "_jax_fn" not in cls.__dict__:
            @jax.custom_vjp
            def fn(*fargs):
                ctx = PyLayerContext()
                return cls.forward(ctx, *fargs)

            def fwd(*fargs):
                ctx = PyLayerContext()
                out = cls.forward(ctx, *fargs)
                # residuals must be jax types: only the saved ARRAYS cross
                # the custom_vjp boundary. Static metadata (ctx.extra,
                # active saved-tensor hooks) rides a per-class LIFO:
                # backward traces replay in reverse order of the forward
                # traces within one differentiated function, so pop()
                # pairs each bwd with ITS OWN application (a single cell
                # would hand every bwd the last application's metadata).
                if "_trace_meta" not in cls.__dict__:
                    import collections
                    cls._trace_meta = collections.deque(maxlen=64)
                cls._trace_meta.append((dict(ctx.extra),
                                        getattr(ctx, "_hooks", None)))
                return out, (ctx._saved, fargs)

            def bwd(res, g):
                saved, fargs = res
                ctx = PyLayerContext()
                ctx._saved = saved
                meta = cls.__dict__.get("_trace_meta")
                extra, hooks = (meta.pop() if meta else ({}, None))
                ctx.extra = dict(extra)
                if hooks is not None:
                    ctx._hooks = hooks
                grads = cls.backward(ctx, g)
                if not isinstance(grads, tuple):
                    grads = (grads,)
                # pad Nones for non-differentiable args
                out = []
                gi = iter(grads)
                for a in fargs:
                    try:
                        out.append(next(gi))
                    except StopIteration:
                        out.append(jnp.zeros_like(a))
                return tuple(
                    jnp.zeros_like(a) if g is None else g
                    for g, a in zip(out, fargs))

            fn.defvjp(fwd, bwd)
            cls._jax_fn = fn
        return cls._jax_fn(*args, **kwargs)


from paddle_tpu.framework import no_grad  # noqa: E402


def backward(tensors, grad_tensors=None, retain_graph=False):
    raise RuntimeError(
        "paddle_tpu is functional: use paddle_tpu.grad/value_and_grad on a "
        "loss function instead of tensor.backward() "
        "(ref eager Backward, paddle/fluid/eager/backward.cc:393 — replaced "
        "by tracing-based AD).")


class saved_tensors_hooks:  # noqa: N801 (reference casing)
    """ref: paddle.autograd.saved_tensors_hooks (python/paddle/autograd/
    saved_tensors_hooks.py; C++ eager/saved_tensors_hooks.cc) — a context
    whose pack/unpack hooks transform activations saved for backward
    (e.g. offload to host, cast down).

    Scope here: tensors saved through ``PyLayerContext.save_for_backward``
    (the runtime this framework controls). XLA-managed residuals inside
    jit are scheduled by the compiler; their memory story is
    ``jax.checkpoint`` policies (distributed.recompute), not hooks."""

    _active = None

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        self._prev = saved_tensors_hooks._active
        saved_tensors_hooks._active = self
        return self

    def __exit__(self, *exc):
        saved_tensors_hooks._active = self._prev
        return False
