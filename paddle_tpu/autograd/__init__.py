"""Autograd surface (ref: python/paddle/autograd/ — paddle.grad, PyLayer,
functional vjp/jvp; C++ engine paddle/fluid/eager/backward.cc:393).

On TPU, AD is tracing-based: there is no tape, no GradNode graph, no
TensorWrapper saved-activation machinery — jax traces the function and
transposes it. What remains framework-level:

- ``PyLayer``: user-defined forward/backward → jax.custom_vjp wrapper;
- ``grad``/``vjp``/``jvp``/``hessian``/``jacobian`` functional API;
- ``saved_tensors_hooks`` analog is subsumed by jax.checkpoint policies
  (see paddle_tpu.distributed.recompute).
"""

import jax
import jax.numpy as jnp

__all__ = ["grad", "value_and_grad", "vjp", "jvp", "jacobian", "hessian",
           "PyLayer", "PyLayerContext", "no_grad", "backward",
           "saved_tensors_hooks"]

grad = jax.grad
value_and_grad = jax.value_and_grad


def vjp(func, xs, v=None):
    """ref: paddle.incubate.autograd.vjp."""
    out, pullback = jax.vjp(func, *(xs if isinstance(xs, (list, tuple))
                                    else (xs,)))
    if v is None:
        v = jnp.ones_like(out)
    return out, pullback(v)


def jvp(func, xs, v=None):
    xs = xs if isinstance(xs, (list, tuple)) else (xs,)
    if v is None:
        v = tuple(jnp.ones_like(x) for x in xs)
    v = v if isinstance(v, (list, tuple)) else (v,)
    return jax.jvp(func, tuple(xs), tuple(v))


def jacobian(func, xs, create_graph=False):
    return jax.jacrev(func)(xs)


def hessian(func, xs, create_graph=False):
    return jax.hessian(func)(xs)


class PyLayerContext:
    """ref: paddle.autograd.PyLayerContext — save_for_backward surface.

    ``apply_hooks=False`` builds the context for the PRIMAL path: pack
    hooks exist to transform residuals kept for backward, so a plain
    (undifferentiated) forward must not pay for — or crash on — them."""

    def __init__(self, apply_hooks=True):
        self._saved = ()
        self.extra = {}
        self._apply_hooks = apply_hooks

    def save_for_backward(self, *tensors):
        hooks = saved_tensors_hooks._current() if self._apply_hooks else None
        if hooks is not None:
            tensors = tuple(hooks.pack_hook(t) for t in tensors)
            self._hooks = hooks
        self._saved = tensors

    @property
    def saved_tensor(self):
        hooks = getattr(self, "_hooks", None)
        if hooks is not None:
            if not hasattr(self, "_unpacked"):
                # unpack once — a backward reading saved_tensor several
                # times must not repeat e.g. a host-to-device transfer
                self._unpacked = tuple(hooks.unpack_hook(t)
                                       for t in self._saved)
            return self._unpacked
        return self._saved

    saved_tensors = saved_tensor


@jax.tree_util.register_pytree_node_class
class _PyLayerResidual:
    """custom_vjp residual wrapper: the saved arrays are pytree children;
    the application's metadata KEY is static aux data — it survives the
    residual round-trip untouched by tracing, so each backward finds its
    own application's (extra, hooks) regardless of pullback order."""

    def __init__(self, saved, meta_id):
        self.saved = saved
        self.meta_id = meta_id

    def tree_flatten(self):
        return (self.saved,), (self.meta_id,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0])


import threading as _threading_mod
_PYLAYER_INIT_LOCK = _threading_mod.Lock()


class PyLayer:
    """User-defined differentiable function (ref: paddle.autograd.PyLayer,
    C++ pylayer/ in eager/). Implemented over jax.custom_vjp::

        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x ** 3
            @staticmethod
            def backward(ctx, dy):
                x, = ctx.saved_tensor
                return 3 * x ** 2 * dy

        y = Cube.apply(x)
    """

    @classmethod
    def apply(cls, *args, **kwargs):
        if "_jax_fn" not in cls.__dict__:
            with _PYLAYER_INIT_LOCK:
                if "_jax_fn" not in cls.__dict__:
                    cls._build()
        return cls._jax_fn(*args, **kwargs)

    @classmethod
    def _build(cls):
        import collections
        import threading
        cls._meta_map = collections.OrderedDict()
        cls._meta_seq = 0
        cls._meta_lock = threading.Lock()

        @jax.custom_vjp
        def fn(*fargs):
            # primal-only path: hooks are for backward residuals
            ctx = PyLayerContext(apply_hooks=False)
            return cls.forward(ctx, *fargs)

        def fwd(*fargs):
            ctx = PyLayerContext()
            out = cls.forward(ctx, *fargs)
            # residuals must be jax types: only the saved ARRAYS cross
            # the custom_vjp boundary. Static metadata (ctx.extra, active
            # hooks) is keyed by a per-application id carried as residual
            # pytree AUX DATA, so every backward finds its own
            # application's metadata no matter what order pullbacks run
            # in — and repeated pullback calls re-read it (get, not pop).
            with cls._meta_lock:
                cls._meta_seq += 1
                mid = cls._meta_seq
                cls._meta_map[mid] = (dict(ctx.extra),
                                      getattr(ctx, "_hooks", None))
                while len(cls._meta_map) > 4096:
                    cls._meta_map.popitem(last=False)
            return out, (_PyLayerResidual(ctx._saved, mid), fargs)

        def bwd(res, g):
            wrapper, fargs = res
            ctx = PyLayerContext()
            ctx._saved = wrapper.saved
            with cls._meta_lock:
                meta = cls._meta_map.get(wrapper.meta_id)
            if meta is None:
                # evicted (>4096 in-flight applications): fail LOUDLY —
                # a ({}, None) default would compute wrong gradients
                raise RuntimeError(
                    f"{cls.__name__}: backward metadata for application "
                    f"#{wrapper.meta_id} was evicted (more than 4096 "
                    "in-flight applications of one PyLayer class)")
            extra, hooks = meta
            ctx.extra = dict(extra)
            if hooks is not None:
                ctx._hooks = hooks
            grads = cls.backward(ctx, g)
            if not isinstance(grads, tuple):
                grads = (grads,)
            # pad Nones for non-differentiable args
            out = []
            gi = iter(grads)
            for a in fargs:
                try:
                    out.append(next(gi))
                except StopIteration:
                    out.append(jnp.zeros_like(a))
            return tuple(
                jnp.zeros_like(a) if g is None else g
                for g, a in zip(out, fargs))

        fn.defvjp(fwd, bwd)
        cls._jax_fn = fn


from paddle_tpu.framework import no_grad  # noqa: E402


def backward(tensors, grad_tensors=None, retain_graph=False):
    raise RuntimeError(
        "paddle_tpu is functional: use paddle_tpu.grad/value_and_grad on a "
        "loss function instead of tensor.backward() "
        "(ref eager Backward, paddle/fluid/eager/backward.cc:393 — replaced "
        "by tracing-based AD).")


class saved_tensors_hooks:  # noqa: N801 (reference casing)
    """ref: paddle.autograd.saved_tensors_hooks (python/paddle/autograd/
    saved_tensors_hooks.py; C++ eager/saved_tensors_hooks.cc) — a context
    whose pack/unpack hooks transform activations saved for backward
    (e.g. offload to host, cast down).

    Scope here: tensors saved through ``PyLayerContext.save_for_backward``
    (the runtime this framework controls). XLA-managed residuals inside
    jit are scheduled by the compiler; their memory story is
    ``jax.checkpoint`` policies (distributed.recompute), not hooks.
    The active context is per-THREAD (≙ the reference's thread-local
    hook stack) so concurrent training threads cannot see each other's
    hooks."""

    import threading as _threading
    _tls = _threading.local()

    @classmethod
    def _current(cls):
        return getattr(cls._tls, "active", None)

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        self._prev = saved_tensors_hooks._current()
        saved_tensors_hooks._tls.active = self
        return self

    def __exit__(self, *exc):
        saved_tensors_hooks._tls.active = self._prev
        return False
