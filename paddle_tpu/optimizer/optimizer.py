"""Optimizer suite (ref: python/paddle/optimizer/ — Optimizer base +
SGD/Momentum/Adam/AdamW/Adamax/Adagrad/Adadelta/RMSProp/Lamb; device kernels
in paddle/fluid/operators/optimizers/).

Design: purely functional update rule over parameter pytrees —

    opt = Adam(learning_rate=1e-3)
    state = opt.init(params)
    params, state = opt.update(grads, state, params)   # jit-friendly

plus a stateful convenience wrapper matching the reference's
``opt.step()`` ergonomics for eager-style loops (see ``bind``). All update
math is vectorized tree-wide so XLA fuses it into a handful of kernels — the
reference instead launches one fused CUDA kernel per parameter per step
(e.g. adam_kernel.cu; here the whole update is one compiled program).
"""

from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.optimizer.lr import LRScheduler

tree_map = jax.tree_util.tree_map


def _lr_value(lr, step):
    if isinstance(lr, LRScheduler):
        return lr.value_at(step)
    return jnp.asarray(lr, jnp.float32)


class Optimizer:
    """Functional optimizer base. Subclasses implement ``init_param`` and
    ``update_param``."""

    def __init__(self, learning_rate=0.001, weight_decay=0.0,
                 grad_clip=None, parameters=None, multi_precision=True):
        self.learning_rate = learning_rate
        self.weight_decay = weight_decay or 0.0
        self.grad_clip = grad_clip
        self.multi_precision = multi_precision
        # decoupled weight decay flag (AdamW-style); L2-style subclasses add
        # wd*p to the gradient instead
        self._decoupled_wd = False
        # DEFERRED bind: subclass __init__ has not run yet, so init_param
        # may depend on attributes (e.g. Adam.moment_dtype) that don't
        # exist — slots are materialized on first use instead
        self._params = None
        self._state = None
        self._deferred_params = parameters

    def _ensure_bound(self):
        if self._deferred_params is not None:
            if self._params is None:
                self._params = self._deferred_params
            if self._state is None:
                # state may already exist: set_state_dict (checkpoint
                # resume) runs before the first step — keep it
                self._state = self.init(self._params)
            self._deferred_params = None

    # -- functional API --------------------------------------------------------
    def init_param(self, p):
        return ()

    def update_param(self, p, g, s, lr, step):
        raise NotImplementedError

    def init(self, params):
        slots = tree_map(lambda p: self.init_param(p), params)
        return {"step": jnp.zeros((), jnp.int32), "slots": slots}

    def update(self, grads, state, params):
        step = state["step"] + 1
        lr = _lr_value(self.learning_rate, step)
        if self.grad_clip is not None:
            grads = self.grad_clip(grads)
        if self.weight_decay and not self._decoupled_wd:
            if callable(self.weight_decay):  # L1Decay/L2Decay regularizer
                grads = tree_map(self.weight_decay, grads, params)
            else:
                grads = tree_map(lambda g, p: g + self.weight_decay * p,
                                 grads, params)

        def upd(p, g, s):
            new_p, new_s = self.update_param(
                p.astype(jnp.float32) if self.multi_precision else p,
                g.astype(jnp.float32) if self.multi_precision else g,
                s, lr, step)
            return new_p.astype(p.dtype), new_s

        out = tree_map(upd, params, grads, state["slots"],
                       is_leaf=lambda x: isinstance(x, jax.Array))
        # out is a tree of (p, s) tuples at param positions; unzip
        new_params = tree_map(lambda pair: pair[0], out,
                              is_leaf=lambda x: isinstance(x, tuple)
                              and len(x) == 2 and isinstance(x[0], jax.Array))
        new_slots = tree_map(lambda pair: pair[1], out,
                             is_leaf=lambda x: isinstance(x, tuple)
                             and len(x) == 2 and isinstance(x[0], jax.Array))
        return new_params, {"step": step, "slots": new_slots}

    # -- stateful convenience --------------------------------------------------
    def bind(self, params):
        """Attach a parameter pytree for paddle-style ``step()`` loops."""
        self._params = params
        self._state = self.init(params)
        return self

    def step(self, grads):
        self._ensure_bound()
        self._params, self._state = self.update(grads, self._state,
                                                self._params)
        return self._params

    @property
    def params(self):
        return self._params

    def clear_grad(self):
        """API parity (ref: Optimizer.clear_grad); gradients are functional
        values here, nothing to clear."""

    def state_dict(self):
        self._ensure_bound()
        d = {"state": self._state} if self._state is not None else {}
        if isinstance(self.learning_rate, LRScheduler):
            d["lr"] = self.learning_rate.state_dict()
        return d

    def set_state_dict(self, d):
        if "state" in d:
            self._state = d["state"]
        if "lr" in d and isinstance(self.learning_rate, LRScheduler):
            self.learning_rate.set_state_dict(d["lr"])

    def get_lr(self):
        step = self._state["step"] if self._state is not None else 0
        return float(_lr_value(self.learning_rate, step))


class SGD(Optimizer):
    """ref: paddle.optimizer.SGD (operators/optimizers/sgd_op)."""

    def update_param(self, p, g, s, lr, step):
        return p - lr * g, s


class Momentum(Optimizer):
    """ref: paddle.optimizer.Momentum (momentum_op; use_nesterov attr)."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def init_param(self, p):
        return jnp.zeros_like(p, jnp.float32)

    def update_param(self, p, g, v, lr, step):
        v = self.momentum * v + g
        if self.use_nesterov:
            return p - lr * (g + self.momentum * v), v
        return p - lr * v, v


class Adam(Optimizer):
    """ref: paddle.optimizer.Adam (phi adam kernel).

    moment_dtype: storage dtype for the m/v slots (default fp32). bf16
    halves-again optimizer memory (1.3B Adam state: 10.4GB fp32 → 5.2GB) at
    a small quality cost — the update itself always computes in fp32."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, moment_dtype=jnp.float32,
                 **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.moment_dtype = moment_dtype

    def init_param(self, p):
        return (jnp.zeros_like(p, self.moment_dtype),
                jnp.zeros_like(p, self.moment_dtype))

    def update_param(self, p, g, s, lr, step):
        m, v = s
        b1, b2 = self.beta1, self.beta2
        m = b1 * m.astype(jnp.float32) + (1 - b1) * g
        v = b2 * v.astype(jnp.float32) + (1 - b2) * jnp.square(g)
        t = step.astype(jnp.float32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + self.epsilon)
        return new_p, (m.astype(self.moment_dtype),
                       v.astype(self.moment_dtype))


class AdamW(Adam):
    """ref: paddle.optimizer.AdamW — decoupled weight decay."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, weight_decay=0.01, apply_decay_param_fun=None,
                 **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon,
                         weight_decay=weight_decay, **kw)
        self._decoupled_wd = True
        self.apply_decay_param_fun = apply_decay_param_fun
        # pre-resolved decay masks for params-dict entries whose value is
        # a stacked pytree (per-param names cannot resolve into it):
        # {entry name: pytree matching the entry, float 0/1 leaves
        # broadcastable to the param} — see models.gpt's stacked layout
        self._decay_masks = {}

    def set_decay_mask(self, entry: str, mask):
        """Register a pre-resolved decay mask for ``params[entry]`` (a
        pytree structurally matching it, leaves 0/1 floats broadcastable
        to each param — e.g. (L, 1, ...) along a stacked layer axis).
        Used when ``apply_decay_param_fun`` is set but the entry folds
        many named params into one pytree."""
        self._decay_masks[entry] = mask

    def update(self, grads, state, params):
        step = state["step"] + 1
        lr = _lr_value(self.learning_rate, step)
        if self.grad_clip is not None:
            grads = self.grad_clip(grads)

        wd = self.weight_decay
        if callable(wd):
            # L1Decay/L2Decay regularizer object: its penalty gradient is
            # wd(0, p); decoupled decay subtracts lr * that from the param
            def decay_term(p):
                return wd(jnp.zeros_like(p), p)
        else:
            def decay_term(p):
                return wd * p

        def upd(path_p, g, s):
            p = path_p
            new_p, new_s = Adam.update_param(self, p.astype(jnp.float32),
                                             g.astype(jnp.float32), s, lr,
                                             step)
            new_p = new_p - lr * decay_term(p.astype(jnp.float32))
            return new_p.astype(p.dtype), new_s

        if self.apply_decay_param_fun is not None and isinstance(params, dict):
            is_pair = (lambda x: isinstance(x, tuple) and len(x) == 2
                       and isinstance(x[0], jax.Array))

            def upd_masked(p, g, s, mk):
                # mk: 0/1 float broadcastable to p (e.g. (L, 1, ...) along
                # a stacked layer axis) — the name mask resolved up front
                new_p, new_s = Adam.update_param(
                    self, p.astype(jnp.float32), g.astype(jnp.float32),
                    s, lr, step)
                new_p = new_p - lr * decay_term(p.astype(jnp.float32)) * mk
                return new_p.astype(p.dtype), new_s

            def upd_named(name):
                def f(p, g, s):
                    new_p, new_s = Adam.update_param(
                        self, p.astype(jnp.float32), g.astype(jnp.float32),
                        s, lr, step)
                    if self.apply_decay_param_fun(name):
                        new_p = new_p - lr * decay_term(
                            p.astype(jnp.float32))
                    return new_p.astype(p.dtype), new_s
                return f
            new_params, new_slots = {}, {}
            for name in params:
                p, g, s = params[name], grads[name], state["slots"][name]
                if not isinstance(p, jax.Array):
                    # pytree-valued entry (stacked block weights): per-leaf
                    # decay rides the registered mask. No mask means the
                    # name fn CANNOT be honored (it can't see into the
                    # folded entry) — fail loudly rather than silently
                    # decaying leaves (LN scales, biases) the per-layer
                    # state would exempt
                    mask = self._decay_masks.get(name)
                    if mask is None:
                        raise ValueError(
                            f"apply_decay_param_fun is set but params "
                            f"entry {name!r} is a pytree with no "
                            f"registered decay mask; resolve the mask "
                            f"against the block template first "
                            f"(set_decay_mask — init_train_state("
                            f"stacked=True) does this)")
                    out = tree_map(
                        upd_masked, p, g, s, mask,
                        is_leaf=lambda x: isinstance(x, jax.Array))
                    new_params[name] = tree_map(lambda pr: pr[0], out,
                                                is_leaf=is_pair)
                    new_slots[name] = tree_map(lambda pr: pr[1], out,
                                               is_leaf=is_pair)
                else:
                    new_params[name], new_slots[name] = upd_named(name)(
                        p, g, s)
            return new_params, {"step": step, "slots": new_slots}

        out = tree_map(upd, params, grads, state["slots"])
        is_pair = (lambda x: isinstance(x, tuple) and len(x) == 2
                   and isinstance(x[0], jax.Array))
        new_params = tree_map(lambda pair: pair[0], out, is_leaf=is_pair)
        new_slots = tree_map(lambda pair: pair[1], out, is_leaf=is_pair)
        return new_params, {"step": step, "slots": new_slots}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_param(self, p):
        return (jnp.zeros_like(p, jnp.float32),
                jnp.zeros_like(p, jnp.float32))

    def update_param(self, p, g, s, lr, step):
        m, u = s
        b1 = self.beta1
        m = b1 * m + (1 - b1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        t = step.astype(jnp.float32)
        return p - lr / (1 - b1 ** t) * m / (u + self.epsilon), (m, u)


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6,
                 initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self.epsilon = epsilon
        self.initial_accumulator_value = initial_accumulator_value

    def init_param(self, p):
        return jnp.full_like(p, self.initial_accumulator_value, jnp.float32)

    def update_param(self, p, g, acc, lr, step):
        acc = acc + jnp.square(g)
        return p - lr * g / (jnp.sqrt(acc) + self.epsilon), acc


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self.epsilon, self.rho = epsilon, rho

    def init_param(self, p):
        return (jnp.zeros_like(p, jnp.float32),
                jnp.zeros_like(p, jnp.float32))

    def update_param(self, p, g, s, lr, step):
        acc_g, acc_dx = s
        rho, eps = self.rho, self.epsilon
        acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
        dx = jnp.sqrt(acc_dx + eps) / jnp.sqrt(acc_g + eps) * g
        acc_dx = rho * acc_dx + (1 - rho) * jnp.square(dx)
        return p - lr * dx, (acc_g, acc_dx)


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon = rho, epsilon
        self.momentum, self.centered = momentum, centered

    def init_param(self, p):
        return (jnp.zeros_like(p, jnp.float32),
                jnp.zeros_like(p, jnp.float32),
                jnp.zeros_like(p, jnp.float32))

    def update_param(self, p, g, s, lr, step):
        mean_sq, mean_g, mom = s
        rho = self.rho
        mean_sq = rho * mean_sq + (1 - rho) * jnp.square(g)
        if self.centered:
            mean_g = rho * mean_g + (1 - rho) * g
            denom = jnp.sqrt(mean_sq - jnp.square(mean_g) + self.epsilon)
        else:
            denom = jnp.sqrt(mean_sq + self.epsilon)
        mom = self.momentum * mom + lr * g / denom
        return p - mom, (mean_sq, mean_g, mom)


class Lamb(Optimizer):
    """ref: paddle.optimizer.Lamb (lamb_op; layer-adaptive Adam for large
    batch — exclusion of bias/norm params via exclude_from_weight_decay_fn)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 exclude_from_weight_decay_fn=None, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lamb_weight_decay = lamb_weight_decay
        self.exclude_fn = exclude_from_weight_decay_fn

    def init_param(self, p):
        return (jnp.zeros_like(p, jnp.float32),
                jnp.zeros_like(p, jnp.float32))

    def update_param(self, p, g, s, lr, step):
        m, v = s
        b1, b2 = self.beta1, self.beta2
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * jnp.square(g)
        t = step.astype(jnp.float32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self.epsilon) + \
            self.lamb_weight_decay * p
        p_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        return p - lr * trust * r, (m, v)


class Lars(Optimizer):
    """ref: lars_momentum_op — layer-wise adaptive rate scaling."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.lars_coeff = lars_coeff
        self.lars_weight_decay = lars_weight_decay

    def init_param(self, p):
        return jnp.zeros_like(p, jnp.float32)

    def update_param(self, p, g, v, lr, step):
        p_norm = jnp.linalg.norm(p)
        g_norm = jnp.linalg.norm(g)
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            self.lars_coeff * p_norm /
            (g_norm + self.lars_weight_decay * p_norm), 1.0)
        v = self.momentum * v + lr * local_lr * (
            g + self.lars_weight_decay * p)
        return p - v, v
