"""Gradient clipping (ref: python/paddle/fluid/clip.py — ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm). Functional over pytrees; the hybrid
distributed variant that reduces the global norm across mesh axes lives in
paddle_tpu.distributed.fleet (HybridParallelClipGrad analog)."""

import jax
import jax.numpy as jnp

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]

tree_map = jax.tree_util.tree_map


class ClipGradByValue:
    def __init__(self, max, min=None):  # noqa: A002
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, grads):
        return tree_map(lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm:
    """Per-tensor norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        def clip(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            return g * jnp.minimum(1.0, self.clip_norm / jnp.maximum(n, 1e-12))
        return tree_map(clip, grads)


class ClipGradByGlobalNorm:
    """Global-norm clip (the hybrid-parallel variant psums the squared norm
    over mesh axes first — see distributed.fleet.HybridParallelClipGrad;
    ref: hybrid_parallel_optimizer.py:45)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm

    def global_norm(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                            for g in leaves))

    def __call__(self, grads):
        norm = self.global_norm(grads)
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(norm, 1e-12))
        return tree_map(lambda g: (g * scale).astype(g.dtype), grads)
