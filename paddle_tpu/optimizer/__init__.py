from paddle_tpu.optimizer.optimizer import (Optimizer, SGD, Momentum, Adam,
                                            AdamW, Adamax, Adagrad, Adadelta,
                                            RMSProp, Lamb, Lars)
from paddle_tpu.optimizer import lr
from paddle_tpu.optimizer.clip import (ClipGradByValue, ClipGradByNorm,
                                       ClipGradByGlobalNorm)

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
           "Adagrad", "Adadelta", "RMSProp", "Lamb", "Lars", "lr",
           "ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]
