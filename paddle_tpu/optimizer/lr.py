"""LR schedulers (ref: python/paddle/optimizer/lr.py — 16 classes).

Each scheduler implements ``value_at(step)`` with jnp math so the learning
rate is computed *inside* the compiled train step from the integer step
counter (no host↔device sync per step, unlike the reference's Python-side
``scheduler.step()``); the paddle-style stateful ``step()/get_lr()`` API is
kept for parity."""

import math

import jax.numpy as jnp

__all__ = ["LRScheduler", "NoamDecay", "ExponentialDecay", "NaturalExpDecay",
           "InverseTimeDecay", "PolynomialDecay", "LinearWarmup",
           "PiecewiseDecay", "CosineAnnealingDecay", "MultiStepDecay",
           "StepDecay", "LambdaDecay", "ReduceOnPlateau", "MultiplicativeDecay",
           "OneCycleLR", "CyclicLR", "CosineAnnealingWarmRestarts"]


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.step()  # initialize

    # -- functional (used inside jit) -----------------------------------------
    def value_at(self, step):
        raise NotImplementedError

    # -- stateful parity API ---------------------------------------------------
    def step(self, epoch=None):
        self.last_epoch = epoch if epoch is not None else self.last_epoch + 1

    def get_lr(self):
        return float(self.value_at(jnp.asarray(max(self.last_epoch, 0))))

    def state_dict(self):
        return {"last_epoch": self.last_epoch}

    def set_state_dict(self, d):
        self.last_epoch = d["last_epoch"]


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0,
                 last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        s = jnp.maximum(step.astype(jnp.float32), 1.0)
        return self.base_lr * self.d_model ** -0.5 * jnp.minimum(
            s ** -0.5, s * self.warmup_steps ** -1.5)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        return self.base_lr * self.gamma ** step.astype(jnp.float32)


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        return self.base_lr * jnp.exp(-self.gamma * step.astype(jnp.float32))


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        return self.base_lr / (1 + self.gamma * step.astype(jnp.float32))


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        s = step.astype(jnp.float32)
        if self.cycle:
            div = jnp.ceil(jnp.maximum(s / self.decay_steps, 1.0))
            decay_steps = self.decay_steps * div
        else:
            decay_steps = self.decay_steps
            s = jnp.minimum(s, decay_steps)
        return (self.base_lr - self.end_lr) * (
            1 - s / decay_steps) ** self.power + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr_after = learning_rate  # scheduler or float
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(end_lr, last_epoch, verbose)

    def value_at(self, step):
        s = step.astype(jnp.float32)
        warm = self.start_lr + (self.end_lr - self.start_lr) * jnp.minimum(
            s / self.warmup_steps, 1.0)
        if isinstance(self.lr_after, LRScheduler):
            after = self.lr_after.value_at(
                jnp.maximum(step - self.warmup_steps, 0))
        else:
            after = jnp.asarray(self.lr_after, jnp.float32)
        return jnp.where(s < self.warmup_steps, warm, after)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def value_at(self, step):
        b = jnp.asarray(self.boundaries)
        idx = jnp.sum(step >= b)
        return jnp.asarray(self.values)[idx]


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0.0, last_epoch=-1,
                 verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        s = step.astype(jnp.float32)
        return self.eta_min + (self.base_lr - self.eta_min) * 0.5 * (
            1 + jnp.cos(jnp.pi * jnp.minimum(s, self.T_max) / self.T_max))


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0.0,
                 last_epoch=-1, verbose=False):
        assert T_mult == 1, "T_mult != 1 not supported in compiled mode"
        self.T_0 = T_0
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        s = jnp.mod(step.astype(jnp.float32), self.T_0)
        return self.eta_min + (self.base_lr - self.eta_min) * 0.5 * (
            1 + jnp.cos(jnp.pi * s / self.T_0))


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        m = jnp.asarray(self.milestones)
        n = jnp.sum(step >= m).astype(jnp.float32)
        return self.base_lr * self.gamma ** n


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        n = (step // self.step_size).astype(jnp.float32)
        return self.base_lr * self.gamma ** n


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1,
                 verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        return self.base_lr * self.lr_lambda(step)


class MultiplicativeDecay(LRScheduler):
    """lr = base_lr * prod_{i=1..step} lambda(i) — cumulative, host-tracked
    (the product over a traced step count is not expressible in one closed
    form for arbitrary lambdas, so this scheduler is stateful like
    ReduceOnPlateau; value_at returns the current host value)."""

    def __init__(self, learning_rate, lr_lambda, last_epoch=-1,
                 verbose=False):
        self.lr_lambda = lr_lambda
        self.current_factor = 1.0
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        return jnp.asarray(self.base_lr * self.current_factor, jnp.float32)

    def step(self, epoch=None):
        prev = getattr(self, "last_epoch", -1)
        self.last_epoch = epoch if epoch is not None else prev + 1
        if self.last_epoch > 0:
            self.current_factor *= float(self.lr_lambda(self.last_epoch))

    def state_dict(self):
        return {"last_epoch": self.last_epoch,
                "current_factor": self.current_factor}

    def set_state_dict(self, d):
        self.last_epoch = d["last_epoch"]
        self.current_factor = d.get("current_factor", 1.0)


class ReduceOnPlateau(LRScheduler):
    """Metric-driven (host-side) scheduler — inherently eager; value_at
    returns the current host value (ref: lr.py ReduceOnPlateau)."""

    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self.current_lr = learning_rate
        super().__init__(learning_rate, -1, verbose)

    def value_at(self, step):
        return jnp.asarray(self.current_lr, jnp.float32)

    def step(self, metrics=None, epoch=None):
        self.last_epoch += 1
        if metrics is None:
            return
        m = float(metrics)
        better = (self.best is None or
                  (self.mode == "min" and m < self.best - self.threshold) or
                  (self.mode == "max" and m > self.best + self.threshold))
        if better:
            self.best = m
            self.num_bad = 0
        elif self.cooldown_counter > 0:
            self.cooldown_counter -= 1
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                self.current_lr = max(self.current_lr * self.factor,
                                      self.min_lr)
                self.cooldown_counter = self.cooldown
                self.num_bad = 0


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3,
                 anneal_strategy="cos", three_phase=False, last_epoch=-1,
                 verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        super().__init__(max_learning_rate, last_epoch, verbose)

    def value_at(self, step):
        s = step.astype(jnp.float32)
        up = self.phase_pct * self.total_steps
        down = self.total_steps - up

        def cos_interp(a, b, pct):
            return b + (a - b) * 0.5 * (1 + jnp.cos(jnp.pi * pct))

        pct_up = jnp.clip(s / jnp.maximum(up, 1.0), 0.0, 1.0)
        pct_down = jnp.clip((s - up) / jnp.maximum(down, 1.0), 0.0, 1.0)
        lr_up = cos_interp(self.initial_lr, self.max_lr, pct_up)
        lr_down = cos_interp(self.max_lr, self.end_lr, pct_down)
        return jnp.where(s < up, lr_up, lr_down)


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate,
                 step_size_up=2000, step_size_down=None, mode="triangular",
                 exp_gamma=1.0, scale_fn=None, scale_mode="cycle",
                 last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.step_up = step_size_up
        self.step_down = step_size_down or step_size_up
        self.mode = mode
        self.exp_gamma = exp_gamma
        super().__init__(base_learning_rate, last_epoch, verbose)

    def value_at(self, step):
        s = step.astype(jnp.float32)
        total = self.step_up + self.step_down
        cycle = jnp.floor(1 + s / total)
        pos = s - (cycle - 1) * total
        frac = jnp.where(pos < self.step_up, pos / self.step_up,
                         1 - (pos - self.step_up) / self.step_down)
        amp = self.max_lr - self.base_lr
        if self.mode == "triangular2":
            amp = amp / (2.0 ** (cycle - 1))
        elif self.mode == "exp_range":
            amp = amp * self.exp_gamma ** s
        return self.base_lr + amp * frac
