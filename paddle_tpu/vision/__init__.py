"""Vision domain (ref: python/paddle/vision/ — datasets, transforms, 12
model families). Models land incrementally in paddle_tpu.vision.models."""

from paddle_tpu.vision import transforms
from paddle_tpu.vision import datasets
from paddle_tpu.vision import models
from paddle_tpu.vision import ops

__all__ = ["transforms", "datasets", "models", "ops"]
