"""Vision domain (ref: python/paddle/vision/ — datasets, transforms, 12
model families). Models land incrementally in paddle_tpu.vision.models."""

from paddle_tpu.vision import transforms
from paddle_tpu.vision import datasets
from paddle_tpu.vision import models
from paddle_tpu.vision import ops

__all__ = ["transforms", "datasets", "models", "ops"]


# -- image backend (ref: paddle.vision.image — get/set_image_backend,
# image_load; 'pil' is the only wired backend in this zero-CV image) -------

_image_backend = "pil"


def set_image_backend(backend: str):
    """ref: vision/image.py set_image_backend ('pil' or 'cv2')."""
    global _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(f"backend must be 'pil' or 'cv2', got {backend!r}")
    if backend == "cv2":
        raise ValueError("cv2 is not available in this environment; "
                         "the 'pil' backend is wired")
    _image_backend = backend


def get_image_backend() -> str:
    """ref: vision/image.py get_image_backend."""
    return _image_backend


def image_load(path, backend=None):
    """ref: vision/image.py image_load — PIL.Image."""
    from PIL import Image
    backend = backend or _image_backend
    if backend != "pil":
        raise ValueError(f"unsupported backend {backend!r}")
    return Image.open(path)


__all__ += ["set_image_backend", "get_image_backend", "image_load"]
