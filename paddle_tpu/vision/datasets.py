"""Vision datasets (ref: python/paddle/vision/datasets/ — MNIST, Cifar,
Flowers, VOC…). Network download is environment-gated; synthetic fallbacks
keep tests/benchmarks hermetic."""

import gzip
import os
import struct

import numpy as np

from paddle_tpu.io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "SyntheticImages"]


class SyntheticImages(Dataset):
    """Deterministic fake image classification data (for tests/benches)."""

    def __init__(self, num_samples=256, image_shape=(1, 28, 28),
                 num_classes=10, seed=0, transform=None):
        self.n = num_samples
        rng = np.random.RandomState(seed)
        self.images = rng.randn(num_samples, *image_shape).astype(np.float32)
        self.labels = rng.randint(0, num_classes,
                                  num_samples).astype(np.int64)
        # plant a learnable signal: mean offset per class
        for i in range(num_samples):
            self.images[i] += 0.5 * self.labels[i] / num_classes
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return self.n


class MNIST(Dataset):
    """ref: paddle.vision.datasets.MNIST. Reads IDX files from
    ``data_dir``; falls back to synthetic data when files are absent
    (zero-egress environments)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None,
                 data_dir=None):
        self.transform = transform
        data_dir = data_dir or os.environ.get("PT_DATA_DIR", "")
        img_f = image_path or os.path.join(
            data_dir, f"{'train' if mode == 'train' else 't10k'}-images-idx3-ubyte.gz")
        lbl_f = label_path or os.path.join(
            data_dir, f"{'train' if mode == 'train' else 't10k'}-labels-idx1-ubyte.gz")
        if os.path.exists(img_f) and os.path.exists(lbl_f):
            self.images = self._read_images(img_f)
            self.labels = self._read_labels(lbl_f)
        else:
            synth = SyntheticImages(4096 if mode == "train" else 512,
                                    (28, 28), 10,
                                    seed=0 if mode == "train" else 1)
            self.images = (synth.images * 64 + 128).clip(0, 255).astype(
                np.uint8)
            self.labels = synth.labels

    @staticmethod
    def _read_images(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)

    @staticmethod
    def _read_labels(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 255.0
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.transform = transform
        synth = SyntheticImages(4096 if mode == "train" else 512,
                                (3, 32, 32), self.NUM_CLASSES,
                                seed=2 if mode == "train" else 3)
        self.images = synth.images
        self.labels = synth.labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar10(_CifarBase):
    NUM_CLASSES = 10


class Cifar100(_CifarBase):
    NUM_CLASSES = 100


_IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".ppm", ".webp",
                   ".npy")


def _scan_files(root, exts, is_valid_file=None):
    ok = is_valid_file or (lambda p: p.lower().endswith(exts))
    out = []
    for dirpath, _, files in sorted(os.walk(root)):
        for f in sorted(files):
            path = os.path.join(dirpath, f)
            if ok(path):
                out.append(path)
    return out


def _default_loader(path):
    """PIL image → HWC uint8 ndarray (.npy files load directly)."""
    if path.lower().endswith(".npy"):
        return np.load(path)
    from PIL import Image
    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"))


class DatasetFolder(Dataset):
    """Directory-per-class dataset (ref: vision/datasets/folder.py
    DatasetFolder): ``root/class_x/xxx.png`` → (image, class_index).
    Classes are the sorted subdirectory names."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or _IMG_EXTENSIONS))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for path in _scan_files(os.path.join(root, c), exts,
                                    is_valid_file):
                self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(
                f"no files with extensions {exts} under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat image folder without labels (ref vision/datasets/folder.py
    ImageFolder): root may contain files directly; returns images only.
    (Not a DatasetFolder subclass: there are no classes/class_to_idx.)"""

    def __init__(self, root, loader=None, extensions=None, transform=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or _IMG_EXTENSIONS))
        self.samples = _scan_files(root, exts)
        if not self.samples:
            raise RuntimeError(f"no images under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform:
            img = self.transform(img)
        return (img,)

    def __len__(self):
        return len(self.samples)


class ImageNet(DatasetFolder):
    """ImageNet layout: ``root/{train,val}/nXXXXXXXX/*.JPEG`` (ref
    vision/datasets; the reference delegates download to the user too —
    the tarballs require manual acquisition)."""

    def __init__(self, root, mode="train", transform=None):
        super().__init__(os.path.join(root, mode), transform=transform)


__all__ += ["DatasetFolder", "ImageFolder", "ImageNet"]
