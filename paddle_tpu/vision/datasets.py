"""Vision datasets (ref: python/paddle/vision/datasets/ — MNIST, Cifar,
Flowers, VOC…). Network download is environment-gated; synthetic fallbacks
keep tests/benchmarks hermetic."""

import gzip
import os
import struct

import numpy as np

from paddle_tpu.io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "SyntheticImages"]


class SyntheticImages(Dataset):
    """Deterministic fake image classification data (for tests/benches)."""

    def __init__(self, num_samples=256, image_shape=(1, 28, 28),
                 num_classes=10, seed=0, transform=None):
        self.n = num_samples
        rng = np.random.RandomState(seed)
        self.images = rng.randn(num_samples, *image_shape).astype(np.float32)
        self.labels = rng.randint(0, num_classes,
                                  num_samples).astype(np.int64)
        # plant a learnable signal: mean offset per class
        for i in range(num_samples):
            self.images[i] += 0.5 * self.labels[i] / num_classes
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return self.n


class MNIST(Dataset):
    """ref: paddle.vision.datasets.MNIST. Reads IDX files from
    ``data_dir``; falls back to synthetic data when files are absent
    (zero-egress environments)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None,
                 data_dir=None):
        self.transform = transform
        data_dir = data_dir or os.environ.get("PT_DATA_DIR", "")
        img_f = image_path or os.path.join(
            data_dir, f"{'train' if mode == 'train' else 't10k'}-images-idx3-ubyte.gz")
        lbl_f = label_path or os.path.join(
            data_dir, f"{'train' if mode == 'train' else 't10k'}-labels-idx1-ubyte.gz")
        if os.path.exists(img_f) and os.path.exists(lbl_f):
            self.images = self._read_images(img_f)
            self.labels = self._read_labels(lbl_f)
        else:
            synth = SyntheticImages(4096 if mode == "train" else 512,
                                    (28, 28), 10,
                                    seed=0 if mode == "train" else 1)
            self.images = (synth.images * 64 + 128).clip(0, 255).astype(
                np.uint8)
            self.labels = synth.labels

    @staticmethod
    def _read_images(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)

    @staticmethod
    def _read_labels(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 255.0
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.transform = transform
        synth = SyntheticImages(4096 if mode == "train" else 512,
                                (3, 32, 32), self.NUM_CLASSES,
                                seed=2 if mode == "train" else 3)
        self.images = synth.images
        self.labels = synth.labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar10(_CifarBase):
    NUM_CLASSES = 10


class Cifar100(_CifarBase):
    NUM_CLASSES = 100
