"""SqueezeNet (ref: python/paddle/vision/models/squeezenet.py)."""

import jax.numpy as jnp

import paddle_tpu.nn as nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class Fire(nn.Module):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return jnp.concatenate([self.relu(self.expand1(x)),
                                self.relu(self.expand3(x))], axis=1)


class SqueezeNet(nn.Module):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.stem = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2))
            cfg = [(96, 16, 64, 64), (128, 16, 64, 64), (128, 32, 128, 128),
                   "pool", (256, 32, 128, 128), (256, 48, 192, 192),
                   (384, 48, 192, 192), (384, 64, 256, 256), "pool",
                   (512, 64, 256, 256)]
        else:
            self.stem = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2))
            cfg = [(64, 16, 64, 64), (128, 16, 64, 64), "pool",
                   (128, 32, 128, 128), (256, 32, 128, 128), "pool",
                   (256, 48, 192, 192), (384, 48, 192, 192),
                   (384, 64, 256, 256), (512, 64, 256, 256)]
        mods = []
        for c in cfg:
            if c == "pool":
                mods.append(nn.MaxPool2D(3, stride=2))
            else:
                mods.append(Fire(*c))
        self.features = nn.Sequential(*mods)
        if num_classes > 0:
            self.classifier = nn.Conv2D(512, num_classes, 1)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.features(self.stem(x))
        if self.num_classes > 0:
            x = self.relu(self.classifier(x))
        if self.with_pool:
            x = self.pool(x)
            return x.reshape(x.shape[0], -1)
        return x


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)
