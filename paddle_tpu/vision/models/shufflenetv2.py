"""ShuffleNetV2 (ref: python/paddle/vision/models/shufflenetv2.py)."""

import jax.numpy as jnp

import paddle_tpu.nn as nn

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x1_0"]


def channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = x.reshape(b, groups, c // groups, h, w)
    x = jnp.swapaxes(x, 1, 2)
    return x.reshape(b, c, h, w)


from paddle_tpu.vision.models._utils import conv_bn_act


def _cba(in_c, out_c, k, s=1, groups=1, act=True):
    return conv_bn_act(in_c, out_c, k, s=s, groups=groups,
                       act="relu" if act else None)


class InvertedResidual(nn.Module):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.stride = stride
        branch = out_c // 2
        if stride == 2:
            self.b1 = nn.Sequential(
                _cba(in_c, in_c, 3, s=2, groups=in_c, act=False),
                _cba(in_c, branch, 1))
            self.b2 = nn.Sequential(
                _cba(in_c, branch, 1),
                _cba(branch, branch, 3, s=2, groups=branch, act=False),
                _cba(branch, branch, 1))
        else:
            self.b1 = None
            self.b2 = nn.Sequential(
                _cba(branch, branch, 1),
                _cba(branch, branch, 3, groups=branch, act=False),
                _cba(branch, branch, 1))

    def forward(self, x):
        if self.stride == 2:
            out = jnp.concatenate([self.b1(x), self.b2(x)], axis=1)
        else:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = jnp.concatenate([x1, self.b2(x2)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Module):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        chans = {0.25: (24, 24, 48, 96, 512),
                 0.5: (24, 48, 96, 192, 1024),
                 1.0: (24, 116, 232, 464, 1024),
                 1.5: (24, 176, 352, 704, 1024),
                 2.0: (24, 244, 488, 976, 2048)}[scale]
        repeats = (4, 8, 4)
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(_cba(3, chans[0], 3, s=2),
                                  nn.MaxPool2D(3, stride=2, padding=1))
        stages = []
        in_c = chans[0]
        for out_c, n in zip(chans[1:4], repeats):
            blocks = [InvertedResidual(in_c, out_c, 2)]
            for _ in range(n - 1):
                blocks.append(InvertedResidual(out_c, out_c, 1))
            stages.append(nn.Sequential(*blocks))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.tail = _cba(in_c, chans[4], 1)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(chans[4], num_classes)

    def forward(self, x):
        x = self.tail(self.stages(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape(x.shape[0], -1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)
