"""Shared building blocks for the vision model zoo."""

import paddle_tpu.nn as nn

__all__ = ["conv_bn_act"]


def conv_bn_act(in_c, out_c, k, s=1, p=None, groups=1, act="relu"):
    """Conv2D + BatchNorm2D + optional activation. ``p=None`` derives
    same-ish padding from the kernel (k//2 per dim, tuple kernels
    included); ``act`` is "relu", "silu", "hardswish", or None/False."""
    if p is None:
        p = tuple(kk // 2 for kk in k) if isinstance(k, tuple) else k // 2
    mods = [nn.Conv2D(in_c, out_c, k, stride=s, padding=p, groups=groups,
                      bias_attr=False),
            nn.BatchNorm2D(out_c)]
    if act == "relu":
        mods.append(nn.ReLU())
    elif act == "silu":
        mods.append(nn.Silu())
    elif act == "hardswish":
        mods.append(nn.Hardswish())
    elif act:
        raise ValueError(f"unknown act {act!r}")
    return nn.Sequential(*mods)
