"""Inception v3 (ref: python/paddle/vision/models/inceptionv3.py).
Compact faithful variant: A/B/C inception blocks with factorized convs."""

import jax.numpy as jnp

import paddle_tpu.nn as nn
from paddle_tpu.vision.models._utils import conv_bn_act as _cba

__all__ = ["InceptionV3", "inception_v3"]


class InceptionA(nn.Module):
    def __init__(self, in_c, pool_c):
        super().__init__()
        self.b1 = _cba(in_c, 64, 1)
        self.b5 = nn.Sequential(_cba(in_c, 48, 1), _cba(48, 64, 5, p=2))
        self.b3 = nn.Sequential(_cba(in_c, 64, 1), _cba(64, 96, 3, p=1),
                                _cba(96, 96, 3, p=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _cba(in_c, pool_c, 1))

    def forward(self, x):
        return jnp.concatenate([self.b1(x), self.b5(x), self.b3(x),
                                self.bp(x)], axis=1)


class InceptionB(nn.Module):
    """7x1/1x7 factorized block."""

    def __init__(self, in_c, mid):
        super().__init__()
        self.b1 = _cba(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _cba(in_c, mid, 1), _cba(mid, mid, (1, 7), p=None),
            _cba(mid, 192, (7, 1), p=None))
        self.b77 = nn.Sequential(
            _cba(in_c, mid, 1), _cba(mid, mid, (7, 1), p=None),
            _cba(mid, mid, (1, 7), p=None),
            _cba(mid, mid, (7, 1), p=None),
            _cba(mid, 192, (1, 7), p=None))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _cba(in_c, 192, 1))

    def forward(self, x):
        return jnp.concatenate([self.b1(x), self.b7(x), self.b77(x),
                                self.bp(x)], axis=1)


class Reduction(nn.Module):
    def __init__(self, in_c, c3, cd):
        super().__init__()
        self.b3 = _cba(in_c, c3, 3, s=2, p=0)
        self.bd = nn.Sequential(_cba(in_c, cd, 1), _cba(cd, cd, 3, p=1),
                                _cba(cd, cd, 3, s=2, p=0))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return jnp.concatenate([self.b3(x), self.bd(x), self.pool(x)],
                               axis=1)


class InceptionV3(nn.Module):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _cba(3, 32, 3, s=2, p=0), _cba(32, 32, 3, p=0),
            _cba(32, 64, 3, p=1),
            nn.MaxPool2D(3, stride=2), _cba(64, 80, 1),
            _cba(80, 192, 3, p=0), nn.MaxPool2D(3, stride=2))
        self.a1 = InceptionA(192, 32)    # → 64+64+96+32 = 256
        self.a2 = InceptionA(256, 64)    # → 64+64+96+64 = 288
        self.red1 = Reduction(288, 384, 96)
        in_b = 288 + 384 + 96            # = 768
        self.b1 = InceptionB(in_b, 128)
        self.b2 = InceptionB(768, 160)
        self.red2 = Reduction(768, 320, 192)
        c_final = 768 + 320 + 192
        self.c_out = c_final
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c_final, num_classes)

    def forward(self, x):
        x = self.a2(self.a1(self.stem(x)))
        x = self.b2(self.b1(self.red1(x)))
        x = self.red2(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape(x.shape[0], -1))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)
