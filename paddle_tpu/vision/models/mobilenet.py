"""MobileNet v1/v2 (ref: python/paddle/vision/models/mobilenet{v1,v2}.py)."""

import paddle_tpu.nn as nn

__all__ = ["MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2"]


def _conv_bn(in_c, out_c, k, s=1, p=0, groups=1, act=True):
    layers = [nn.Conv2D(in_c, out_c, k, stride=s, padding=p, groups=groups,
                        bias_attr=False),
              nn.BatchNorm2D(out_c)]
    if act:
        layers.append(nn.ReLU6())
    return nn.Sequential(*layers)


class MobileNetV1(nn.Module):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
            [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_conv_bn(3, c(32), 3, 2, 1)]
        for in_c, out_c, s in cfg:
            layers.append(_conv_bn(c(in_c), c(in_c), 3, s, 1,
                                   groups=c(in_c)))
            layers.append(_conv_bn(c(in_c), c(out_c), 1))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from paddle_tpu.tensor.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


class _InvertedResidual(nn.Module):
    def __init__(self, in_c, out_c, stride, expand):
        super().__init__()
        hidden = int(round(in_c * expand))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand != 1:
            layers.append(_conv_bn(in_c, hidden, 1))
        layers += [_conv_bn(hidden, hidden, 3, stride, 1, groups=hidden),
                   _conv_bn(hidden, out_c, 1, act=False)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Module):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = c(32)
        layers = [_conv_bn(3, in_c, 3, 2, 1)]
        for t, ch, n, s in cfg:
            out_c = c(ch)
            for i in range(n):
                layers.append(_InvertedResidual(in_c, out_c,
                                                s if i == 0 else 1, t))
                in_c = out_c
        self.last_c = c(1280)
        layers.append(_conv_bn(in_c, self.last_c, 1))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(self.last_c,
                                                      num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from paddle_tpu.tensor.manipulation import flatten
            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


class _SE(nn.Module):
    """Squeeze-excite (MobileNetV3; ref mobilenetv3.py SEModule)."""

    def __init__(self, c, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(c, c // r, 1)
        self.fc2 = nn.Conv2D(c // r, c, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _V3Block(nn.Module):
    def __init__(self, in_c, exp, out_c, k, s, se, act):
        super().__init__()
        from paddle_tpu.vision.models._utils import conv_bn_act
        a = "hardswish" if act == "HS" else "relu"
        self.use_res = s == 1 and in_c == out_c
        mods = []
        if exp != in_c:
            mods.append(conv_bn_act(in_c, exp, 1, act=a))
        mods.append(conv_bn_act(exp, exp, k, s=s, groups=exp, act=a))
        if se:
            mods.append(_SE(exp))
        mods.append(conv_bn_act(exp, out_c, 1, act=None))
        self.body = nn.Sequential(*mods)

    def forward(self, x):
        out = self.body(x)
        return x + out if self.use_res else out


class _MobileNetV3(nn.Module):
    def __init__(self, cfg, last_exp, last_c, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        from paddle_tpu.vision.models._utils import conv_bn_act
        self.stem = conv_bn_act(3, 16, 3, s=2, act="hardswish")
        blocks = []
        in_c = 16
        for k, exp, out_c, se, act, s in cfg:
            blocks.append(_V3Block(in_c, exp, out_c, k, s, se, act))
            in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        self.tail = conv_bn_act(in_c, last_exp, 1, act="hardswish")
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.head = nn.Sequential(nn.Linear(last_exp, last_c),
                                      nn.Hardswish(),
                                      nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.tail(self.blocks(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.head(x.reshape(x.shape[0], -1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, num_classes=1000, with_pool=True):
        cfg = [  # k, exp, out, SE, act, stride (ref mobilenetv3.py)
            (3, 16, 16, True, "RE", 2), (3, 72, 24, False, "RE", 2),
            (3, 88, 24, False, "RE", 1), (5, 96, 40, True, "HS", 2),
            (5, 240, 40, True, "HS", 1), (5, 240, 40, True, "HS", 1),
            (5, 120, 48, True, "HS", 1), (5, 144, 48, True, "HS", 1),
            (5, 288, 96, True, "HS", 2), (5, 576, 96, True, "HS", 1),
            (5, 576, 96, True, "HS", 1)]
        super().__init__(cfg, 576, 1024, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, num_classes=1000, with_pool=True):
        cfg = [
            (3, 16, 16, False, "RE", 1), (3, 64, 24, False, "RE", 2),
            (3, 72, 24, False, "RE", 1), (5, 72, 40, True, "RE", 2),
            (5, 120, 40, True, "RE", 1), (5, 120, 40, True, "RE", 1),
            (3, 240, 80, False, "HS", 2), (3, 200, 80, False, "HS", 1),
            (3, 184, 80, False, "HS", 1), (3, 184, 80, False, "HS", 1),
            (3, 480, 112, True, "HS", 1), (3, 672, 112, True, "HS", 1),
            (5, 672, 160, True, "HS", 2), (5, 960, 160, True, "HS", 1),
            (5, 960, 160, True, "HS", 1)]
        super().__init__(cfg, 960, 1280, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, **kwargs):
    return MobileNetV3Small(**kwargs)


def mobilenet_v3_large(pretrained=False, **kwargs):
    return MobileNetV3Large(**kwargs)


__all__ += ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
            "mobilenet_v3_large"]
