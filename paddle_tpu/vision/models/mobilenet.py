"""MobileNet v1/v2 (ref: python/paddle/vision/models/mobilenet{v1,v2}.py)."""

import paddle_tpu.nn as nn

__all__ = ["MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2"]


def _conv_bn(in_c, out_c, k, s=1, p=0, groups=1, act=True):
    layers = [nn.Conv2D(in_c, out_c, k, stride=s, padding=p, groups=groups,
                        bias_attr=False),
              nn.BatchNorm2D(out_c)]
    if act:
        layers.append(nn.ReLU6())
    return nn.Sequential(*layers)


class MobileNetV1(nn.Module):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
            [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_conv_bn(3, c(32), 3, 2, 1)]
        for in_c, out_c, s in cfg:
            layers.append(_conv_bn(c(in_c), c(in_c), 3, s, 1,
                                   groups=c(in_c)))
            layers.append(_conv_bn(c(in_c), c(out_c), 1))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from paddle_tpu.tensor.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


class _InvertedResidual(nn.Module):
    def __init__(self, in_c, out_c, stride, expand):
        super().__init__()
        hidden = int(round(in_c * expand))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand != 1:
            layers.append(_conv_bn(in_c, hidden, 1))
        layers += [_conv_bn(hidden, hidden, 3, stride, 1, groups=hidden),
                   _conv_bn(hidden, out_c, 1, act=False)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Module):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = c(32)
        layers = [_conv_bn(3, in_c, 3, 2, 1)]
        for t, ch, n, s in cfg:
            out_c = c(ch)
            for i in range(n):
                layers.append(_InvertedResidual(in_c, out_c,
                                                s if i == 0 else 1, t))
                in_c = out_c
        self.last_c = c(1280)
        layers.append(_conv_bn(in_c, self.last_c, 1))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(self.last_c,
                                                      num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from paddle_tpu.tensor.manipulation import flatten
            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
