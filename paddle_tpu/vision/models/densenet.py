"""DenseNet (ref: python/paddle/vision/models/densenet.py)."""

import jax.numpy as jnp

import paddle_tpu.nn as nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet201"]


class DenseLayer(nn.Module):
    def __init__(self, in_c, growth, bn_size):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.relu = nn.ReLU()

    def forward(self, x):
        y = self.conv1(self.relu(self.bn1(x)))
        y = self.conv2(self.relu(self.bn2(y)))
        return jnp.concatenate([x, y], axis=1)


class Transition(nn.Module):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.relu = nn.ReLU()
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Module):
    def __init__(self, layers=121, growth_rate=None, bn_size=4,
                 num_classes=1000, with_pool=True):
        super().__init__()
        cfg = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
               169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
               264: (6, 12, 64, 48)}[layers]
        # 161 defaults to the wider (48, 96) stem ONLY when the caller
        # did not choose a growth rate
        if growth_rate is None:
            growth_rate = 48 if layers == 161 else 32
        init_c = 96 if layers == 161 else 64
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(init_c), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        blocks = []
        c = init_c
        for i, n in enumerate(cfg):
            for _ in range(n):
                blocks.append(DenseLayer(c, growth_rate, bn_size))
                c += growth_rate
            if i != len(cfg) - 1:
                blocks.append(Transition(c, c // 2))
                c //= 2
        self.blocks = nn.Sequential(*blocks)
        self.bn_final = nn.BatchNorm2D(c)
        self.relu = nn.ReLU()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.relu(self.bn_final(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape(x.shape[0], -1))
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(161, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(201, **kwargs)
