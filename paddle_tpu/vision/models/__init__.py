"""Vision model zoo (ref: python/paddle/vision/models/ — 12 families)."""

from paddle_tpu.vision.models.lenet import LeNet
from paddle_tpu.vision.models.resnet import (ResNet, resnet18, resnet34,
                                             resnet50, resnet101, resnet152,
                                             BasicBlock, BottleneckBlock)
from paddle_tpu.vision.models.vgg import VGG, vgg11, vgg13, vgg16, vgg19
from paddle_tpu.vision.models.mobilenet import (MobileNetV1, MobileNetV2,
                                                mobilenet_v1, mobilenet_v2)
from paddle_tpu.vision.models.alexnet import AlexNet, alexnet

__all__ = ["LeNet", "ResNet", "resnet18", "resnet34", "resnet50",
           "resnet101", "resnet152", "BasicBlock", "BottleneckBlock", "VGG",
           "vgg11", "vgg13", "vgg16", "vgg19", "MobileNetV1", "MobileNetV2",
           "mobilenet_v1", "mobilenet_v2", "AlexNet", "alexnet"]
