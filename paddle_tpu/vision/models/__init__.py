"""Vision model zoo (ref: python/paddle/vision/models/ — 12 families)."""

from paddle_tpu.vision.models.lenet import LeNet
from paddle_tpu.vision.models.resnet import (ResNet, resnet18, resnet34,
                                             resnet50, resnet101, resnet152,
                                             BasicBlock, BottleneckBlock)
from paddle_tpu.vision.models.vgg import VGG, vgg11, vgg13, vgg16, vgg19
from paddle_tpu.vision.models.mobilenet import (MobileNetV1, MobileNetV2,
                                                MobileNetV3Small,
                                                MobileNetV3Large,
                                                mobilenet_v1, mobilenet_v2,
                                                mobilenet_v3_small,
                                                mobilenet_v3_large)
from paddle_tpu.vision.models.alexnet import AlexNet, alexnet
from paddle_tpu.vision.models.squeezenet import SqueezeNet, squeezenet1_0, \
    squeezenet1_1
from paddle_tpu.vision.models.shufflenetv2 import ShuffleNetV2, \
    shufflenet_v2_x0_25, shufflenet_v2_x1_0
from paddle_tpu.vision.models.densenet import DenseNet, densenet121, \
    densenet161, densenet201
from paddle_tpu.vision.models.googlenet import GoogLeNet, googlenet
from paddle_tpu.vision.models.inceptionv3 import InceptionV3, inception_v3
from paddle_tpu.vision.models.ppyoloe import PPYOLOE, ppyoloe_s

__all__ = ["LeNet", "ResNet", "resnet18", "resnet34", "resnet50",
           "resnet101", "resnet152", "BasicBlock", "BottleneckBlock", "VGG",
           "vgg11", "vgg13", "vgg16", "vgg19", "MobileNetV1", "MobileNetV2",
           "MobileNetV3Small", "MobileNetV3Large",
           "mobilenet_v1", "mobilenet_v2", "mobilenet_v3_small",
           "mobilenet_v3_large", "AlexNet", "alexnet",
           "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
           "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x1_0",
           "DenseNet", "densenet121", "densenet161", "densenet201",
           "GoogLeNet", "googlenet", "InceptionV3", "inception_v3",
           "PPYOLOE", "ppyoloe_s"]
