"""GoogLeNet / Inception v1 (ref: python/paddle/vision/models/googlenet.py).
The reference's two auxiliary classifier heads (a training-era vanishing-
gradient workaround predating BatchNorm) are omitted: every conv here is
BN'd, which is the modern replacement for that trick."""

import jax.numpy as jnp

import paddle_tpu.nn as nn
from paddle_tpu.vision.models._utils import conv_bn_act as _cba

__all__ = ["GoogLeNet", "googlenet"]


class Inception(nn.Module):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _cba(in_c, c1, 1)
        self.b2 = nn.Sequential(_cba(in_c, c3r, 1), _cba(c3r, c3, 3, p=1))
        self.b3 = nn.Sequential(_cba(in_c, c5r, 1), _cba(c5r, c5, 5, p=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _cba(in_c, proj, 1))

    def forward(self, x):
        return jnp.concatenate(
            [self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class GoogLeNet(nn.Module):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _cba(3, 64, 7, s=2, p=3), nn.MaxPool2D(3, stride=2, padding=1),
            _cba(64, 64, 1), _cba(64, 192, 3, p=1),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.i3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.pool3(self.i3b(self.i3a(self.stem(x))))
        x = self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(x)))))
        x = self.i5b(self.i5a(self.pool4(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape(x.shape[0], -1))
        return x


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)
