"""PP-YOLOE-style anchor-free detector (BASELINE.md row 5).

Reference analog: the PP-YOLOE family trained with the reference's
detection stack (paddle/fluid/operators/detection/ C++ ops: iou_similarity,
yolo_box, distribute_fpn_proposals, matrix_nms; the Python model lives in
the external PaddleDetection repo). This is the TPU-native re-design:

- backbone: CSPRep-style stages (RepConv 3x3+1x1 branches, fuseable for
  inference) — ≙ CSPRepResNet;
- neck: PAN (top-down FPN + bottom-up augmentation) over strides 8/16/32;
- head: anchor-free, per-cell class logits + Distribution Focal Loss
  regression (discretized l/t/r/b distances) — ≙ ET-head;
- assignment: FCOS-style center-inside-box with per-level scale ranges,
  fully vectorized over padded (B, M, 5) ground-truth arrays so the whole
  train step is ONE static-shape jitted program (the reference assigns
  with dynamic gather/scatter ops; XLA wants masks);
- losses: sigmoid focal (cls) + GIoU + DFL (reg);
- inference: decode + per-image NMS (vision.ops.nms, host side).

Simplifications vs full PP-YOLOE, stated honestly: the task-aligned
(TAL) assigner is replaced by center+scale assignment, and the
varifocal IoU-quality target by a binary focal target. Both affect final
mAP, neither affects the systems surface (shapes, losses, export).
"""

import math
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu.nn as nn
from paddle_tpu.nn import functional as F

__all__ = ["PPYOLOE", "ppyoloe_s", "detection_loss", "decode_predictions",
           "decode_predictions_jit"]

_STRIDES = (8, 16, 32)
# per-level max-side ranges (FCOS scale assignment)
_RANGES = ((0.0, 64.0), (64.0, 128.0), (128.0, 1e8))


class ConvBNAct(nn.Module):
    def __init__(self, in_c, out_c, k=3, s=1, g=1, act=True):
        super().__init__()
        from paddle_tpu.vision.models._utils import conv_bn_act
        self.body = conv_bn_act(in_c, out_c, k, s=s, groups=g,
                                act="silu" if act else None)

    def forward(self, x):
        return self.body(x)


class RepConv(nn.Module):
    """Training-time 3x3 + 1x1 parallel branches (≙ RepVGG block used by
    CSPRepResNet); inference fusion folds them into one conv."""

    def __init__(self, in_c, out_c):
        super().__init__()
        self.conv3 = ConvBNAct(in_c, out_c, 3, act=False)
        self.conv1 = ConvBNAct(in_c, out_c, 1, act=False)

    def forward(self, x):
        return F.silu(self.conv3(x) + self.conv1(x))


class CSPStage(nn.Module):
    def __init__(self, in_c, out_c, n_blocks):
        super().__init__()
        mid = out_c // 2
        self.down = ConvBNAct(in_c, out_c, 3, s=2)
        self.split1 = ConvBNAct(out_c, mid, 1)
        self.split2 = ConvBNAct(out_c, mid, 1)
        self.blocks = nn.Sequential(*[RepConv(mid, mid)
                                      for _ in range(n_blocks)])
        self.merge = ConvBNAct(2 * mid, out_c, 1)

    def forward(self, x):
        x = self.down(x)
        a = self.split1(x)
        b = self.blocks(self.split2(x))
        return self.merge(jnp.concatenate([a, b], axis=1))


class PAN(nn.Module):
    """Top-down + bottom-up feature pyramid (≙ PP-YOLOE CustomCSPPAN)."""

    def __init__(self, chans: Sequence[int], out_c: int):
        super().__init__()
        c3, c4, c5 = chans
        self.lat5 = ConvBNAct(c5, out_c, 1)
        self.lat4 = ConvBNAct(c4, out_c, 1)
        self.lat3 = ConvBNAct(c3, out_c, 1)
        self.td4 = RepConv(2 * out_c, out_c)
        self.td3 = RepConv(2 * out_c, out_c)
        self.bu4 = ConvBNAct(out_c, out_c, 3, s=2)
        self.bu5 = ConvBNAct(out_c, out_c, 3, s=2)
        self.out4 = RepConv(2 * out_c, out_c)
        self.out5 = RepConv(2 * out_c, out_c)

    def forward(self, feats):
        f3, f4, f5 = feats
        p5 = self.lat5(f5)
        up5 = jnp.repeat(jnp.repeat(p5, 2, axis=2), 2, axis=3)
        p4 = self.td4(jnp.concatenate([self.lat4(f4), up5], axis=1))
        up4 = jnp.repeat(jnp.repeat(p4, 2, axis=2), 2, axis=3)
        p3 = self.td3(jnp.concatenate([self.lat3(f3), up4], axis=1))
        n4 = self.out4(jnp.concatenate([p4, self.bu4(p3)], axis=1))
        n5 = self.out5(jnp.concatenate([p5, self.bu5(n4)], axis=1))
        return p3, n4, n5


class Head(nn.Module):
    """Anchor-free head: per cell `num_classes` logits + 4*(reg_max+1)
    DFL bins (≙ ET-head minus the attention branch)."""

    def __init__(self, in_c, num_classes, reg_max=16):
        super().__init__()
        self.num_classes = num_classes
        self.reg_max = reg_max
        self.stem_cls = ConvBNAct(in_c, in_c, 3)
        self.stem_reg = ConvBNAct(in_c, in_c, 3)
        self.cls = nn.Conv2D(in_c, num_classes, 1)
        self.reg = nn.Conv2D(in_c, 4 * (reg_max + 1), 1)

    def forward(self, x):
        b = x.shape[0]
        cls = self.cls(self.stem_cls(x))          # (B, C, H, W)
        reg = self.reg(self.stem_reg(x))          # (B, 4*(R+1), H, W)
        hw = cls.shape[2] * cls.shape[3]
        cls = jnp.moveaxis(cls, 1, -1).reshape(b, hw, self.num_classes)
        reg = jnp.moveaxis(reg, 1, -1).reshape(b, hw, 4, self.reg_max + 1)
        return cls, reg


class PPYOLOE(nn.Module):
    def __init__(self, num_classes=80, width=32, depth=1, reg_max=16):
        super().__init__()
        self.num_classes = num_classes
        self.reg_max = reg_max
        w = width
        self.stem = ConvBNAct(3, w, 3, s=2)
        self.stage1 = CSPStage(w, 2 * w, depth)        # stride 4
        self.stage2 = CSPStage(2 * w, 4 * w, depth)    # stride 8  -> f3
        self.stage3 = CSPStage(4 * w, 8 * w, depth)    # stride 16 -> f4
        self.stage4 = CSPStage(8 * w, 16 * w, depth)   # stride 32 -> f5
        self.neck = PAN((4 * w, 8 * w, 16 * w), 4 * w)
        self.heads = nn.LayerList([Head(4 * w, num_classes, reg_max)
                                   for _ in _STRIDES])

    def forward(self, x):
        """x: (B, 3, H, W), H/W divisible by 32. Returns per-level lists
        (cls (B, HW, C), reg (B, HW, 4, R+1)) concatenated over levels,
        plus the anchor centers/strides."""
        x = self.stem(x)
        x = self.stage1(x)
        f3 = self.stage2(x)
        f4 = self.stage3(f3)
        f5 = self.stage4(f4)
        feats = self.neck((f3, f4, f5))
        cls_all, reg_all, centers, strides = [], [], [], []
        for f, head, stride in zip(feats, self.heads, _STRIDES):
            cls, reg = head(f)
            h, w = f.shape[2], f.shape[3]
            yy, xx = jnp.meshgrid(jnp.arange(h), jnp.arange(w),
                                  indexing="ij")
            ctr = (jnp.stack([xx, yy], -1).reshape(-1, 2) + 0.5) * stride
            cls_all.append(cls)
            reg_all.append(reg)
            centers.append(ctr.astype(jnp.float32))
            strides.append(jnp.full((h * w,), stride, jnp.float32))
        return (jnp.concatenate(cls_all, 1), jnp.concatenate(reg_all, 1),
                jnp.concatenate(centers, 0), jnp.concatenate(strides, 0))


def _dfl_decode(reg, strides):
    """(.., A, 4, R+1) bin logits → (.., A, 4) l/t/r/b pixel distances."""
    r = reg.shape[-1]
    proj = jnp.arange(r, dtype=jnp.float32)
    dist = jnp.einsum("...r,r->...", jax.nn.softmax(reg, -1), proj)
    return dist * strides[..., None]


def _boxes_from_dist(centers, dist):
    x1 = centers[..., 0] - dist[..., 0]
    y1 = centers[..., 1] - dist[..., 1]
    x2 = centers[..., 0] + dist[..., 2]
    y2 = centers[..., 1] + dist[..., 3]
    return jnp.stack([x1, y1, x2, y2], -1)


def _giou(a, b):
    """Generalized IoU between (..., 4) xyxy boxes."""
    ix1 = jnp.maximum(a[..., 0], b[..., 0])
    iy1 = jnp.maximum(a[..., 1], b[..., 1])
    ix2 = jnp.minimum(a[..., 2], b[..., 2])
    iy2 = jnp.minimum(a[..., 3], b[..., 3])
    inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
    area_a = jnp.clip(a[..., 2] - a[..., 0], 0) * \
        jnp.clip(a[..., 3] - a[..., 1], 0)
    area_b = jnp.clip(b[..., 2] - b[..., 0], 0) * \
        jnp.clip(b[..., 3] - b[..., 1], 0)
    union = area_a + area_b - inter
    iou = inter / jnp.maximum(union, 1e-9)
    cx1 = jnp.minimum(a[..., 0], b[..., 0])
    cy1 = jnp.minimum(a[..., 1], b[..., 1])
    cx2 = jnp.maximum(a[..., 2], b[..., 2])
    cy2 = jnp.maximum(a[..., 3], b[..., 3])
    hull = jnp.clip(cx2 - cx1, 0) * jnp.clip(cy2 - cy1, 0)
    return iou - (hull - union) / jnp.maximum(hull, 1e-9)


def _assign(centers, strides, gt_boxes, gt_valid):
    """FCOS center+scale assignment, fully masked/static.

    centers (A, 2), gt_boxes (M, 4) xyxy, gt_valid (M,) bool →
    (assigned_gt (A,) int [index into M, or -1], pos (A,) bool).
    Among the gts containing a center (at the right scale level) the
    SMALLEST area wins (FCOS ambiguity rule)."""
    cx = centers[:, 0][:, None]
    cy = centers[:, 1][:, None]
    inside = ((cx >= gt_boxes[None, :, 0]) & (cx <= gt_boxes[None, :, 2])
              & (cy >= gt_boxes[None, :, 1]) & (cy <= gt_boxes[None, :, 3]))
    side = jnp.maximum(gt_boxes[:, 2] - gt_boxes[:, 0],
                       gt_boxes[:, 3] - gt_boxes[:, 1])
    lo = jnp.zeros_like(strides)
    hi = jnp.zeros_like(strides)
    for s, (a, b) in zip(_STRIDES, _RANGES):
        sel = strides == s
        lo = jnp.where(sel, a, lo)
        hi = jnp.where(sel, b, hi)
    scale_ok = (side[None, :] >= lo[:, None]) & (side[None, :] < hi[:, None])
    cand = inside & scale_ok & gt_valid[None, :]
    area = (gt_boxes[:, 2] - gt_boxes[:, 0]) * \
        (gt_boxes[:, 3] - gt_boxes[:, 1])
    cost = jnp.where(cand, area[None, :], jnp.inf)
    assigned = jnp.argmin(cost, axis=1)
    pos = jnp.isfinite(jnp.min(cost, axis=1))
    return jnp.where(pos, assigned, -1), pos


def detection_loss(cls_logits, reg_logits, centers, strides, gt_boxes,
                   gt_labels, gt_valid, num_classes, reg_max=16,
                   focal_gamma=2.0, focal_alpha=0.25):
    """Focal + GIoU + DFL over padded ground truth (B, M, ...)."""
    b = cls_logits.shape[0]
    assigned, pos = jax.vmap(_assign, in_axes=(None, None, 0, 0))(
        centers, strides, gt_boxes, gt_valid)       # (B, A)
    safe = jnp.maximum(assigned, 0)
    tgt_box = jnp.take_along_axis(gt_boxes, safe[..., None], axis=1)
    tgt_cls = jnp.take_along_axis(gt_labels, safe, axis=1)

    # focal classification over all cells
    onehot = jax.nn.one_hot(tgt_cls, num_classes) * pos[..., None]
    p = jax.nn.sigmoid(cls_logits.astype(jnp.float32))
    ce = -(onehot * jnp.log(jnp.clip(p, 1e-7))
           + (1 - onehot) * jnp.log(jnp.clip(1 - p, 1e-7)))
    pt = onehot * p + (1 - onehot) * (1 - p)
    alpha_t = onehot * focal_alpha + (1 - onehot) * (1 - focal_alpha)
    n_pos = jnp.maximum(jnp.sum(pos), 1.0)
    cls_loss = jnp.sum(alpha_t * (1 - pt) ** focal_gamma * ce) / n_pos

    # regression on positive cells
    dist = _dfl_decode(reg_logits.astype(jnp.float32), strides[None])
    pred_box = _boxes_from_dist(centers[None], dist)
    giou_loss = jnp.sum(jnp.where(pos, 1.0 - _giou(pred_box, tgt_box),
                                  0.0)) / n_pos

    # DFL: two-hot CE against the fractional target distance (per side)
    tdist = jnp.stack([
        centers[None, :, 0] - tgt_box[..., 0],
        centers[None, :, 1] - tgt_box[..., 1],
        tgt_box[..., 2] - centers[None, :, 0],
        tgt_box[..., 3] - centers[None, :, 1]], -1) / strides[None, :, None]
    tdist = jnp.clip(tdist, 0.0, reg_max - 0.01)
    lo_bin = jnp.floor(tdist)
    w_hi = tdist - lo_bin
    logp = jax.nn.log_softmax(reg_logits.astype(jnp.float32), -1)
    lo_lp = jnp.take_along_axis(logp, lo_bin.astype(jnp.int32)[..., None],
                                -1)[..., 0]
    hi_lp = jnp.take_along_axis(
        logp, (lo_bin + 1).astype(jnp.int32)[..., None], -1)[..., 0]
    dfl = -((1 - w_hi) * lo_lp + w_hi * hi_lp)
    dfl_loss = jnp.sum(jnp.where(pos[..., None], dfl, 0.0)) / (4 * n_pos)

    return cls_loss + 2.0 * giou_loss + 0.5 * dfl_loss, {
        "cls": cls_loss, "giou": giou_loss, "dfl": dfl_loss,
        "n_pos": n_pos}


def decode_predictions(cls_logits, reg_logits, centers, strides,
                       score_thresh=0.3, iou_thresh=0.5, top_k=100):
    """Host-side inference postprocess: scores + boxes + NMS per image
    (≙ yolo_box + matrix_nms ops). Returns a list of dicts."""
    from paddle_tpu.vision.ops import nms
    p = jax.nn.sigmoid(cls_logits.astype(jnp.float32))
    dist = _dfl_decode(reg_logits.astype(jnp.float32), strides[None])
    boxes = _boxes_from_dist(centers[None], dist)
    out = []
    for bi in range(p.shape[0]):
        scores = np.asarray(jnp.max(p[bi], -1))
        labels = np.asarray(jnp.argmax(p[bi], -1))
        bx = np.asarray(boxes[bi])
        keep = scores >= score_thresh
        bx, scores, labels = bx[keep], scores[keep], labels[keep]
        order = np.argsort(-scores)[:top_k]
        bx, scores, labels = bx[order], scores[order], labels[order]
        if len(bx):
            # per-category NMS (≙ multiclass matrix_nms): boxes only
            # suppress others of the SAME class
            kept = np.asarray(nms(jnp.asarray(bx), iou_thresh,
                                  scores=jnp.asarray(scores),
                                  category_idxs=jnp.asarray(labels),
                                  categories=np.arange(p.shape[-1])))
            bx, scores, labels = bx[kept], scores[kept], labels[kept]
        out.append({"boxes": bx, "scores": scores, "labels": labels})
    return out


def decode_predictions_jit(cls_logits, reg_logits, centers, strides,
                           score_thresh=0.3, post_thresh=0.3, top_k=100,
                           pre_nms: int = 400, use_gaussian=False,
                           gaussian_sigma=2.0):
    """Fully-jittable batched decode + matrix NMS (VERDICT r4 item 7).

    The host path (`decode_predictions`) compacts per image, so eval can
    never compile into one program. This path keeps everything fixed-size
    the matrix-NMS way (≙ paddle/fluid/operators/detection/
    matrix_nms_op.cc): every box's score is DECAYED by its IoU with
    higher-scored same-class boxes instead of being removed, then a
    static top-k picks the survivors.

    Returns (boxes (B, K, 4), scores (B, K), labels (B, K), valid (B, K))
    — invalid slots have score 0. Same semantics as the host path up to
    decay-vs-suppress tolerance: linear decay zeroes an IoU=1 duplicate
    exactly like greedy suppression, partial overlaps keep a decayed
    score the greedy path would drop entirely.
    """
    p = jax.nn.sigmoid(cls_logits.astype(jnp.float32))       # (B, M, C)
    dist = _dfl_decode(reg_logits.astype(jnp.float32), strides[None])
    boxes = _boxes_from_dist(centers[None], dist)            # (B, M, 4)
    m = p.shape[1]
    pre = min(pre_nms, m)

    def one(pi, bx):
        from paddle_tpu.vision.ops import _iou_matrix, matrix_nms_decay
        scores = jnp.max(pi, -1)
        labels = jnp.argmax(pi, -1).astype(jnp.int32)
        scores = jnp.where(scores >= score_thresh, scores, 0.0)
        val, idx = jax.lax.top_k(scores, pre)                # sorted desc
        bsel, lsel = bx[idx], labels[idx]
        iou = _iou_matrix(bsel, bsel)
        same = lsel[:, None] == lsel[None, :]
        final = val * jnp.clip(
            matrix_nms_decay(iou, same, use_gaussian, gaussian_sigma),
            0.0, 1.0)
        final = jnp.where(final > post_thresh, final, 0.0)
        k = min(top_k, pre)
        out_val, oi = jax.lax.top_k(final, k)
        ob, ov, ol = bsel[oi], out_val, lsel[oi]
        if k < top_k:  # honor the documented (B, top_k) contract
            padn = top_k - k
            ob = jnp.pad(ob, ((0, padn), (0, 0)))
            ov = jnp.pad(ov, (0, padn))
            ol = jnp.pad(ol, (0, padn))
        return ob, ov, ol, ov > 0.0

    return jax.vmap(one)(p, boxes)


def ppyoloe_s(num_classes=80, **kwargs):
    return PPYOLOE(num_classes=num_classes, width=32, depth=1, **kwargs)


def build_train_step(model: PPYOLOE, optimizer):
    """One jitted detection train step over padded COCO-shaped batches."""
    def step(params, buffers, opt_state, images, gt_boxes, gt_labels,
             gt_valid, key):
        def loss_fn(p):
            m = model.merge_params({**buffers, **p})
            with nn.stateful(training=True, rng=key) as ctx:
                cls, reg, centers, strides = m(images)
                loss, parts = detection_loss(
                    cls, reg, centers, strides, gt_boxes, gt_labels,
                    gt_valid, model.num_classes, model.reg_max)
            return loss, (ctx.updates, parts)
        (loss, (updates, parts)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_state = optimizer.update(grads, opt_state, params)
        return new_params, new_state, updates, loss, parts

    return jax.jit(step)
