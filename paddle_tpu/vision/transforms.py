"""Image transforms (ref: python/paddle/vision/transforms/) — numpy/host-side
preprocessing feeding the DataLoader."""

import math
import numbers

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "Pad", "RandomResizedCrop", "BrightnessTransform",
           "to_tensor", "normalize", "resize", "hflip", "vflip"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


def to_tensor(img, data_format="CHW"):
    arr = np.asarray(img, np.float32)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.max() > 1.5:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return arr


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW"):
    img = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std, self.data_format = mean, std, data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def _resize_np(img, size):
    """Nearest-neighbor resize without PIL dependency (HWC numpy)."""
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    ridx = (np.arange(oh) * h // oh).astype(int)
    cidx = (np.arange(ow) * w // ow).astype(int)
    return img[ridx][:, cidx]


def resize(img, size, interpolation="bilinear"):
    return _resize_np(np.asarray(img), size)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size

    def __call__(self, img):
        return resize(img, self.size)


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else size

    def __call__(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=None, keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def __call__(self, img):
        img = np.asarray(img)
        if self.padding:
            p = self.padding
            img = np.pad(img, [(p, p), (p, p)] + [(0, 0)] * (img.ndim - 2))
        h, w = img.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                return _resize_np(img[i:i + ch, j:j + cw], self.size)
        return _resize_np(img, self.size)


def hflip(img):
    return np.asarray(img)[:, ::-1]


def vflip(img):
    return np.asarray(img)[::-1]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        return hflip(img) if np.random.rand() < self.prob else np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if np.random.rand() < self.prob else np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding

    def __call__(self, img):
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)
        img = np.asarray(img)
        return np.pad(img, [(p[1], p[3]), (p[0], p[2])]
                      + [(0, 0)] * (img.ndim - 2))


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(img * factor, 0, 255 if img.max() > 1.5 else 1.0)


def _as_float(img):
    img = np.asarray(img, np.float32)
    hi = 255.0 if img.max() > 1.5 else 1.0
    return img, hi


def _chw(img):
    """True if the channel axis is first (C in {1,3} heuristic)."""
    return img.ndim == 3 and img.shape[0] in (1, 3) and img.shape[2] > 3


def _channel_axis(img):
    """Channel axis index, or None for 2-D (already grayscale) images."""
    if img.ndim == 2:
        return None
    return 0 if _chw(img) else -1


class ContrastTransform:
    """ref transforms.py ContrastTransform: blend with the mean."""

    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        img, hi = _as_float(img)
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(img.mean() + f * (img - img.mean()), 0, hi)


class SaturationTransform:
    """Blend with the per-pixel grayscale."""

    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        img, hi = _as_float(img)
        ax = _channel_axis(img)
        if ax is None:
            return img  # grayscale: no chroma to scale
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = img.mean(axis=ax, keepdims=True)
        return np.clip(gray + f * (img - gray), 0, hi)


class HueTransform:
    """Channel-rotation hue shift (cheap HSV-free approximation of ref
    HueTransform; exact for hue steps of 1/3)."""

    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        img, hi = _as_float(img)
        ax = _channel_axis(img)
        if ax is None or img.shape[ax] != 3:
            return img
        shift = np.random.uniform(-self.value, self.value)
        # continuous interpolation between identity and rolled channels
        rolled = np.roll(img, 1 if shift >= 0 else -1, axis=ax)
        w = min(abs(shift) * 3.0, 1.0)
        return np.clip((1 - w) * img + w * rolled, 0, hi)


class ColorJitter:
    """Brightness/contrast/saturation/hue in random order (ref
    transforms.py ColorJitter:959)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def __call__(self, img):
        for i in np.random.permutation(len(self.ts)):
            img = self.ts[i](img)
        return img


class Grayscale:
    def __init__(self, num_output_channels=1, keys=None):
        self.n = num_output_channels

    def __call__(self, img):
        img, _ = _as_float(img)
        ax = _channel_axis(img)
        if ax is None:
            return img if self.n == 1 else np.repeat(
                img[..., None], self.n, axis=-1)
        gray = img.mean(axis=ax, keepdims=True)
        return np.repeat(gray, self.n, axis=ax)


class RandomRotation:
    """Nearest-neighbor rotation about the center (ref RandomRotation)."""

    def __init__(self, degrees, keys=None):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)

    def __call__(self, img):
        img = np.asarray(img)
        chw = _chw(img)
        if chw:
            img = np.moveaxis(img, 0, -1)
        h, w = img.shape[:2]
        theta = math.radians(np.random.uniform(*self.degrees))
        yy, xx = np.mgrid[0:h, 0:w]
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        ys = cy + (yy - cy) * math.cos(theta) - (xx - cx) * math.sin(theta)
        xs = cx + (yy - cy) * math.sin(theta) + (xx - cx) * math.cos(theta)
        yi = np.clip(np.round(ys).astype(int), 0, h - 1)
        xi = np.clip(np.round(xs).astype(int), 0, w - 1)
        inside = (ys >= 0) & (ys <= h - 1) & (xs >= 0) & (xs <= w - 1)
        out = np.where(inside[..., None] if img.ndim == 3 else inside,
                       img[yi, xi], 0)
        if chw:
            out = np.moveaxis(out, -1, 0)
        return out.astype(img.dtype)


class RandomErasing:
    """Cutout-style occlusion (ref transforms.py RandomErasing:1718)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def __call__(self, img):
        img = np.array(img)  # copy: erasing mutates
        if np.random.rand() >= self.prob:
            return img
        chw = _chw(img)
        h, w = (img.shape[1:] if chw else img.shape[:2])
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ratio = math.exp(np.random.uniform(
                math.log(self.ratio[0]), math.log(self.ratio[1])))
            eh = int(round(math.sqrt(target * ratio)))
            ew = int(round(math.sqrt(target / ratio)))
            if eh <= h and ew <= w:
                y = np.random.randint(0, h - eh + 1)
                x = np.random.randint(0, w - ew + 1)
                v = self.value
                if chw and np.ndim(v) == 1:
                    v = np.reshape(v, (-1, 1, 1))  # per-channel fill
                if chw:
                    img[:, y:y + eh, x:x + ew] = v
                else:
                    img[y:y + eh, x:x + ew] = v
                break
        return img


__all__ += ["ContrastTransform", "SaturationTransform", "HueTransform",
            "ColorJitter", "Grayscale", "RandomRotation", "RandomErasing"]
