"""Vision ops (ref: python/paddle/vision/ops.py — yolo_box, nms, roi_align,
deform_conv, DetectionOutput helpers)."""

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["nms", "box_coder", "yolo_box", "roi_align", "distribute_fpn_proposals"]


def _iou_matrix(boxes1, boxes2):
    area1 = (boxes1[:, 2] - boxes1[:, 0]) * (boxes1[:, 3] - boxes1[:, 1])
    area2 = (boxes2[:, 2] - boxes2[:, 0]) * (boxes2[:, 3] - boxes2[:, 1])
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / (area1[:, None] + area2[None, :] - inter + 1e-9)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """ref: paddle.vision.ops.nms. Static-shape greedy NMS via lax loop:
    returns keep mask indices (host-compacted)."""
    boxes = jnp.asarray(boxes)
    n = boxes.shape[0]
    if scores is None:
        scores = jnp.ones((n,))
    scores = jnp.asarray(scores)
    order = jnp.argsort(-scores)
    boxes_sorted = boxes[order]
    iou = _iou_matrix(boxes_sorted, boxes_sorted)

    def body(i, keep):
        # suppress j>i with iou>thr if i kept
        sup = (iou[i] > iou_threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    kept_sorted = np.nonzero(np.asarray(jax.device_get(keep)))[0]
    result = np.asarray(jax.device_get(order))[kept_sorted]
    if top_k is not None:
        result = result[:top_k]
    return jnp.asarray(result)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0):
    pb = jnp.asarray(prior_box)
    tb = jnp.asarray(target_box)
    var = jnp.asarray(prior_box_var) if prior_box_var is not None else 1.0
    pw = pb[:, 2] - pb[:, 0] + (0 if box_normalized else 1)
    ph = pb[:, 3] - pb[:, 1] + (0 if box_normalized else 1)
    px = pb[:, 0] + pw / 2
    py = pb[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + (0 if box_normalized else 1)
        th = tb[:, 3] - tb[:, 1] + (0 if box_normalized else 1)
        tx = tb[:, 0] + tw / 2
        ty = tb[:, 1] + th / 2
        out = jnp.stack([(tx - px) / pw, (ty - py) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=-1)
        return out / var
    # decode
    d = tb * var
    ox = d[..., 0] * pw + px
    oy = d[..., 1] * ph + py
    ow = jnp.exp(d[..., 2]) * pw
    oh = jnp.exp(d[..., 3]) * ph
    return jnp.stack([ox - ow / 2, oy - oh / 2, ox + ow / 2, oy + oh / 2],
                     axis=-1)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """ref: paddle.vision.ops.yolo_box (phi yolo_box kernel) — decode YOLO
    head predictions to boxes+scores."""
    x = jnp.asarray(x)
    b, c, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    x = x.reshape(b, na, -1, h, w)  # (B, A, 5+cls, H, W)
    grid_x = jnp.arange(w, dtype=jnp.float32)
    grid_y = jnp.arange(h, dtype=jnp.float32)
    cx = (jax.nn.sigmoid(x[:, :, 0]) * scale_x_y
          - (scale_x_y - 1) / 2 + grid_x[None, None, None, :]) / w
    cy = (jax.nn.sigmoid(x[:, :, 1]) * scale_x_y
          - (scale_x_y - 1) / 2 + grid_y[None, None, :, None]) / h
    input_h = downsample_ratio * h
    input_w = downsample_ratio * w
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / input_h
    obj = jax.nn.sigmoid(x[:, :, 4])
    cls = jax.nn.sigmoid(x[:, :, 5:5 + class_num])
    img_size = jnp.asarray(img_size, jnp.float32)  # (B, 2) h,w
    img_h = img_size[:, 0][:, None, None, None]
    img_w = img_size[:, 1][:, None, None, None]
    x0 = (cx - bw / 2) * img_w
    y0 = (cy - bh / 2) * img_h
    x1 = (cx + bw / 2) * img_w
    y1 = (cy + bh / 2) * img_h
    if clip_bbox:
        x0 = jnp.clip(x0, 0, img_w - 1)
        y0 = jnp.clip(y0, 0, img_h - 1)
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1).reshape(b, -1, 4)
    scores = (obj[:, :, None] * cls).transpose(0, 1, 3, 4, 2).reshape(
        b, -1, class_num)
    mask = (obj > conf_thresh).reshape(b, -1)
    scores = scores * mask[..., None]
    return boxes, scores


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """ref: paddle.vision.ops.roi_align — bilinear pooled ROI features."""
    x = jnp.asarray(x)  # (N, C, H, W)
    boxes = jnp.asarray(boxes)  # (R, 4)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n, c, h, w = x.shape
    offset = 0.5 if aligned else 0.0

    # assume single image (N=1) or boxes_num maps rois → image 0; general
    # batched variant handled by vmapping over images upstream
    feat = x[0]

    def one_roi(box):
        x0, y0, x1, y1 = box * spatial_scale - offset
        rw = jnp.maximum(x1 - x0, 1e-3)
        rh = jnp.maximum(y1 - y0, 1e-3)
        ys = y0 + (jnp.arange(oh) + 0.5) * rh / oh
        xs = x0 + (jnp.arange(ow) + 0.5) * rw / ow
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
        y0i = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
        x0i = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
        y1i = jnp.clip(y0i + 1, 0, h - 1)
        x1i = jnp.clip(x0i + 1, 0, w - 1)
        wy = yy - y0i
        wx = xx - x0i
        v00 = feat[:, y0i, x0i]
        v01 = feat[:, y0i, x1i]
        v10 = feat[:, y1i, x0i]
        v11 = feat[:, y1i, x1i]
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                + v10 * wy * (1 - wx) + v11 * wy * wx)

    return jax.vmap(one_roi)(boxes)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False):
    rois = np.asarray(jax.device_get(fpn_rois))
    ws = rois[:, 2] - rois[:, 0]
    hs = rois[:, 3] - rois[:, 1]
    scale = np.sqrt(np.maximum(ws * hs, 1e-6))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(int)
    outs = []
    restore = np.argsort(
        np.concatenate([np.nonzero(lvl == l)[0]
                        for l in range(min_level, max_level + 1)]))
    for l in range(min_level, max_level + 1):
        outs.append(jnp.asarray(rois[lvl == l]))
    counts = [int((lvl == l).sum()) for l in range(min_level, max_level + 1)]
    return outs, jnp.asarray(restore), [jnp.asarray([c]) for c in counts]
