"""Vision ops (ref: python/paddle/vision/ops.py — yolo_box, nms, roi_align,
deform_conv, DetectionOutput helpers)."""

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["nms", "box_coder", "yolo_box", "roi_align",
           "distribute_fpn_proposals", "roi_pool", "psroi_pool",
           "matrix_nms", "prior_box", "deform_conv2d", "DeformConv2D",
           "generate_proposals",
           "yolo_loss", "read_file", "decode_jpeg", "RoIAlign", "RoIPool",
           "PSRoIPool", "ConvNormActivation"]


def _iou_matrix(boxes1, boxes2):
    area1 = (boxes1[:, 2] - boxes1[:, 0]) * (boxes1[:, 3] - boxes1[:, 1])
    area2 = (boxes2[:, 2] - boxes2[:, 0]) * (boxes2[:, 3] - boxes2[:, 1])
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / (area1[:, None] + area2[None, :] - inter + 1e-9)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """ref: paddle.vision.ops.nms. Static-shape greedy NMS via lax loop:
    returns keep mask indices (host-compacted)."""
    boxes = jnp.asarray(boxes)
    n = boxes.shape[0]
    if scores is None:
        scores = jnp.ones((n,))
    scores = jnp.asarray(scores)
    order = jnp.argsort(-scores)
    boxes_sorted = boxes[order]
    iou = _iou_matrix(boxes_sorted, boxes_sorted)

    def body(i, keep):
        # suppress j>i with iou>thr if i kept
        sup = (iou[i] > iou_threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    kept_sorted = np.nonzero(np.asarray(jax.device_get(keep)))[0]
    result = np.asarray(jax.device_get(order))[kept_sorted]
    if top_k is not None:
        result = result[:top_k]
    return jnp.asarray(result)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0):
    pb = jnp.asarray(prior_box)
    tb = jnp.asarray(target_box)
    var = jnp.asarray(prior_box_var) if prior_box_var is not None else 1.0
    pw = pb[:, 2] - pb[:, 0] + (0 if box_normalized else 1)
    ph = pb[:, 3] - pb[:, 1] + (0 if box_normalized else 1)
    px = pb[:, 0] + pw / 2
    py = pb[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + (0 if box_normalized else 1)
        th = tb[:, 3] - tb[:, 1] + (0 if box_normalized else 1)
        tx = tb[:, 0] + tw / 2
        ty = tb[:, 1] + th / 2
        out = jnp.stack([(tx - px) / pw, (ty - py) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=-1)
        return out / var
    # decode
    d = tb * var
    ox = d[..., 0] * pw + px
    oy = d[..., 1] * ph + py
    ow = jnp.exp(d[..., 2]) * pw
    oh = jnp.exp(d[..., 3]) * ph
    return jnp.stack([ox - ow / 2, oy - oh / 2, ox + ow / 2, oy + oh / 2],
                     axis=-1)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """ref: paddle.vision.ops.yolo_box (phi yolo_box kernel) — decode YOLO
    head predictions to boxes+scores."""
    x = jnp.asarray(x)
    b, c, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    x = x.reshape(b, na, -1, h, w)  # (B, A, 5+cls, H, W)
    grid_x = jnp.arange(w, dtype=jnp.float32)
    grid_y = jnp.arange(h, dtype=jnp.float32)
    cx = (jax.nn.sigmoid(x[:, :, 0]) * scale_x_y
          - (scale_x_y - 1) / 2 + grid_x[None, None, None, :]) / w
    cy = (jax.nn.sigmoid(x[:, :, 1]) * scale_x_y
          - (scale_x_y - 1) / 2 + grid_y[None, None, :, None]) / h
    input_h = downsample_ratio * h
    input_w = downsample_ratio * w
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / input_h
    obj = jax.nn.sigmoid(x[:, :, 4])
    cls = jax.nn.sigmoid(x[:, :, 5:5 + class_num])
    img_size = jnp.asarray(img_size, jnp.float32)  # (B, 2) h,w
    img_h = img_size[:, 0][:, None, None, None]
    img_w = img_size[:, 1][:, None, None, None]
    x0 = (cx - bw / 2) * img_w
    y0 = (cy - bh / 2) * img_h
    x1 = (cx + bw / 2) * img_w
    y1 = (cy + bh / 2) * img_h
    if clip_bbox:
        x0 = jnp.clip(x0, 0, img_w - 1)
        y0 = jnp.clip(y0, 0, img_h - 1)
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1).reshape(b, -1, 4)
    scores = (obj[:, :, None] * cls).transpose(0, 1, 3, 4, 2).reshape(
        b, -1, class_num)
    mask = (obj > conf_thresh).reshape(b, -1)
    scores = scores * mask[..., None]
    return boxes, scores


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """ref: paddle.vision.ops.roi_align — bilinear pooled ROI features."""
    x = jnp.asarray(x)  # (N, C, H, W)
    boxes = jnp.asarray(boxes)  # (R, 4)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n, c, h, w = x.shape
    offset = 0.5 if aligned else 0.0
    img_idx = _roi_img_idx(x, boxes, boxes_num)

    def one_roi(box, idx):
        feat = x[idx]
        x0, y0, x1, y1 = box * spatial_scale - offset
        rw = jnp.maximum(x1 - x0, 1e-3)
        rh = jnp.maximum(y1 - y0, 1e-3)
        ys = y0 + (jnp.arange(oh) + 0.5) * rh / oh
        xs = x0 + (jnp.arange(ow) + 0.5) * rw / ow
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
        y0i = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
        x0i = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
        y1i = jnp.clip(y0i + 1, 0, h - 1)
        x1i = jnp.clip(x0i + 1, 0, w - 1)
        wy = yy - y0i
        wx = xx - x0i
        v00 = feat[:, y0i, x0i]
        v01 = feat[:, y0i, x1i]
        v10 = feat[:, y1i, x0i]
        v11 = feat[:, y1i, x1i]
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                + v10 * wy * (1 - wx) + v11 * wy * wx)

    return jax.vmap(one_roi)(boxes, img_idx)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False):
    rois = np.asarray(jax.device_get(fpn_rois))
    ws = rois[:, 2] - rois[:, 0]
    hs = rois[:, 3] - rois[:, 1]
    scale = np.sqrt(np.maximum(ws * hs, 1e-6))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(int)
    outs = []
    restore = np.argsort(
        np.concatenate([np.nonzero(lvl == l)[0]
                        for l in range(min_level, max_level + 1)]))
    for l in range(min_level, max_level + 1):
        outs.append(jnp.asarray(rois[lvl == l]))
    counts = [int((lvl == l).sum()) for l in range(min_level, max_level + 1)]
    return outs, jnp.asarray(restore), [jnp.asarray([c]) for c in counts]


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """ref: vision/ops.py roi_pool:1685 — max-pooled ROI bins (the
    pre-align Fast-RCNN pooling; quantized bin edges). Exact: every pixel
    of the quantized ROI is assigned to its bin with a dense in-bin mask
    and max-reduced — data-dependent bin SIZES with static shapes."""
    x = jnp.asarray(x)
    boxes = jnp.asarray(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n, c, h, w = x.shape
    img_idx = _roi_img_idx(x, boxes, boxes_num)

    def one_roi(box, idx):
        feat = x[idx]
        x0 = jnp.round(box[0] * spatial_scale).astype(jnp.int32)
        y0 = jnp.round(box[1] * spatial_scale).astype(jnp.int32)
        x1 = jnp.maximum(jnp.round(box[2] * spatial_scale).astype(
            jnp.int32), x0 + 1)
        y1 = jnp.maximum(jnp.round(box[3] * spatial_scale).astype(
            jnp.int32), y0 + 1)
        ys = jnp.arange(h)
        xs = jnp.arange(w)
        # reference bin ranges OVERLAP: bin i spans
        # [floor(i*extent/bins), ceil((i+1)*extent/bins)) relative to the
        # ROI origin — boundary pixels belong to both neighbors
        hh = jnp.maximum(y1 - y0, 1).astype(jnp.float32)
        ww = jnp.maximum(x1 - x0, 1).astype(jnp.float32)
        i = jnp.arange(oh, dtype=jnp.float32)
        j = jnp.arange(ow, dtype=jnp.float32)
        y_lo = y0 + jnp.floor(i * hh / oh).astype(jnp.int32)
        y_hi = y0 + jnp.ceil((i + 1) * hh / oh).astype(jnp.int32)
        x_lo = x0 + jnp.floor(j * ww / ow).astype(jnp.int32)
        x_hi = x0 + jnp.ceil((j + 1) * ww / ow).astype(jnp.int32)
        in_y = (ys >= y0) & (ys < y1)
        in_x = (xs >= x0) & (xs < x1)
        # (oh, H) and (ow, W) bin-membership masks
        my = ((ys[None, :] >= y_lo[:, None])
              & (ys[None, :] < y_hi[:, None]) & in_y[None, :])
        mx = ((xs[None, :] >= x_lo[:, None])
              & (xs[None, :] < x_hi[:, None]) & in_x[None, :])
        mask = my[:, None, :, None] & mx[None, :, None, :]  # (oh,ow,H,W)
        vals = jnp.where(mask[None], feat[:, None, None, :, :], -jnp.inf)
        out = jnp.max(vals, axis=(-2, -1))  # (C, oh, ow)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(boxes, img_idx)


def _roi_img_idx(x, boxes, boxes_num):
    """Image index per ROI from ``boxes_num`` (ROIs r of image i for the
    i-th entry). Without boxes_num a single-image batch is required —
    silently pooling every ROI from image 0 would be a wrong-answer trap.
    Returns indices, not gathered maps: the per-ROI row gather happens
    INSIDE the vmapped body so XLA can fuse it with the spatial gather
    instead of materializing an (R, C, H, W) copy."""
    n = x.shape[0]
    if boxes_num is None:
        if n != 1:
            raise ValueError(
                "batched input needs boxes_num to map ROIs to images")
        img_idx = np.zeros(boxes.shape[0], np.int32)
    else:
        counts = np.asarray(jax.device_get(jnp.asarray(boxes_num))
                            ).reshape(-1)
        img_idx = np.repeat(np.arange(len(counts)), counts)
    return jnp.asarray(img_idx)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """ref: vision/ops.py psroi_pool:1553 — position-sensitive ROI pool
    (R-FCN). Reference channel layout is CHANNEL-major: input channel
    (k*pooled_h + i)*pooled_w + j feeds (out_channel k, bin (i, j))."""
    x = jnp.asarray(x)
    boxes = jnp.asarray(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n, c, h, w = x.shape
    assert c % (oh * ow) == 0, "channels must divide output_size^2"
    co = c // (oh * ow)
    img_idx = _roi_img_idx(x, boxes, boxes_num)

    def one_roi(box, idx):
        feat = x[idx].reshape(co, oh, ow, h, w)
        x0, y0, x1, y1 = box * spatial_scale
        rw = jnp.maximum(x1 - x0, 0.1)
        rh = jnp.maximum(y1 - y0, 0.1)
        samples = 4
        ys = y0 + (jnp.arange(oh * samples) + 0.5) * rh / (oh * samples)
        xs = x0 + (jnp.arange(ow * samples) + 0.5) * rw / (ow * samples)
        yi = jnp.clip(ys.astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(xs.astype(jnp.int32), 0, w - 1)
        # (co, oh, ow, oh*s, ow*s) gathered → bin (i, j) pools its own
        # sample window from channel block (i, j)
        vals = feat[:, :, :, yi[:, None], xi[None, :]]
        vals = vals.reshape(co, oh, ow, oh, samples, ow, samples)
        idx_i = jnp.arange(oh)
        idx_j = jnp.arange(ow)
        # non-contiguous advanced indices move to the FRONT:
        # picked is (oh, ow, co, samples, samples)
        picked = vals[:, idx_i[:, None], idx_j[None, :],
                      idx_i[:, None], :, idx_j[None, :], :]
        return jnp.transpose(jnp.mean(picked, axis=(-2, -1)), (2, 0, 1))

    return jax.vmap(one_roi)(boxes, img_idx)


def matrix_nms_decay(iou, same_cls, use_gaussian=False,
                     gaussian_sigma=2.0):
    """Matrix-NMS score-decay factors (SOLOv2 eq. 5) — the ONE definition
    shared by `matrix_nms` and PP-YOLOE's jit decode. Inputs are sorted
    by descending score; box j decays box i iff j < i and same class.
    ``comp[j]`` compensates for how suppressed the decayer j itself is."""
    n = iou.shape[0]
    lower = jnp.tril(jnp.ones((n, n), bool), k=-1)   # j < i: j decays i
    decay_iou = jnp.where(same_cls & lower, iou, 0.0)
    comp_iou = jnp.max(decay_iou, axis=1)[None, :]
    if use_gaussian:
        decay = jnp.exp(-(decay_iou ** 2 - comp_iou ** 2) / gaussian_sigma)
    else:
        decay = (1.0 - decay_iou) / jnp.maximum(1.0 - comp_iou, 1e-9)
    decay = jnp.where(same_cls & lower, decay, 1.0)
    return jnp.min(decay, axis=1)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True):
    """ref: vision/ops.py matrix_nms:2430 (SOLOv2) — parallel soft-NMS:
    every box's score is decayed by its IoU with all higher-scored boxes,
    no sequential suppression loop. O(n^2) matrix math → TPU-friendly.
    Single-image (1, C, M) inputs; returns (out (K, 6), index, rois_num)."""
    boxes = jnp.asarray(bboxes)[0]      # (M, 4)
    scr = jnp.asarray(scores)[0]        # (C, M)
    c, m = scr.shape
    keep_mask = scr > score_threshold
    if background_label >= 0 and c > 1:
        # the background class never competes for detection slots
        keep_mask = keep_mask & (jnp.arange(c)[:, None] != background_label)
    flat_scores = jnp.where(keep_mask, scr, 0.0).reshape(-1)
    top = min(nms_top_k, c * m)
    order = jnp.argsort(-flat_scores)[:top]
    sel_cls = order // m
    sel_box = order % m
    sel_scores = flat_scores[order]
    sel_boxes = boxes[sel_box]
    iou = _iou_matrix(sel_boxes, sel_boxes)
    same_cls = sel_cls[:, None] == sel_cls[None, :]
    final = sel_scores * matrix_nms_decay(iou, same_cls, use_gaussian,
                                          gaussian_sigma)
    keep = final > post_threshold
    order2 = jnp.argsort(-jnp.where(keep, final, -1.0))[:keep_top_k]
    out = jnp.concatenate(
        [sel_cls[order2][:, None].astype(boxes.dtype),
         final[order2][:, None], sel_boxes[order2]], axis=1)
    n_kept = jnp.sum(keep.astype(jnp.int32))
    return out, sel_box[order2], jnp.asarray([n_kept])


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),  # noqa: A002
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False):
    """ref: vision/ops.py prior_box:485 (SSD anchors)."""
    feat_h, feat_w = jnp.asarray(input).shape[2:4]
    img_h, img_w = jnp.asarray(image).shape[2:4]
    step_h = steps[1] or img_h / feat_h
    step_w = steps[0] or img_w / feat_w
    # ≙ ExpandAspectRatios: each ratio immediately followed by its
    # reciprocal when flip — the per-cell anchor order is positional
    ars = []
    for a in aspect_ratios:
        ars.append(a)
        if flip and a != 1.0:
            ars.append(1.0 / a)
    # reference per-cell anchor ORDER (prior_box kernel): for each
    # min_size: the ar=1 min box, then [max box if
    # min_max_aspect_ratios_order] interleaved with the other-ar boxes —
    # an SSD head trained against the reference decodes by position, so
    # the order is part of the contract
    sizes = []
    for i, ms in enumerate(min_sizes):
        sizes.append((float(ms), float(ms)))  # ar = 1 first
        rest = [(ms * np.sqrt(a), ms / np.sqrt(a)) for a in ars if a != 1.0]
        mx_box = None
        if max_sizes:
            mx = max_sizes[i]
            mx_box = (np.sqrt(ms * mx), np.sqrt(ms * mx))
        if min_max_aspect_ratios_order and mx_box is not None:
            sizes.append(mx_box)
            sizes.extend(rest)
        else:
            sizes.extend(rest)
            if mx_box is not None:
                sizes.append(mx_box)
    sizes = np.asarray(sizes, np.float32)  # (A, 2) w,h
    cy = (np.arange(feat_h) + offset) * step_h
    cx = (np.arange(feat_w) + offset) * step_w
    cxg, cyg = np.meshgrid(cx, cy)
    a = len(sizes)
    boxes = np.zeros((feat_h, feat_w, a, 4), np.float32)
    boxes[..., 0] = (cxg[..., None] - sizes[:, 0] / 2) / img_w
    boxes[..., 1] = (cyg[..., None] - sizes[:, 1] / 2) / img_h
    boxes[..., 2] = (cxg[..., None] + sizes[:, 0] / 2) / img_w
    boxes[..., 3] = (cyg[..., None] + sizes[:, 1] / 2) / img_h
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          boxes.shape).copy()
    return jnp.asarray(boxes), jnp.asarray(var)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """ref: vision/ops.py deform_conv2d:858 (DCNv1/v2 kernels) — sampling
    positions shifted by learned offsets, bilinear-gathered then reduced
    with the kernel weights. Gather + einsum: MXU-friendly, no custom op."""
    x = jnp.asarray(x)
    offset = jnp.asarray(offset)
    weight = jnp.asarray(weight)  # (Cout, Cin/groups, kh, kw)
    n, c, h, w = x.shape
    cout, cin_g, kh, kw = weight.shape
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    oh = (h + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
    ow = (w + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
    assert groups == 1 and deformable_groups == 1, \
        "deform_conv2d: groups/deformable_groups > 1 not supported"

    # base sampling grid (oh, ow, kh, kw)
    base_y = (jnp.arange(oh) * s[0] - p[0])[:, None, None, None] \
        + (jnp.arange(kh) * d[0])[None, None, :, None]
    base_x = (jnp.arange(ow) * s[1] - p[1])[None, :, None, None] \
        + (jnp.arange(kw) * d[1])[None, None, None, :]
    off = offset.reshape(n, kh, kw, 2, oh, ow)
    dy = jnp.transpose(off[:, :, :, 0], (0, 3, 4, 1, 2))
    dx = jnp.transpose(off[:, :, :, 1], (0, 3, 4, 1, 2))
    sy = base_y[None] + dy  # (N, oh, ow, kh, kw)
    sx = base_x[None] + dx

    y0 = jnp.floor(sy)
    x0 = jnp.floor(sx)
    wy = sy - y0
    wx = sx - x0

    def gather(img, yi, xi):
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi.astype(jnp.int32), 0, h - 1)
        xc = jnp.clip(xi.astype(jnp.int32), 0, w - 1)
        vals = img[:, yc, xc]  # (C, oh, ow, kh, kw)
        return jnp.where(valid[None], vals, 0.0)

    def one_image(img, y0, x0, wy, wx, m):
        v00 = gather(img, y0, x0)
        v01 = gather(img, y0, x0 + 1)
        v10 = gather(img, y0 + 1, x0)
        v11 = gather(img, y0 + 1, x0 + 1)
        sampled = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                   + v10 * wy * (1 - wx) + v11 * wy * wx)
        if m is not None:
            sampled = sampled * m[None]
        # (C, oh, ow, kh, kw) × (Cout, C, kh, kw) → (Cout, oh, ow)
        return jnp.einsum("cyxhw,ochw->oyx", sampled, weight)

    if mask is None:
        out = jax.vmap(lambda img, a, b, cc, dd: one_image(
            img, a, b, cc, dd, None))(x, y0, x0, wy, wx)
    else:
        masks = jnp.asarray(mask).reshape(n, kh, kw, oh, ow).transpose(
            0, 3, 4, 1, 2)
        out = jax.vmap(one_image)(x, y0, x0, wy, wx, masks)
    if bias is not None:
        out = out + jnp.asarray(bias)[None, :, None, None]
    return out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False):
    """ref: vision/ops.py generate_proposals:2241 (RPN head): decode
    deltas on anchors, clip, drop tiny boxes, NMS. Single image.
    Layouts follow the reference: scores (1, A, H, W), bbox_deltas
    (1, 4A, H, W), anchors/variances (H, W, A, 4) or flat (H*W*A, 4) in
    (H, W, A)-major order — everything is flattened to that same order
    before decoding."""
    s = jnp.asarray(scores)[0]                       # (A, H, W)
    a_num, fh, fw = s.shape
    scr = jnp.transpose(s, (1, 2, 0)).reshape(-1)    # (H, W, A) major
    deltas = jnp.transpose(
        jnp.asarray(bbox_deltas)[0].reshape(a_num, 4, fh, fw),
        (2, 3, 0, 1)).reshape(-1, 4)
    anc = jnp.asarray(anchors).reshape(-1, 4)
    var = jnp.asarray(variances).reshape(-1, 4)
    boxes = box_coder(anc, None, deltas * var,
                      code_type="decode_center_size")
    ih, iw = [float(v) for v in np.asarray(jax.device_get(
        jnp.asarray(img_size))).reshape(-1)[:2]]
    off = 1.0 if pixel_offset else 0.0
    boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, iw - off),
                       jnp.clip(boxes[:, 1], 0, ih - off),
                       jnp.clip(boxes[:, 2], 0, iw - off),
                       jnp.clip(boxes[:, 3], 0, ih - off)], axis=1)
    ws = boxes[:, 2] - boxes[:, 0] + off
    hs = boxes[:, 3] - boxes[:, 1] + off
    valid = (ws >= min_size) & (hs >= min_size)
    scr = jnp.where(valid, scr, -1.0)
    top = min(pre_nms_top_n, scr.shape[0])
    order = jnp.argsort(-scr)[:top]
    keep = nms(boxes[order], iou_threshold=nms_thresh, scores=scr[order],
               top_k=post_nms_top_n)
    sel = np.asarray(jax.device_get(order[keep]))
    # filtered (sub-min_size) boxes are REMOVED, not returned at score -1
    sel = sel[np.asarray(jax.device_get(valid))[sel]]
    return boxes[sel], scr[sel], jnp.asarray([len(sel)])


from paddle_tpu.nn.module import Module as _Module  # noqa: E402
from paddle_tpu.nn.module import Parameter as _Parameter  # noqa: E402


class DeformConv2D(_Module):
    """Layer form of deform_conv2d (ref: vision/ops.py DeformConv2D:1096)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        if groups != 1 or deformable_groups != 1:
            raise NotImplementedError(
                "DeformConv2D: groups/deformable_groups > 1 not supported")
        k = (kernel_size,) * 2 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        rs = np.random.RandomState(0)
        bound = float(np.sqrt(1.0 / (in_channels * k[0] * k[1])))
        self.weight = _Parameter(jnp.asarray(
            rs.uniform(-bound, bound,
                       (out_channels, in_channels // groups, *k)),
            jnp.float32))
        self.bias = (None if bias_attr is False else _Parameter(
            jnp.zeros((out_channels,), jnp.float32)))
        self.stride, self.padding, self.dilation = stride, padding, dilation

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self.stride, self.padding, self.dilation,
                             mask=mask)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0):
    """ref: vision/ops.py yolo_loss:52 (yolov3_loss op) — per-sample
    YOLOv3 loss over one detection scale.

    x: (N, S*(5+class_num), H, W) raw head output; gt_box (N, B, 4)
    center-xywh normalized to [0, 1]; gt_label (N, B) int32 (boxes with
    w<=0 are padding); anchors: flat [w0, h0, w1, h1, ...] pixel sizes;
    anchor_mask: indices of this scale's anchors. Returns (N,) loss.
    """
    x = jnp.asarray(x)
    gt_box = jnp.asarray(gt_box, jnp.float32)
    gt_label = jnp.asarray(gt_label, jnp.int32)
    n, c, h, w = x.shape
    s = len(anchor_mask)
    assert c == s * (5 + class_num), (c, s, class_num)
    all_anch = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask_anch = jnp.asarray(all_anch[list(anchor_mask)])   # (S, 2) pixels
    input_size = downsample_ratio * h
    if gt_score is None:
        gt_score = jnp.ones(gt_label.shape, jnp.float32)

    p = x.reshape(n, s, 5 + class_num, h, w)
    tx, ty = p[:, :, 0], p[:, :, 1]           # (N, S, H, W)
    tw, th = p[:, :, 2], p[:, :, 3]
    tobj = p[:, :, 4]
    tcls = p[:, :, 5:]                        # (N, S, class_num, H, W)

    # -- target assignment: best anchor (wh IoU) per gt box ----------------
    gw = gt_box[..., 2] * input_size          # (N, B) pixels
    gh = gt_box[..., 3] * input_size
    inter = (jnp.minimum(gw[..., None], all_anch[None, None, :, 0])
             * jnp.minimum(gh[..., None], all_anch[None, None, :, 1]))
    union = (gw * gh)[..., None] + (all_anch[:, 0] * all_anch[:, 1]
                                    )[None, None] - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-9), -1)  # (N, B)
    valid = gt_box[..., 2] > 0
    # the gt lands on this scale iff its best anchor is in anchor_mask
    mask_arr = jnp.asarray(list(anchor_mask))
    on_scale = jnp.any(best[..., None] == mask_arr[None, None], -1) & valid
    slot = jnp.argmax(
        (best[..., None] == mask_arr[None, None]).astype(jnp.int32), -1)
    gi = jnp.clip((gt_box[..., 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[..., 1] * h).astype(jnp.int32), 0, h - 1)

    # scatter per-gt targets into (N, S, H, W) grids; off-scale/padding
    # boxes write to an extra discard slot S (dropped after the scatter),
    # so they can never clobber a real box landing at the same cell
    slot_or_discard = jnp.where(on_scale, slot, s)

    def scat(values, fill=0.0):
        out = jnp.full((n, s + 1, h, w), fill, jnp.float32)
        bidx = jnp.arange(n)[:, None] * jnp.ones_like(slot)
        out = out.at[bidx, slot_or_discard, gj, gi].set(values)
        return out[:, :s]

    obj_target = scat(jnp.ones_like(gw))
    sx = gt_box[..., 0] * w - gi               # σ(tx) target in [0,1)
    sy = gt_box[..., 1] * h - gj
    twt = jnp.log(jnp.maximum(gw[..., None] / mask_anch[None, None, :, 0],
                              1e-9))           # (N, B, S)
    twt = jnp.take_along_axis(twt, slot[..., None], -1)[..., 0]
    tht = jnp.log(jnp.maximum(gh[..., None] / mask_anch[None, None, :, 1],
                              1e-9))
    tht = jnp.take_along_axis(tht, slot[..., None], -1)[..., 0]
    box_w = 2.0 - gt_box[..., 2] * gt_box[..., 3]  # small-box upweight
    pos = obj_target > 0                       # (N, S, H, W) bool
    # scale_x_y (YOLOv4 grid sensitivity): pred bx = sigma(tx)*a-(a-1)/2,
    # so the sigmoid target is (offset + (a-1)/2)/a (matches yolo_box)
    a = float(scale_x_y)
    sx = jnp.clip((sx + (a - 1.0) / 2.0) / a, 0.0, 1.0)
    sy = jnp.clip((sy + (a - 1.0) / 2.0) / a, 0.0, 1.0)
    x_t, y_t = scat(sx), scat(sy)
    w_t, h_t = scat(twt), scat(tht)
    wgt = scat(box_w * gt_score)
    lbl = scat(gt_label.astype(jnp.float32), fill=-1.0).astype(jnp.int32)

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target + jnp.log1p(
            jnp.exp(-jnp.abs(logit)))

    loss_xy = wgt * (bce(tx, x_t) + bce(ty, y_t)) * pos
    loss_wh = wgt * (jnp.abs(tw - w_t) + jnp.abs(th - h_t)) * pos

    # objectness: positives → 1; negatives whose PREDICTED box overlaps
    # any gt above ignore_thresh are ignored
    cx = (jnp.arange(w)[None, None, None] + jax.nn.sigmoid(tx)) / w
    cy = (jnp.arange(h)[None, None, :, None] + jax.nn.sigmoid(ty)) / h
    pw = mask_anch[None, :, None, None, 0] * jnp.exp(tw) / input_size
    ph = mask_anch[None, :, None, None, 1] * jnp.exp(th) / input_size
    px1, px2 = cx - pw / 2, cx + pw / 2
    py1, py2 = cy - ph / 2, cy + ph / 2
    g = gt_box[:, None, None, None]            # (N, 1, 1, 1, B, 4)
    gx1 = g[..., 0] - g[..., 2] / 2
    gx2 = g[..., 0] + g[..., 2] / 2
    gy1 = g[..., 1] - g[..., 3] / 2
    gy2 = g[..., 1] + g[..., 3] / 2
    iw = jnp.maximum(jnp.minimum(px2[..., None], gx2)
                     - jnp.maximum(px1[..., None], gx1), 0)
    ih = jnp.maximum(jnp.minimum(py2[..., None], gy2)
                     - jnp.maximum(py1[..., None], gy1), 0)
    inter_p = iw * ih
    area_p = (px2 - px1)[..., None] * (py2 - py1)[..., None]
    area_g = (gx2 - gx1) * (gy2 - gy1)
    iou = inter_p / jnp.maximum(area_p + area_g - inter_p, 1e-9)
    iou = jnp.where(valid[:, None, None, None], iou, 0.0)
    ignore = (jnp.max(iou, -1) > ignore_thresh) & ~pos
    obj_w = jnp.where(ignore, 0.0, 1.0)
    loss_obj = obj_w * bce(tobj, obj_target)

    smooth = 1.0 / max(class_num, 1) if use_label_smooth else 0.0
    onehot = jax.nn.one_hot(jnp.clip(lbl, 0, class_num - 1), class_num,
                            axis=2)
    onehot = onehot * (1.0 - smooth) + smooth / class_num
    loss_cls = jnp.sum(bce(tcls, onehot), axis=2) * pos

    total = (loss_xy + loss_wh + loss_obj + loss_cls)
    return jnp.sum(total, axis=(1, 2, 3))


def read_file(path):
    """ref: vision/ops.py read_file — file bytes as a uint8 tensor."""
    with open(path, "rb") as f:
        return jnp.asarray(np.frombuffer(f.read(), np.uint8))


def decode_jpeg(x, mode="unchanged"):
    """ref: vision/ops.py decode_jpeg (nvjpeg-backed there; PIL here —
    image IO is host-side input-pipeline work on TPU). x: uint8 bytes
    tensor from read_file. Returns (C, H, W) uint8."""
    import io as _io

    from PIL import Image
    img = Image.open(_io.BytesIO(np.asarray(x, np.uint8).tobytes()))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return jnp.asarray(arr)


class RoIAlign(_Module):
    """Layer form of roi_align (ref: vision/ops.py RoIAlign:1310)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool(_Module):
    """Layer form of roi_pool (ref: vision/ops.py RoIPool:1154)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool(_Module):
    """Layer form of psroi_pool (ref: vision/ops.py PSRoIPool:1076)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


class ConvNormActivation(_Module):
    """ref: vision/ops.py ConvNormActivation — Conv2D + BatchNorm2D +
    activation block (the torchvision-style building block)."""

    _DEFAULT = object()  # distinguishes "unspecified" from explicit None

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 padding=None, groups=1, norm_layer=_DEFAULT,
                 activation_layer=_DEFAULT, dilation=1, bias=None):
        super().__init__()
        from paddle_tpu import nn as _nn
        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        nl = _nn.BatchNorm2D if norm_layer is self._DEFAULT else norm_layer
        al = _nn.ReLU if activation_layer is self._DEFAULT \
            else activation_layer
        if bias is None:
            bias = nl is None  # reference: conv bias only without a norm
        self.conv = _nn.Conv2D(in_channels, out_channels, kernel_size,
                               stride, padding, dilation=dilation,
                               groups=groups,
                               bias_attr=None if bias else False)
        self.norm = nl(out_channels) if nl is not None else None
        self.act = al() if al is not None else None

    def forward(self, x):
        x = self.conv(x)
        if self.norm is not None:
            x = self.norm(x)
        if self.act is not None:
            x = self.act(x)
        return x
