"""Model zoo (ref: PaddleNLP model families + python/paddle/vision/models).

The flagship pretrain family is GPT (ref: PaddleNLP gpt-3, the BASELINE
north-star config); vision models live in paddle_tpu.vision.models.
"""

from paddle_tpu.models import gpt
from paddle_tpu.models.gpt import (GPT, GPTConfig, gpt_tiny, gpt3_125m,
                                   gpt3_350m, gpt3_1p3b)

__all__ = ["gpt", "GPT", "GPTConfig", "gpt_tiny", "gpt3_125m", "gpt3_350m",
           "gpt3_1p3b"]
