"""Model zoo (ref: PaddleNLP model families + python/paddle/vision/models).

The flagship pretrain family is GPT (ref: PaddleNLP gpt-3, the BASELINE
north-star config); vision models live in paddle_tpu.vision.models.
"""

from paddle_tpu.models import gpt
from paddle_tpu.models import bert
from paddle_tpu.models import ernie
from paddle_tpu.models.ernie import (Ernie, ErnieConfig,
                                     ErnieForSequenceClassification)
from paddle_tpu.models.bert import (Bert, BertConfig, BertForPretraining,
                                    BertForSequenceClassification,
                                    bert_tiny, bert_base, bert_large)
from paddle_tpu.models.gpt import (GPT, GPTConfig, gpt_tiny, gpt3_125m,
                                   gpt3_350m, gpt3_1p3b)

__all__ = ["gpt", "GPT", "GPTConfig", "gpt_tiny", "gpt3_125m", "gpt3_350m",
           "gpt3_1p3b", "bert", "Bert", "BertConfig", "BertForPretraining",
           "BertForSequenceClassification", "bert_tiny", "bert_base",
           "bert_large", "ernie", "Ernie",
           "ErnieConfig", "ErnieForSequenceClassification"]
