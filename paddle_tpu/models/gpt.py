"""GPT decoder-only LM — the flagship pretrain model family.

Reference analog: PaddleNLP gpt-3 trained with fleet hybrid parallel
(SURVEY §2.2, §3.4; BASELINE north-star "GPT-3 1.3B pretrain, DP×MP×PP").
The reference expresses parallelism as wrapper modules + NCCL ops
(mp_layers.py ColumnParallelLinear:173 / RowParallelLinear:327,
pipeline_parallel.py:117 1F1B); here the same strategies are sharding
annotations on one jitted program over a named mesh:

- TP  ≙ megatron Column/Row parallel: qkv/up weights sharded P('fsdp','tp'),
  out/down weights P('tp','fsdp'); XLA inserts the reduce-scatter/all-reduce
  the reference codes by hand (c_identity / mp_allreduce, mpu/mp_ops.py).
- FSDP ≙ sharding stage 3: every weight additionally sharded over 'fsdp';
  XLA all-gathers at use and reduce-scatters grads (ZeRO-3 semantics without
  the reference's gather/release hooks, group_sharded_stage3.py:59).
- SP: activation seq axis sharded over 'sp' (capability absent in the
  reference, SURVEY §5.7).
- PP ≙ GPipe/1F1B: see `pipelined_apply` — stage-stacked weights sharded
  P('pp') with a rolling activation buffer; XLA compiles the roll into a
  collective-permute ring over ICI. (ref contrast: FleetExecutor/interceptor
  runtime + send_v2/recv_v2 ops.)
"""

import collections
import dataclasses
import functools
import math
import re
import weakref
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu import nn
from paddle_tpu.distributed.mesh import LAYOUT, mesh_safe_spec
from paddle_tpu.incubate.moe import EXPERT_PARTITION_RULES
from paddle_tpu.nn.module import Module, Parameter, LayerList
from paddle_tpu.nn import functional as F


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    max_seq_len: int = 1024
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_mult: int = 4
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    use_bias: bool = True
    tie_embeddings: bool = True
    # remat ≙ reference recompute (fleet/recompute/recompute.py:386)
    remat: bool = False
    # MoE (≙ incubate MoE GPT): every `moe_every`-th block swaps its FFN for
    # an expert-parallel MoELayer; 0 experts = dense
    moe_experts: int = 0
    moe_every: int = 2
    moe_aux_weight: float = 0.01
    moe_gate: str = "gshard"
    # GQA/MQA: fewer KV heads than query heads — the KV cache (and the
    # decode HBM roofline) shrinks by n_heads/n_kv_heads. None = MHA.
    n_kv_heads: Optional[int] = None
    # Rotary position embeddings (Llama-family positions) instead of the
    # learned wpe table; max_seq_len still caps the cache length.
    rope: bool = False
    rope_theta: float = 10000.0

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    @property
    def kv_heads(self):
        kv = self.n_kv_heads or self.n_heads
        if self.n_heads % kv:
            raise ValueError(
                f"n_heads {self.n_heads} not divisible by n_kv_heads {kv}")
        return kv

    @property
    def d_ffn(self):
        return self.d_model * self.ffn_mult

    def flops_per_token(self) -> float:
        """Model FLOPs per token (fwd+bwd), 6*N + attention term."""
        n = self.num_params(non_embedding=True)
        attn = 12 * self.n_layers * self.d_model * self.max_seq_len
        return 6 * n + attn

    def num_params(self, non_embedding: bool = False) -> int:
        d, L = self.d_model, self.n_layers
        kv_dim = self.kv_heads * self.head_dim
        per_layer = (d * (d + 2 * kv_dim)   # wqkv (GQA-sized kv)
                     + d * d                # wo
                     + 2 * d * self.d_ffn + 4 * d)
        if self.use_bias:
            # bqkv(d+2kv) + bo(d) + bup(ffn) + bdown(d)
            per_layer += (d + 2 * kv_dim) + 2 * d + self.d_ffn
        n = L * per_layer + 2 * d  # + final ln
        if not non_embedding:
            n += self.vocab_size * d
            if not self.rope:
                n += self.max_seq_len * d
            if not self.tie_embeddings:
                n += self.vocab_size * d
        return n


def _normal(key, shape, std, dtype):
    return (std * jax.random.normal(key, shape)).astype(dtype)


def _flag(name):
    from paddle_tpu import flags
    return flags.get_flag(name)


def stack_block_weights(blocks):
    """One scan-stacked pytree over structurally identical blocks.

    Not a plain tree_map: per-instance STATIC attributes (e.g. the
    ``_path`` tag_paths stores — "blocks.0" vs "blocks.1") differ
    between blocks and would fail tree_map's aux-data equality even
    though the blocks are computationally identical. Leaves are stacked
    positionally and rebuilt with block 0's treedef, whose static
    metadata drives the scanned body."""
    leaves = [jax.tree_util.tree_leaves(b) for b in blocks]
    treedef = jax.tree_util.tree_structure(blocks[0])
    if any(len(ls) != len(leaves[0]) for ls in leaves):
        raise ValueError("blocks are structurally heterogeneous; "
                         "cannot scan-stack")
    return jax.tree_util.tree_unflatten(
        treedef, [jnp.stack(xs) for xs in zip(*leaves)])


def _use_decode_kernel(T: int) -> bool:
    """Route single-token decode through the Pallas flash-decode kernel.
    Disabled under a multi-device mesh: GSPMD has no partitioning rule for
    the pallas custom-call, so a tp-sharded KV cache would be all-gathered
    per layer per step — the einsum path lets the partitioner shard."""
    if T % 128 or not _flag("use_pallas_kernels"):
        return False
    from paddle_tpu.distributed.mesh import get_mesh
    mesh = get_mesh()
    return mesh is None or mesh.size == 1


class GPTBlock(Module):
    """Pre-LN transformer decoder block with fused qkv (one (d,3d) matmul
    keeps the MXU busy vs three thin ones)."""

    def __init__(self, cfg: GPTConfig, key: jax.Array, use_moe=False):
        super().__init__()
        d, h = cfg.d_model, cfg.n_heads
        self.n_heads = h
        self.kv_heads = cfg.kv_heads
        self.head_dim = cfg.head_dim
        self.rope = cfg.rope
        self.rope_theta = cfg.rope_theta
        if cfg.rope and cfg.head_dim % 2:
            raise ValueError("rope needs an even head_dim")
        kv_dim = self.kv_heads * self.head_dim
        self.dropout = cfg.dropout
        ks = jax.random.split(key, 4)
        std = 0.02
        resid_std = std / math.sqrt(2 * cfg.n_layers)
        dt = cfg.dtype
        self.use_moe = use_moe
        self.ln1_scale = Parameter(jnp.ones((d,), jnp.float32))
        self.ln1_bias = Parameter(jnp.zeros((d,), jnp.float32))
        self.ln2_scale = Parameter(jnp.ones((d,), jnp.float32))
        self.ln2_bias = Parameter(jnp.zeros((d,), jnp.float32))
        self.wqkv = Parameter(_normal(ks[0], (d, d + 2 * kv_dim), std, dt))
        self.wo = Parameter(_normal(ks[1], (d, d), resid_std, dt))
        if use_moe:
            from paddle_tpu.incubate.moe import MoELayer
            self.moe = MoELayer(d, cfg.d_ffn, cfg.moe_experts,
                                gate=cfg.moe_gate, dtype=dt,
                                seed=int(jax.random.randint(
                                    ks[2], (), 0, 2**31 - 1)))
            self.wup = self.wdown = None
        else:
            self.moe = None
            self.wup = Parameter(_normal(ks[2], (d, cfg.d_ffn), std, dt))
            self.wdown = Parameter(_normal(ks[3], (cfg.d_ffn, d),
                                           resid_std, dt))
        if cfg.use_bias:
            self.bqkv = Parameter(jnp.zeros((d + 2 * kv_dim,), dt))
            self.bo = Parameter(jnp.zeros((d,), dt))
            if not use_moe:
                self.bup = Parameter(jnp.zeros((cfg.d_ffn,), dt))
                self.bdown = Parameter(jnp.zeros((d,), dt))
            else:
                self.bup = self.bdown = None
        else:
            self.bqkv = self.bo = self.bup = self.bdown = None

    def _split_qkv(self, qkv):
        """(B, L, d+2·kv_dim) fused projection → q (B, L, H, D),
        k/v (B, L, Hkv, D) — GQA-sized kv."""
        b, L = qkv.shape[:2]
        d = self.n_heads * self.head_dim
        kvd = self.kv_heads * self.head_dim
        q = qkv[..., :d].reshape(b, L, self.n_heads, self.head_dim)
        k = qkv[..., d:d + kvd].reshape(b, L, self.kv_heads,
                                        self.head_dim)
        v = qkv[..., d + kvd:].reshape(b, L, self.kv_heads, self.head_dim)
        return q, k, v

    def _apply_rope(self, x, positions):
        """Rotary embedding on (B, L, Hx, D) at absolute ``positions``
        (B, L) or (L,) (Llama-family positions; rotate-half convention)."""
        if not self.rope:
            return x
        half = self.head_dim // 2
        freqs = self.rope_theta ** (
            -jnp.arange(0, half, dtype=jnp.float32) / half)
        pos = jnp.asarray(positions, jnp.float32)
        ang = pos[..., None] * freqs               # (..., L, half)
        while ang.ndim < 3:
            ang = ang[None]
        cos = jnp.cos(ang)[:, :, None, :]
        sin = jnp.sin(ang)[:, :, None, :]
        x32 = x.astype(jnp.float32)
        x1, x2 = x32[..., :half], x32[..., half:]
        out = jnp.concatenate(
            [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
        return out.astype(x.dtype)

    def _attention(self, q, k, v, s):
        """Ring attention over the 'sp' axis when the global mesh shards the
        sequence (SURVEY §5.7 gap — new capability); otherwise the flash /
        XLA path. Disabled inside the vmapped pipeline stages (shard_map
        does not nest under that vmap), where GSPMD handles 'sp'."""
        from paddle_tpu.distributed.mesh import get_mesh
        mesh = get_mesh()
        shape = dict(mesh.shape) if mesh is not None else {}
        sp = shape.get("sp", 1)
        # shard_map needs every spec'd dim divisible by its mesh axes
        # (unlike with_sharding_constraint, which tolerates odd shapes)
        divisible = (s % sp == 0
                     and q.shape[0] % (shape.get("dp", 1)
                                       * shape.get("fsdp", 1)) == 0
                     and self.n_heads % shape.get("tp", 1) == 0)
        if (sp > 1 and not _in_pipeline() and divisible
                and self.kv_heads == self.n_heads):
            from paddle_tpu.distributed.ring_attention import (
                sequence_parallel_attention)
            return sequence_parallel_attention(q, k, v, mesh, causal=True,
                                               mode="ring")
        return F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                              dropout_p=0.0)

    def _ln(self, x, scale, bias):
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, -1, keepdims=True)
        var = jnp.var(x32, -1, keepdims=True)
        y = (x32 - mu) * lax.rsqrt(var + 1e-5) * scale + bias
        return y.astype(x.dtype)

    def _block_tail(self, x, attn):
        """Post-attention half of the block (out-proj + MLP), shared by
        every cached-decode variant — ONE definition."""
        o = attn @ self.wo
        if self.bo is not None:
            o = o + self.bo
        x = x + o
        h = self._ln(x, self.ln2_scale, self.ln2_bias)
        if self.moe is not None:
            h, _ = self.moe(h, None)
        else:
            h = jax.nn.gelu(h @ self.wup + (self.bup if self.bup is not None
                                            else 0.0))
            h = h @ self.wdown
            if self.bdown is not None:
                h = h + self.bdown
        return x + h

    def _qkv(self, x, positions):
        """LN1 + fused QKV (+ rope at ``positions``) — the shared front
        half of every cached-decode variant (ONE definition).
        x: (B, K, d) → q (B,K,H,D), k/v (B,K,Hkv,D)."""
        K = x.shape[1]
        h = self._ln(x, self.ln1_scale, self.ln1_bias)
        qkv = h @ self.wqkv
        if self.bqkv is not None:
            qkv = qkv + self.bqkv
        q, k, v = self._split_qkv(qkv)
        if self.rope:
            pos2 = positions[:, None] + jnp.arange(K)[None, :]
            q = self._apply_rope(q, pos2)
            k = self._apply_rope(k, pos2)
        return q, k, v

    def _write_kv_rows(self, kv, k, v, positions):
        """Write each row's K new KV entries ((B, K, Hkv, D), any dtype)
        into the head-major caches at per-row ``positions`` — the ONE
        cache-write definition."""
        k_cache, v_cache = kv

        def write(cache, new, pos):  # (Hkv, T, D) ← (Hkv, K, D) at pos
            return lax.dynamic_update_slice(cache, new, (0, pos, 0))

        k_cache = jax.vmap(write)(
            k_cache, jnp.transpose(k, (0, 2, 1, 3)).astype(k_cache.dtype),
            positions)
        v_cache = jax.vmap(write)(
            v_cache, jnp.transpose(v, (0, 2, 1, 3)).astype(v_cache.dtype),
            positions)
        return k_cache, v_cache

    def _qkv_write(self, x, kv, positions):
        """`_qkv` + per-row cache write at ``positions``.
        x: (B, K, d) → (q (B,K,H,D), new k/v caches (B,Hkv,T,D))."""
        q, k, v = self._qkv(x, positions)
        k_cache, v_cache = self._write_kv_rows(kv, k, v, positions)
        return q, k_cache, v_cache

    def decode_rows(self, x, kv, positions, allow_kernel: bool = True):
        """K-token ragged decode that does NOT write the cache: the
        bandwidth-optimal serving primitive (VERDICT r5 decode work).

        The previous engine formulation carried the caches through the
        layer scan as xs AND ys, so XLA rebuilt the whole (L, S, H, T, D)
        buffer every token (~2x the cache size in pure copy traffic per
        step). Here the cache is read-only; the current K tokens'
        attention contribution is folded in analytically (their K/V rows
        ride alongside the prefix softmax as extra columns), and the rows
        are returned for the CALLER to write back — one tiny
        dynamic_update_slice per sequence per step instead of a
        full-cache rebuild.

        x: (B, K, d) embeddings at positions [positions[b],
        positions[b]+K); kv: head-major (B, Hkv, T, D) holding each row's
        prefix [0, positions[b]) (entries at/after positions[b] are
        ignored). Row (b, j) attends to the prefix plus new rows i <= j —
        the same [0, positions[b]+j] window as the cache-writing path.

        Returns (y, k_rows, v_rows) with rows (B, K, Hkv, D) in cache
        dtype.
        """
        b, K, d = x.shape
        k_cache, v_cache = kv
        T = k_cache.shape[2]
        q, k, v = self._qkv(x, positions)
        # round-trip the new rows through the CACHE dtype before they
        # enter attention: row i<j must look identical to verify row j
        # (K>1) as it would to a later K=1 step reading it from the
        # cache, or speculative acceptance would not be lossless when
        # cache_dtype differs from the compute dtype
        k = k.astype(k_cache.dtype)
        v = v.astype(v_cache.dtype)
        scale = 1.0 / math.sqrt(self.head_dim)
        if (allow_kernel and K == 1
                and T >= int(_flag("decode_kernel_min_t"))
                and _use_decode_kernel(T)):
            # long caches: the flash-decode kernel reads ONLY each row's
            # valid prefix blocks (clamped index maps); the fresh row is
            # folded into its online softmax analytically via the
            # returned (m, l) stats. The dense einsum below reads the
            # whole T whatever the lengths — at serving cache lengths
            # that is the dominant wasted bandwidth.
            from paddle_tpu.ops.pallas.decode_attention import (
                decode_attention, fold_fresh_row)
            o, m, l = decode_attention(
                q[:, 0].astype(k_cache.dtype), k_cache, v_cache,
                positions, scale=scale, return_stats=True)
            attn = fold_fresh_row(o, m, l, q[:, 0], k[:, 0], v[:, 0],
                                  scale, self.n_heads // self.kv_heads)
            attn = attn.reshape(b, K, d).astype(x.dtype)
            return self._block_tail(x, attn), k, v
        # GQA via grouped einsum against the UN-expanded cache (query
        # head h reads kv head h // group — same convention as the
        # flash-decode kernel); never jnp.repeat the cache in HBM
        group = self.n_heads // self.kv_heads
        qg = q.reshape(b, K, self.kv_heads, group, self.head_dim)
        att = jnp.einsum("bkhgd,bhtd->bhgkt", qg, k_cache) * scale
        k_pos = jnp.arange(T)[None, None, None, None, :]
        att = jnp.where(k_pos < positions[:, None, None, None, None],
                        att.astype(jnp.float32), -jnp.inf)
        # the K new rows attend to each other causally (row j sees rows
        # i <= j); their logits join the prefix as K extra columns
        att_new = jnp.einsum("bkhgd,bihd->bhgki", qg, k) * scale
        causal = jnp.arange(K)[None, None, None, :, None] \
            >= jnp.arange(K)[None, None, None, None, :]
        att_new = jnp.where(causal, att_new.astype(jnp.float32), -jnp.inf)
        full = jax.nn.softmax(
            jnp.concatenate([att, att_new], axis=-1), axis=-1)
        # probabilities stay in the COMPUTE dtype (only K/V round-trip
        # through the cache dtype): quantized-cache configs must not
        # also truncate the attention weights
        p_cache = full[..., :T].astype(x.dtype)
        p_new = full[..., T:].astype(x.dtype)
        attn = (jnp.einsum("bhgkt,bhtd->bkhgd", p_cache, v_cache)
                + jnp.einsum("bhgki,bihd->bkhgd", p_new, v))
        attn = attn.reshape(b, K, d).astype(x.dtype)
        return self._block_tail(x, attn), k, v

    def verify_step(self, x, kv, positions):
        """K-token decode with RAGGED per-row cache positions, writing the
        rows into the cache (≙ masked_multihead_attention in
        fused_multi_transformer_op.cu at K=1, which likewise takes a
        per-sequence ``sequence_lengths`` tensor; K>1 is the
        speculative-decoding verify primitive — no reference analog, the
        reference decodes strictly one token per kernel launch).

        One attention definition: delegates to `decode_rows` and writes
        the returned rows at ``positions`` (callers that own the cache
        buffer — the decode engine — call `decode_rows` directly and
        batch the writes).
        """
        y, k_rows, v_rows = self.decode_rows(x, kv, positions)
        return y, self._write_kv_rows(kv, k_rows, v_rows, positions)

    def decode_step(self, x, kv, positions):
        """One-token ragged decode: the Pallas flash-decode kernel when
        it can engage, else `verify_step` with K=1 (same einsum math —
        one definition, not a drifted copy)."""
        if not _use_decode_kernel(kv[0].shape[2]):
            return self.verify_step(x, kv, positions)
        b, L, d = x.shape
        q, k_cache, v_cache = self._qkv_write(x, kv, positions)
        from paddle_tpu.ops.pallas.decode_attention import decode_attention
        attn = decode_attention(q[:, 0].astype(k_cache.dtype), k_cache,
                                v_cache, positions + 1,
                                scale=1.0 / math.sqrt(self.head_dim))
        attn = attn.astype(x.dtype).reshape(b, 1, d)
        return self._block_tail(x, attn), (k_cache, v_cache)

    def forward_cached(self, x, kv, pos):
        """Decode/prefill step with a KV cache and a SCALAR start
        position (≙ fused_multi_transformer_op.cu CacheKV write + masked
        attention over the prefix). x: (B, L, d) at positions
        [pos, pos+L); delegates to the ragged-position variants."""
        b = x.shape[0]
        positions = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
        if x.shape[1] == 1:
            return self.decode_step(x, kv, positions)
        return self.verify_step(x, kv, positions)

    def forward(self, x, rng_key=None, aux_acc=None):
        b, s, d = x.shape
        h = self._ln(x, self.ln1_scale, self.ln1_bias)
        qkv = h @ self.wqkv
        if self.bqkv is not None:
            qkv = qkv + self.bqkv
        q, k, v = self._split_qkv(qkv)
        q = _shard_act(q, LAYOUT.activation("sp", "tp", None))
        k = _shard_act(k, LAYOUT.activation("sp", "tp", None))
        v = _shard_act(v, LAYOUT.activation("sp", "tp", None))
        if self.rope:
            q = self._apply_rope(q, jnp.arange(s))
            k = self._apply_rope(k, jnp.arange(s))
        attn = self._attention(q, k, v, s)
        attn = attn.reshape(b, s, d)
        o = attn @ self.wo
        if self.bo is not None:
            o = o + self.bo
        x = x + _maybe_dropout(o, self.dropout, rng_key, 1)
        h = self._ln(x, self.ln2_scale, self.ln2_bias)
        if self.moe is not None:
            h, aux = self.moe(h, rng_key)
            if aux_acc is not None:
                aux_acc.append(aux)
        else:
            h = jax.nn.gelu(h @ self.wup + (self.bup if self.bup is not None
                                            else 0.0))
            h = _shard_act(h, LAYOUT.activation("sp", "tp"))
            h = h @ self.wdown
            if self.bdown is not None:
                h = h + self.bdown
        x = x + _maybe_dropout(h, self.dropout, rng_key, 2)
        return _shard_act(x, LAYOUT.activation("sp", None))


_BATCH_AXES = LAYOUT.batch_axes

_PIPELINE_DEPTH = 0


def _in_pipeline() -> bool:
    return _PIPELINE_DEPTH > 0


def _maybe_dropout(x, p, key, salt):
    if p == 0.0 or key is None:
        return x
    k = jax.random.fold_in(key, salt)
    keep = jax.random.bernoulli(k, 1.0 - p, x.shape)
    return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)


def _shard_act(x, spec: P):
    """Constrain activation sharding when a global mesh is installed and we
    are under its trace; no-op otherwise (single-chip / no mesh)."""
    from paddle_tpu.distributed.mesh import get_mesh
    mesh = get_mesh()
    if mesh is None or mesh.size == 1:
        return x
    try:
        return lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    except Exception:
        return x


def final_ln(x, scale, bias, eps: float = 1e-5):
    """The head's pre-projection LayerNorm (fp32 statistics) — the single
    definition shared by GPT.head, fused_lm_loss, and the decode engine."""
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps) * scale
            + bias).astype(x.dtype)


def _gathered_table(w):
    """ZeRO-3 gather-for-use on an fsdp-sharded embedding table: a lookup
    from a d-sharded table produces d-sharded rows, and the partitioner has
    no efficient transition from that to batch-sharded activations — it
    falls back to "involuntary full rematerialization" (MULTICHIP_r02
    phase-D warning). All-gathering the d dim first (what GroupSharded
    stage-3 forward pre-hooks do, group_sharded_stage3.py:59) keeps the
    gather local and the transition free."""
    from paddle_tpu.distributed.mesh import get_mesh
    mesh = get_mesh()
    if mesh is None or dict(mesh.shape).get("fsdp", 1) == 1:
        return w
    try:
        return lax.with_sharding_constraint(
            w, NamedSharding(mesh, P("tp", None)))
    except Exception:
        return w


class GPT(Module):
    """≙ PaddleNLP GPTForPretraining (decoder-only, learned positions)."""

    def __init__(self, cfg: GPTConfig, seed: int = 0):
        super().__init__()
        self.cfg = cfg
        key = jax.random.PRNGKey(seed)
        kw, kp, kh, kb = jax.random.split(key, 4)
        dt = cfg.dtype
        self.wte = Parameter(_normal(kw, (cfg.vocab_size, cfg.d_model),
                                     0.02, dt))
        # rope models carry positions in the attention rotation, not a
        # learned table
        self.wpe = None if cfg.rope else Parameter(
            _normal(kp, (cfg.max_seq_len, cfg.d_model), 0.01, dt))
        if cfg.moe_experts > 0 and cfg.moe_every < 1:
            raise ValueError(
                f"moe_every must be >= 1, got {cfg.moe_every}")
        if cfg.moe_experts > 0 and cfg.remat:
            raise ValueError("moe_experts with remat is unsupported (the "
                             "aux-loss accumulator cannot cross a "
                             "jax.checkpoint boundary)")
        self.blocks = LayerList([
            GPTBlock(cfg, jax.random.fold_in(kb, i),
                     use_moe=(cfg.moe_experts > 0
                              and (i + 1) % cfg.moe_every == 0))
            for i in range(cfg.n_layers)])
        self.lnf_scale = Parameter(jnp.ones((cfg.d_model,), jnp.float32))
        self.lnf_bias = Parameter(jnp.zeros((cfg.d_model,), jnp.float32))
        if not cfg.tie_embeddings:
            self.lm_head = Parameter(_normal(kh, (cfg.d_model,
                                                  cfg.vocab_size), 0.02, dt))
        else:
            self.lm_head = None

    def merge_params(self, params):
        """Module.merge_params plus stacked-state awareness: when the
        state carries ``_stacked_blocks`` (init_train_state(stacked=True)),
        ALSO rebind each per-layer block to a sliced view of the stack —
        otherwise every consumer outside the scan forward (decode
        forward_cached, generate, state_dict export) would silently read
        the init-time weights still sitting in self.blocks. Inside jit
        the unconsumed slices are dead code XLA eliminates; outside jit
        they materialize only if actually used."""
        new = Module.merge_params(self, params)
        st = getattr(new, "_stacked_blocks", None)
        if st is not None:
            for i in range(new.cfg.n_layers):
                blk = jax.tree_util.tree_map(lambda x, i=i: x[i], st)
                object.__setattr__(new.blocks, f"item_{i}", blk)
        return new

    def embed(self, tokens):
        s = tokens.shape[-1]
        if _tp_sharded_vocab(tokens.shape[0], s, self.cfg.vocab_size,
                             self.cfg.d_model):
            from paddle_tpu.distributed.mesh import get_mesh
            from paddle_tpu.distributed.mp_ops import (
                vocab_parallel_embedding)
            # ≙ VocabParallelEmbedding (mp_layers.py:37): masked local
            # lookup + psum — the (V, d) table is never all-gathered
            x = vocab_parallel_embedding(self.wte, tokens, mesh=get_mesh())
        else:
            x = jnp.take(_gathered_table(self.wte), tokens, axis=0)
        if self.wpe is not None:  # rope models position in attention
            x = x + self.wpe[:s]
        return _shard_act(x, LAYOUT.activation("sp", None))

    def head(self, x):
        x = final_ln(x, self.lnf_scale, self.lnf_bias)
        w = self.wte.T if self.lm_head is None else self.lm_head
        logits = x @ w
        return _shard_act(logits, LAYOUT.activation("sp", "tp"))

    def hidden_states(self, tokens, rng_key=None, aux_acc=None):
        """Final hidden states (B, S, d) — forward minus the LM head (the
        fused-CE loss path consumes these directly so (B, S, V) logits
        never materialize).

        Homogeneous (dense) stacks run the layer loop as lax.scan over
        in-jit-stacked block weights: the compiled program contains ONE
        layer body instead of L unrolled copies, which cuts the 1.3B
        train-step compile from tens of minutes to minutes (the decode
        path has always done this; XLA's cost for the in-trace stack is
        a single fused gather the partitioner shards like the weights).
        MoE stacks (structurally heterogeneous blocks) and the
        ``scan_layers=False`` escape hatch keep the unrolled loop."""
        x = self.embed(tokens)
        L = self.cfg.n_layers
        dense = all(self.blocks[i].moe is None for i in range(L))
        prestacked = getattr(self, "_stacked_blocks", None)
        use_scan = prestacked is not None or (dense and L > 1
                                              and _flag("scan_layers"))
        if use_scan and scan_partition_hazard():
            # ≥3-axis mesh on this CPU build: the scanned backward
            # miscompiles (see scan_partition_hazard) — unroll instead.
            # merge_params bound per-layer views of a pre-stacked state
            # onto self.blocks, so the unrolled loop serves both forms.
            use_scan = False
        if use_scan:
            # in-trace stacking copies every block weight (and its grad
            # transpose un-stacks) — ~2x block-param HBM the unrolled
            # loop never needed; a state built by
            # init_train_state(stacked=True) carries the weights
            # pre-stacked so the scan consumes them with ZERO extra
            # in-program buffers (this is what made the 1.3B step OOM
            # on 16GB while the round-start unrolled form fit)
            stacked = prestacked if prestacked is not None else \
                stack_block_weights([self.blocks[i] for i in range(L)])
            if prestacked is not None:
                from paddle_tpu.distributed.mesh import get_mesh
                mesh = get_mesh()
                if mesh is not None and mesh.size > 1:
                    # re-assert the layer-leading PARTITION_RULES specs
                    # inside the trace: the scanned body then runs
                    # fsdp/tp-sharded matmuls on each layer slice instead
                    # of the partitioner falling back to replicating the
                    # whole (L, ...) stack. (The in-trace-stacked branch
                    # keeps propagation-only sharding — constraining it
                    # would perturb the established per-layer numerics.)
                    stacked = _shard_stacked(stacked, self.blocks[0],
                                             mesh)

            def body(h, blk_i):
                blk, i = blk_i
                k = (jax.random.fold_in(rng_key, i)
                     if rng_key is not None else None)
                return blk(h, k), None

            if self.cfg.remat:
                body = jax.checkpoint(body)
            x, _ = lax.scan(body, x, (stacked, jnp.arange(L)))
            return x
        # remat never coexists with MoE (enforced in __init__), so the
        # checkpointed closure does not capture aux_acc
        blk_fn = (jax.checkpoint(lambda b, h, k: b(h, k),
                                 static_argnums=())
                  if self.cfg.remat
                  else (lambda b, h, k: b(h, k, aux_acc=aux_acc)))
        for i in range(L):
            k = (jax.random.fold_in(rng_key, i)
                 if rng_key is not None else None)
            x = blk_fn(self.blocks[i], x, k)
        return x

    def forward(self, tokens, rng_key=None, return_aux=False):
        """return_aux=True additionally returns the summed MoE load-balance
        aux loss (zeros for dense configs); threaded explicitly — no
        global state, safe across multiple forwards per trace."""
        aux_acc = []
        x = self.hidden_states(tokens, rng_key, aux_acc)
        logits = self.head(x)
        if return_aux:
            aux = jnp.zeros((), jnp.float32)
            for a in aux_acc:
                aux = aux + a
            return logits, aux
        return logits

    # -- KV-cache decoding (≙ inference/api/analysis_predictor.h:95 decode
    # serving + fused_multi_transformer_op.cu CacheKV) ---------------------

    def init_cache(self, batch: int, max_len: Optional[int] = None,
                   dtype=None):
        """Preallocated per-layer (k, v) caches, head-major (B, H, T, D)
        each (the flash-decode kernel's layout)."""
        cfg = self.cfg
        T = max_len or cfg.max_seq_len
        dt = dtype or cfg.dtype
        shape = (batch, cfg.kv_heads, T, cfg.head_dim)
        return [(jnp.zeros(shape, dt), jnp.zeros(shape, dt))
                for _ in range(cfg.n_layers)]

    def embed_at(self, tokens, pos):
        """Embedding for a chunk starting at (possibly traced) `pos`."""
        L = tokens.shape[-1]
        x = jnp.take(_gathered_table(self.wte), tokens, axis=0)
        if self.wpe is None:
            return x
        return x + lax.dynamic_slice_in_dim(self.wpe, pos, L)

    def forward_cached(self, tokens, cache, pos):
        """(B, L) tokens at positions [pos, pos+L) → (logits, new_cache)."""
        x = self.embed_at(tokens, pos)
        new_cache = []
        for i in range(self.cfg.n_layers):
            x, kv = self.blocks[i].forward_cached(x, cache[i], pos)
            new_cache.append(kv)
        return self.head(x), new_cache


def _sample_token(logits, rng, temperature: float, top_p: float,
                  top_k: int):
    """Greedy (temperature==0) / temperature / top-k / nucleus sampling.
    logits: (B, V) fp32."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with mass >= top_p (shifted cumsum keeps
        # the first token crossing the threshold)
        keep = (cum - probs) < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_l, jnp.inf), axis=-1)
        logits = jnp.where(logits < cutoff[:, None], -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def generate(model: "GPT", tokens, max_new_tokens: int,
             temperature: float = 0.0, top_p: float = 1.0, top_k: int = 0,
             eos_id: Optional[int] = None, rng=None,
             max_len: Optional[int] = None):
    """Autoregressive generation with a functional KV cache (≙ the decode
    loop the reference serves through AnalysisPredictor +
    fused_multi_transformer; VERDICT r1 item 2).

    tokens: (B, S0) prompt. Returns (B, S0 + max_new_tokens) int32 — after
    eos (if given) positions are padded with eos. Greedy by default;
    temperature/top-k/top-p sampling otherwise. The decode loop is a
    lax.scan inside ONE jit, so serving pays a single dispatch.
    """
    cfg = model.cfg
    b, s0 = tokens.shape
    total = s0 + max_new_tokens
    T = max_len or cfg.max_seq_len
    assert total <= T, f"{total} tokens exceed cache length {T}"
    if rng is None:
        rng = jax.random.PRNGKey(0)

    params, _ = model.split_params()
    key = (b, s0, T, max_new_tokens, temperature, top_p, top_k, eos_id)
    cache_d = _GEN_CACHE.setdefault(model, collections.OrderedDict())
    run = cache_d.get(key)
    if run is None:
        run = jax.jit(functools.partial(
            _generate_impl, model, b, s0, T, max_new_tokens, temperature,
            top_p, top_k, eos_id))
        cache_d[key] = run
        # LRU bound: a long-lived server sweeping shapes must not
        # accumulate compiled executables forever (VERDICT r2 weak 11)
        while len(cache_d) > _GEN_CACHE_MAX:
            cache_d.popitem(last=False)
    else:
        cache_d.move_to_end(key)
    return run(params, jnp.asarray(tokens, jnp.int32), rng)


def _stacked_forward_cached(m: GPT, stacked, tokens, kc, vc, pos):
    """Cached forward with the layer loop as lax.scan over stacked weights:
    the compiled decode program contains ONE layer body instead of L
    unrolled copies — at 1.3B this cuts serving compile time ~L×.
    kc/vc: (L, B, Hkv, T, D)."""
    x = m.embed_at(tokens, pos)

    def layer(x, blk_kv):
        blk, k_l, v_l = blk_kv
        x, (k_l, v_l) = blk.forward_cached(x, (k_l, v_l), pos)
        return x, (k_l, v_l)

    x, (kc, vc) = lax.scan(layer, x, (stacked, kc, vc))
    return m.head(x), kc, vc


def _stacked_decode_rows(m: GPT, stacked, cur, kc, vc, pos):
    """One-token cached decode with the caches as READ-ONLY scan xs:
    each layer emits only its new KV row (`GPTBlock.decode_rows`), and
    because every batch row decodes at the same scalar ``pos``, ONE
    dynamic_update_slice per cache writes all (L, B) rows. The previous
    formulation carried the caches through the scan as ys, making XLA
    rebuild the whole (L, B, Hkv, T, D) buffer every token (~2x the
    cache size in copy traffic — the dominant decode overhead measured
    on hardware, r5)."""
    b = cur.shape[0]
    x = m.embed_at(cur[:, None], pos)
    positions = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))

    def layer(x, blk_kv):
        blk, k_l, v_l = blk_kv
        y, k_rows, v_rows = blk.decode_rows(x, (k_l, v_l), positions)
        return y, (k_rows, v_rows)

    x, (k_rows, v_rows) = lax.scan(layer, x, (stacked, kc, vc))
    kr = jnp.transpose(k_rows, (0, 1, 3, 2, 4))   # (L, B, Hkv, 1, D)
    vr = jnp.transpose(v_rows, (0, 1, 3, 2, 4))
    kc = lax.dynamic_update_slice(kc, kr, (0, 0, 0, pos, 0))
    vc = lax.dynamic_update_slice(vc, vr, (0, 0, 0, pos, 0))
    return m.head(x), kc, vc


def _generate_impl(model, b, s0, T, max_new_tokens, temperature, top_p,
                   top_k, eos_id, params, tokens, rng):
    m = model.merge_params(params)
    homogeneous = all(m.blocks[i].moe is None
                      for i in range(m.cfg.n_layers))
    if homogeneous:
        return _generate_scan(m, b, s0, T, max_new_tokens, temperature,
                              top_p, top_k, eos_id, tokens, rng)
    cache = m.init_cache(b, T)
    logits, cache = m.forward_cached(tokens, cache, 0)
    last = logits[:, -1].astype(jnp.float32)
    rng, k0 = jax.random.split(rng)
    nxt = _sample_token(last, k0, temperature, top_p, top_k)
    done = jnp.zeros((b,), bool) if eos_id is None else (nxt == eos_id)
    if max_new_tokens == 1:
        return jnp.concatenate([tokens, nxt[:, None]], axis=1)

    def step(carry, _):
        cache, cur, pos, rng, done = carry
        logits, cache = m.forward_cached(cur[:, None], cache, pos)
        rng, k = jax.random.split(rng)
        nx = _sample_token(logits[:, -1].astype(jnp.float32), k,
                           temperature, top_p, top_k)
        if eos_id is not None:
            nx = jnp.where(done, eos_id, nx)
            done = done | (nx == eos_id)
        return (cache, nx, pos + 1, rng, done), nx

    (_, _, _, _, _), rest = lax.scan(
        step, (cache, nxt, jnp.int32(s0), rng, done),
        None, length=max_new_tokens - 1)
    out = jnp.concatenate([nxt[:, None], rest.T], axis=1)
    return jnp.concatenate([tokens, out], axis=1)


def _decode_mesh(cfg, b):
    """The active mesh when it can shard decode: tp divides heads, dp
    divides batch (≙ HybridParallelInference serving TP,
    fleet/utils/hybrid_parallel_inference.py:23)."""
    from paddle_tpu.distributed.mesh import get_mesh
    mesh = get_mesh()
    if mesh is None or mesh.size == 1 or _in_pipeline():
        return None
    shape = dict(mesh.shape)
    if (cfg.n_heads % shape.get("tp", 1)
            or cfg.kv_heads % shape.get("tp", 1)   # GQA cache sharding
            or b % shape.get("dp", 1)):
        return None
    return mesh


def stacked_block_specs(template_blk, spec_fn=None):
    """Per-leaf PartitionSpecs for the scan-stacked form of one template
    block: each param's PARTITION_RULES spec behind a leading (replicated)
    layer axis (``LAYOUT.stacked``). Leaf→name mapping goes by object
    identity against the template block (Module pytree paths are
    index-keyed). ``spec_fn`` maps a param name to its per-block spec
    (default: this module's `partition_spec`; models.bert passes its
    own). Returns (template_leaves, treedef, specs) — derivable BEFORE
    any stacking happens, so init can place the stacked state with
    out_shardings instead of re-laying it out afterwards."""
    spec_fn = spec_fn or partition_spec
    id2name = {id(v): n for n, v in template_blk.named_parameters()}
    tleaves, treedef = jax.tree_util.tree_flatten(template_blk)
    specs = [LAYOUT.stacked(spec_fn(id2name.get(id(t), "")),
                            ndim=t.ndim + 1)
             for t in tleaves]
    return tleaves, treedef, specs


def stacked_partition_specs(stacked, template_blk, spec_fn=None):
    """Per-leaf PartitionSpecs for an already scan-stacked block pytree —
    the ONE spec derivation shared by the sharded generate path, the
    tensor-parallel DecodeEngine, and the sharded-stacked train state
    (which derives them pre-stack via `stacked_block_specs`)."""
    _, _, specs = stacked_block_specs(template_blk, spec_fn)
    sleaves, streedef = jax.tree_util.tree_flatten(stacked)
    return sleaves, streedef, specs


def scan_partition_hazard() -> bool:
    """True when the scan-over-stacked-layers forward must NOT be used
    under the current global mesh: on this CPU XLA build (jax 0.4.37),
    GSPMD partitioning of a ``lax.scan`` whose xs carry the stacked
    block weights MISCOMPILES the backward once the mesh has three or
    more nontrivial axes (dp×tp×fsdp). Bisect evidence (tracked as the
    former standing tier-1 reds, test_gpt_model tp_fsdp /
    test_bert tp_sharded parity): every 1- and 2-axis mesh is
    BIT-exact against the single-device step, the 3-axis mesh is off
    by ~1e-3 in the loss and ~0.1 absolute in the wte gradient, and
    float64 ground truth sides with the dense program (grad error
    2.7e-8 dense vs 0.117 sharded) — wrong math, not reduction-order
    noise. Unrolling the layer loop restores bit-exactness; activation
    constraints, the vocab-parallel embedding, and `_gathered_table`
    were all ruled out. TPU backends keep the scan (the 1.3B compile
    time depends on it, and the bug reproduces only on this CPU
    build's partitioner)."""
    import jax as _jax
    if _jax.default_backend() != "cpu":
        return False
    from paddle_tpu.distributed.mesh import get_mesh
    mesh = get_mesh()
    if mesh is None:
        return False
    return sum(1 for v in mesh.shape.values() if v > 1) >= 3


def _shard_stacked(stacked, template_blk, mesh, spec_fn=None):
    """Constrain stacked per-layer weights by PARTITION_RULES with a
    leading (replicated) layer axis, so the decode jit runs TP-sharded
    matmuls instead of replicating every block."""
    sleaves, streedef, specs = stacked_partition_specs(stacked,
                                                       template_blk,
                                                       spec_fn)
    out = []
    for leaf, spec in zip(sleaves, specs):
        try:
            leaf = lax.with_sharding_constraint(
                leaf, NamedSharding(mesh, mesh_safe_spec(spec, mesh)))
        except Exception:
            pass
        out.append(leaf)
    return jax.tree_util.tree_unflatten(streedef, out)


def _generate_scan(m: GPT, b, s0, T, max_new_tokens, temperature, top_p,
                   top_k, eos_id, tokens, rng):
    """Homogeneous (dense) stack: layer loop via lax.scan (small HLO)."""
    cfg = m.cfg
    L = cfg.n_layers
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[m.blocks[i] for i in range(L)])
    shape = (L, b, cfg.kv_heads, T, cfg.head_dim)
    kc = jnp.zeros(shape, cfg.dtype)
    vc = jnp.zeros(shape, cfg.dtype)
    mesh = _decode_mesh(cfg, b)
    if mesh is not None:
        # KV cache sharded over tp heads + dp batch: the whole decode loop
        # then runs TP-parallel with psum'd attention/MLP outputs
        kv_spec = NamedSharding(mesh, P(None, "dp", "tp", None, None))
        kc = lax.with_sharding_constraint(kc, kv_spec)
        vc = lax.with_sharding_constraint(vc, kv_spec)
        stacked = _shard_stacked(stacked, m.blocks[0], mesh)
    logits, kc, vc = _stacked_forward_cached(m, stacked, tokens, kc, vc, 0)
    rng, k0 = jax.random.split(rng)
    nxt = _sample_token(logits[:, -1].astype(jnp.float32), k0, temperature,
                        top_p, top_k)
    done = jnp.zeros((b,), bool) if eos_id is None else (nxt == eos_id)
    if max_new_tokens == 1:
        return jnp.concatenate([tokens, nxt[:, None]], axis=1)

    def step(carry, _):
        kc, vc, cur, pos, rng, done = carry
        logits, kc, vc = _stacked_decode_rows(
            m, stacked, cur, kc, vc, pos)
        rng, k = jax.random.split(rng)
        nx = _sample_token(logits[:, -1].astype(jnp.float32), k,
                           temperature, top_p, top_k)
        if eos_id is not None:
            nx = jnp.where(done, eos_id, nx)
            done = done | (nx == eos_id)
        return (kc, vc, nx, pos + 1, rng, done), nx

    _, rest = lax.scan(step, (kc, vc, nxt, jnp.int32(s0), rng, done),
                       None, length=max_new_tokens - 1)
    out = jnp.concatenate([nxt[:, None], rest.T], axis=1)
    return jnp.concatenate([tokens, out], axis=1)


_GEN_CACHE = weakref.WeakKeyDictionary()
_GEN_CACHE_MAX = 8  # compiled-executable LRU bound per model

GPT.generate = generate


# ---------------------------------------------------------------------------
# Loss & sharding rules
# ---------------------------------------------------------------------------

def _tp_sharded_vocab(b, s, vocab, d_model=None) -> bool:
    """True when the global mesh tp-shards the vocab axis and every mapped
    dim divides its mesh axes (shard_map's requirement; GSPMD tolerates odd
    shapes, shard_map does not)."""
    from paddle_tpu.distributed.mesh import get_mesh
    mesh = get_mesh()
    if mesh is None or _in_pipeline():
        return False
    shape = dict(mesh.shape)
    tp = shape.get("tp", 1)
    fsdp = shape.get("fsdp", 1)
    return (tp > 1 and vocab % tp == 0
            and s % shape.get("sp", 1) == 0
            and b % (shape.get("dp", 1) * fsdp) == 0
            and (d_model is None or d_model % fsdp == 0))


def lm_loss(logits, labels):
    """Causal LM next-token loss; logits (B,S,V) fp32-softmaxed.

    When the mesh tp-shards the vocab axis, dispatches to the
    vocab-parallel CE (mp_ops.parallel_cross_entropy ≙ the reference's
    c_softmax_with_cross_entropy_op.cu) — no device ever materializes a
    full-vocab logit row. Dense path otherwise."""
    b, s, vocab = logits.shape
    if _tp_sharded_vocab(b, s, vocab):
        from paddle_tpu.distributed.mesh import get_mesh
        from paddle_tpu.distributed.mp_ops import parallel_cross_entropy
        # keep shapes sp/tp-divisible: score all S positions, mask the last
        # (its next-token label does not exist) via ignore_index
        shifted = jnp.concatenate(
            [labels[:, 1:], jnp.full((b, 1), -1, labels.dtype)], axis=1)
        tok = parallel_cross_entropy(logits, shifted, mesh=get_mesh(),
                                     ignore_index=-1)
        return jnp.sum(tok) / (b * (s - 1))
    logits = logits[:, :-1].astype(jnp.float32)
    labels = labels[:, 1:]
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None],
                                 axis=-1)[..., 0]
    return jnp.mean(logz - picked)


def _use_fused_ce(cfg) -> bool:
    """Route the train loss through the Pallas fused blockwise CE.
    Requires: kernel flag on, dense stack, no multi-device mesh (the
    sharded cases go through parallel_cross_entropy / GSPMD), and a vocab
    with a 128-multiple block divisor."""
    if not _flag("use_pallas_kernels") or cfg.moe_experts > 0:
        return False
    from paddle_tpu.distributed.mesh import get_mesh
    mesh = get_mesh()
    if mesh is not None and mesh.size > 1:
        return False
    from paddle_tpu.ops.pallas.fused_ce import _pick_block_v
    try:
        _pick_block_v(cfg.vocab_size, 512)
    except ValueError:
        return False
    return True


def fused_lm_loss(m: GPT, tokens, rng_key=None, force: bool = False):
    """Causal LM loss with head-LN + LM projection + softmax-CE fused so
    the (B, S, V) logits and their grads never exist in HBM
    (≙ c_softmax_with_cross_entropy_op.cu:38-192 — here the fusion also
    swallows the projection matmul, the reference only fuses the CE).
    Falls back to forward()+lm_loss when the kernel can't engage."""
    if not force and not _use_fused_ce(m.cfg):
        return lm_loss(m(tokens, rng_key=rng_key), tokens)
    from paddle_tpu.ops.pallas.fused_ce import fused_softmax_cross_entropy
    x = m.hidden_states(tokens, rng_key)
    b, s, d = x.shape
    xn = final_ln(x, m.lnf_scale, m.lnf_bias)
    w = m.wte if m.lm_head is None else m.lm_head.T   # (V, d)
    rows = xn[:, :-1].reshape(b * (s - 1), d)
    labels = tokens[:, 1:].reshape(-1)
    per_tok = fused_softmax_cross_entropy(rows, w, labels)
    return jnp.sum(per_tok) / (b * (s - 1))


# (regex on param path → PartitionSpec). Megatron-style TP composed with
# ZeRO-3-style fsdp (ref: mp_layers.py + group_sharded_stage3.py), spelled
# in the canonical SpecLayout vocabulary (distributed.mesh.LAYOUT) so GPT,
# BERT, the planner, and auto_parallel all speak one sharding language.
PARTITION_RULES = (
    (r"wte$", LAYOUT.vocab_embedding()),
    (r"wpe$", LAYOUT.position_table()),
    (r"lm_head$", LAYOUT.vocab_head()),
    (r"wqkv$", LAYOUT.column()),
    (r"bqkv$", LAYOUT.column_bias()),
    (r"wo$", LAYOUT.row()),
    (r"wup$", LAYOUT.column()),
    (r"bup$", LAYOUT.column_bias()),
    (r"wdown$", LAYOUT.row()),
    (r"(bo|bdown)$", LAYOUT.row_bias()),
    (r"(ln1|ln2|lnf)_(scale|bias)$", LAYOUT.norm()),
) + EXPERT_PARTITION_RULES


def partition_spec(path: str) -> P:
    for pat, spec in PARTITION_RULES:
        if re.search(pat, path):
            return spec
    return P()


def param_shardings(params: Dict[str, jax.Array], mesh: Mesh):
    return {k: NamedSharding(mesh, partition_spec(k)) for k in params}


def shard_params(params: Dict[str, jax.Array], mesh: Mesh):
    """Place a param dict onto the mesh per PARTITION_RULES (≙ the moment
    fleet.distributed_model() scatters weights).

    Always copies: device_put may alias when the sharding already matches,
    and the donating train steps would then delete the caller's arrays."""
    shardings = param_shardings(params, mesh)
    return {k: jax.device_put(jnp.copy(v), shardings[k])
            for k, v in params.items()}


# ---------------------------------------------------------------------------
# Train step builders
# ---------------------------------------------------------------------------

def build_train_step(model: GPT, optimizer, mesh: Optional[Mesh] = None,
                     donate: bool = True):
    """One jitted SPMD train step: fwd → loss → bwd → optimizer update.

    Parallelism (dp/fsdp/tp/sp) comes entirely from operand shardings +
    the activation constraints inside the model — XLA inserts all
    collectives (SURVEY §5.8 mapping). ≙ the reference's
    HybridParallelOptimizer.step + EagerReducer allreduce path.

    With PT_NUMERICS_EVERY > 0 (ISSUE 18) the step returns a 4th
    output: the packed numerics vector — per-layer grad AND
    param-update stats over the stacked layer axis plus the NaN
    provenance header — at the configured cadence. Capture reads the
    grads/updates the step already computed, so it cannot perturb the
    update math.
    """
    from paddle_tpu.observability import numerics as _nm
    num_on = _nm.enabled()
    num_box = _nm.LayoutBox()

    def step(params, opt_state, tokens, rng):
        def loss_fn(p):
            m = model.merge_params(p)
            if model.cfg.moe_experts > 0:
                logits, aux = m(tokens, rng_key=rng, return_aux=True)
                return lm_loss(logits, tokens) \
                    + model.cfg.moe_aux_weight * aux
            # dense: fused blockwise CE when it can engage — the (B,S,V)
            # logits never hit HBM (falls back internally otherwise)
            return fused_lm_loss(m, tokens, rng_key=rng)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = _nm.poison_grads(grads, step_count=opt_state["step"])
        new_params, new_state = optimizer.update(grads, opt_state, params)
        if num_on:
            updates = jax.tree_util.tree_map(
                lambda n, o: n - o, new_params, params)
            packed = _nm.capture_step(
                grads, loss=loss, updates=updates,
                step_count=opt_state["step"], box=num_box)
            return new_params, new_state, loss, packed
        return new_params, new_state, loss

    kw = {}
    if donate:
        kw["donate_argnums"] = (0, 1)
    fn = jax.jit(step, **kw)
    fn.numerics_layout = num_box
    return fn


def register_stacked_decay_mask(optimizer, template_blk, n_layers: int,
                                name_of, entry: str):
    """Resolve a name-keyed weight-decay mask against the block template
    ONCE and broadcast it along the layer axis: leaf j of the stacked
    block pytree gets an (L, 1, ...) float mask whose layer-l entry is
    ``decay_fn(name_of(l, <param name>))`` — exactly the names the
    per-layer state presents — registered on the optimizer under the
    stacked ``entry`` (`AdamW.set_decay_mask`). Layer-varying decisions
    are preserved (the mask has one row per layer); the common uniform
    case folds into a broadcast at compile time. Shared by the GPT and
    BERT stacked layouts."""
    decay_fn = optimizer.apply_decay_param_fun
    set_mask = getattr(optimizer, "set_decay_mask", None)
    if set_mask is None:
        raise ValueError(
            f"optimizer {type(optimizer).__name__} sets "
            "apply_decay_param_fun but has no set_decay_mask(); the "
            "stacked layout needs the masked update path "
            "(paddle_tpu.optimizer.AdamW)")
    id2name = {id(v): n for n, v in template_blk.named_parameters()}
    tleaves, treedef = jax.tree_util.tree_flatten(template_blk)
    masks = []
    for t in tleaves:
        name = id2name.get(id(t), "")
        col = [float(bool(decay_fn(name_of(i, name))))
               for i in range(n_layers)]
        masks.append(jnp.asarray(col, jnp.float32).reshape(
            (n_layers,) + (1,) * t.ndim))
    set_mask(entry, jax.tree_util.tree_unflatten(treedef, masks))


def init_train_state(model: GPT, optimizer, mesh: Optional[Mesh] = None,
                     stacked: bool = False):
    """Params + optimizer state, sharded onto the mesh if given.

    ``stacked=True`` (dense models): block weights enter the state
    PRE-stacked along a leading layer axis, under one ``_stacked_blocks``
    key that merge_params binds back onto the model. The scan-over-layers
    forward then reads them directly — without this, the in-trace
    ``stack_block_weights`` materializes a full copy of every block
    weight inside the step (plus the stacked cotangent on the way back),
    which pushed the 1.3B train step past 16GB HBM.

    With a multi-device ``mesh`` the stacked leaves are placed by their
    `stacked_block_specs` (PARTITION_RULES behind a replicated layer
    axis, `LAYOUT.stacked`): the stacking jit emits them directly into
    that layout via out_shardings, so the scan-over-layers fast path and
    hybrid dp/fsdp/tp parallelism compose instead of excluding each
    other. An ``apply_decay_param_fun`` decay mask is resolved against
    the block template once and broadcast along the layer axis
    (`AdamW.set_decay_mask`) — the name-keyed fn itself can't see into
    the folded '_stacked_blocks' entry."""
    if stacked:
        L = model.cfg.n_layers
        if any(model.blocks[i].moe is not None for i in range(L)):
            raise ValueError("MoE stacks are heterogeneous; stacked "
                             "layout needs a dense model")
        params, _ = model.split_params()
        params = {k: v for k, v in params.items()
                  if not k.startswith("blocks.")}
        blocks = [model.blocks[i] for i in range(L)]
        if getattr(optimizer, "apply_decay_param_fun", None) is not None:
            register_stacked_decay_mask(
                optimizer, model.blocks[0], L,
                lambda i, name: f"blocks.item_{i}.{name}",
                "_stacked_blocks")
        if mesh is not None and mesh.size > 1:
            params = shard_params(params, mesh)
            tleaves, treedef, specs = stacked_block_specs(model.blocks[0])
            sh_tree = jax.tree_util.tree_unflatten(
                treedef, [NamedSharding(mesh, mesh_safe_spec(s, mesh))
                          for s in specs])
            # one jit stacks AND places: every stacked leaf lands sharded
            # by its layer-leading spec — never materialized replicated —
            # and its buffers are fresh, so step donation can't free the
            # module's own arrays
            params["_stacked_blocks"] = jax.jit(
                stack_block_weights, out_shardings=sh_tree)(blocks)
            opt_state = jax.jit(optimizer.init)(params)
        else:
            # jnp.stack allocates fresh buffers, so donation in the train
            # step never frees the module's own arrays
            params = {k: jnp.copy(v) for k, v in params.items()}
            params["_stacked_blocks"] = stack_block_weights(blocks)
            opt_state = optimizer.init(params)
        return params, opt_state
    params, _ = model.split_params()
    if mesh is not None and mesh.size > 1:
        params = shard_params(params, mesh)
        opt_state = jax.jit(optimizer.init)(params)
    else:
        # copy: the jitted step donates its inputs, and split_params aliases
        # the module's own arrays — donation must not delete those.
        params = {k: jnp.copy(v) for k, v in params.items()}
        opt_state = optimizer.init(params)
    return params, opt_state


# ---------------------------------------------------------------------------
# SPMD pipeline parallelism (GPipe schedule in one XLA program)
# ---------------------------------------------------------------------------

def stack_blocks(model: GPT, n_stages: int):
    """Stack the per-layer block pytrees into one pytree with leading axes
    (n_stages, layers_per_stage, ...). The stage axis is sharded over 'pp'.
    ≙ PipelineLayer._segment_network (parallel_layers/pp_layers.py:550).

    MoE models pipeline too, provided the stack is homogeneous (either
    every block dense or every block MoE — moe_every=1); mixed stacks
    cannot share one stacked pytree. Uneven L % n_stages is handled by
    padding the short stages with masked (skipped) layer slots — use
    stack_blocks_uneven to get the mask.
    """
    stacked, mask = stack_blocks_uneven(model, n_stages)
    if mask is not None:
        raise ValueError(
            f"{model.cfg.n_layers} layers not divisible by {n_stages} "
            f"stages; use stack_blocks_uneven / pass uneven=True to "
            f"init_pipelined_state")
    return stacked


def stack_blocks_uneven(model: GPT, n_stages: int):
    """Like stack_blocks but allows L % n_stages != 0 (≙ the reference's
    seg_method-custom uneven segmentation, pp_layers.py:550): short stages
    are padded by REUSING their first layer's weights under a mask that
    skips the slot at run time (weights must exist for a uniform pytree;
    the mask guarantees they are never applied). Returns (stacked, mask)
    where mask is (n_stages, lps) bool — None when evenly divisible."""
    L = model.cfg.n_layers
    lps = -(-L // n_stages)  # ceil
    counts = [min(lps, L - s * lps) for s in range(n_stages)]
    if any(c <= 0 for c in counts):
        raise ValueError(f"{L} layers over {n_stages} stages leaves an "
                         f"empty stage; reduce n_stages")
    stacked = _stack_block_rows(model, counts, lps, (n_stages,))
    return stacked, layer_slot_mask(L, n_stages)


def _stack_block_rows(model, counts, slots, lead_shape):
    """Stack per-layer block pytrees into groups of ``slots`` layer slots
    (one group per entry in ``counts``), padding short groups by REUSING
    their first layer's weights under a run-time mask, then reshape the
    leading group axis to ``lead_shape``. Shared by the plain and
    interleaved stackings."""
    kinds = {b.moe is not None for b in model.blocks}
    if len(kinds) > 1:
        raise ValueError(
            "pipeline stacking needs homogeneous blocks (all dense or all "
            "MoE, e.g. moe_every=1); mixed dense/MoE stacks cannot stack")
    rows = []
    idx = 0
    for take in counts:
        layer_ids = list(range(idx, idx + take))
        idx += take
        layer_ids += [layer_ids[0]] * (slots - take)  # placeholders, masked
        rows.append([model.blocks[i] for i in layer_ids])
    flat = [b for row in rows for b in row]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *flat)
    return jax.tree_util.tree_map(
        lambda x: x.reshape(tuple(lead_shape) + (slots,) + x.shape[1:]),
        stacked)


def layer_slot_mask(n_layers: int, n_stages: int):
    """(n_stages, ceil(L/S)) bool mask of real layer slots; None if even."""
    if n_layers % n_stages == 0:
        return None
    lps = -(-n_layers // n_stages)
    counts = [min(lps, n_layers - s * lps) for s in range(n_stages)]
    return jnp.asarray([[i < c for i in range(lps)] for c in counts])


def stack_blocks_interleaved(model: GPT, n_stages: int, n_virtual: int):
    """Interleaved (virtual-stage) stacking: leading axes
    (n_virtual, n_stages, layers_per_global_stage, ...), where pp rank r
    holds the n_virtual chunks {v·S + r} of the S·V-deep global pipeline
    (≙ PipelineParallelWithInterleave's model-chunk assignment,
    fleet/meta_parallel/pipeline_parallel.py:457). Returns
    (stacked, mask) with mask (V, S, lpg) marking real layer slots
    (None when L divides evenly)."""
    L = model.cfg.n_layers
    S, V = n_stages, n_virtual
    G = S * V
    if L < G:
        raise ValueError(f"{L} layers over {G} global stages leaves an "
                         f"empty stage; reduce n_stages or n_virtual")
    counts = _balanced_counts(L, G)
    stacked = _stack_block_rows(model, counts, counts[0], (V, S))
    return stacked, interleaved_slot_mask(L, S, V)


def _balanced_counts(n_layers: int, n_groups: int):
    """Balanced layer counts per group: the first L%G groups get one layer
    more (finer placement than ceil-greedy — the interleave's point)."""
    q, rem = divmod(n_layers, n_groups)
    return [q + 1] * rem + [q] * (n_groups - rem)


def interleaved_slot_mask(n_layers: int, n_stages: int, n_virtual: int):
    """(V, S, lpg) bool mask of real layer slots under the balanced
    interleaved split; None when evenly divisible."""
    G = n_stages * n_virtual
    if n_layers % G == 0:
        return None
    counts = _balanced_counts(n_layers, G)
    lpg = counts[0]
    flat = jnp.asarray([[i < c for i in range(lpg)] for c in counts])
    return flat.reshape(n_virtual, n_stages, lpg)


def pipelined_apply_interleaved(stacked_blocks, x_mb, n_stages: int,
                                n_virtual: int, remat_stages: bool = False,
                                layer_mask=None, collect_aux: bool = False):
    """Virtual-stage (interleaved) rolling-buffer schedule: the buffer has
    one row per GLOBAL stage, shaped (V, S, ...) with the S axis sharded
    over 'pp' — pp rank r owns its V chunk rows. One tick advances every
    live row one global stage; the flat roll (v, S-1) → (v+1, 0) is the
    chunk boundary hop, which stays ON-RANK only for the ring neighbor —
    XLA lowers the whole shift to one collective-permute.

    Honest scheduling note (vs PipelineParallelWithInterleave,
    pipeline_parallel.py:457): inside ONE XLA program the backward is the
    reversed forward scan, so fwd/bwd interleaving — where Megatron's
    bubble ÷V comes from — is the compiler's call, not ours; this variant
    buys finer-grained layer placement (uneven models balance over S·V
    slots instead of S) and halves the per-hop activation dwell time. The
    schedule-owned interleave with real 1F1B overlap is the cross-host
    runtime (distributed/fleet_executor.py, n_virtual>1).
    """
    global _PIPELINE_DEPTH
    n_micro = x_mb.shape[0]
    S, V = n_stages, n_virtual
    G = S * V
    if layer_mask is None:
        lpg = jax.tree_util.tree_leaves(stacked_blocks)[0].shape[2]
        layer_mask = jnp.ones((V, S, lpg), bool)

    def stage_fn(blocks_one_stage, h, mask_one_stage):
        def body(hh, blk_m):
            blk, m = blk_m
            if collect_aux:
                out, aux = _moe_block_with_aux(blk, hh)
                aux = jnp.where(m, aux, 0.0)
            else:
                out = blk(hh)
                aux = jnp.zeros((), jnp.float32)
            hh = jnp.where(m, out, hh)
            return hh, aux
        h, auxs = lax.scan(body, h, (blocks_one_stage, mask_one_stage))
        return h, jnp.sum(auxs)

    if remat_stages:
        stage_fn = jax.checkpoint(stage_fn)

    # per-(v, r) block trees extracted once, outside the tick scan (same
    # adjoint-accumulation reasoning as pipelined_apply)
    row_blocks = [[jax.tree_util.tree_map(lambda x, v=v, r=r: x[v, r],
                                          stacked_blocks)
                   for r in range(S)] for v in range(V)]

    state = jnp.zeros((V, S) + x_mb.shape[1:], x_mb.dtype)
    outputs = jnp.zeros_like(x_mb)
    aux_total = jnp.zeros((), jnp.float32)

    def tick(carry, t):
        state, outputs, aux_total = carry
        inp = lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
        state = state.at[0, 0].set(inp)
        state = _shard_act(state, P(None, "pp", _BATCH_AXES, "sp", None))
        rows = []
        for v in range(V):
            rank_rows = []
            for r in range(S):
                g = v * S + r
                live = ((t - g) >= 0) & ((t - g) < n_micro)
                h, aux_r = lax.cond(
                    live,
                    lambda h, b=row_blocks[v][r], mk=layer_mask[v, r]:
                        stage_fn(b, h, mk),
                    lambda h: (h, jnp.zeros((), jnp.float32)),
                    state[v, r])
                rank_rows.append(h)
                # live-guarded by the cond's false branch: bubble rows
                # contribute zero aux
                aux_total = aux_total + aux_r
            rows.append(jnp.stack(rank_rows))
        processed = jnp.stack(rows)
        out_t = processed[V - 1, S - 1]
        outputs = lax.cond(
            t >= G - 1,
            lambda o: lax.dynamic_update_index_in_dim(
                o, out_t, jnp.clip(t - (G - 1), 0, n_micro - 1), 0),
            lambda o: o, outputs)
        flat = processed.reshape((G,) + processed.shape[2:])
        state = jnp.roll(flat, 1, axis=0).reshape(state.shape)
        return (state, outputs, aux_total), None

    _PIPELINE_DEPTH += 1
    try:
        (state, outputs, aux_total), _ = lax.scan(
            tick, (state, outputs, aux_total),
            jnp.arange(n_micro + G - 1))
    finally:
        _PIPELINE_DEPTH -= 1
    if collect_aux:
        return outputs, aux_total
    return outputs


def unstack_blocks(stacked, n_layers: int):
    """Inverse of stack_blocks → list of per-layer block pytrees."""
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((n_layers,) + x.shape[2:]), stacked)
    return [jax.tree_util.tree_map(lambda x: x[i], flat)
            for i in range(n_layers)]


def pipelined_apply(stacked_blocks, x_mb, n_stages: int,
                    remat_stages: bool = False, layer_mask=None,
                    collect_aux: bool = False,
                    skip_dead_rows: Optional[bool] = None):
    """GPipe schedule as a rolling buffer over a 'pp'-sharded stage axis.

    x_mb: (n_micro, mb, seq, d) microbatched activations (post-embedding).
    Returns (n_micro, mb, seq, d) outputs of the last stage — or
    (outputs, aux) when collect_aux (MoE load-balance loss summed over all
    real layer applications, bubble rows masked out).

    Stage i's current input lives in row i of `state` (sharded P('pp')); one
    schedule tick = vmapped stage compute (each pp rank runs its own stage —
    rows are independent) + roll(+1) of the buffer, which XLA lowers to a
    collective-permute ring over ICI. Total ticks = n_micro + n_stages - 1;
    the bubble is the same as the reference's 1F1B warmup/cooldown
    (pipeline_parallel.py:117). Backward is jax.grad through the scan — the
    reversed schedule the reference hand-codes.

    layer_mask (n_stages, lps) marks real vs padded layer slots for uneven
    L % n_stages (stack_blocks_uneven); padded slots pass h through.

    remat_stages=True checkpoints each stage's compute, so the backward
    holds only per-tick stage BOUNDARY activations instead of every
    intermediate — the memory profile that motivates the reference's 1F1B
    over GPipe, achieved here with rematerialization instead of schedule
    reordering (in one XLA program the compiler owns the schedule).

    skip_dead_rows: warmup/cooldown rows hold zeros; with a REAL pp mesh
    their compute is free wall-clock (the owning rank idles while live
    ranks set the tick's critical path), so the vmapped stage keeps the
    single-program SPMD shape. WITHOUT a pp mesh (stages time-multiplexed
    on one device — the single-chip bench case) dead rows cost real time;
    this mode unrolls the stage loop with a ``lax.cond`` per row so dead
    ticks skip the FLOPs (VERDICT r2 item 9). Default: auto (skip iff no
    pp>1 mesh axis).
    """
    global _PIPELINE_DEPTH
    n_micro = x_mb.shape[0]
    S = n_stages
    if skip_dead_rows is None:
        from paddle_tpu.distributed.mesh import get_mesh
        m = get_mesh()
        skip_dead_rows = m is None or dict(m.shape).get("pp", 1) == 1
    if layer_mask is None:
        layer_mask = jnp.ones(
            (S, jax.tree_util.tree_leaves(stacked_blocks)[0].shape[1]),
            bool)

    def stage_fn(blocks_one_stage, h, mask_one_stage):
        def body(hh, blk_m):
            blk, m = blk_m
            if collect_aux:
                out, aux = _moe_block_with_aux(blk, hh)
                aux = jnp.where(m, aux, 0.0)
            else:
                out = blk(hh)
                aux = jnp.zeros((), jnp.float32)
            hh = jnp.where(m, out, hh)
            return hh, aux
        h, auxs = lax.scan(body, h, (blocks_one_stage, mask_one_stage))
        return h, jnp.sum(auxs)

    if remat_stages:
        stage_fn = jax.checkpoint(stage_fn)

    vstage = jax.vmap(stage_fn)
    # per-row block trees are extracted ONCE, outside the tick scan: each
    # row's weights then enter the scan as its own constant, so the
    # backward accumulates dW_r directly across ticks. Indexing inside the
    # tick instead would make every tick's adjoint materialize a full
    # (S, ...)-stacked zero buffer per row and scatter dW_r into it — the
    # dominant cost of the measured single-chip pp2 backward overhead.
    if skip_dead_rows:
        row_blocks = [jax.tree_util.tree_map(lambda x, r=r: x[r],
                                             stacked_blocks)
                      for r in range(S)]

    state = jnp.zeros((S,) + x_mb.shape[1:], x_mb.dtype)
    outputs = jnp.zeros_like(x_mb)
    aux_total = jnp.zeros((), jnp.float32)

    def tick(carry, t):
        state, outputs, aux_total = carry
        inp = lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
        state = lax.dynamic_update_index_in_dim(state, inp, 0, 0)
        state = _shard_act(state, P("pp", _BATCH_AXES, "sp", None))
        if skip_dead_rows:
            rows, aux_rows = [], []
            for r in range(S):
                live_r = ((t - r) >= 0) & ((t - r) < n_micro)
                h_r, aux_r = lax.cond(
                    live_r,
                    lambda h, b=row_blocks[r], mk=layer_mask[r]:
                        stage_fn(b, h, mk),
                    lambda h: (h, jnp.zeros((), jnp.float32)),
                    state[r])
                rows.append(h_r)
                aux_rows.append(aux_r)
            processed = jnp.stack(rows)
            aux_s = jnp.stack(aux_rows)
        else:
            processed, aux_s = vstage(stacked_blocks, state, layer_mask)
        # row i is live iff its current microbatch index t-i is real
        # (warmup/cooldown rows chew zeros; their aux must not count)
        live = ((t - jnp.arange(S)) >= 0) & ((t - jnp.arange(S)) < n_micro)
        aux_total = aux_total + jnp.sum(jnp.where(live, aux_s, 0.0))
        out_t = processed[-1]
        outputs = lax.cond(
            t >= S - 1,
            lambda o: lax.dynamic_update_index_in_dim(
                o, out_t, jnp.clip(t - (S - 1), 0, n_micro - 1), 0),
            lambda o: o, outputs)
        state = jnp.roll(processed, 1, axis=0)
        return (state, outputs, aux_total), None

    _PIPELINE_DEPTH += 1
    try:
        (state, outputs, aux_total), _ = lax.scan(
            tick, (state, outputs, aux_total),
            jnp.arange(n_micro + S - 1))
    finally:
        _PIPELINE_DEPTH -= 1
    if collect_aux:
        return outputs, aux_total
    return outputs


def _moe_block_with_aux(blk: GPTBlock, x):
    """One MoE block forward returning (out, aux) — used by the pipeline
    where the list-accumulator pattern cannot cross the scan."""
    acc = []
    out = blk(x, aux_acc=acc)
    aux = acc[0] if acc else jnp.zeros((), jnp.float32)
    return out, aux


def pipeline_partition_spec(path: str, n_virtual: int = 1) -> P:
    """Partition spec for a stacked-block param: leading axes (S, lps) —
    or (V, S, lpg) for the interleaved stacking, where only S shards."""
    return LAYOUT.pipeline_stacked(partition_spec(path.split(".")[-1]),
                                   n_virtual)


def build_pipelined_train_step(model: GPT, optimizer, mesh: Mesh,
                               n_stages: int, n_micro: int,
                               remat_stages: bool = False,
                               n_virtual: int = 1):
    """Full hybrid dp×fsdp×tp×sp×pp train step (≙ §3.4 call stack:
    fleet.distributed_model + train_batch + HybridParallelOptimizer.step,
    all fused into one XLA program). ``n_virtual > 1`` uses the
    interleaved virtual-stage buffer (stacked blocks from
    ``stack_blocks_interleaved``)."""
    cfg = model.cfg
    use_moe = cfg.moe_experts > 0
    if n_virtual > 1:
        mask = interleaved_slot_mask(cfg.n_layers, n_stages, n_virtual)
    else:
        mask = layer_slot_mask(cfg.n_layers, n_stages)

    def step(emb_params, stacked_blocks, opt_state, tokens, rng):
        # tokens: (n_micro, mb, seq)
        nm, mb, s = tokens.shape
        def loss_fn(emb_p, blocks_p):
            m = model.merge_params(emb_p)
            x = m.embed(tokens.reshape(nm * mb, s))
            x = x.reshape(nm, mb, s, -1)
            if n_virtual > 1:
                out = pipelined_apply_interleaved(
                    blocks_p, x, n_stages, n_virtual,
                    remat_stages=remat_stages, layer_mask=mask,
                    collect_aux=use_moe)
            else:
                out = pipelined_apply(blocks_p, x, n_stages,
                                      remat_stages=remat_stages,
                                      layer_mask=mask, collect_aux=use_moe)
            x, aux = out if use_moe else (out, 0.0)
            logits = m.head(x.reshape(nm * mb, s, -1))
            loss = lm_loss(logits, tokens.reshape(nm * mb, s))
            if use_moe:
                # normalize: aux accumulated over n_micro microbatches
                loss = loss + cfg.moe_aux_weight * aux / nm
            return loss

        loss, (g_emb, g_blocks) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(emb_params, stacked_blocks)
        (new_emb, new_blocks), new_state = optimizer.update(
            (g_emb, g_blocks), opt_state, (emb_params, stacked_blocks))
        return new_emb, new_blocks, new_state, loss

    return jax.jit(step, donate_argnums=(0, 1, 2))


def init_pipelined_state(model: GPT, optimizer, mesh: Mesh, n_stages: int,
                         n_virtual: int = 1):
    """Split params into (embedding/head dict, pp-stacked blocks) and place
    them on the mesh."""
    params, _ = model.split_params()
    emb_params = {k: v for k, v in params.items()
                  if not k.startswith("blocks.")}
    # jnp.copy: donation in the train step must not delete module arrays
    # (device_put aliases when the sharding already matches)
    emb_params = {k: jax.device_put(
        jnp.copy(v), NamedSharding(mesh, partition_spec(k))) for k, v in
        emb_params.items()}
    if n_virtual > 1:
        stacked, _ = stack_blocks_interleaved(model, n_stages, n_virtual)
    else:
        stacked, _ = stack_blocks_uneven(model, n_stages)
    # `stacked` is itself a GPTBlock pytree (leaves have extra leading
    # axes); place each named param per the pipeline rules.
    for name in sorted(stacked._params):
        arr = getattr(stacked, name)
        object.__setattr__(stacked, name, jax.device_put(
            arr, NamedSharding(mesh,
                               pipeline_partition_spec(name, n_virtual))))
    opt_state = jax.jit(optimizer.init)((emb_params, stacked))
    return emb_params, stacked, opt_state


# ---------------------------------------------------------------------------
# Presets (PaddleNLP gpt-3 family sizes)
# ---------------------------------------------------------------------------

def gpt_tiny(**kw):
    d = dict(vocab_size=256, max_seq_len=64, d_model=64, n_layers=2,
             n_heads=2, dtype=jnp.float32)
    d.update(kw)
    return GPTConfig(**d)


def gpt3_125m(**kw):
    d = dict(d_model=768, n_layers=12, n_heads=12)
    d.update(kw)
    return GPTConfig(**d)


def gpt3_350m(**kw):
    d = dict(d_model=1024, n_layers=24, n_heads=16)
    d.update(kw)
    return GPTConfig(**d)


def gpt3_1p3b(**kw):
    d = dict(d_model=2048, n_layers=24, n_heads=16, max_seq_len=2048)
    d.update(kw)
    return GPTConfig(**d)


def llama_style_1b(**kw):
    """Llama-family shape: rope positions, GQA kv heads, no biases,
    untied head — the modern serving config (the GQA cache is 4x smaller,
    which raises the decode HBM roofline by the same factor)."""
    d = dict(d_model=2048, n_layers=22, n_heads=16, n_kv_heads=4,
             rope=True, use_bias=False, tie_embeddings=False,
             max_seq_len=2048, vocab_size=32000)
    d.update(kw)
    return GPTConfig(**d)
