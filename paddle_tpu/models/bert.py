"""BERT encoder family (≙ the reference's ERNIE/BERT stack served from
PaddleNLP on top of fleet; BASELINE.md row "ERNIE-3.0 / BERT-base finetune").

TPU-first shape: one fused-QKV post-LN encoder block (large MXU matmuls,
bf16 by default), sharding-annotated for the same dp/fsdp/tp mesh axes as
models.gpt — Megatron column/row TP falls out of PARTITION_RULES + GSPMD
rather than wrapper layers (ref contrast: mp_layers.py ColumnParallelLinear).
"""

import dataclasses
import math
import re
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.mesh import LAYOUT, mesh_safe_spec
from paddle_tpu.nn.module import Module, Parameter, LayerList
from paddle_tpu.nn import functional as F


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30528          # padded to a multiple of 64 for MXU
    max_position: int = 512
    type_vocab_size: int = 2
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_mult: int = 4
    dropout: float = 0.1
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    @property
    def d_ffn(self):
        return self.d_model * self.ffn_mult

    def num_params(self, non_embedding: bool = False) -> int:
        d = self.d_model
        per_layer = 4 * d * d + 2 * d * self.d_ffn + 9 * d + self.d_ffn
        n = self.n_layers * per_layer + 2 * d
        if not non_embedding:
            n += (self.vocab_size + self.max_position
                  + self.type_vocab_size) * d
        return n

    def flops_per_token(self) -> float:
        """fwd+bwd model FLOPs per token (6N + attention term)."""
        n = self.num_params(non_embedding=True)
        attn = 12 * self.n_layers * self.d_model * self.max_position
        return 6 * n + attn


def _normal(key, shape, std, dtype):
    return (std * jax.random.normal(key, shape)).astype(dtype)


class BertLayer(Module):
    """Post-LN encoder block (original BERT residual order)."""

    def __init__(self, cfg: BertConfig, key):
        super().__init__()
        d = cfg.d_model
        self.n_heads = cfg.n_heads
        self.head_dim = cfg.head_dim
        self.dropout = cfg.dropout
        ks = jax.random.split(key, 4)
        std = 0.02
        dt = cfg.dtype
        self.wqkv = Parameter(_normal(ks[0], (d, 3 * d), std, dt))
        self.bqkv = Parameter(jnp.zeros((3 * d,), dt))
        self.wo = Parameter(_normal(ks[1], (d, d), std, dt))
        self.bo = Parameter(jnp.zeros((d,), dt))
        self.wup = Parameter(_normal(ks[2], (d, cfg.d_ffn), std, dt))
        self.bup = Parameter(jnp.zeros((cfg.d_ffn,), dt))
        self.wdown = Parameter(_normal(ks[3], (cfg.d_ffn, d), std, dt))
        self.bdown = Parameter(jnp.zeros((d,), dt))
        self.ln1_scale = Parameter(jnp.ones((d,), jnp.float32))
        self.ln1_bias = Parameter(jnp.zeros((d,), jnp.float32))
        self.ln2_scale = Parameter(jnp.ones((d,), jnp.float32))
        self.ln2_bias = Parameter(jnp.zeros((d,), jnp.float32))

    def _ln(self, x, scale, bias):
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, -1, keepdims=True)
        var = jnp.var(x32, -1, keepdims=True)
        return ((x32 - mu) * lax.rsqrt(var + 1e-12) * scale
                + bias).astype(x.dtype)

    def forward(self, x, attn_bias=None, rng_key=None, kv_lens=None):
        b, s, d = x.shape
        qkv = x @ self.wqkv + self.bqkv
        qkv = qkv.reshape(b, s, 3, self.n_heads, self.head_dim)
        qkv = _shard_act(qkv, P(("dp", "fsdp"), None, None, "tp", None))
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        # kv_lens routes the Pallas flash kernel (contiguous padding mask);
        # attn_bias covers the XLA fallback path
        attn = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_bias, is_causal=False, dropout_p=0.0,
            kv_lens=kv_lens)
        attn = attn.reshape(b, s, d) @ self.wo + self.bo
        attn = _maybe_dropout(attn, self.dropout, rng_key, 1)
        x = self._ln(x + attn, self.ln1_scale, self.ln1_bias)
        h = jax.nn.gelu(x @ self.wup + self.bup)
        h = _shard_act(h, P(("dp", "fsdp"), None, "tp"))
        h = h @ self.wdown + self.bdown
        h = _maybe_dropout(h, self.dropout, rng_key, 2)
        x = self._ln(x + h, self.ln2_scale, self.ln2_bias)
        return _shard_act(x, P(("dp", "fsdp"), None, None))


def _maybe_dropout(x, p, key, salt):
    if p == 0.0 or key is None:
        return x
    k = jax.random.fold_in(key, salt)
    keep = jax.random.bernoulli(k, 1.0 - p, x.shape)
    return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)


def _shard_act(x, spec: P):
    from paddle_tpu.distributed.mesh import get_mesh
    mesh = get_mesh()
    if mesh is None or mesh.size == 1:
        return x
    try:
        return lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    except Exception:
        return x


class Bert(Module):
    """Encoder trunk: embeddings → L layers → (sequence_output, pooled)."""

    def __init__(self, cfg: BertConfig, seed: int = 0):
        super().__init__()
        self.cfg = cfg
        key = jax.random.PRNGKey(seed)
        kw, kp, kt, kpool, kl = jax.random.split(key, 5)
        d, dt = cfg.d_model, cfg.dtype
        self.wte = Parameter(_normal(kw, (cfg.vocab_size, d), 0.02, dt))
        self.wpe = Parameter(_normal(kp, (cfg.max_position, d), 0.02, dt))
        self.wtype = Parameter(_normal(kt, (cfg.type_vocab_size, d),
                                       0.02, dt))
        self.emb_ln_scale = Parameter(jnp.ones((d,), jnp.float32))
        self.emb_ln_bias = Parameter(jnp.zeros((d,), jnp.float32))
        self.layers = LayerList([
            BertLayer(cfg, jax.random.fold_in(kl, i))
            for i in range(cfg.n_layers)])
        self.pooler_w = Parameter(_normal(kpool, (d, d), 0.02, dt))
        self.pooler_b = Parameter(jnp.zeros((d,), dt))

    def forward(self, tokens, token_type_ids=None, attention_mask=None,
                rng_key=None, extra_embed=None):
        """``extra_embed``: optional additive embedding plane folded in
        BEFORE the embedding LayerNorm (the ERNIE task-type embedding
        rides this hook — models/ernie.py)."""
        b, s = tokens.shape
        x = jnp.take(self.wte, tokens, axis=0) + self.wpe[:s]
        if token_type_ids is not None:
            x = x + jnp.take(self.wtype, token_type_ids, axis=0)
        else:
            x = x + self.wtype[0]
        if extra_embed is not None:
            x = x + extra_embed
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, -1, keepdims=True)
        var = jnp.var(x32, -1, keepdims=True)
        x = ((x32 - mu) * lax.rsqrt(var + 1e-12) * self.emb_ln_scale
             + self.emb_ln_bias).astype(x.dtype)
        x = _shard_act(x, P(("dp", "fsdp"), None, None))
        attn_bias = None
        kv_lens = None
        if attention_mask is not None:
            # (B, S) 1=keep → additive bias (B, 1, 1, S) broadcast over
            # heads and query positions (finite fill: fully-masked rows
            # must produce zeros, not NaN softmax)
            keep = attention_mask.astype(bool)
            attn_bias = jnp.where(
                keep[:, None, None, :], 0.0, -1e30).astype(jnp.float32)
            # per-example KV length lets the Pallas kernel SKIP blocks
            # beyond the padding (fused_softmax_mask.cu.h analog). Only a
            # contiguous valid prefix may declare a length; rows with
            # interior holes (packed sequences) keep the full length and
            # rely on the bias alone — checked per example, in-trace.
            lens = jnp.sum(keep.astype(jnp.int32), axis=-1)
            is_prefix = jnp.all(
                keep == (jnp.arange(s)[None, :] < lens[:, None]), axis=-1)
            kv_lens = jnp.where(is_prefix, lens, s)
        from paddle_tpu import flags as _flags
        from paddle_tpu.models.gpt import scan_partition_hazard
        prestacked = getattr(self, "_stacked_layers", None)
        use_scan = prestacked is not None or (
            self.cfg.n_layers > 1 and _flags.get_flag("scan_layers"))
        if use_scan and scan_partition_hazard():
            # ≥3-axis mesh on this CPU build miscompiles the scanned
            # backward (gpt.scan_partition_hazard has the bisect) —
            # unroll; a pre-stacked state's per-layer views were bound
            # onto self.layers by merge_params, so both forms serve.
            use_scan = False
        if use_scan:
            # one compiled encoder-layer body instead of L unrolled
            # copies (L-fold faster XLA compile — same rationale and
            # helper as the GPT stack). A state built by
            # init_train_state(stacked=True) carries the weights
            # pre-stacked, so the scan consumes them with zero in-trace
            # copy (the in-trace stack costs ~2x block-param HBM per
            # step: the stack forward plus its grad-unstack transpose)
            from paddle_tpu.models.gpt import (_shard_stacked,
                                               stack_block_weights)
            stacked = prestacked if prestacked is not None else \
                stack_block_weights(
                    [self.layers[i] for i in range(self.cfg.n_layers)])
            if prestacked is not None:
                from paddle_tpu.distributed.mesh import get_mesh
                mesh = get_mesh()
                if mesh is not None and mesh.size > 1:
                    # same rationale as GPT.hidden_states: constrain only
                    # the PRE-stacked state; in-trace stacks keep
                    # propagation-only sharding (established numerics)
                    stacked = _shard_stacked(stacked, self.layers[0],
                                             mesh, spec_fn=partition_spec)

            def body(h, lyr_i):
                lyr, i = lyr_i
                k = (jax.random.fold_in(rng_key, i)
                     if rng_key is not None else None)
                return lyr(h, attn_bias=attn_bias, rng_key=k,
                           kv_lens=kv_lens), None

            x, _ = jax.lax.scan(
                body, x, (stacked, jnp.arange(self.cfg.n_layers)))
        else:
            for i in range(self.cfg.n_layers):
                k = (jax.random.fold_in(rng_key, i)
                     if rng_key is not None else None)
                x = self.layers[i](x, attn_bias=attn_bias, rng_key=k,
                                   kv_lens=kv_lens)
        pooled = jnp.tanh(x[:, 0] @ self.pooler_w + self.pooler_b)
        return x, pooled

    def merge_params(self, params):
        new = Module.merge_params(self, params)
        _bind_stacked(new)
        return new


def _bind_stacked(trunk: "Bert"):
    """Rebind each per-layer module to a sliced view of the pre-stacked
    state (same contract as GPT.merge_params): consumers outside the scan
    forward (state_dict export, unrolled escape hatch) must never read
    the init-time weights still sitting in ``trunk.layers``. Inside jit
    the unconsumed slices are dead code XLA eliminates."""
    st = getattr(trunk, "_stacked_layers", None)
    if st is not None:
        for i in range(trunk.cfg.n_layers):
            lyr = jax.tree_util.tree_map(lambda x, i=i: x[i], st)
            object.__setattr__(trunk.layers, f"item_{i}", lyr)


class BertForPretraining(Module):
    """MLM + NSP heads (decoder tied to wte, ≙ BertPretrainingHeads)."""

    def __init__(self, cfg: BertConfig, seed: int = 0):
        super().__init__()
        self.cfg = cfg
        self.bert = Bert(cfg, seed)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), 999)
        d, dt = cfg.d_model, cfg.dtype
        k1, k2 = jax.random.split(key)
        self.mlm_transform_w = Parameter(_normal(k1, (d, d), 0.02, dt))
        self.mlm_transform_b = Parameter(jnp.zeros((d,), dt))
        self.mlm_ln_scale = Parameter(jnp.ones((d,), jnp.float32))
        self.mlm_ln_bias = Parameter(jnp.zeros((d,), jnp.float32))
        self.mlm_bias = Parameter(jnp.zeros((cfg.vocab_size,), jnp.float32))
        self.nsp_w = Parameter(_normal(k2, (d, 2), 0.02, dt))
        self.nsp_b = Parameter(jnp.zeros((2,), dt))

    def merge_params(self, params):
        new = Module.merge_params(self, params)
        # subclasses may re-home the trunk (Ernie moves it under
        # .ernie.bert); only a directly-attached Bert can carry the
        # pre-stacked state this head's init_train_state produces
        trunk = getattr(new, "bert", None)
        if isinstance(trunk, Bert):
            _bind_stacked(trunk)
        return new

    def mlm_head(self, h):
        """Transform + LN + tied vocab projection over (..., d) states."""
        h = jax.nn.gelu(h @ self.mlm_transform_w + self.mlm_transform_b)
        h32 = h.astype(jnp.float32)
        mu = jnp.mean(h32, -1, keepdims=True)
        var = jnp.var(h32, -1, keepdims=True)
        h = ((h32 - mu) * lax.rsqrt(var + 1e-12) * self.mlm_ln_scale
             + self.mlm_ln_bias).astype(h.dtype)
        return h @ self.bert.wte.T + self.mlm_bias

    def forward(self, tokens, token_type_ids=None, attention_mask=None,
                rng_key=None, mlm_positions=None):
        """mlm_positions (B, M): compute MLM logits only at those gathered
        positions — the standard pretraining optimization (the reference
        gathers masked positions before the vocab projection too; at 15%
        masking this removes ~85% of the vocab-head FLOPs). Returns
        (B, M, V) logits then; (B, S, V) when None."""
        seq, pooled = self.bert(tokens, token_type_ids, attention_mask,
                                rng_key)
        if mlm_positions is not None:
            seq = jnp.take_along_axis(seq, mlm_positions[..., None], axis=1)
        mlm_logits = self.mlm_head(seq)
        nsp_logits = pooled @ self.nsp_w + self.nsp_b
        return (_shard_act(mlm_logits, P(("dp", "fsdp"), None, "tp")),
                nsp_logits)


class BertForSequenceClassification(Module):
    """Finetune head (≙ ERNIE/BERT fine-tuning configs in BASELINE.md)."""

    def __init__(self, cfg: BertConfig, num_classes: int = 2, seed: int = 0):
        super().__init__()
        self.cfg = cfg
        self.bert = Bert(cfg, seed)
        k = jax.random.fold_in(jax.random.PRNGKey(seed), 12345)
        self.cls_w = Parameter(_normal(k, (cfg.d_model, num_classes),
                                       0.02, cfg.dtype))
        self.cls_b = Parameter(jnp.zeros((num_classes,), cfg.dtype))

    def merge_params(self, params):
        new = Module.merge_params(self, params)
        # subclasses may re-home the trunk (Ernie moves it under
        # .ernie.bert); only a directly-attached Bert can carry the
        # pre-stacked state this head's init_train_state produces
        trunk = getattr(new, "bert", None)
        if isinstance(trunk, Bert):
            _bind_stacked(trunk)
        return new

    def forward(self, tokens, token_type_ids=None, attention_mask=None,
                rng_key=None):
        _, pooled = self.bert(tokens, token_type_ids, attention_mask,
                              rng_key)
        return pooled @ self.cls_w + self.cls_b


def mlm_loss(mlm_logits, labels, ignore_index: int = -100):
    """Masked-LM CE: positions with ignore_index contribute nothing.
    Dispatches to the vocab-parallel CE when the mesh tp-shards the vocab
    axis (same path as models.gpt.lm_loss)."""
    from paddle_tpu.models.gpt import _tp_sharded_vocab
    b, s, v = mlm_logits.shape
    if _tp_sharded_vocab(b, s, v):
        from paddle_tpu.distributed.mesh import get_mesh
        from paddle_tpu.distributed.mp_ops import parallel_cross_entropy
        tok = parallel_cross_entropy(mlm_logits, labels, mesh=get_mesh(),
                                     ignore_index=ignore_index)
        n = jnp.maximum(jnp.sum(labels != ignore_index), 1)
        return jnp.sum(tok) / n
    return F.cross_entropy(mlm_logits.astype(jnp.float32), labels,
                           ignore_index=ignore_index)


def pretrain_loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels):
    loss = mlm_loss(mlm_logits, mlm_labels)
    nsp = F.cross_entropy(nsp_logits.astype(jnp.float32), nsp_labels)
    return loss + nsp


# Megatron TP × ZeRO-3 fsdp rules, spelled in the same SpecLayout
# vocabulary as models.gpt.PARTITION_RULES (distributed.mesh.LAYOUT)
PARTITION_RULES = (
    (r"wte$", LAYOUT.vocab_embedding()),
    (r"(wpe|wtype)$", LAYOUT.position_table()),
    (r"wqkv$", LAYOUT.column()),
    (r"bqkv$", LAYOUT.column_bias()),
    (r"wo$", LAYOUT.row()),
    (r"wup$", LAYOUT.column()),
    (r"bup$", LAYOUT.column_bias()),
    (r"wdown$", LAYOUT.row()),
    (r"mlm_transform_w$", LAYOUT.root_linear()),
    (r"mlm_bias$", LAYOUT.vocab_bias()),
    (r"(pooler_w|nsp_w|cls_w)$", LAYOUT.root_linear()),
    (r".*", LAYOUT.replicated()),
)


def partition_spec(path: str) -> P:
    for pat, spec in PARTITION_RULES:
        if re.search(pat, path):
            return spec
    return P()


def shard_params(params: Dict[str, jax.Array], mesh: Mesh):
    return {k: jax.device_put(
        jnp.copy(v), NamedSharding(mesh, partition_spec(k)))
        for k, v in params.items()}


def build_pretrain_step(model: BertForPretraining, optimizer,
                        mesh: Optional[Mesh] = None, donate: bool = True,
                        max_predictions: Optional[int] = None):
    """``max_predictions``: static per-example cap on MLM positions. When
    set, the step sorts masked positions first and computes the vocab head
    only on those M slots (slots beyond the actual masked count carry the
    ignore label and contribute nothing). Equal loss, ~85% fewer
    vocab-head FLOPs at 15% masking.

    With PT_NUMERICS_EVERY > 0 (ISSUE 18) the step returns a 4th
    output: the packed numerics vector — per-layer grad and
    param-update stats over the ``*_stacked_layers`` axis plus the NaN
    provenance header — at the configured cadence."""
    from paddle_tpu.observability import numerics as _nm
    num_on = _nm.enabled()
    num_box = _nm.LayoutBox()

    def step(params, opt_state, tokens, type_ids, attn_mask, mlm_labels,
             nsp_labels, rng):
        pos = labels = None
        if max_predictions is not None:
            masked_first = jnp.argsort(mlm_labels == -100, axis=1,
                                       stable=True)
            pos = masked_first[:, :max_predictions]
            labels = jnp.take_along_axis(mlm_labels, pos, axis=1)

        def loss_fn(p):
            m = model.merge_params(p)
            mlm_logits, nsp_logits = m(tokens, type_ids, attn_mask,
                                       rng_key=rng, mlm_positions=pos)
            return pretrain_loss(
                mlm_logits, nsp_logits,
                mlm_labels if labels is None else labels, nsp_labels)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = _nm.poison_grads(grads, step_count=opt_state["step"])
        new_params, new_state = optimizer.update(grads, opt_state, params)
        if num_on:
            updates = jax.tree_util.tree_map(
                lambda n, o: n - o, new_params, params)
            packed = _nm.capture_step(
                grads, loss=loss, updates=updates,
                step_count=opt_state["step"], box=num_box)
            return new_params, new_state, loss, packed
        return new_params, new_state, loss

    kw = {"donate_argnums": (0, 1)} if donate else {}
    fn = jax.jit(step, **kw)
    fn.numerics_layout = num_box
    return fn


def _trunk_of(model) -> (Bert, str):
    """(encoder trunk, its param-path prefix) for any BERT-family head."""
    if isinstance(model, Bert):
        return model, ""
    trunk = getattr(model, "bert", None)
    if not isinstance(trunk, Bert):
        raise ValueError(
            f"{type(model).__name__} does not expose a .bert trunk; the "
            "stacked layout supports Bert and the Bert* heads")
    return trunk, "bert."


def init_train_state(model, optimizer, mesh: Optional[Mesh] = None,
                     stacked: bool = False):
    """Params + optimizer state, sharded onto the mesh if given.

    ``stacked=True``: encoder layers enter the state PRE-stacked under a
    ``{prefix}_stacked_layers`` key the forward scan consumes directly —
    the previous in-trace ``stack_block_weights`` copied every layer
    weight inside the step, the exact cost the GPT path eliminated. Same
    SpecLayout-aware placement as GPT: under a multi-device mesh each
    stacked leaf is emitted sharded by its layer-leading PARTITION_RULES
    spec via the stacking jit's out_shardings."""
    from paddle_tpu.models.gpt import (register_stacked_decay_mask,
                                       stack_block_weights,
                                       stacked_block_specs)
    params, _ = model.split_params()
    if stacked:
        trunk, prefix = _trunk_of(model)
        L = trunk.cfg.n_layers
        entry = f"{prefix}_stacked_layers"
        params = {k: v for k, v in params.items()
                  if not k.startswith(f"{prefix}layers.")}
        layers = [trunk.layers[i] for i in range(L)]
        if getattr(optimizer, "apply_decay_param_fun", None) is not None:
            register_stacked_decay_mask(
                optimizer, trunk.layers[0], L,
                lambda i, name: f"{prefix}layers.item_{i}.{name}", entry)
        if mesh is not None and mesh.size > 1:
            params = shard_params(params, mesh)
            _, treedef, specs = stacked_block_specs(trunk.layers[0],
                                                    partition_spec)
            sh_tree = jax.tree_util.tree_unflatten(
                treedef, [NamedSharding(mesh, mesh_safe_spec(s, mesh))
                          for s in specs])
            params[entry] = jax.jit(
                stack_block_weights, out_shardings=sh_tree)(layers)
            opt_state = jax.jit(optimizer.init)(params)
        else:
            params = {k: jnp.copy(v) for k, v in params.items()}
            params[entry] = stack_block_weights(layers)
            opt_state = optimizer.init(params)
        return params, opt_state
    if mesh is not None and mesh.size > 1:
        params = shard_params(params, mesh)
        opt_state = jax.jit(optimizer.init)(params)
    else:
        params = {k: jnp.copy(v) for k, v in params.items()}
        opt_state = optimizer.init(params)
    return params, opt_state


def bert_tiny(**kw):
    d = dict(vocab_size=256, max_position=64, d_model=64, n_layers=2,
             n_heads=2, dropout=0.0, type_vocab_size=2, dtype=jnp.float32)
    d.update(kw)
    return BertConfig(**d)


def bert_base(**kw):
    d = dict(d_model=768, n_layers=12, n_heads=12)
    d.update(kw)
    return BertConfig(**d)


def bert_large(**kw):
    d = dict(d_model=1024, n_layers=24, n_heads=16)
    d.update(kw)
    return BertConfig(**d)
