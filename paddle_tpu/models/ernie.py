"""ERNIE 3.0 model family (the BASELINE.md "ERNIE-3.0 / BERT-base
finetune" row; architecture per the PaddleNLP ernie modeling configs —
the reference core repo ships the transformer blocks, PaddleNLP the
configs).

Architecturally ERNIE 3.0's dense trunk is a BERT-style encoder with one
addition this module implements: a TASK-TYPE embedding plane added to the
token/position/segment sum (task_type_vocab_size=3 in the released
configs — universal representation vs task-specific heads select
different task ids). Everything else — the encoder stack, the Megatron
TP × ZeRO partitioning, the flash-attention kv_lens fast path, the AMP
policy — is shared with models/bert.py, which is the point: one tuned
encoder serves both families."""

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from paddle_tpu.models.bert import (Bert, BertConfig,
                                    BertForSequenceClassification,
                                    PARTITION_RULES, _normal)
from paddle_tpu.nn.module import Module, Parameter

__all__ = ["ErnieConfig", "Ernie", "ErnieForSequenceClassification",
           "ernie3_base", "ernie3_medium", "ernie3_micro",
           "PARTITION_RULES"]


@dataclass(frozen=True)
class ErnieConfig(BertConfig):
    vocab_size: int = 40000          # ernie-3.0 zh vocab
    task_type_vocab_size: int = 3


class Ernie(Module):
    """ERNIE trunk = Bert trunk + task-type embedding (added before the
    embedding LayerNorm, matching the released model's embedding sum)."""

    def __init__(self, cfg: ErnieConfig, seed: int = 0, trunk=None):
        super().__init__()
        self.cfg = cfg
        self.bert = trunk if trunk is not None else Bert(cfg, seed)
        k = jax.random.fold_in(jax.random.PRNGKey(seed), 777)
        self.wtask = Parameter(_normal(
            k, (cfg.task_type_vocab_size, cfg.d_model), 0.02, cfg.dtype))

    def forward(self, tokens, token_type_ids=None, task_type_ids=None,
                attention_mask=None, rng_key=None):
        task = (jnp.take(self.wtask, task_type_ids, axis=0)
                if task_type_ids is not None else self.wtask[0])
        # the shared Bert trunk folds the task plane in before its
        # embedding LayerNorm — ONE encoder implementation for both
        return self.bert(tokens, token_type_ids, attention_mask, rng_key,
                         extra_embed=task)


class ErnieForSequenceClassification(BertForSequenceClassification):
    """The finetune configuration of the BASELINE row: the shared BERT
    classifier head over the ERNIE trunk (one head implementation for
    both families)."""

    def __init__(self, cfg: ErnieConfig, num_classes: int = 2,
                 seed: int = 0):
        super().__init__(cfg, num_classes, seed)
        # re-home the already-built trunk inside the Ernie wrapper (no
        # second Bert construction)
        self.ernie = Ernie(cfg, seed, trunk=self.bert)
        del self.bert

    def forward(self, tokens, token_type_ids=None, task_type_ids=None,
                attention_mask=None, rng_key=None):
        _, pooled = self.ernie(tokens, token_type_ids, task_type_ids,
                               attention_mask, rng_key)
        return pooled @ self.cls_w + self.cls_b


def ernie3_base(**kw):
    # released ernie-3.0-base config: 2048 positions, 4 segment types
    d = dict(d_model=768, n_layers=12, n_heads=12, max_position=2048,
             type_vocab_size=4)
    d.update(kw)
    return ErnieConfig(**d)


def ernie3_medium(**kw):
    d = dict(d_model=768, n_layers=6, n_heads=12, max_position=2048,
             type_vocab_size=4)
    d.update(kw)
    return ErnieConfig(**d)


def ernie3_micro(**kw):
    d = dict(vocab_size=1024, max_position=64, d_model=64, n_layers=2,
             n_heads=2, dropout=0.0, dtype=jnp.float32)
    d.update(kw)
    return ErnieConfig(**d)
