"""PT001 — host syncs in traced or dispatch-path code.

The framework's central performance invariant (docs/serving.md): the
serving hot loop enqueues device work and harvests results lag-one with
exactly ONE packed device→host transfer — any other sync serializes the
pipeline; and code that runs at trace time cannot concretize a tracer at
all. This rule machine-checks both:

- **jit scope** (functions handed to ``jax.jit``/``pjit``/``shard_map``
  plus everything they reach — parameters are tracers): ``.item()``,
  ``jax.device_get``, ``block_until_ready``, ``np.asarray``/``np.array``
  on array-derived values, and ``int()/float()/bool()`` on
  array-derived values are errors.
- **dispatch scope** (``step``/``run``/``drain`` methods of the
  engines in ``paddle_tpu/inference/`` and everything they reach —
  the lag-one pipeline): the same sinks are errors when the operand
  provably derives from a device computation; a bare
  ``np.asarray``/``np.array`` whose operand the analysis cannot type
  is a *warning* in ``inference/`` files (prove it host-resident and
  suppress inline, or restructure).
- **anywhere**: ``np.asarray(x).shape`` / ``.ndim`` / ``.size`` /
  ``.dtype`` — a full host copy to read metadata that ``np.shape(x)``
  reads for free — is an error regardless of scope.

Taint is a simple intra-function forward pass: results of
``jnp.*``/``jax.*``/``lax.*`` calls (and of ``self._x_fn(...)`` where
``self._x_fn = jax.jit(...)`` appears in the same file) are
array-valued; assignment propagates; ``.shape``/``.size``/``.ndim``/
``.dtype`` reads are static metadata and break the chain.
"""

import ast
from typing import Dict, Optional, Set

from paddle_tpu.analysis import callgraph
from paddle_tpu.analysis.engine import Rule

METADATA_ATTRS = {"shape", "ndim", "size", "dtype", "nbytes", "itemsize",
                  "sharding", "aval", "weak_type"}
# dotted prefixes whose call results live on device. Deliberately NOT
# bare "jax." — jax.devices(), jax.tree_util.* etc. return host values.
ARRAY_PREFIXES = ("jnp.", "lax.", "jax.numpy.", "jax.lax.",
                  "jax.random.", "jax.nn.")
ARRAY_EXACT = {"jax.device_put"}
SYNC_CALL_NAMES = {"device_get", "block_until_ready"}
NP_CONVERTERS = {"asarray", "array", "ascontiguousarray", "copy"}
DISPATCH_ROOT_NAMES = {"step", "run", "drain"}
DISPATCH_FILES = ("inference/decode_engine.py", "inference/paged_engine.py")


def _np_converter_call(node: ast.Call) -> bool:
    """np.asarray(x) / numpy.array(x)-style conversion call."""
    d = callgraph.dotted(node.func)
    if not d or "." not in d:
        return False
    mod, _, name = d.rpartition(".")
    return mod.split(".")[0] in ("np", "numpy") and name in NP_CONVERTERS


def _jit_valued_attrs(ctx) -> Set[str]:
    """Attribute names assigned from jit-wrapper calls anywhere in the
    file (``self._multi_fn = jax.jit(...)``) — calling one yields a
    device value."""
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            if callgraph.terminal_name(
                    node.value.func) in callgraph.JIT_ROOT_NAMES:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute):
                        out.add(tgt.attr)
    return out


class _Taint:
    def __init__(self, fn_node, params_tainted: bool,
                 jit_attrs: Set[str]):
        self.names: Set[str] = set()
        self.jit_attrs = jit_attrs
        if params_tainted:
            a = fn_node.args if hasattr(fn_node, "args") else None
            if a is not None:
                for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                    self.names.add(arg.arg)
                for va in (a.vararg, a.kwarg):
                    if va is not None:
                        self.names.add(va.arg)
        # forward-propagate through assignments to a fixpoint (bounded)
        assigns = [n for n in callgraph.iter_own_nodes(fn_node)
                   if isinstance(n, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign))]
        for _ in range(4):
            changed = False
            for node in assigns:
                value = node.value
                if value is None or not self.expr(value):
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    changed |= self._mark_target(t)
            if not changed:
                break

    def _mark_target(self, t) -> bool:
        changed = False
        if isinstance(t, ast.Name) and t.id not in self.names:
            self.names.add(t.id)
            changed = True
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                changed |= self._mark_target(e)
        return changed

    def expr(self, node) -> bool:
        """Is this expression array-derived?"""
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            if node.attr in METADATA_ATTRS:
                return False
            return self.expr(node.value)
        if isinstance(node, ast.Subscript):
            return self.expr(node.value)
        if isinstance(node, ast.Call):
            d = callgraph.dotted(node.func)
            if d:
                name = d.rsplit(".", 1)[-1]
                if name not in SYNC_CALL_NAMES and (
                        d in ARRAY_EXACT
                        or any(d.startswith(p) for p in ARRAY_PREFIXES)):
                    return True
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in self.jit_attrs):
                return True
            # method calls on tainted values (x.astype(), x.at[..].set())
            if isinstance(node.func, ast.Attribute):
                return self.expr(node.func.value)
            return False
        if isinstance(node, ast.BinOp):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.Compare):
            return self.expr(node.left) or any(
                self.expr(c) for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return self.expr(node.body) or self.expr(node.orelse)
        return False


class HostSyncRule(Rule):
    def __init__(self):
        super().__init__(id="PT001", severity="error",
                         description="host sync in traced or "
                                     "dispatch-path code")

    # -- scopes -------------------------------------------------------------
    def _dispatch_scope(self, project):
        g = project.callgraph
        roots = g.functions_matching(
            lambda f: f.ctx.relpath.endswith(DISPATCH_FILES) and f.cls
            and f.name in DISPATCH_ROOT_NAMES)
        return g.reachable(roots)

    def check(self, ctx, project):
        g = project.callgraph
        jit_scope = g.jit_scope()
        dispatch_scope = getattr(project, "_pt001_dispatch", None)
        if dispatch_scope is None:
            dispatch_scope = project._pt001_dispatch = \
                self._dispatch_scope(project)
        jit_attrs = _jit_valued_attrs(ctx)

        for fn in g.by_file.get(ctx.relpath, []):
            in_jit = fn in jit_scope
            in_disp = fn in dispatch_scope
            yield from self._check_fn(ctx, fn, in_jit, in_disp,
                                      jit_attrs)
        # metadata-via-copy applies to module-level code too
        yield from self._check_meta_copy(ctx, None, ctx.tree,
                                         module_level=True)

    # -- per-function -------------------------------------------------------
    def _check_fn(self, ctx, fn, in_jit, in_disp, jit_attrs):
        yield from self._check_meta_copy(ctx, fn, fn.node)
        if not (in_jit or in_disp):
            return
        where = ("traced (jit/shard_map) code" if in_jit
                 else "the serving dispatch path")
        taint = _Taint(fn.node, params_tainted=in_jit, jit_attrs=jit_attrs)
        for node in callgraph.iter_own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            # x.item()
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                yield self.finding(
                    ctx, node,
                    f".item() blocks on the device inside {where}",
                    symbol=fn.qual)
                continue
            # jax.device_get / block_until_ready
            name = callgraph.terminal_name(node.func)
            if name in SYNC_CALL_NAMES:
                yield self.finding(
                    ctx, node,
                    f"{name}() forces a device→host sync inside {where}",
                    symbol=fn.qual)
                continue
            # np.asarray / np.array
            if _np_converter_call(node) and node.args:
                if taint.expr(node.args[0]):
                    yield self.finding(
                        ctx, node,
                        f"{callgraph.dotted(node.func)} on a device "
                        f"value is a blocking device→host copy inside "
                        f"{where}",
                        symbol=fn.qual)
                elif in_disp and ctx.relpath.endswith(DISPATCH_FILES):
                    yield self.finding(
                        ctx, node,
                        f"{callgraph.dotted(node.func)} in the dispatch "
                        f"path: a device-resident operand would sync "
                        f"the pipeline — prove it host-resident and "
                        f"suppress, or hoist it off the hot loop",
                        symbol=fn.qual, severity="warning")
                continue
            # int()/float()/bool() on array-derived values
            if (isinstance(node.func, ast.Name)
                    and node.func.id in ("int", "float", "bool")
                    and node.args and taint.expr(node.args[0])):
                yield self.finding(
                    ctx, node,
                    f"{node.func.id}() concretizes a device value "
                    f"inside {where}",
                    symbol=fn.qual)

    # -- anywhere: np.asarray(x).shape --------------------------------------
    def _check_meta_copy(self, ctx, fn, root, module_level=False):
        nodes = (self._module_level_nodes(root) if module_level
                 else callgraph.iter_own_nodes(root))
        for node in nodes:
            if (isinstance(node, ast.Attribute)
                    and node.attr in ("shape", "ndim", "size", "dtype")
                    and isinstance(node.value, ast.Call)
                    and _np_converter_call(node.value)):
                yield self.finding(
                    ctx, node,
                    f"full host copy just to read .{node.attr} — "
                    f"np.shape()/np.ndim() read array metadata without "
                    f"a transfer",
                    symbol=fn.qual if fn else "<module>")

    @staticmethod
    def _module_level_nodes(tree):
        stack = list(ast.iter_child_nodes(tree))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))
