"""PT003 — side effects inside traced code.

A function handed to ``jax.jit``/``shard_map``/``pjit`` executes its
Python body once per COMPILE, not once per step. A ``stats.add`` /
``trace.span`` / ``faults.fire`` call there looks like per-step
telemetry but records per-trace; mutating enclosing state
(``results.append(...)`` on a closure list, ``self.x = ...``) bakes
tracers into objects that outlive the trace. Both are bugs the profiler
only exposes as "metric never moves" / "leaked tracer" much later.

Scope: the call graph's jit scope (roots + everything reachable).
Sinks:

- calls into ``paddle_tpu.stats`` (add/observe/set_value/timer/reset),
  ``paddle_tpu.observability.trace`` (span/begin/end/complete/instant),
  ``paddle_tpu.testing.faults`` (fire/transform/slot_mask/inject) —
  resolved through each file's imports, so aliases work;
- ``print(...)``;
- mutation of non-local state: mutating method calls
  (append/extend/update/...) or subscript/attribute assignment whose
  base is a closure/global name or ``self`` — locals are fine (building
  a list of scan ys at trace time is idiomatic).
"""

import ast
from typing import Dict, Set

from paddle_tpu.analysis import callgraph
from paddle_tpu.analysis.engine import Rule

SIDE_EFFECT_MODULES = {
    "stats": ({"paddle_tpu.stats", "stats"},
              {"add", "observe", "set_value", "timer", "reset",
               "snapshot", "export"}),
    "trace": ({"paddle_tpu.observability.trace", "observability.trace",
               "trace"},
              {"span", "begin", "end", "complete", "instant", "export"}),
    "faults": ({"paddle_tpu.testing.faults", "testing.faults", "faults"},
               {"fire", "transform", "slot_mask", "inject",
                "corrupt_file", "install_rule", "clear"}),
}

# unambiguous container mutators only — generic names (update, add,
# pop, clear, remove) collide with optimizer.update(), stats.add(), ...
MUTATORS = {"append", "extend", "insert", "setdefault", "popitem",
            "appendleft", "popleft"}


def _local_names(fn_node) -> Set[str]:
    out: Set[str] = set()
    a = getattr(fn_node, "args", None)
    if a is not None:
        for arg in (a.posonlyargs + a.args + a.kwonlyargs):
            out.add(arg.arg)
        for va in (a.vararg, a.kwarg):
            if va is not None:
                out.add(va.arg)
    for node in callgraph.iter_own_nodes(fn_node):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            out.add(node.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            for t in ast.walk(node.optional_vars):
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(node, ast.comprehension):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


class TracedSideEffectRule(Rule):
    def __init__(self):
        super().__init__(id="PT003", severity="error",
                         description="side effect inside traced code")

    def _module_kind(self, ctx, project, base_name: str):
        """'stats' / 'trace' / 'faults' when ``base_name`` is an import
        alias of one of the observability modules in this file."""
        imports = project.callgraph.imports.get(ctx.relpath, {})
        target = imports.get(base_name)
        if target is None:
            return None
        for kind, (modnames, _) in SIDE_EFFECT_MODULES.items():
            if target in modnames or target.endswith("." + kind):
                return kind
        return None

    def check(self, ctx, project):
        g = project.callgraph
        jit_scope = g.jit_scope()
        for fn in g.by_file.get(ctx.relpath, []):
            if fn not in jit_scope:
                continue
            if fn.name in ("__init__", "__new__", "__post_init__"):
                # constructing a fresh object at trace time is idiomatic
                # (pytree containers); it mutates nothing that outlives
                # the object being built
                continue
            locals_ = _local_names(fn.node)
            for node in callgraph.iter_own_nodes(fn.node):
                yield from self._check_node(ctx, project, fn, node,
                                            locals_)

    def _check_node(self, ctx, project, fn, node, locals_):
        # print()
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            yield self.finding(
                ctx, node,
                "print() inside traced code runs once per compile, not "
                "per step (use jax.debug.print for runtime values)",
                symbol=fn.qual)
            return
        # stats./trace./faults. calls
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)):
            kind = self._module_kind(ctx, project, node.func.value.id)
            if kind is not None:
                _, methods = SIDE_EFFECT_MODULES[kind]
                if node.func.attr in methods:
                    yield self.finding(
                        ctx, node,
                        f"{node.func.value.id}.{node.func.attr}() "
                        f"inside traced code records at TRACE time "
                        f"(once per compile) — hoist it to the host "
                        f"side of the dispatch",
                        symbol=fn.qual)
                    return
        # container mutation on non-local state
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in MUTATORS):
            base = node.func.value
            if isinstance(base, ast.Name) and base.id not in locals_:
                yield self.finding(
                    ctx, node,
                    f"mutating closure/global '{base.id}' inside "
                    f"traced code leaks trace-time values (and "
                    f"re-runs only on retrace)",
                    symbol=fn.qual, severity="warning")
            elif (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"):
                yield self.finding(
                    ctx, node,
                    f"mutating self.{base.attr} inside traced code "
                    f"leaks trace-time values into the instance",
                    symbol=fn.qual, severity="warning")
            return
        # subscript/attribute assignment to non-local state
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id not in locals_):
                    yield self.finding(
                        ctx, t,
                        f"assigning into closure/global "
                        f"'{t.value.id}[...]' inside traced code bakes "
                        f"the trace-time value",
                        symbol=fn.qual, severity="warning")
                elif (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    yield self.finding(
                        ctx, t,
                        f"assigning self.{t.attr} inside traced code "
                        f"stores a tracer on the instance (leaks the "
                        f"trace; mutate state via carried values "
                        f"instead)",
                        symbol=fn.qual, severity="warning")
