"""ptlint core: file loading, suppression handling, rule driving.

The analysis engine is deliberately dependency-free (pure ``ast`` — no
jax import), so ``tools/ptlint.py`` runs in milliseconds on a CPU-only
CI shard and can lint the tree even when the accelerator stack is
broken.

Model:

- A :class:`FileContext` is one parsed source file plus its suppression
  comments.
- A :class:`Project` is the set of files under lint plus the package
  call graph (``paddle_tpu.analysis.callgraph``) rules share.
- A :class:`Rule` contributes :class:`Finding`\\ s; the engine filters
  suppressed ones and hands the rest to the baseline layer
  (``paddle_tpu.analysis.baseline``) which decides what is NEW.

Suppressions (checked per finding line):

    x = np.asarray(pkt)   # ptlint: disable=PT001 -- the ONE harvest copy
    # ptlint: disable=PT003 -- next-line form
    stats.add("collective/calls")

and a whole-file form near the top of the file::

    # ptlint: disable-file=PT004

``disable=all`` silences every rule for the line/file.
"""

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set

SEVERITIES = ("error", "warning")

_SUPPRESS_RE = re.compile(
    r"#\s*ptlint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9_,\s]+?)(?:\s*--.*)?$")


@dataclass
class Finding:
    rule: str
    severity: str
    path: str                 # repo-relative, posix separators
    line: int
    col: int
    message: str
    symbol: str = ""          # enclosing function qualname, if any
    fingerprint: str = ""     # filled by baseline.fingerprint_all

    def format(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} {self.severity}: {self.message}{sym}")


class FileContext:
    """One source file under lint."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set of rule ids (or {"all"}) suppressed on that line
        self.line_suppressions: Dict[int, Set[str]] = {}
        self.file_suppressions: Set[str] = set()
        self._scan_suppressions()

    def _scan_suppressions(self):
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            kind, rules = m.group(1), m.group(2)
            ids = {r.strip().upper() for r in rules.split(",") if r.strip()}
            if kind == "disable-file":
                self.file_suppressions |= ids
            elif text.strip().startswith("#"):
                # standalone comment: applies to the next CODE line
                # (explanations may span several comment lines)
                j = i + 1
                while j <= len(self.lines) and (
                        not self.lines[j - 1].strip()
                        or self.lines[j - 1].strip().startswith("#")):
                    j += 1
                self.line_suppressions.setdefault(j, set()).update(ids)
            else:
                self.line_suppressions.setdefault(i, set()).update(ids)

    def suppressed(self, rule: str, line: int) -> bool:
        if {"ALL", rule.upper()} & self.file_suppressions:
            return True
        ids = self.line_suppressions.get(line, ())
        return "ALL" in ids or rule.upper() in ids

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def segment(self, node: ast.AST) -> str:
        try:
            return ast.get_source_segment(self.source, node) or ""
        except Exception:
            return ""


class Project:
    """Every file under lint + the shared call graph."""

    def __init__(self, files: List[FileContext], root: str):
        self.files = files
        self.root = root
        self.by_relpath = {f.relpath: f for f in files}
        from paddle_tpu.analysis import callgraph
        self.callgraph = callgraph.CallGraph(files)

    def file(self, relpath: str) -> Optional[FileContext]:
        return self.by_relpath.get(relpath)


@dataclass
class Rule:
    """Base rule: subclasses set ``id``/``severity`` and implement
    :meth:`check`."""

    id: str = "PT000"
    severity: str = "error"
    description: str = ""

    def check(self, ctx: FileContext,
              project: Project) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, message: str,
                symbol: str = "", severity: Optional[str] = None
                ) -> Finding:
        return Finding(rule=self.id,
                       severity=severity or self.severity,
                       path=ctx.relpath,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       message=message, symbol=symbol)


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def load_project(paths: Sequence[str],
                 root: Optional[str] = None) -> Project:
    """Parse every .py under ``paths`` into a Project. Files that fail
    to parse are skipped with a note on the returned project
    (``project.parse_errors``)."""
    root = os.path.abspath(root or os.getcwd())
    files, errors = [], []
    for path in iter_py_files(paths):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, root)
        try:
            with open(ap, "r", encoding="utf-8") as f:
                src = f.read()
            files.append(FileContext(ap, rel, src))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append((rel, f"{type(e).__name__}: {e}"))
    project = Project(files, root)
    project.parse_errors = errors
    return project


def default_rules() -> List[Rule]:
    from paddle_tpu.analysis import (rules_collectives, rules_env,
                                     rules_host_sync, rules_retrace,
                                     rules_side_effects, rules_tpu)
    return [rules_host_sync.HostSyncRule(),
            rules_retrace.RetraceHazardRule(),
            rules_side_effects.TracedSideEffectRule(),
            rules_collectives.CollectiveOrderRule(),
            rules_env.EnvContractRule(),
            # geometry rules: no-ops unless project.geom_specs is
            # attached (tools/ptgeom.py harvests it)
            *rules_tpu.geom_rules()]


def run(project: Project,
        rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Run ``rules`` over every file; suppressed findings are dropped;
    the rest come back sorted and fingerprinted."""
    from paddle_tpu.analysis import baseline
    rules = list(rules) if rules is not None else default_rules()
    findings: List[Finding] = []
    for ctx in project.files:
        for rule in rules:
            for f in rule.check(ctx, project):
                if not ctx.suppressed(f.rule, f.line):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    baseline.fingerprint_all(findings, project)
    return findings
