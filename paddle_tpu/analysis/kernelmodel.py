"""Trace-time Pallas kernel-geometry harvester (ISSUE 20).

``harvest`` intercepts ``pl.pallas_call`` while a kernel wrapper runs
under ``jax.eval_shape`` and records one :class:`KernelSpec` per launch
site: block shapes + dtypes from the in/out BlockSpecs, ``ANY``/SMEM
memory spaces, the grid, scalar-prefetch operand count, ``pltpu.VMEM``
scratch shapes, and the ``input_output_aliases`` pairs. Nothing
executes — ``eval_shape`` only abstract-evaluates, so the sweep runs on
a CPU-only CI shard in seconds, for geometries (r06-scale pools) whose
buffers could never be allocated on the host.

The module itself imports NOTHING outside the stdlib at module level:
``rules_tpu`` (and through it ptlint's jax-free bootstrap) can import
the spec model and the VMEM arithmetic without jax. Everything that
needs jax — the interception shim, the geometry registry sweep — pulls
it in lazily.

Geometry registry: each kernel module under ``ops/pallas/`` exposes a
``ptgeom_cases()`` hook returning :class:`GeomCase` rows — the bench
model ladder (tiny → r06-scale, :data:`LADDER`) crossed with that
kernel's autotune candidate space. ``tools/ptgeom.py`` sweeps them and
drives the PT006–PT009 rules in ``rules_tpu``.
"""

import contextlib
import dataclasses
import functools
import importlib
import inspect
import os
import sys
from typing import (Any, Callable, Dict, List, Optional, Sequence,
                    Tuple)

__all__ = [
    "OperandSpec", "ScratchSpec", "KernelSpec", "GeomCase", "LADDER",
    "KERNEL_MODULES", "itemsize", "sublane", "sds", "harvest",
    "iter_cases", "sweep", "vmem_estimate", "vmem_budget_bytes",
    "budget_reason", "DOUBLE_BUFFER", "VMEM_RESERVE_BYTES",
]

# bytes per element by canonical dtype name — kept local so the rule
# layer never needs numpy/jax to price a block
_ITEMSIZE = {
    "bool": 1, "int8": 1, "uint8": 1, "float8_e4m3fn": 1,
    "float8_e5m2": 1, "float8_e4m3b11fnuz": 1,
    "int16": 2, "uint16": 2, "float16": 2, "bfloat16": 2,
    "int32": 4, "uint32": 4, "float32": 4,
    "int64": 8, "uint64": 8, "float64": 8, "complex64": 8,
}

# Mosaic pipelines blocked operands through VMEM double-buffered (fetch
# block i+1 while computing on block i) — each blocked operand costs
# TWO block-sized windows of residency
DOUBLE_BUFFER = 2

# VMEM the budget model holds back for the compiler's own spills /
# semaphores on a ~16 MiB core
VMEM_RESERVE_BYTES = 512 * 1024


def itemsize(dtype) -> int:
    """Bytes per element for a dtype given as name string, np.dtype,
    or scalar type — without importing numpy when the name is known."""
    name = getattr(dtype, "name", None)
    if name is None:
        name = getattr(dtype, "__name__", None) or str(dtype)
    name = name.strip()
    if name in _ITEMSIZE:
        return _ITEMSIZE[name]
    try:  # exotic dtypes: fall back to numpy if it is importable
        import numpy as _np
        return int(_np.dtype(dtype).itemsize)
    except Exception:
        return 4


def sublane(dtype) -> int:
    """Minimum second-minor tile multiple for a dtype on TPU:
    (8, 128) f32, (16, 128) bf16/f16, (32, 128) int8/fp8."""
    return {1: 32, 2: 16}.get(itemsize(dtype), 8)


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


@dataclasses.dataclass
class OperandSpec:
    """One blocked (or whole-array) operand of a pallas launch."""

    role: str                      # "in" | "out"
    index: int                     # global operand index (counts prefetch)
    shape: Tuple[int, ...]
    dtype: str
    block: Optional[Tuple[int, ...]]   # None = whole array
    space: str                     # "vmem" | "any" | "smem" | "sem"
    # grid dims the index map depends on; None = data-dependent map
    # (e.g. reads a scalar-prefetch ref) that static probing cannot see
    deps: Optional[Tuple[int, ...]] = None
    # probed map outputs {grid_point: block_index} for alias comparison
    probes: Dict[Tuple[int, ...], Tuple[int, ...]] = \
        dataclasses.field(default_factory=dict)
    map_id: Optional[int] = None   # id() of the index_map callable

    def block_shape(self) -> Tuple[int, ...]:
        return self.block if self.block is not None else self.shape

    def block_bytes(self) -> int:
        return _prod(self.block_shape()) * itemsize(self.dtype)


@dataclasses.dataclass
class ScratchSpec:
    shape: Tuple[int, ...]
    dtype: str
    space: str = "vmem"

    def nbytes(self) -> int:
        return _prod(self.shape) * itemsize(self.dtype)


@dataclasses.dataclass
class KernelSpec:
    """Everything PT006–PT009 need about one pallas launch site."""

    body: str                      # kernel function name
    path: str                      # repo-relative launch site (posix)
    abspath: str
    line: int
    grid: Tuple[int, ...]
    num_scalar_prefetch: int
    inputs: List[OperandSpec]
    outputs: List[OperandSpec]
    scratch: List[ScratchSpec]
    aliases: Dict[int, int]        # global input index -> output index
    kernel: str = ""               # registry family (GeomCase.kernel)
    geometry: str = ""
    config: str = ""

    def name(self) -> str:
        return self.kernel or self.body


def vmem_budget_bytes() -> int:
    """PT006 budget: ``PT_VMEM_BUDGET_MB`` (default 16, the per-core
    VMEM size) minus a fixed compiler reserve."""
    try:
        mb = float(os.environ.get("PT_VMEM_BUDGET_MB", "16") or "16")
    except ValueError:
        mb = 16.0
    return max(0, int(mb * (1 << 20)) - VMEM_RESERVE_BYTES)


def vmem_estimate(spec: KernelSpec) -> int:
    """Static VMEM residency model: Σ block bytes × double-buffer
    factor over VMEM-pipelined in/out operands, plus VMEM scratch.
    ANY/SMEM operands don't occupy VMEM block windows; an aliased
    input shares its output's buffer and is not double-counted."""
    total = 0
    aliased_in = set(spec.aliases)
    for op in list(spec.inputs) + list(spec.outputs):
        if op.space != "vmem":
            continue
        if op.role == "in" and op.index in aliased_in:
            continue
        factor = DOUBLE_BUFFER if (op.block is not None and spec.grid) \
            else 1
        total += factor * op.block_bytes()
    for sc in spec.scratch:
        if sc.space == "vmem":
            total += sc.nbytes()
    return total


# ---------------------------------------------------------------------------
# interception shim
# ---------------------------------------------------------------------------

def _launch_site() -> Tuple[str, int]:
    """First stack frame outside this module and outside jax: the
    kernel wrapper's ``pl.pallas_call`` expression."""
    here = os.path.abspath(__file__)
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        posix = os.path.abspath(fn).replace(os.sep, "/")
        if (posix != here.replace(os.sep, "/")
                and "/jax/" not in posix and "/jax_" not in posix
                and not fn.startswith("<")):
            return os.path.abspath(fn), f.f_lineno
        f = f.f_back
    return here, 0


def _space_of(ms) -> str:
    s = str(ms).lower() if ms is not None else ""
    if "smem" in s:
        return "smem"
    if "any" in s:
        return "any"
    if "sem" in s:
        return "sem"
    return "vmem"


def _dtype_name(dt) -> str:
    name = getattr(dt, "name", None)
    if name:
        return str(name)
    name = getattr(dt, "__name__", None)
    if name:
        return str(name)
    return str(dt)


def _analyze_map(imap, grid):
    """Probe an index map at a few grid points: which grid dims does it
    depend on, and what block does it pick there? Returns
    ``(None, {})`` for data-dependent maps (scalar-prefetch reads)."""
    if imap is None or not grid:
        return (), {}
    extra = 0
    try:
        params = list(inspect.signature(imap).parameters.values())
        if not any(p.kind == p.VAR_POSITIONAL for p in params):
            extra = max(0, len(params) - len(grid))
    except (TypeError, ValueError):
        pass

    def ev(pt):
        out = imap(*pt, *([None] * extra))
        return tuple(int(v) for v in out)

    base = tuple(0 for _ in grid)
    try:
        b0 = ev(base)
    except Exception:
        return None, {}
    probes = {base: b0}
    deps = []
    try:
        for d, n in enumerate(grid):
            changed = False
            for val in sorted({1, 2, int(n) - 1}):
                if not 0 < val < int(n):
                    continue
                pt = base[:d] + (val,) + base[d + 1:]
                o = ev(pt)
                probes[pt] = o
                if o != b0:
                    changed = True
            if changed:
                deps.append(d)
    except Exception:
        return None, {}
    return tuple(deps), probes


def _operand_spec(role, index, aval, bspec, grid) -> OperandSpec:
    block = getattr(bspec, "block_shape", None) if bspec is not None \
        else None
    imap = getattr(bspec, "index_map", None) if bspec is not None \
        else None
    space = _space_of(getattr(bspec, "memory_space", None)
                      if bspec is not None else None)
    if block is None and space == "vmem" and bspec is not None \
            and getattr(bspec, "memory_space", None) is None:
        # pl.BlockSpec() with neither block nor space: whole array
        space = "any"
    deps, probes = _analyze_map(imap, grid)
    return OperandSpec(
        role=role, index=index,
        shape=tuple(int(s) for s in aval.shape),
        dtype=_dtype_name(aval.dtype),
        block=None if block is None else tuple(int(b) for b in block),
        space=space, deps=deps, probes=probes,
        map_id=None if imap is None else id(imap))


def _build_spec(kernel_fn, call_kw, operands, site) -> KernelSpec:
    kfn = kernel_fn
    while isinstance(kfn, functools.partial):
        kfn = kfn.func
    body = getattr(kfn, "__name__", str(kfn))

    gs = call_kw.get("grid_spec")
    if gs is not None:
        grid = tuple(int(g) for g in (gs.grid or ()))
        in_specs = list(gs.in_specs or ())
        out_specs = gs.out_specs
        nsp = int(getattr(gs, "num_scalar_prefetch", 0) or 0)
        scratch = list(getattr(gs, "scratch_shapes", ()) or ())
    else:
        grid = call_kw.get("grid", ())
        grid = (grid,) if isinstance(grid, int) else \
            tuple(int(g) for g in (grid or ()))
        in_specs = list(call_kw.get("in_specs") or ())
        out_specs = call_kw.get("out_specs")
        nsp = 0
        scratch = list(call_kw.get("scratch_shapes") or ())
    if out_specs is None:
        out_specs = []
    elif not isinstance(out_specs, (list, tuple)):
        out_specs = [out_specs]
    out_shape = call_kw.get("out_shape")
    if out_shape is None:
        out_shape = []
    elif not isinstance(out_shape, (list, tuple)):
        out_shape = [out_shape]

    tensor_ops = list(operands)[nsp:]
    inputs = [
        _operand_spec("in", nsp + i, aval, bspec, grid)
        for i, (aval, bspec) in enumerate(zip(tensor_ops, in_specs))]
    outputs = [
        _operand_spec("out", i, sd, bspec, grid)
        for i, (sd, bspec) in enumerate(zip(out_shape, out_specs))]
    scratch_specs = [
        ScratchSpec(shape=tuple(int(s) for s in
                                getattr(sc, "shape", ()) or ()),
                    dtype=_dtype_name(getattr(sc, "dtype", "float32")),
                    space=_space_of(getattr(sc, "memory_space", None)))
        for sc in scratch]
    aliases = dict(call_kw.get("input_output_aliases") or {})

    abspath, line = site
    return KernelSpec(
        body=body, path="", abspath=abspath, line=line, grid=grid,
        num_scalar_prefetch=nsp, inputs=inputs, outputs=outputs,
        scratch=scratch_specs,
        aliases={int(k): int(v) for k, v in aliases.items()})


@contextlib.contextmanager
def intercept_pallas(records: List[KernelSpec]):
    """Patch ``pl.pallas_call`` to record a KernelSpec per launch while
    delegating to the real implementation (trace semantics unchanged)."""
    from jax.experimental import pallas as pl
    orig = pl.pallas_call

    def shim(kernel, *call_args, **call_kw):
        inner = orig(kernel, *call_args, **call_kw)
        site = _launch_site()

        def traced(*operands):
            records.append(_build_spec(kernel, call_kw, operands, site))
            return inner(*operands)
        return traced

    pl.pallas_call = shim
    try:
        yield records
    finally:
        pl.pallas_call = orig


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def harvest(run: Callable[[], Any],
            root: Optional[str] = None) -> List[KernelSpec]:
    """Run ``run()`` (which should drive kernel wrappers under
    ``jax.eval_shape``) with interception on; return the harvested
    specs with ``path`` made repo-relative."""
    root = root or repo_root()
    records: List[KernelSpec] = []
    with intercept_pallas(records):
        run()
    for spec in records:
        spec.path = os.path.relpath(spec.abspath, root).replace(
            os.sep, "/")
    return records


# ---------------------------------------------------------------------------
# geometry registry
# ---------------------------------------------------------------------------

# the bench model ladder (tools/profile_decode.py PD_SIZE, bench.py
# trials): tiny smoke geometry, GPT-3 350M, and the r06 recapture
# flagship (gpt3_1p3b)
LADDER: Dict[str, Dict[str, Any]] = {
    "tiny": dict(dm=64, layers=2, heads=2, kv_heads=2, vocab=256,
                 seq=64, page=128, dtype="float32"),
    "350m": dict(dm=1024, layers=24, heads=16, kv_heads=16,
                 vocab=50304, seq=1024, page=128, dtype="bfloat16"),
    "r06": dict(dm=2048, layers=24, heads=16, kv_heads=16,
                vocab=50304, seq=2048, page=128, dtype="bfloat16"),
}

KERNEL_MODULES = (
    "flash_attention", "decode_attention", "paged_attention",
    "decode_megakernel", "fused_ce", "layer_norm", "quant_matmul")


@dataclasses.dataclass
class GeomCase:
    """One registry row: drive ``run()`` (under eval_shape) and label
    the harvested specs with (kernel, geometry, config)."""

    kernel: str
    geometry: str
    config: str
    run: Callable[[], Any]


def sds(shape, dtype):
    """jax.ShapeDtypeStruct from a shape tuple + dtype name string."""
    import jax
    import jax.numpy as jnp
    if isinstance(dtype, str):
        dtype = getattr(jnp, dtype)
    return jax.ShapeDtypeStruct(tuple(int(s) for s in shape), dtype)


def iter_cases(kernels: Optional[Sequence[str]] = None,
               geoms: Optional[Sequence[str]] = None,
               extra_modules: Sequence[Any] = ()) -> List[GeomCase]:
    """Collect every ``ptgeom_cases()`` row from the kernel modules
    (plus any extra modules), filtered by kernel family / geometry."""
    cases: List[GeomCase] = []
    for name in KERNEL_MODULES:
        mod = importlib.import_module(f"paddle_tpu.ops.pallas.{name}")
        cases.extend(mod.ptgeom_cases())
    for mod in extra_modules:
        cases.extend(mod.ptgeom_cases())
    if kernels:
        keep = {k.strip() for k in kernels if k and k.strip()}
        cases = [c for c in cases if c.kernel in keep]
    if geoms:
        keepg = {g.strip() for g in geoms if g and g.strip()}
        cases = [c for c in cases if c.geometry in keepg]
    return cases


def sweep(cases: Sequence[GeomCase], root: Optional[str] = None):
    """Harvest every case. Returns ``(specs, errors)`` where errors is
    ``[(case, exception), ...]`` — a failed harvest means the geometry
    was NOT checked, so callers treat it like a parse error."""
    specs: List[KernelSpec] = []
    errors: List[Tuple[GeomCase, Exception]] = []
    for case in cases:
        try:
            got = harvest(case.run, root=root)
        except Exception as e:  # the case itself is broken
            errors.append((case, e))
            continue
        for spec in got:
            spec.kernel = case.kernel
            spec.geometry = case.geometry
            spec.config = case.config
        specs.extend(got)
    return specs, errors


def budget_reason(run: Callable[[], Any],
                  budget: Optional[int] = None) -> Optional[str]:
    """Autotune guard (PT006): dry-run ``run`` under interception and
    return a refusal reason if any harvested launch exceeds the VMEM
    budget — ``autotune.tune`` skips such candidates without spending
    chip time on them. Returns None when everything fits."""
    budget = vmem_budget_bytes() if budget is None else budget
    worst = None
    for spec in harvest(run):
        est = vmem_estimate(spec)
        if est > budget and (worst is None or est > worst[1]):
            worst = (spec, est)
    if worst is None:
        return None
    spec, est = worst
    return (f"{spec.name()}: estimated VMEM {est / (1 << 20):.2f} MiB "
            f"exceeds budget {budget / (1 << 20):.2f} MiB "
            f"({est / max(budget, 1):.1f}x) [PT006]")
