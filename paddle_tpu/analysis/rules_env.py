"""PT005 — PT_* environment-variable contract drift.

Every ``os.environ`` / ``os.getenv`` read (or write) of a ``PT_*`` name
must be declared in the ``paddle_tpu/flags.py`` env registry
(``declare_env`` / ``declare_env_prefix``), which is also what the
docs/observability.md contract table is generated from. Undeclared reads
are how knobs like ``PT_SERVE_INFLIGHT`` silently fork from their
documentation.

Tool namespaces registered with ``declare_tool_prefix`` (``PD_``,
``FLEETOBS_``) are checked the same way: a ``PD_*`` read anywhere in
the linted tree (``tools/`` is linted alongside the package) needs its
own ``declare_env`` row. Names outside the registered namespaces
(``HOME``, ``JAX_*``) stay out of contract.

The declared set is parsed from the AST of the ``flags.py`` found in the
linted tree (falling back to ``<root>/paddle_tpu/flags.py`` when linting
a subtree), never imported — the linter stays jax-free.
"""

import ast
import os
import re
from typing import Optional, Set, Tuple

from paddle_tpu.analysis import callgraph
from paddle_tpu.analysis.engine import Rule

_ENV_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")


def _declared_from_tree(tree) -> Tuple[Set[str], Set[str], Set[str]]:
    names: Set[str] = set()
    prefixes: Set[str] = set()
    tool_prefixes: Set[str] = set()
    target = {"declare_env": names, "declare_env_prefix": prefixes,
              "declare_tool_prefix": tool_prefixes}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = callgraph.terminal_name(node.func)
        if fname not in target:
            continue
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            target[fname].add(node.args[0].value)
    return names, prefixes, tool_prefixes


def _env_name_of(node, ctx) -> Optional[Tuple[str, ast.AST]]:
    """(PT_* name, anchor node) when ``node`` reads/writes a PT_* env
    var, else None."""
    # os.environ["PT_X"] / env["PT_X"]  (load or store)
    if isinstance(node, ast.Subscript):
        key = node.slice
        if (isinstance(key, ast.Constant) and isinstance(key.value, str)
                and _ENV_NAME_RE.match(key.value)
                and _looks_env(node.value, ctx)):
            return key.value, node
        return None
    # os.environ.get / .setdefault / os.getenv
    if isinstance(node, ast.Call) and node.args:
        arg0 = node.args[0]
        if not (isinstance(arg0, ast.Constant)
                and isinstance(arg0.value, str)
                and _ENV_NAME_RE.match(arg0.value)):
            return None
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "getenv":
                return arg0.value, node
            if (node.func.attr in ("get", "setdefault", "pop")
                    and _looks_env(node.func.value, ctx)):
                return arg0.value, node
        elif isinstance(node.func, ast.Name) and \
                node.func.id == "getenv":
            return arg0.value, node
    return None


def _looks_env(base, ctx) -> bool:
    """Does ``base`` plausibly denote an environment mapping?"""
    seg = ctx.segment(base) or ""
    if "environ" in seg:
        return True
    name = callgraph.terminal_name(base)
    return name in ("environ", "env", "_env")


class EnvContractRule(Rule):
    def __init__(self, extra_declared: Optional[Set[str]] = None):
        super().__init__(id="PT005", severity="error",
                         description="undeclared PT_* env var")
        self.extra_declared = set(extra_declared or ())

    def _declared(self, project) -> Tuple[Set[str], Set[str]]:
        cached = getattr(project, "_pt005_declared", None)
        if cached is not None:
            return cached
        names: Set[str] = set(self.extra_declared)
        prefixes: Set[str] = set()
        tools: Set[str] = set()
        found = False
        for f in project.files:
            if os.path.basename(f.relpath) == "flags.py":
                n, p, t = _declared_from_tree(f.tree)
                if n or p or t:
                    found = True
                names |= n
                prefixes |= p
                tools |= t
        if not found:
            # linting a subtree: pull the package registry off disk
            cand = os.path.join(project.root, "paddle_tpu", "flags.py")
            if os.path.exists(cand):
                try:
                    with open(cand, "r", encoding="utf-8") as fh:
                        n, p, t = _declared_from_tree(
                            ast.parse(fh.read()))
                    names |= n
                    prefixes |= p
                    tools |= t
                except (SyntaxError, OSError):
                    pass
        # (names, prefixes) tuple shape is public-ish (tests unpack it);
        # the checked tool namespaces cache separately
        project._pt005_declared = (names, prefixes)
        project._pt005_tool_prefixes = tools
        return names, prefixes

    def check(self, ctx, project):
        names, prefixes = self._declared(project)
        tools = getattr(project, "_pt005_tool_prefixes", set())
        for node in ast.walk(ctx.tree):
            hit = _env_name_of(node, ctx)
            if hit is None:
                continue
            var, anchor = hit
            if not (var.startswith("PT_")
                    or any(var.startswith(t) for t in tools)):
                continue   # outside every contract namespace
            if var in names or any(var.startswith(p) for p in prefixes):
                continue
            yield self.finding(
                ctx, anchor,
                f"undeclared env var '{var}': add a "
                f"flags.declare_env(...) entry (and the "
                f"docs/observability.md table row it generates)")
