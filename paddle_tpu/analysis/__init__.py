"""ptlint — TPU-aware static analysis for paddle_tpu.

Pure-``ast`` (no jax import): lints the tree for the invariant classes
the profiler only catches after the fact. Rule families:

======  =====================================================
PT001   host syncs in traced / serving-dispatch code
PT002   jit retrace & recompile hazards
PT003   side effects (stats/trace/faults, mutation) in traced code
PT004   rank-divergent collective ordering (static deadlock)
PT005   PT_* env vars missing from the flags.py contract registry
PT006   pallas launch over the static VMEM budget (ptgeom)
PT007   blocked operand tiled off the (sublane, 128) grid (ptgeom)
PT008   ANY-pool aliasing contract violations (ptgeom)
PT009   grid blocking that re-reads an operand >=2x (ptgeom)
======  =====================================================

PT006–PT009 consume kernel geometry harvested at trace time by
``paddle_tpu.analysis.kernelmodel`` (``jax.eval_shape`` + a
``pl.pallas_call`` interception shim); run them with
``python tools/ptgeom.py`` (jax required, unlike ptlint). On a plain
ptlint run they see no ``project.geom_specs`` and stay silent.

Library use::

    from paddle_tpu.analysis import load_project, run
    project = load_project(["paddle_tpu"])
    findings = run(project)

CLI: ``python tools/ptlint.py paddle_tpu`` (exits nonzero on findings
not in tools/ptlint_baseline.json). Suppress a deliberate finding inline
with ``# ptlint: disable=PT001 -- why`` (docs/static-analysis.md).
"""

from paddle_tpu.analysis.engine import (FileContext, Finding, Project,
                                        Rule, default_rules,
                                        load_project, run)
from paddle_tpu.analysis import baseline

__all__ = ["FileContext", "Finding", "Project", "Rule",
           "default_rules", "load_project", "run", "baseline"]
