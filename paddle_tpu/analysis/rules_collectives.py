"""PT004 — collective-ordering divergence under rank conditionals.

SPMD collectives (psum, all_gather, broadcast, host barriers, …) are
rendezvous points: EVERY rank must issue the same collectives in the
same order, or the program deadlocks (EQuARX-style collective rewrites
assume exactly this invariant; so does XLA's scheduler). The classic way
to break it is rank-conditional code::

    if jax.process_index() == 0:
        meta = broadcast_one_to_all(meta)     # ranks 1.. never arrive
    ...

This rule walks ``if``/ternary branches whose condition mentions a
rank-like quantity (``rank``, ``process_index``, ``process_id``,
``axis_index``, ``local_rank``, ``node_rank``, ``is_master``,
``coordinator``) and flags any collective that appears in one arm but
not the other — including the no-else case, where the collective runs
on a strict subset of ranks by construction.

The correct shape — rank-0-only *local* work between collectives that
all ranks reach (checkpoint save's commit protocol) — does not trip the
rule: the collectives sit outside the conditional.
"""

import ast
import re
from typing import Set

from paddle_tpu.analysis import callgraph
from paddle_tpu.analysis.engine import Rule

COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "psum_scatter",
    "all_gather", "all_reduce", "all_to_all", "reduce_scatter",
    "broadcast", "broadcast_one_to_all", "sync_global_devices",
    "barrier", "group_reduce", "group_all_gather", "alltoall",
    "alltoall_single", "send_recv_ring", "process_allgather",
}

_RANK_RE = re.compile(
    r"\b(rank|local_rank|node_rank|process_id|process_index|"
    r"axis_index|is_master|coordinator|pid0|is_main)\b", re.IGNORECASE)


def _rank_conditional(ctx, test_node) -> bool:
    seg = ctx.segment(test_node)
    if seg and _RANK_RE.search(seg):
        return True
    for node in ast.walk(test_node):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and _RANK_RE.search(name):
            return True
    return False


def _collectives_in(subtree) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(subtree):
        if isinstance(node, ast.Call):
            name = callgraph.terminal_name(node.func)
            if name in COLLECTIVES:
                out.add(name)
    return out


def _collective_calls(subtree):
    for node in ast.walk(subtree):
        if isinstance(node, ast.Call):
            name = callgraph.terminal_name(node.func)
            if name in COLLECTIVES:
                yield name, node


class CollectiveOrderRule(Rule):
    def __init__(self):
        super().__init__(id="PT004", severity="error",
                         description="rank-divergent collective order")

    def check(self, ctx, project):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.If):
                if _rank_conditional(ctx, node.test):
                    yield from self._diff(ctx, node, node.test,
                                          node.body, node.orelse)
            elif isinstance(node, ast.IfExp):
                if _rank_conditional(ctx, node.test):
                    yield from self._diff(ctx, node, node.test,
                                          [node.body], [node.orelse])

    def _diff(self, ctx, if_node, test, body, orelse):
        cond = " ".join(ctx.segment(test).split()) or "<rank cond>"
        body_set = set()
        for stmt in body:
            body_set |= _collectives_in(stmt)
        else_set = set()
        for stmt in orelse:
            else_set |= _collectives_in(stmt)
        if body_set == else_set:
            return
        reported = set()
        for arm, other, stmts, arm_name in (
                (body_set, else_set, body, "true"),
                (else_set, body_set, orelse, "false")):
            for name in sorted(arm - other):
                for cname, cnode in self._arm_calls(stmts):
                    if cname == name and (name, arm_name) not in reported:
                        reported.add((name, arm_name))
                        yield self.finding(
                            ctx, cnode,
                            f"collective '{name}' is issued only on the "
                            f"{arm_name} arm of rank-conditional "
                            f"`{cond}` — ranks taking the other arm "
                            f"never rendezvous (static deadlock)")

    @staticmethod
    def _arm_calls(stmts):
        for stmt in stmts:
            yield from _collective_calls(stmt)
