"""PT006–PT009 — static TPU kernel-geometry contracts (ISSUE 20).

These rules consume the :class:`~paddle_tpu.analysis.kernelmodel.
KernelSpec` rows that ``tools/ptgeom.py`` harvests (attached to the
project as ``project.geom_specs``) and ride the existing ptlint
engine: inline ``# ptlint: disable=PT00x -- rationale`` suppressions at
the ``pl.pallas_call`` launch site, content-anchored baseline
fingerprints, the same CLI exit-code contract. With no harvested specs
(a plain ``ptlint`` run) every rule is a no-op, so the jax-free lint
gate is unchanged.

- **PT006** VMEM budget: Σ in/out block bytes × the double-buffer
  pipelining factor + VMEM scratch must fit ``PT_VMEM_BUDGET_MB``
  (default 16 MB) minus a compiler reserve. One finding per launch
  site naming the worst (config, geometry) pair.
- **PT007** tiling alignment: a CHOSEN tile (block dim strictly inside
  the array dim) must keep the trailing dim a multiple of 128 lanes
  and the second-minor a multiple of the dtype sublane (8 f32 /
  16 bf16 / 32 int8) — a misaligned block silently pads on chip and
  inflates both VMEM residency and HBM bytes.
- **PT008** aliasing contracts: a whole-array ``ANY``-space pool that
  matches an output must be input_output_aliased (else the kernel pays
  a full HBM pool copy per launch), and an aliased pair whose block
  shapes or index maps diverge is a corruption hazard.
- **PT009** grid-cost sanity: per-grid-step HBM bytes implied by the
  block index maps vs the minimal traffic — flags a kernel whose
  blocking re-fetches an operand ≥2x per launch (the revisit window
  the pipeline could have held is smaller than the operand's reuse
  distance), with the analytic roofline cost from ``devprof`` when
  available.
"""

import types
from typing import Dict, Iterable, List, Tuple

from paddle_tpu.analysis import kernelmodel
from paddle_tpu.analysis.engine import Rule

_MIB = 1 << 20

# PT009 ignores re-reads whose EXTRA per-launch traffic is below this —
# re-streaming a few KiB of scales is noise, re-streaming weight slabs
# is the finding
PT009_MIN_EXTRA_BYTES = 1 * _MIB


def _node(line: int):
    return types.SimpleNamespace(lineno=line, col_offset=0)


def _site_groups(ctx, project):
    """Harvested specs for this file, grouped per (line, family)."""
    groups: Dict[Tuple[int, str], List] = {}
    for spec in getattr(project, "geom_specs", ()) or ():
        if spec.path != ctx.relpath:
            continue
        groups.setdefault((spec.line, spec.name()), []).append(spec)
    return sorted(groups.items())


class VmemBudgetRule(Rule):
    """PT006 — static VMEM residency vs PT_VMEM_BUDGET_MB."""

    def __init__(self):
        super().__init__(
            id="PT006", severity="error",
            description="pallas launch whose blocked operands + scratch "
                        "exceed the static VMEM budget")

    def check(self, ctx, project) -> Iterable:
        budget = kernelmodel.vmem_budget_bytes()
        for (line, name), specs in _site_groups(ctx, project):
            worst = max(specs, key=kernelmodel.vmem_estimate)
            est = kernelmodel.vmem_estimate(worst)
            if est <= budget:
                continue
            yield self.finding(
                ctx, _node(line),
                f"{name}: estimated VMEM {est / _MIB:.2f} MiB exceeds "
                f"budget {budget / _MIB:.2f} MiB "
                f"({est / max(budget, 1):.1f}x) — worst at geometry "
                f"'{worst.geometry}' config '{worst.config}' "
                f"(grid {worst.grid}; 2x-buffered blocks + scratch)",
                symbol=name)


class TilingAlignmentRule(Rule):
    """PT007 — chosen tiles must respect (sublane, 128-lane) multiples."""

    def __init__(self):
        super().__init__(
            id="PT007", severity="warning",
            description="blocked operand tiled off the (sublane, 128) "
                        "grid — the block silently pads on chip")

    def check(self, ctx, project) -> Iterable:
        for (line, name), specs in _site_groups(ctx, project):
            viols = []
            seen = set()
            for spec in specs:
                for op in list(spec.inputs) + list(spec.outputs):
                    if op.space != "vmem" or op.block is None:
                        continue
                    bs, shp = op.block, op.shape
                    checks = []
                    if bs and 0 < bs[-1] < shp[-1] and bs[-1] % 128:
                        checks.append((len(bs) - 1, bs[-1], 128,
                                       "lane"))
                    # bs[-2] == 1 is degenerate row-streaming (one
                    # layer/row slab per grid step): the sublane pad is
                    # inherent to indexing a single row, not a fixable
                    # tiling choice, so it stays quiet.
                    if len(bs) >= 2 and 1 < bs[-2] < shp[-2]:
                        sub = kernelmodel.sublane(op.dtype)
                        if bs[-2] % sub:
                            checks.append((len(bs) - 2, bs[-2], sub,
                                           "sublane"))
                    for dim, got, want, kind in checks:
                        key = (op.role, op.index, dim, got)
                        if key in seen:
                            continue
                        seen.add(key)
                        viols.append(
                            f"{op.role}[{op.index}] block {bs} dim "
                            f"{dim} = {got} is not a multiple of "
                            f"{want} ({kind}, dtype {op.dtype}, "
                            f"geometry '{spec.geometry}' config "
                            f"'{spec.config}')")
            if viols:
                yield self.finding(
                    ctx, _node(line),
                    f"{name}: misaligned tile pads on chip — "
                    + "; ".join(viols[:3])
                    + (f" (+{len(viols) - 3} more)"
                       if len(viols) > 3 else ""),
                    symbol=name)


class AliasContractRule(Rule):
    """PT008 — ANY pools must alias; aliased pairs must agree."""

    def __init__(self):
        super().__init__(
            id="PT008", severity="error",
            description="in-place pool not input_output_aliased, or an "
                        "aliased pair with diverging geometry")

    def _spec_violations(self, spec) -> List[str]:
        out: List[str] = []
        aliased_in = set(spec.aliases)
        by_index = {op.index: op for op in spec.inputs}
        any_outs = [o for o in spec.outputs if o.space == "any"]
        alias_tgt = set(spec.aliases.values())
        for op in spec.inputs:
            if op.space != "any" or op.index in aliased_in:
                continue
            for o in any_outs:
                if o.index in alias_tgt:
                    continue
                if o.shape == op.shape and o.dtype == op.dtype:
                    out.append(
                        f"ANY-space pool in[{op.index}] "
                        f"{op.shape}:{op.dtype} matches out[{o.index}] "
                        f"but is not input_output_aliased — the launch "
                        f"pays a full HBM pool copy")
                    break
        for gi, oi in sorted(spec.aliases.items()):
            inp = by_index.get(gi)
            outp = spec.outputs[oi] if 0 <= oi < len(spec.outputs) \
                else None
            if inp is None or outp is None:
                out.append(f"alias {gi}->{oi} names a missing operand")
                continue
            if inp.shape != outp.shape or inp.dtype != outp.dtype:
                out.append(
                    f"alias {gi}->{oi} shape/dtype mismatch: "
                    f"{inp.shape}:{inp.dtype} vs "
                    f"{outp.shape}:{outp.dtype}")
                continue
            if inp.block != outp.block:
                out.append(
                    f"alias {gi}->{oi} block mismatch: {inp.block} vs "
                    f"{outp.block} — in-place writes land in the wrong "
                    f"window")
                continue
            if inp.map_id is not None and inp.map_id == outp.map_id:
                continue
            if inp.deps is None or outp.deps is None:
                continue  # data-dependent maps: cannot probe statically
            for pt, idx in inp.probes.items():
                oidx = outp.probes.get(pt)
                if oidx is not None and oidx != idx:
                    out.append(
                        f"alias {gi}->{oi} index maps diverge at grid "
                        f"{pt}: in->{idx} vs out->{oidx} — aliased "
                        f"write corrupts a block the input never "
                        f"presented")
                    break
        return out

    def check(self, ctx, project) -> Iterable:
        for (line, name), specs in _site_groups(ctx, project):
            msgs = []
            for spec in specs:
                for v in self._spec_violations(spec):
                    if v not in msgs:
                        msgs.append(v)
            if msgs:
                yield self.finding(
                    ctx, _node(line),
                    f"{name}: " + "; ".join(msgs[:3])
                    + (f" (+{len(msgs) - 3} more)"
                       if len(msgs) > 3 else ""),
                    symbol=name)


def _reread(spec, op):
    """(factor, fetches, distinct) — how many block fetches the
    row-major grid traversal implies vs the distinct blocks touched."""
    grid = spec.grid
    if not grid or op.block is None or op.deps is None:
        return None
    gp = 1
    for g in grid:
        gp *= int(g)
    deps = set(op.deps)
    distinct = 1
    for d in deps:
        distinct *= int(grid[d])
    run = 1
    for d in reversed(range(len(grid))):
        if d in deps:
            break
        run *= int(grid[d])
    fetches = gp // max(run, 1)
    return fetches / max(distinct, 1), fetches, distinct


def _roofline_suffix(extra_bytes: int) -> str:
    try:
        from paddle_tpu.observability import devprof
        secs = devprof.hbm_seconds(extra_bytes)
    except Exception:
        return ""
    if not secs:
        return ""
    return f" (~{secs * 1e6:.0f} us/launch at roofline HBM peak)"


class GridCostRule(Rule):
    """PT009 — blocking that re-reads an operand >=2x per launch."""

    def __init__(self):
        super().__init__(
            id="PT009", severity="warning",
            description="grid traversal re-fetches a blocked operand "
                        ">=2x per launch vs minimal HBM traffic")

    def check(self, ctx, project) -> Iterable:
        for (line, name), specs in _site_groups(ctx, project):
            worst = None
            for spec in specs:
                for op in spec.inputs:
                    if op.space != "vmem":
                        continue
                    rr = _reread(spec, op)
                    if rr is None:
                        continue
                    factor, fetches, distinct = rr
                    extra = (fetches - distinct) * op.block_bytes()
                    if factor < 2 or extra < PT009_MIN_EXTRA_BYTES:
                        continue
                    if worst is None or extra > worst[0]:
                        worst = (extra, factor, fetches, distinct, op,
                                 spec)
            if worst is None:
                continue
            extra, factor, fetches, distinct, op, spec = worst
            yield self.finding(
                ctx, _node(line),
                f"{name}: in[{op.index}] block {op.block_shape()} is "
                f"fetched {fetches}x per launch but only {distinct} "
                f"distinct blocks exist ({factor:.0f}x re-read, "
                f"+{extra / _MIB:.1f} MiB HBM over minimal at geometry "
                f"'{spec.geometry}' config '{spec.config}')"
                + _roofline_suffix(extra),
                symbol=name)


def geom_rules() -> List[Rule]:
    return [VmemBudgetRule(), TilingAlignmentRule(),
            AliasContractRule(), GridCostRule()]
