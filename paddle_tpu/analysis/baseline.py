"""Baseline (grandfather) file for ptlint findings.

A finding's **fingerprint** is content-anchored, not line-anchored:
``sha1(rule | path | symbol | normalized source line | k)`` where ``k``
disambiguates identical lines within one (rule, path, symbol) group by
order of appearance. Unrelated edits that shift line numbers therefore
do NOT invalidate the baseline; editing the flagged line itself does —
which is the desired behavior (the finding should be re-triaged).

The checked-in file (tools/ptlint_baseline.json) records deliberate,
explained findings; ``tools/ptlint.py --error-on-new`` fails only on
findings NOT in it. Prefer inline ``# ptlint: disable=`` suppressions
for deliberate sites (self-documenting); use the baseline for bulk
grandfathering when adopting a new rule.
"""

import hashlib
import json
from typing import Dict, List, Tuple

BASELINE_VERSION = 1


def _normalize(text: str) -> str:
    return "".join(text.split())


def fingerprint_all(findings, project) -> None:
    """Fill ``finding.fingerprint`` for every finding (in place)."""
    seen: Dict[Tuple[str, str, str, str], int] = {}
    for f in findings:
        ctx = project.file(f.path)
        norm = _normalize(ctx.line_text(f.line)) if ctx else ""
        key = (f.rule, f.path, f.symbol, norm)
        k = seen.get(key, 0)
        seen[key] = k + 1
        raw = "|".join((f.rule, f.path, f.symbol, norm, str(k)))
        f.fingerprint = hashlib.sha1(raw.encode()).hexdigest()[:16]


def write(path: str, findings) -> None:
    payload = {
        "version": BASELINE_VERSION,
        "findings": [
            {"fingerprint": f.fingerprint, "rule": f.rule, "path": f.path,
             "symbol": f.symbol, "message": f.message}
            for f in findings],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def load(path: str) -> Dict[str, dict]:
    """fingerprint -> recorded entry. Missing file = empty baseline."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            payload = json.load(fh)
    except FileNotFoundError:
        return {}
    if payload.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version "
            f"{payload.get('version')!r} (want {BASELINE_VERSION})")
    return {e["fingerprint"]: e for e in payload.get("findings", [])}


def partition(findings, baseline: Dict[str, dict]):
    """(new, known): findings absent from / present in the baseline."""
    new: List = []
    known: List = []
    for f in findings:
        (known if f.fingerprint in baseline else new).append(f)
    return new, known
