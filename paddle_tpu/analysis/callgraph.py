"""Lightweight package call graph for ptlint.

Pure-AST, name-based and deliberately conservative (an over-
approximation: unresolvable calls match every same-named definition in
the project) — the rules that consume it (PT001 host-sync scope, PT003
traced-side-effect scope) want "could this run inside a traced program /
the dispatch loop", and a false edge only widens the lint scope, never
hides a finding.

Three things are computed in one pass per file:

- every function/lambda definition with its enclosing-scope qualname,
- the called names inside each definition (terminal name only:
  ``self._pump()`` records ``_pump``),
- **jit roots**: functions handed to ``jax.jit`` / ``jit`` / ``pjit`` /
  ``shard_map`` (call-site args, decorators, ``partial(jax.jit, ...)``
  decorators, and one level of wrapper nesting like
  ``jax.jit(checkify.checkify(fn))``).

Bare-name uses are first run through local reference aliases
(``step = self._traced`` makes a later ``jit(step)`` resolve to
``_traced``, NOT to every function named ``step``) — the one spot where
precision beats over-approximation, because a false jit root drags a
host-only method into trace scope and produces false PT001/PT003
findings on it.

Reachability (`reachable`) walks call edges plus the
parent→nested-function edge: a ``def one(carry, _)`` defined inside a
jitted body executes at trace time even though it is only ever *passed*
to ``lax.scan``.
"""

import ast
from typing import Dict, Iterable, List, Optional, Set

# call-ee names that wrap a function for tracing. Terminal-name match:
# jax.jit, framework.jit, pjit, jax.shard_map ... all end in one of these.
JIT_WRAPPER_NAMES = {"jit", "pjit", "shard_map", "checkify", "named_call",
                     "vmap", "pmap", "grad", "value_and_grad", "scan",
                     "while_loop", "fori_loop", "cond", "remat",
                     "checkpoint", "custom_vjp", "custom_jvp"}
# Of those, the ones whose wrapped function really enters a NEW trace
# context on its own (scan/cond bodies only trace when already inside
# one, but marking them roots is harmless over-approximation kept OFF
# to avoid noise):
JIT_ROOT_NAMES = {"jit", "pjit", "shard_map", "checkify", "pmap"}


def dotted(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute chains, 'jit' for a Name, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def iter_own_nodes(func_node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body WITHOUT descending into nested function /
    class definitions (those are separate FunctionInfos)."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class FunctionInfo:
    __slots__ = ("ctx", "node", "name", "qual", "cls", "parent",
                 "children", "calls", "aliases", "lineno")

    def __init__(self, ctx, node, name, qual, cls, parent):
        self.ctx = ctx            # FileContext
        self.node = node
        self.name = name          # terminal name ('<lambda>' for Lambda)
        self.qual = qual          # relpath::Class.meth.<locals>.inner
        self.cls = cls            # enclosing class name or ""
        self.parent = parent      # enclosing FunctionInfo or None
        self.children: List["FunctionInfo"] = []
        # typed call edges: (base, name) — base '' for bare names,
        # 'self'/'cls', a module alias, or '<expr>' (see resolve_edge)
        self.calls: Set[tuple] = set()
        # local reference aliases: ``step = self._traced`` records
        # {'step': ('self', '_traced')} so later uses of the bare name
        # resolve to the real target, not every same-named definition
        self.aliases: Dict[str, tuple] = {}
        self.lineno = getattr(node, "lineno", 1)

    def __repr__(self):
        return f"FunctionInfo({self.qual})"


class _FileVisitor(ast.NodeVisitor):
    def __init__(self, ctx, graph):
        self.ctx = ctx
        self.graph = graph
        self.fn_stack: List[FunctionInfo] = []
        self.cls_stack: List[str] = []
        self.scope_names: List[str] = []   # for quals

    # -- scopes -------------------------------------------------------------
    def _add_function(self, node, name):
        qual = self.ctx.relpath + "::" + ".".join(
            self.scope_names + [name])
        parent = self.fn_stack[-1] if self.fn_stack else None
        cls = self.cls_stack[-1] if self.cls_stack else ""
        info = FunctionInfo(self.ctx, node, name, qual, cls, parent)
        if parent is not None:
            parent.children.append(info)
        self.graph._register(info)
        return info

    def visit_ClassDef(self, node):
        for dec in node.decorator_list:
            self.visit(dec)
        self.cls_stack.append(node.name)
        self.scope_names.append(node.name)
        for child in node.body:
            self.visit(child)
        self.scope_names.pop()
        self.cls_stack.pop()

    def _visit_funcdef(self, node):
        for dec in node.decorator_list:
            self.visit(dec)
            self._check_jit_decorator(dec, node)
        info = self._add_function(node, node.name)
        self.fn_stack.append(info)
        self.scope_names.extend([node.name, "<locals>"])
        for child in node.body:
            self.visit(child)
        for default in (node.args.defaults + node.args.kw_defaults):
            if default is not None:
                self.visit(default)
        self.scope_names.pop()
        self.scope_names.pop()
        self.fn_stack.pop()

    visit_FunctionDef = _visit_funcdef
    visit_AsyncFunctionDef = _visit_funcdef

    def visit_Lambda(self, node):
        info = self._add_function(node, "<lambda>")
        self.fn_stack.append(info)
        self.scope_names.extend(["<lambda>", "<locals>"])
        self.visit(node.body)
        self.scope_names.pop()
        self.scope_names.pop()
        self.fn_stack.pop()

    # -- imports ------------------------------------------------------------
    def visit_Import(self, node):
        imports = self.graph.imports.setdefault(self.ctx.relpath, {})
        for alias in node.names:
            imports[alias.asname or alias.name.split(".")[0]] = alias.name
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        imports = self.graph.imports.setdefault(self.ctx.relpath, {})
        mod = node.module or ""
        for alias in node.names:
            imports[alias.asname or alias.name] = (
                f"{mod}.{alias.name}" if mod else alias.name)
        self.generic_visit(node)

    # -- aliases ------------------------------------------------------------
    def visit_Assign(self, node):
        """Record ``name = self.method`` / ``name = module.fn`` /
        ``name = other_name`` reference aliases so a later bare-name
        use (a call, or being handed to jax.jit) resolves to the REAL
        target instead of smearing over every same-named definition —
        the ``step = self._traced; jit(step)`` pattern must not mark an
        unrelated host-side ``step`` method as a jit root."""
        if (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, (ast.Name, ast.Attribute))):
            edge = self._call_edge(node.value)
            if edge is not None and edge != ("", node.targets[0].id):
                if self.fn_stack:
                    self.fn_stack[-1].aliases[
                        node.targets[0].id] = edge
                else:
                    self.graph.module_aliases.setdefault(
                        self.ctx.relpath, {})[
                        node.targets[0].id] = edge
        self.generic_visit(node)

    def _translate(self, edge, depth: int = 0):
        """Follow bare-name aliases (innermost scope first, then module
        level) to the edge they actually reference; depth-capped for
        alias chains."""
        base, name = edge
        if base != "" or depth > 4:
            return edge
        for fn in reversed(self.fn_stack):
            tgt = fn.aliases.get(name)
            if tgt is not None:
                return self._translate(tgt, depth + 1)
        tgt = self.graph.module_aliases.get(
            self.ctx.relpath, {}).get(name)
        if tgt is not None:
            return self._translate(tgt, depth + 1)
        return edge

    # -- calls --------------------------------------------------------------
    @staticmethod
    def _call_edge(func):
        """(base, name) for a call: base '' for bare names, the base
        identifier for one-level attribute calls ('self', a module
        alias, a local object), '<expr>' for deeper chains."""
        if isinstance(func, ast.Name):
            return ("", func.id)
        if isinstance(func, ast.Attribute):
            v = func.value
            if isinstance(v, ast.Name):
                return (v.id, func.attr)
            return ("<expr>", func.attr)
        return None

    def visit_Call(self, node):
        edge = self._call_edge(node.func)
        if edge:
            edge = self._translate(edge)
        if edge and self.fn_stack:
            self.fn_stack[-1].calls.add(edge)
        if edge and edge[1] in JIT_ROOT_NAMES:
            self._mark_roots_from_call(node)
        self.generic_visit(node)

    def _mark_roots_from_call(self, call: ast.Call):
        if not call.args:
            return
        self._mark_root_expr(call.args[0])

    def _mark_root_expr(self, expr, depth: int = 0):
        if depth > 2:
            return
        if isinstance(expr, ast.Lambda):
            self.graph._pending_lambda_roots.append(expr)
        elif isinstance(expr, (ast.Name, ast.Attribute)):
            edge = self._call_edge(expr)
            if edge:
                self.graph._pending_name_roots.append(
                    (self.ctx.relpath,) + self._translate(edge))
        elif isinstance(expr, ast.Call):
            # jax.jit(checkify.checkify(fn)) — descend one wrapper level
            for a in expr.args:
                self._mark_root_expr(a, depth + 1)

    def _check_jit_decorator(self, dec, funcdef):
        name = terminal_name(dec.func if isinstance(dec, ast.Call)
                             else dec)
        if name in JIT_ROOT_NAMES:
            self.graph._pending_name_roots.append(
                (self.ctx.relpath, "", funcdef.name))
        elif (isinstance(dec, ast.Call) and name == "partial"
                and dec.args
                and terminal_name(dec.args[0]) in JIT_ROOT_NAMES):
            self.graph._pending_name_roots.append(
                (self.ctx.relpath, "", funcdef.name))


class CallGraph:
    def __init__(self, files):
        self.functions: List[FunctionInfo] = []
        self.by_name: Dict[str, List[FunctionInfo]] = {}
        self.by_file: Dict[str, List[FunctionInfo]] = {}
        self.by_node: Dict[ast.AST, FunctionInfo] = {}
        self.imports: Dict[str, Dict[str, str]] = {}
        # module-level reference aliases per file (see visit_Assign)
        self.module_aliases: Dict[str, Dict[str, tuple]] = {}
        self._pending_name_roots = []
        self._pending_lambda_roots = []
        for ctx in files:
            _FileVisitor(ctx, self).visit(ctx.tree)
        self._module_index = self._build_module_index(files)
        self._import_closure: Dict[str, Set[str]] = {}
        self.jit_roots: Set[FunctionInfo] = set()
        for relpath, base, name in self._pending_name_roots:
            self.jit_roots.update(self.resolve_edge(base, name, relpath))
        for lam in self._pending_lambda_roots:
            info = self.by_node.get(lam)
            if info is not None:
                self.jit_roots.add(info)
        self._jit_scope: Optional[Set[FunctionInfo]] = None

    def _register(self, info: FunctionInfo):
        self.functions.append(info)
        self.by_name.setdefault(info.name, []).append(info)
        self.by_file.setdefault(info.ctx.relpath, []).append(info)
        self.by_node[info.node] = info

    @staticmethod
    def _build_module_index(files) -> Dict[str, str]:
        """dotted module name -> relpath for every linted file."""
        out: Dict[str, str] = {}
        for ctx in files:
            mod = ctx.relpath[:-3] if ctx.relpath.endswith(".py") \
                else ctx.relpath
            mod = mod.replace("/", ".")
            if mod.endswith(".__init__"):
                mod = mod[:-len(".__init__")]
            out[mod] = ctx.relpath
        return out

    def _imported_files(self, relpath: str) -> Set[str]:
        """Relpaths of project modules this file imports (any depth in
        the file — function-level imports count)."""
        cached = self._import_closure.get(relpath)
        if cached is not None:
            return cached
        out: Set[str] = set()
        for target in self.imports.get(relpath, {}).values():
            # target may be a module or module.symbol — try both
            for cand in (target, target.rpartition(".")[0]):
                hit = self._module_index.get(cand)
                if hit is not None:
                    out.add(hit)
                    break
        self._import_closure[relpath] = out
        return out

    def resolve_edge(self, base: str, name: str,
                     from_file: str) -> List[FunctionInfo]:
        """Definitions a call ``base.name(...)`` (base '' = bare name)
        may refer to. Resolution is deliberately narrow — a global
        name fallback smears scopes across the package via generic
        method names like ``run``/``update``:

        - same file always wins;
        - bare names may follow a ``from x import name`` alias;
        - ``self.``/``cls.`` methods may live in an imported base-class
          file (PagedDecodeEngine calling ResilientScheduler._pump);
        - ``alias.name`` where alias imports a project MODULE resolves
          inside that module only (``gpt_lib._sample_token``);
        - any other base (an arbitrary object) stays same-file.
        """
        cands = self.by_name.get(name, [])
        local = [c for c in cands if c.ctx.relpath == from_file]
        if local:
            return local
        imports = self.imports.get(from_file, {})
        if base == "":
            target = imports.get(name)
            if target:
                for cand in (target, target.rpartition(".")[0]):
                    hit = self._module_index.get(cand)
                    if hit:
                        return [c for c in cands
                                if c.ctx.relpath == hit]
            return []
        if base in ("self", "cls"):
            imported = self._imported_files(from_file)
            return [c for c in cands if c.ctx.relpath in imported]
        target = imports.get(base)
        if target:
            hit = self._module_index.get(target)
            if hit:
                return [c for c in cands if c.ctx.relpath == hit]
        return []

    def reachable(self,
                  roots: Iterable[FunctionInfo]) -> Set[FunctionInfo]:
        """BFS over call edges + nested definitions."""
        seen: Set[FunctionInfo] = set()
        frontier = [r for r in roots]
        while frontier:
            fn = frontier.pop()
            if fn in seen:
                continue
            seen.add(fn)
            frontier.extend(fn.children)
            for base, name in fn.calls:
                frontier.extend(
                    self.resolve_edge(base, name, fn.ctx.relpath))
        return seen

    def jit_scope(self) -> Set[FunctionInfo]:
        """Every function that may execute at trace time."""
        if self._jit_scope is None:
            self._jit_scope = self.reachable(self.jit_roots)
        return self._jit_scope

    def functions_matching(self, pred) -> List[FunctionInfo]:
        return [f for f in self.functions if pred(f)]
