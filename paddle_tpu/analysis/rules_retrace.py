"""PT002 — retrace / recompile hazards.

A jitted function should compile ONCE per static signature; the engines'
no-recompile property tests assert exactly that. Four hazard shapes this
rule catches statically:

1. ``jax.jit(...)`` (or ``pjit``/``shard_map``) called inside a
   ``for``/``while`` loop — a fresh wrapper per iteration defeats jax's
   C++ dispatch cache at best and recompiles at worst. Hoist the wrapper
   and reuse it.
2. A jit-wrapped function reading a module-level global that some other
   code rebinds (``global X`` + assignment, or repeated module-level
   assignment): the closure baked the trace-time value — later mutation
   silently does NOT reach the compiled program.
3. ``static_argnums``/``static_argnames`` whose call sites pass
   list/dict/set literals — unhashable statics raise at dispatch, and
   per-call fresh containers would retrace every call even when
   hashable.
4. Per-call shape-string cache keys (``cache[f"...{x.shape}"]`` or
   ``cache[str(x.shape)]``) — building the key from a live array every
   call invites one compiled program per observed string; key on a
   static tuple (or bucket the shapes).
"""

import ast
from typing import Dict, List, Optional, Set

from paddle_tpu.analysis import callgraph
from paddle_tpu.analysis.engine import Rule

_WRAP_NAMES = {"jit", "pjit", "shard_map"}


def _is_jit_wrap_call(node: ast.Call) -> bool:
    return callgraph.terminal_name(node.func) in _WRAP_NAMES


def _static_positions(node: ast.Call) -> Optional[List[int]]:
    """Literal static_argnums positions of a jit call, if present."""
    for kw in node.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return [v.value]
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if (isinstance(e, ast.Constant)
                            and isinstance(e.value, int)):
                        out.append(e.value)
                return out
    return None


def _unhashable_literal(node) -> bool:
    return isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp))


class RetraceHazardRule(Rule):
    def __init__(self):
        super().__init__(id="PT002", severity="error",
                         description="jit retrace/recompile hazard")

    def check(self, ctx, project):
        yield from self._check_jit_in_loop(ctx)
        yield from self._check_mutated_global_closures(ctx, project)
        yield from self._check_static_argnums(ctx)
        yield from self._check_shape_keys(ctx)

    # -- 1: jit under a loop ------------------------------------------------
    def _check_jit_in_loop(self, ctx):
        hits = []

        def walk(node, loop_depth):
            for child in ast.iter_child_nodes(node):
                d = loop_depth
                if isinstance(child, (ast.For, ast.While, ast.AsyncFor,
                                      ast.ListComp, ast.SetComp,
                                      ast.DictComp, ast.GeneratorExp)):
                    d += 1
                if (isinstance(child, ast.Call)
                        and _is_jit_wrap_call(child) and d > 0):
                    hits.append(child)
                walk(child, d)

        walk(ctx.tree, 0)
        for node in hits:
            name = callgraph.terminal_name(node.func)
            yield self.finding(
                ctx, node,
                f"{name}(...) inside a loop builds a fresh traced "
                f"wrapper per iteration (dispatch-cache miss / "
                f"recompile); hoist it out and reuse one wrapper")

    # -- 2: jit roots over mutated globals ----------------------------------
    def _mutated_globals(self, ctx) -> Set[str]:
        module_assigns: Dict[str, int] = {}
        mutated: Set[str] = set()
        for stmt in ctx.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    module_assigns[t.id] = module_assigns.get(t.id, 0) + 1
                    if (isinstance(stmt, ast.AugAssign)
                            or module_assigns[t.id] > 1):
                        mutated.add(t.id)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Global):
                for name in node.names:
                    if name in module_assigns:
                        mutated.add(name)
        return mutated

    def _check_mutated_global_closures(self, ctx, project):
        mutated = self._mutated_globals(ctx)
        if not mutated:
            return
        g = project.callgraph
        roots = [f for f in g.jit_roots if f.ctx is ctx]
        for fn in roots:
            assigned = {n.id for n in ast.walk(fn.node)
                        if isinstance(n, ast.Name)
                        and isinstance(n.ctx, ast.Store)}
            reported = set()   # one finding per (fn, global) is enough
            for node in ast.walk(fn.node):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in mutated
                        and node.id not in assigned
                        and node.id not in reported):
                    reported.add(node.id)
                    yield self.finding(
                        ctx, node,
                        f"jit-wrapped '{fn.name}' closes over global "
                        f"'{node.id}' which is rebound elsewhere — the "
                        f"compiled program keeps the trace-time value; "
                        f"pass it as an argument instead",
                        symbol=fn.qual)

    # -- 3: unhashable static args ------------------------------------------
    def _check_static_argnums(self, ctx):
        jitted: Dict[str, List[int]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                call = node.value
                if not _is_jit_wrap_call(call):
                    continue
                pos = _static_positions(call)
                if pos is None:
                    continue
                for t in node.targets:
                    name = callgraph.terminal_name(t)
                    if name:
                        jitted[name] = pos
            # immediate call: jax.jit(f, static_argnums=(0,))(bad, ...)
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Call)
                    and _is_jit_wrap_call(node.func)):
                pos = _static_positions(node.func)
                for p in (pos or []):
                    if (p < len(node.args)
                            and _unhashable_literal(node.args[p])):
                        yield self.finding(
                            ctx, node.args[p],
                            f"static arg {p} is an unhashable literal — "
                            f"static_argnums values must be hashable "
                            f"(and fresh containers retrace per call)")
        if not jitted:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = callgraph.terminal_name(node.func)
            if name not in jitted:
                continue
            for p in jitted[name]:
                if p < len(node.args) and _unhashable_literal(
                        node.args[p]):
                    yield self.finding(
                        ctx, node.args[p],
                        f"call passes an unhashable literal at static "
                        f"position {p} of jitted '{name}'")

    # -- 4: per-call shape-string keys --------------------------------------
    def _shapey(self, node) -> bool:
        """f-string / str() built from a .shape read."""
        if isinstance(node, ast.JoinedStr):
            return any(
                isinstance(v, ast.FormattedValue)
                and any(isinstance(a, ast.Attribute) and a.attr == "shape"
                        for a in ast.walk(v.value))
                for v in node.values)
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "str" and node.args):
            return any(isinstance(a, ast.Attribute) and a.attr == "shape"
                       for a in ast.walk(node.args[0]))
        return False

    def _check_shape_keys(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Subscript) and self._shapey(
                    node.slice):
                yield self.finding(
                    ctx, node,
                    "per-call shape-string cache key — key on the "
                    "static shape tuple (or bucket shapes) so the "
                    "compile set stays bounded",
                    severity="warning")
