"""Reference interpreter for the ONNX op subset the exporter emits.

Each op follows the ONNX operator spec (numpy, plain loops — tiny shapes
only). This is a deliberately independent execution path from the jax
layers: the export-parity tests run the *parsed file* through this
interpreter and compare against ``layer(x)``, so a wrong attribute, a
mislabeled tensor, or a wrong weight layout in the exporter shows up as a
numeric mismatch, not just a structural one.
"""

import math

import numpy as np

_ERF = np.vectorize(math.erf)


def _pad2d(x, pads):
    # ONNX pads = [h_begin, w_begin, h_end, w_end]
    hb, wb, he, we = pads
    return np.pad(x, ((0, 0), (0, 0), (hb, he), (wb, we)))


def _conv(x, w, b, attrs):
    group = attrs.get("group", 1)
    sh, sw = attrs.get("strides", [1, 1])
    dh, dw = attrs.get("dilations", [1, 1])
    xp = _pad2d(x, attrs.get("pads", [0, 0, 0, 0]))
    n, cin, H, W = xp.shape
    m, cin_g, kh, kw = w.shape
    oh = (H - (dh * (kh - 1) + 1)) // sh + 1
    ow = (W - (dw * (kw - 1) + 1)) // sw + 1
    out = np.zeros((n, m, oh, ow), np.float64)
    m_g = m // group
    for g in range(group):
        xs = xp[:, g * cin_g:(g + 1) * cin_g]
        for oc in range(g * m_g, (g + 1) * m_g):
            for i in range(oh):
                for j in range(ow):
                    patch = xs[:, :, i * sh:i * sh + dh * (kh - 1) + 1:dh,
                               j * sw:j * sw + dw * (kw - 1) + 1:dw]
                    out[:, oc, i, j] = np.sum(
                        patch * w[oc][None], axis=(1, 2, 3))
    if b is not None:
        out += b[None, :, None, None]
    return out


def _pool(x, attrs, mode):
    kh, kw = attrs["kernel_shape"]
    sh, sw = attrs.get("strides", [kh, kw])
    pads = attrs.get("pads", [0, 0, 0, 0])
    fill = -np.inf if mode == "max" else 0.0
    hb, wb, he, we = pads
    xp = np.pad(x, ((0, 0), (0, 0), (hb, he), (wb, we)),
                constant_values=fill)
    n, c, H, W = xp.shape
    oh = (H - kh) // sh + 1
    ow = (W - kw) // sw + 1
    out = np.zeros((n, c, oh, ow), np.float64)
    include_pad = attrs.get("count_include_pad", 0)
    for i in range(oh):
        for j in range(ow):
            win = xp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
            if mode == "max":
                out[:, :, i, j] = win.max(axis=(2, 3))
            elif include_pad:
                out[:, :, i, j] = win.mean(axis=(2, 3))
            else:  # divisor = count of non-pad elements in this window
                h0, w0 = i * sh, j * sw
                vh = min(h0 + kh, hb + x.shape[2]) - max(h0, hb)
                vw = min(w0 + kw, wb + x.shape[3]) - max(w0, wb)
                out[:, :, i, j] = win.sum(axis=(2, 3)) / float(vh * vw)
    return out


def run_model(parsed: dict, feeds: dict):
    """Execute a parse_model() dict; returns list of graph-output arrays."""
    g = parsed["graph"]
    env = {k: np.asarray(v, np.float64)
           for k, v in g["initializers"].items()}
    env.update({k: np.asarray(v, np.float64) for k, v in feeds.items()})

    for nd in g["nodes"]:
        op, ins, attrs = nd["op_type"], nd["input"], nd["attrs"]
        x = env[ins[0]] if ins else None
        if op == "Gemm":
            a, bm = env[ins[0]], env[ins[1]]
            if attrs.get("transA", 0):
                a = a.T
            if attrs.get("transB", 0):
                bm = bm.T
            y = attrs.get("alpha", 1.0) * (a @ bm)
            if len(ins) > 2:
                y = y + attrs.get("beta", 1.0) * env[ins[2]]
        elif op == "Conv":
            y = _conv(x, env[ins[1]],
                      env[ins[2]] if len(ins) > 2 else None, attrs)
        elif op == "MaxPool":
            y = _pool(x, attrs, "max")
        elif op == "AveragePool":
            y = _pool(x, attrs, "avg")
        elif op == "GlobalAveragePool":
            y = x.mean(axis=(2, 3), keepdims=True)
        elif op == "BatchNormalization":
            scale, bias, mean, var = (env[i] for i in ins[1:5])
            eps = attrs.get("epsilon", 1e-5)
            shp = (1, -1) + (1,) * (x.ndim - 2)
            y = ((x - mean.reshape(shp))
                 / np.sqrt(var.reshape(shp) + eps)) \
                * scale.reshape(shp) + bias.reshape(shp)
        elif op == "Relu":
            y = np.maximum(x, 0)
        elif op == "LeakyRelu":
            y = np.where(x >= 0, x, attrs.get("alpha", 0.01) * x)
        elif op == "Sigmoid":
            y = 1.0 / (1.0 + np.exp(-x))
        elif op == "Tanh":
            y = np.tanh(x)
        elif op == "Erf":
            y = _ERF(x)
        elif op == "Softmax":
            ax = attrs.get("axis", -1)
            e = np.exp(x - x.max(axis=ax, keepdims=True))
            y = e / e.sum(axis=ax, keepdims=True)
        elif op == "Flatten":
            ax = attrs.get("axis", 1)
            y = x.reshape(int(np.prod(x.shape[:ax]) or 1), -1)
        elif op == "Identity":
            y = x
        elif op == "Add":
            y = env[ins[0]] + env[ins[1]]
        elif op == "Mul":
            y = env[ins[0]] * env[ins[1]]
        elif op == "Div":
            y = env[ins[0]] / env[ins[1]]
        else:
            raise NotImplementedError(f"interpreter lacks op {op}")
        env[nd["output"][0]] = y

    return [env[o["name"]] for o in g["outputs"]]
