"""ONNX export shim (ref: python/paddle/onnx/export.py delegates to external
paddle2onnx). The TPU-native interchange format is StableHLO
(paddle_tpu.jit.save); ONNX export is available when the optional onnx
package exists, else raises with guidance."""

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export is delegated to external tooling in the reference "
        "(python/paddle/onnx/export.py → paddle2onnx). paddle_tpu's native "
        "serving format is StableHLO: use paddle_tpu.jit.save(layer, path, "
        "input_spec=...) and serve via any StableHLO-consuming runtime.")
