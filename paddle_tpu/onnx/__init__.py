"""ONNX export (ref: python/paddle/onnx/export.py).

The reference delegates to the external paddle2onnx package; this
environment has neither it nor the ``onnx`` package, so export here is
self-contained: a structural walk over the layer tree emits ONNX
ModelProto bytes through the wire-format writer in ``_wire.py``. The
TPU-native interchange format remains StableHLO (``paddle_tpu.jit.save``);
ONNX export covers the feed-forward layer subset interchange tooling
actually consumes (MLPs / CNNs: Linear, Conv2D, BatchNorm, pooling,
Flatten, the common activations). Layers outside the subset raise with
guidance rather than exporting something silently wrong.

Verification story: ``load_model`` parses the emitted bytes back and
``paddle_tpu.onnx._numpy_eval.run_model`` executes them per the ONNX
operator spec; tests/test_onnx_export.py pins numeric parity between the
exported file and ``layer(x)``.
"""

import math

import numpy as np

from paddle_tpu.onnx import _wire

__all__ = ["export", "load_model"]

_SUPPORTED = ("Linear, Conv2D, BatchNorm/1D/2D, ReLU, LeakyReLU, GELU "
              "(exact), Sigmoid, Tanh, Softmax, MaxPool2D, AvgPool2D, "
              "AdaptiveAvgPool2D(1), Flatten(start_axis=1), Dropout "
              "(inference no-op), Identity, Sequential")


def _pair(v):
    if isinstance(v, str):
        raise ValueError(
            f"string padding mode {v!r} does not export: ONNX Conv/pool "
            "pads are explicit integers — construct the layer with numeric "
            "padding")
    if isinstance(v, (list, tuple)):
        if len(v) != 2:
            raise ValueError(f"expected 2 spatial values, got {v}")
        return [int(v[0]), int(v[1])]
    return [int(v), int(v)]


def _pads4(padding):
    ph, pw = _pair(padding)
    return [ph, pw, ph, pw]


class _Ctx:
    def __init__(self, sym_batch=False):
        self.nodes = []
        self.initializers = []
        self.value_infos = []
        self.counter = {}
        self.sym_batch = sym_batch  # input batch dim is symbolic "N"

    def name(self, op):
        i = self.counter.get(op, 0)
        self.counter[op] = i + 1
        return f"{op.lower()}_{i}"

    def add_init(self, name, array):
        self.initializers.append(_wire.tensor(name, np.asarray(array)))
        return name

    def sym_shape(self, shape):
        # every op in the exportable subset preserves the batch dim, so a
        # symbolic input batch stays symbolic through all recorded shapes
        if self.sym_batch and shape:
            return ["N"] + list(shape[1:])
        return shape

    def emit(self, op, inputs, out_shape, **attrs):
        nm = self.name(op)
        out = f"{nm}_out"
        self.nodes.append(_wire.node(op, inputs, [out], name=nm, **attrs))
        self.value_infos.append(_wire.value_info(out, _wire.FLOAT,
                                                 self.sym_shape(out_shape)))
        return out, out_shape


def _conv_out(size, k, p, s, d):
    return (size + 2 * p - d * (k - 1) - 1) // s + 1


def _emit_layer(layer, x, shape, ctx):
    """Append nodes for one leaf layer; returns (tensor name, shape)."""
    from paddle_tpu import nn
    from paddle_tpu.nn.layer.norm import _BatchNormBase

    cls = type(layer).__name__

    if isinstance(layer, nn.Sequential):
        for sub in layer:
            x, shape = _emit_layer(sub, x, shape, ctx)
        return x, shape

    if isinstance(layer, nn.Linear):
        if len(shape) != 2:
            raise ValueError(
                f"Linear expects a 2D tensor at export (got rank "
                f"{len(shape)}); insert nn.Flatten() before it")
        w = ctx.add_init(ctx.name("w"), np.asarray(layer.weight, np.float32))
        ins = [x, w]
        if layer.bias is not None:
            ins.append(ctx.add_init(ctx.name("b"),
                                    np.asarray(layer.bias, np.float32)))
        # weight layout (in, out) → Gemm transB=0
        return ctx.emit("Gemm", ins, [shape[0], layer.out_features],
                        alpha=1.0, beta=1.0, transA=0, transB=0)

    if isinstance(layer, nn.Conv2D):
        if layer.transpose or layer.data_format != "NCHW":
            raise ValueError("only plain NCHW Conv2D exports to ONNX")
        strides = _pair(layer.stride)
        dil = _pair(layer.dilation)
        pads = _pads4(layer.padding)
        kh, kw = layer.kernel_size
        n, _, H, W = shape
        oshape = [n, layer.out_channels,
                  _conv_out(H, kh, pads[0], strides[0], dil[0]),
                  _conv_out(W, kw, pads[1], strides[1], dil[1])]
        w = ctx.add_init(ctx.name("w"), np.asarray(layer.weight, np.float32))
        ins = [x, w]
        if layer.bias is not None:
            ins.append(ctx.add_init(ctx.name("b"),
                                    np.asarray(layer.bias, np.float32)))
        return ctx.emit("Conv", ins, oshape, kernel_shape=[kh, kw],
                        strides=strides, pads=pads, dilations=dil,
                        group=layer.groups)

    if isinstance(layer, _BatchNormBase):
        if layer.data_format != "NCHW":
            raise ValueError(
                "only channel-first batch norm exports (ONNX "
                "BatchNormalization normalizes axis 1); got data_format="
                f"{layer.data_format!r}")
        scale = np.ones(layer.num_features, np.float32) \
            if layer.weight is None else np.asarray(layer.weight, np.float32)
        bias = np.zeros(layer.num_features, np.float32) \
            if layer.bias is None else np.asarray(layer.bias, np.float32)
        ins = [x,
               ctx.add_init(ctx.name("scale"), scale),
               ctx.add_init(ctx.name("bn_bias"), bias),
               ctx.add_init(ctx.name("mean"),
                            np.asarray(layer._mean, np.float32)),
               ctx.add_init(ctx.name("var"),
                            np.asarray(layer._variance, np.float32))]
        return ctx.emit("BatchNormalization", ins, shape,
                        epsilon=float(layer.epsilon))

    if isinstance(layer, (nn.MaxPool2D, nn.AvgPool2D)):
        k = _pair(layer.kernel_size)
        s = _pair(layer.stride if layer.stride is not None
                  else layer.kernel_size)
        pads = _pads4(layer.padding)
        if layer.kwargs.get("ceil_mode"):
            raise ValueError("ceil_mode pooling is not exported")
        if layer.kwargs.get("divisor_override") is not None:
            raise ValueError("divisor_override has no ONNX AveragePool "
                             "analog and is not exported")
        n, c, H, W = shape
        oshape = [n, c, _conv_out(H, k[0], pads[0], s[0], 1),
                  _conv_out(W, k[1], pads[1], s[1], 1)]
        if isinstance(layer, nn.MaxPool2D):
            if layer.kwargs.get("return_mask"):
                raise ValueError("MaxPool2D(return_mask=True) returns "
                                 "(values, indices); only the single-output "
                                 "form exports")
            return ctx.emit("MaxPool", [x], oshape, kernel_shape=k,
                            strides=s, pads=pads)
        # F.avg_pool2d exclusive=True default == count_include_pad=0
        include = 0 if layer.kwargs.get("exclusive", True) else 1
        return ctx.emit("AveragePool", [x], oshape, kernel_shape=k,
                        strides=s, pads=pads, count_include_pad=include)

    if cls == "AdaptiveAvgPool2D":
        osz = _pair(layer.output_size)
        if osz != [1, 1]:
            raise ValueError("only AdaptiveAvgPool2D(output_size=1) exports "
                             "(→ GlobalAveragePool)")
        return ctx.emit("GlobalAveragePool", [x], [shape[0], shape[1], 1, 1])

    if isinstance(layer, nn.Flatten):
        if layer.start_axis != 1 or layer.stop_axis not in (-1,
                                                            len(shape) - 1):
            raise ValueError("only Flatten(start_axis=1, stop_axis=-1) "
                             "exports (ONNX Flatten emits 2D)")
        flat = 1
        for d in shape[1:]:
            flat *= d
        return ctx.emit("Flatten", [x], [shape[0], flat], axis=1)

    if isinstance(layer, (nn.Dropout, nn.Identity)):
        return ctx.emit("Identity", [x], shape)

    if cls == "ReLU":
        return ctx.emit("Relu", [x], shape)
    if cls == "LeakyReLU":
        alpha = layer._args[0] if layer._args else \
            layer._kwargs.get("negative_slope", 0.01)
        return ctx.emit("LeakyRelu", [x], shape, alpha=float(alpha))
    if cls == "Sigmoid":
        return ctx.emit("Sigmoid", [x], shape)
    if cls == "Tanh":
        return ctx.emit("Tanh", [x], shape)
    if cls == "Softmax":
        axis = layer._args[0] if layer._args else \
            layer._kwargs.get("axis", -1)
        return ctx.emit("Softmax", [x], shape, axis=int(axis))
    if cls == "GELU":
        approx = layer._args[0] if layer._args else \
            layer._kwargs.get("approximate", False)
        if approx:
            raise ValueError("only exact GELU exports (erf decomposition)")
        # 0.5 * x * (1 + erf(x / sqrt(2))) — core-opset decomposition
        sqrt2 = ctx.add_init(ctx.name("c"),
                             np.float32(math.sqrt(2.0)).reshape(()))
        half = ctx.add_init(ctx.name("c"), np.float32(0.5).reshape(()))
        one = ctx.add_init(ctx.name("c"), np.float32(1.0).reshape(()))
        t, _ = ctx.emit("Div", [x, sqrt2], shape)
        t, _ = ctx.emit("Erf", [t], shape)
        t, _ = ctx.emit("Add", [t, one], shape)
        t, _ = ctx.emit("Mul", [x, t], shape)
        return ctx.emit("Mul", [t, half], shape)

    raise NotImplementedError(
        f"layer {cls} has no ONNX export rule. Exportable subset: "
        f"{_SUPPORTED}. For full-model deployment use paddle_tpu.jit.save "
        f"(StableHLO).")


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Export a feed-forward layer to an ONNX file; returns the path.

    ``input_spec``: a single InputSpec, a shape tuple (batch dim may be
    None or -1 → symbolic "N"), or an example array. Signature matches the
    reference (python/paddle/onnx/export.py); extra ``**configs`` are
    accepted for parity and ignored.
    """
    from paddle_tpu.static import InputSpec
    from paddle_tpu.version import __version__

    if opset_version < 13:
        raise ValueError(
            f"opset_version={opset_version} unsupported: the emitted graph "
            "uses opset-13 semantics (negative Softmax axis, N-D Softmax); "
            "pass opset_version >= 13")
    if input_spec is None:
        raise ValueError("input_spec is required (shape tuple, InputSpec, "
                         "or example array)")
    # reference call sites pass a LIST of specs; ours is single-input, so
    # unwrap a one-element list of spec/shape/array and refuse multi-input
    if isinstance(input_spec, (list, tuple)) and input_spec and (
            isinstance(input_spec[0], (InputSpec, list, tuple))
            or hasattr(input_spec[0], "shape")):
        if len(input_spec) > 1:
            raise ValueError(
                f"got {len(input_spec)} input specs; only single-input "
                "models export to ONNX here (multi-input graphs: use "
                "paddle_tpu.jit.save)")
        input_spec = input_spec[0]
    if isinstance(input_spec, InputSpec):
        in_shape = list(input_spec.shape)
    elif hasattr(input_spec, "shape"):
        in_shape = list(input_spec.shape)
    else:
        in_shape = list(input_spec)
    if any(d is None or d == -1 for d in in_shape[1:]):
        raise ValueError(
            "only the batch dim may be dynamic: spatial/feature sizes "
            "drive conv/pool/Flatten shape propagation, so they must be "
            f"concrete (got {tuple(in_shape)})")
    sym_batch = in_shape[0] is None or in_shape[0] == -1
    sym_shape = (["N"] if sym_batch else [int(in_shape[0])]) \
        + [int(d) for d in in_shape[1:]]
    # shape propagation needs concrete sizes; the batch dim is never
    # load-bearing in the exportable subset, so a symbolic batch
    # propagates as 1 and is re-symbolized in every recorded value_info
    calc_shape = [1 if isinstance(d, str) else d for d in sym_shape]

    ctx = _Ctx(sym_batch=sym_batch)
    out_name, out_shape = _emit_layer(layer, "input", calc_shape, ctx)
    if not ctx.nodes:
        raise ValueError(
            "layer produced no ONNX nodes (empty container?) — a graph "
            "whose output is its input is rejected by ONNX checkers")
    out_shape = ctx.sym_shape(out_shape)

    g = _wire.graph(
        type(layer).__name__,
        ctx.nodes,
        [_wire.value_info("input", _wire.FLOAT, sym_shape)],
        [_wire.value_info(out_name, _wire.FLOAT, out_shape)],
        ctx.initializers,
        ctx.value_infos[:-1])
    buf = _wire.model(g, opset_version, "paddle_tpu", __version__)

    if not str(path).endswith(".onnx"):
        path = str(path) + ".onnx"
    with open(path, "wb") as f:
        f.write(buf)
    return path


def load_model(path):
    """Parse an ONNX file written by :func:`export` into a plain dict
    (nodes / initializers / graph io) — inspection + test surface."""
    with open(path, "rb") as f:
        return _wire.parse_model(f.read())
