"""Minimal ONNX protobuf wire-format writer/reader.

The environment has no ``onnx`` package, and the reference delegates ONNX
export to external tooling (python/paddle/onnx/export.py → paddle2onnx).
Rather than keeping a raise-only stub, this module emits spec-conformant
ONNX ModelProto bytes directly: protobuf's wire format is just
``(field_number << 3 | wire_type)`` tags followed by varints / fixed32 /
length-delimited payloads, so a self-contained encoder for the handful of
ONNX messages we need is small and dependency-free.

Field numbers follow onnx/onnx.proto (the stable public schema):

  ModelProto:    1 ir_version, 2 producer_name, 3 producer_version,
                 4 domain, 5 model_version, 7 graph, 8 opset_import
  OperatorSetId: 1 domain, 2 version
  GraphProto:    1 node, 2 name, 5 initializer, 11 input, 12 output,
                 13 value_info
  NodeProto:     1 input, 2 output, 3 name, 4 op_type, 5 attribute
  AttributeProto:1 name, 2 f, 3 i, 4 s, 7 floats, 8 ints, 20 type
                 (type enum: FLOAT=1 INT=2 STRING=3 FLOATS=6 INTS=7)
  TensorProto:   1 dims, 2 data_type, 8 name, 9 raw_data
                 (data_type: FLOAT=1 INT32=6 INT64=7)
  ValueInfoProto:1 name, 2 type
  TypeProto:     1 tensor_type {1 elem_type, 2 shape}
  TensorShape:   1 dim {1 dim_value, 2 dim_param}

The reader below parses exactly what the writer emits (used by
``paddle_tpu.onnx.load_model`` and the export-parity tests); it is a
generic tag/value walker, so models written by other exporters with the
same subset of fields also load.
"""

import struct

FLOAT, INT32, INT64 = 1, 6, 7
_ATTR_FLOAT, _ATTR_INT, _ATTR_STRING = 1, 2, 3
_ATTR_FLOATS, _ATTR_INTS = 6, 7


# ---------------------------------------------------------------- encoding

def _varint(v: int) -> bytes:
    if v < 0:  # protobuf int64: negative values use 10-byte two's complement
        v += 1 << 64
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _f_varint(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v)


def _f_bytes(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _f_str(field: int, s: str) -> bytes:
    return _f_bytes(field, s.encode("utf-8"))


def _f_fixed32(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def attribute(name: str, value) -> bytes:
    """AttributeProto bytes; type inferred from the python value."""
    body = _f_str(1, name)
    if isinstance(value, bool):
        raise TypeError("use int for ONNX int attributes")
    if isinstance(value, float):
        body += _f_fixed32(2, value) + _f_varint(20, _ATTR_FLOAT)
    elif isinstance(value, int):
        body += _f_varint(3, value) + _f_varint(20, _ATTR_INT)
    elif isinstance(value, str):
        body += _f_bytes(4, value.encode("utf-8")) + _f_varint(20,
                                                               _ATTR_STRING)
    elif isinstance(value, (list, tuple)) and value and isinstance(
            value[0], float):
        packed = b"".join(struct.pack("<f", v) for v in value)
        body += _f_bytes(7, packed) + _f_varint(20, _ATTR_FLOATS)
    elif isinstance(value, (list, tuple)):
        packed = b"".join(_varint(int(v)) for v in value)
        body += _f_bytes(8, packed) + _f_varint(20, _ATTR_INTS)
    else:
        raise TypeError(f"unsupported attribute value {value!r}")
    return body


def node(op_type: str, inputs, outputs, name: str = "", **attrs) -> bytes:
    body = b"".join(_f_str(1, i) for i in inputs)
    body += b"".join(_f_str(2, o) for o in outputs)
    if name:
        body += _f_str(3, name)
    body += _f_str(4, op_type)
    for k in sorted(attrs):
        body += _f_bytes(5, attribute(k, attrs[k]))
    return body


def tensor(name: str, array) -> bytes:
    """TensorProto from a numpy array (float32/int32/int64 raw_data)."""
    import numpy as np
    a = np.ascontiguousarray(array)
    kind = {"float32": FLOAT, "int32": INT32, "int64": INT64}.get(str(a.dtype))
    if kind is None:
        a = a.astype(np.float32)
        kind = FLOAT
    body = b"".join(_f_varint(1, d) for d in a.shape)
    body += _f_varint(2, kind)
    body += _f_str(8, name)
    body += _f_bytes(9, a.tobytes())
    return body


def value_info(name: str, elem_type: int, shape) -> bytes:
    """shape entries: int (fixed) or str (symbolic dim_param)."""
    dims = b""
    for d in shape:
        dims += _f_bytes(1, _f_str(2, d) if isinstance(d, str)
                         else _f_varint(1, int(d)))
    tensor_type = _f_varint(1, elem_type) + _f_bytes(2, dims)
    return _f_str(1, name) + _f_bytes(2, _f_bytes(1, tensor_type))


def graph(name: str, nodes, inputs, outputs, initializers,
          value_infos=()) -> bytes:
    body = b"".join(_f_bytes(1, n) for n in nodes)
    body += _f_str(2, name)
    body += b"".join(_f_bytes(5, t) for t in initializers)
    body += b"".join(_f_bytes(11, vi) for vi in inputs)
    body += b"".join(_f_bytes(12, vi) for vi in outputs)
    body += b"".join(_f_bytes(13, vi) for vi in value_infos)
    return body


def model(graph_bytes: bytes, opset_version: int, producer: str,
          producer_version: str) -> bytes:
    opset = _f_str(1, "") + _f_varint(2, opset_version)
    return (_f_varint(1, 8)                 # ir_version 8 (ONNX 1.13 line)
            + _f_str(2, producer)
            + _f_str(3, producer_version)
            + _f_bytes(7, graph_bytes)
            + _f_bytes(8, opset))


# ---------------------------------------------------------------- decoding

def _read_varint(buf, pos):
    shift = v = 0
    while True:
        b = buf[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            if v >= 1 << 63:  # negative int64
                v -= 1 << 64
            return v, pos
        shift += 7


def _walk(buf):
    """Yield (field_number, wire_type, value) over a message's fields."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            v = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        elif wire == 1:
            v = struct.unpack("<d", buf[pos:pos + 8])[0]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, v


def _parse_attr(buf):
    import numpy as np
    name = atype = None
    raw = {}
    for f, _, v in _walk(buf):
        if f == 1:
            name = v.decode()
        elif f == 20:
            atype = v
        else:
            raw[f] = v
    if atype == _ATTR_FLOAT:
        val = raw[2]
    elif atype == _ATTR_INT:
        val = raw[3]
    elif atype == _ATTR_STRING:
        val = raw[4].decode()
    elif atype == _ATTR_FLOATS:
        val = list(np.frombuffer(raw[7], "<f4"))
    elif atype == _ATTR_INTS:
        ints, pos = [], 0
        while pos < len(raw[8]):
            x, pos = _read_varint(raw[8], pos)
            ints.append(x)
        val = ints
    else:
        raise ValueError(f"unsupported attribute type {atype}")
    return name, val


def _parse_node(buf):
    n = {"input": [], "output": [], "op_type": "", "name": "", "attrs": {}}
    for f, _, v in _walk(buf):
        if f == 1:
            n["input"].append(v.decode())
        elif f == 2:
            n["output"].append(v.decode())
        elif f == 3:
            n["name"] = v.decode()
        elif f == 4:
            n["op_type"] = v.decode()
        elif f == 5:
            k, val = _parse_attr(v)
            n["attrs"][k] = val
    return n


def _parse_tensor(buf):
    import numpy as np
    dims, dtype, name, raw = [], FLOAT, "", b""
    for f, _, v in _walk(buf):
        if f == 1:
            dims.append(v)
        elif f == 2:
            dtype = v
        elif f == 8:
            name = v.decode()
        elif f == 9:
            raw = v
    np_dtype = {FLOAT: "<f4", INT32: "<i4", INT64: "<i8"}[dtype]
    return name, np.frombuffer(raw, np_dtype).reshape(dims)


def _parse_value_info(buf):
    name, elem, shape = "", None, []
    for f, _, v in _walk(buf):
        if f == 1:
            name = v.decode()
        elif f == 2:
            for f2, _, tt in _walk(v):
                if f2 != 1:
                    continue
                for f3, _, v3 in _walk(tt):
                    if f3 == 1:
                        elem = v3
                    elif f3 == 2:
                        for f4, _, dim in _walk(v3):
                            if f4 != 1:
                                continue
                            for f5, _, dv in _walk(dim):
                                shape.append(dv.decode() if f5 == 2 else dv)
    return {"name": name, "elem_type": elem, "shape": shape}


def parse_model(buf: bytes) -> dict:
    """Decode ModelProto bytes → plain dict (nodes/initializers/io/meta)."""
    out = {"ir_version": None, "producer_name": "", "producer_version": "",
           "opset": None, "graph": None}
    for f, _, v in _walk(buf):
        if f == 1:
            out["ir_version"] = v
        elif f == 2:
            out["producer_name"] = v.decode()
        elif f == 3:
            out["producer_version"] = v.decode()
        elif f == 8:
            for f2, _, v2 in _walk(v):
                if f2 == 2:
                    out["opset"] = v2
        elif f == 7:
            g = {"name": "", "nodes": [], "initializers": {}, "inputs": [],
                 "outputs": [], "value_info": []}
            for f2, _, v2 in _walk(v):
                if f2 == 1:
                    g["nodes"].append(_parse_node(v2))
                elif f2 == 2:
                    g["name"] = v2.decode()
                elif f2 == 5:
                    name, arr = _parse_tensor(v2)
                    g["initializers"][name] = arr
                elif f2 == 11:
                    g["inputs"].append(_parse_value_info(v2))
                elif f2 == 12:
                    g["outputs"].append(_parse_value_info(v2))
                elif f2 == 13:
                    g["value_info"].append(_parse_value_info(v2))
            out["graph"] = g
    return out
