"""Typed runtime flag system.

Reference analog: gflags + `PADDLE_DEFINE_EXPORTED_*`
(paddle/fluid/platform/flags.cc, 74 definitions) exposed to Python via
`paddle.set_flags/get_flags` (paddle/fluid/pybind/global_value_getter_setter.cc:212).

Here flags are plain typed Python registrations, overridable via environment
variables ``PT_FLAGS_<NAME>`` at import time and ``set_flags`` at runtime.
"""

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

_lock = threading.Lock()


@dataclass
class _Flag:
    name: str
    default: Any
    type: type
    help: str
    validator: Optional[Callable[[Any], bool]] = None
    value: Any = None


_REGISTRY: Dict[str, _Flag] = {}


def _coerce(ftype: type, raw: Any) -> Any:
    if ftype is bool and isinstance(raw, str):
        return raw.lower() in ("1", "true", "yes", "on")
    return ftype(raw)


def define_flag(name: str, default: Any, help: str = "",
                ftype: Optional[type] = None,
                validator: Optional[Callable[[Any], bool]] = None) -> None:
    """Register a flag. Environment ``PT_FLAGS_<NAME>`` overrides the default."""
    ftype = ftype or type(default)
    env = os.environ.get("PT_FLAGS_" + name.upper())
    value = _coerce(ftype, env) if env is not None else default
    with _lock:
        _REGISTRY[name] = _Flag(name, default, ftype, help, validator, value)


def get_flags(names=None) -> Dict[str, Any]:
    """Read flag values. ``names`` may be a str, list of str, or None (=all)."""
    if names is None:
        names = list(_REGISTRY)
    if isinstance(names, str):
        names = [names]
    out = {}
    for n in names:
        if n not in _REGISTRY:
            raise KeyError(f"unknown flag {n!r}")
        out[n] = _REGISTRY[n].value
    return out


def get_flag(name: str) -> Any:
    return get_flags(name)[name]


def set_flags(flags: Dict[str, Any]) -> None:
    with _lock:
        for name, val in flags.items():
            if name not in _REGISTRY:
                raise KeyError(f"unknown flag {name!r}")
            f = _REGISTRY[name]
            val = _coerce(f.type, val)
            if f.validator is not None and not f.validator(val):
                raise ValueError(f"invalid value {val!r} for flag {name!r}")
            f.value = val


def describe_flags() -> str:
    lines = []
    for f in sorted(_REGISTRY.values(), key=lambda f: f.name):
        lines.append(f"{f.name} (={f.value!r}, default {f.default!r}): {f.help}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Core framework flags (analogs of paddle/fluid/platform/flags.cc entries).
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False,
            "Sweep op outputs for NaN/Inf during training "
            "(ref: FLAGS_check_nan_inf, framework/details/nan_inf_utils_detail.cc).")
define_flag("benchmark", False, "Synchronize and time each step.")
define_flag("matmul_precision", "default",
            "Precision for matmul/conv on TPU: default|high|highest "
            "(maps to jax.lax.Precision).")
define_flag("default_dtype", "float32", "Default floating dtype for creation ops.")
define_flag("conv_workspace_limit_mb", 512,
            "Kept for API parity; XLA manages conv scratch itself.")
define_flag("use_pallas_kernels", True,
            "Use Pallas TPU kernels for fused ops (flash attention etc.) "
            "when running on TPU; falls back to XLA-fused reference impls.")
define_flag("decode_kernel_min_t", 1024,
            "Cache length at/above which the decode engine's one-token "
            "step routes attention through the flash-decode kernel "
            "(reads only valid prefix blocks) instead of the dense "
            "einsum over the whole cache. Short caches stay on the "
            "einsum — the kernel's per-program overhead beats the "
            "bandwidth saving there.")
define_flag("scan_layers", True,
            "Run homogeneous transformer stacks as lax.scan over stacked "
            "block weights (one compiled layer body instead of L unrolled "
            "copies — L-fold faster XLA compiles). Off restores the "
            "unrolled Python loop.")
define_flag("log_level", "warning", "Framework log level.")
define_flag("stats_at_exit", False,
            "Dump the StatRegistry table to stderr at process exit "
            "(operator scrape path for launch/elastic CLI processes).")
define_flag("allocator_strategy", "xla",
            "Kept for API parity (ref auto_growth/naive_best_fit); on TPU the "
            "XLA/PJRT runtime owns HBM allocation.")
