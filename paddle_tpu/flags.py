"""Typed runtime flag system.

Reference analog: gflags + `PADDLE_DEFINE_EXPORTED_*`
(paddle/fluid/platform/flags.cc, 74 definitions) exposed to Python via
`paddle.set_flags/get_flags` (paddle/fluid/pybind/global_value_getter_setter.cc:212).

Here flags are plain typed Python registrations, overridable via environment
variables ``PT_FLAGS_<NAME>`` at import time and ``set_flags`` at runtime.
"""

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

_lock = threading.Lock()


# ---------------------------------------------------------------------------
# PT_* environment-variable contract registry.
#
# Every ``os.environ`` / ``os.getenv`` read of a ``PT_*`` name anywhere in
# the package must have a ``declare_env`` entry here (enforced by ptlint
# rule PT005 — paddle_tpu/analysis/rules_env.py). The registry is the one
# source of truth the docs table in docs/observability.md is generated
# from (``env_contract_markdown``), so a knob like PT_SERVE_INFLIGHT can
# never silently fork from its documentation.
# ---------------------------------------------------------------------------

@dataclass
class EnvVar:
    name: str
    help: str
    default: Optional[str] = None
    owner: str = ""          # module that consumes it (doc pointer)


_ENV_REGISTRY: Dict[str, EnvVar] = {}
_ENV_PREFIXES: Dict[str, EnvVar] = {}
_TOOL_PREFIXES: Dict[str, EnvVar] = {}


def declare_tool_prefix(prefix: str, help: str, owner: str = "") -> None:
    """Bring a TOOL env namespace (e.g. ``PD_`` for profile_decode
    report knobs) under the contract. Unlike ``declare_env_prefix``
    this does NOT declare every name in the namespace — it widens the
    checked set: once ``PD_`` is registered, ptlint PT005 flags any
    ``PD_*`` read (in paddle_tpu/ *and* tools/) that lacks its own
    ``declare_env`` entry, exactly like a ``PT_*`` read would be."""
    if not prefix.endswith("_"):
        raise ValueError(f"tool prefix must end with '_', got {prefix!r}")
    _TOOL_PREFIXES[prefix] = EnvVar(prefix + "*", help, None, owner)


def _in_contract_namespace(name: str) -> bool:
    return name.startswith("PT_") or any(
        name.startswith(p) for p in _TOOL_PREFIXES)


def declare_env(name: str, help: str, default: Optional[str] = None,
                owner: str = "") -> None:
    """Register one environment variable in the contract: a ``PT_*``
    name, or a tool name under a ``declare_tool_prefix`` namespace
    (register the prefix first)."""
    if not _in_contract_namespace(name):
        raise ValueError(
            f"env contract covers PT_* and registered tool-prefix "
            f"names ({sorted(_TOOL_PREFIXES)}), got {name!r}")
    _ENV_REGISTRY[name] = EnvVar(name, help, default, owner)


def declare_env_prefix(prefix: str, help: str, owner: str = "") -> None:
    """Register a PT_* name FAMILY (e.g. ``PT_FLAGS_<flag>``)."""
    if not prefix.startswith("PT_"):
        raise ValueError(f"env contract covers PT_* names, got {prefix!r}")
    _ENV_PREFIXES[prefix] = EnvVar(prefix + "*", help, None, owner)


def env_registry() -> Dict[str, EnvVar]:
    out = dict(_ENV_REGISTRY)
    out.update({k + "*": v for k, v in _ENV_PREFIXES.items()})
    return out


def tool_prefix_registry() -> Dict[str, EnvVar]:
    """The registered tool env namespaces (``declare_tool_prefix``)."""
    return dict(_TOOL_PREFIXES)


def env_declared(name: str) -> bool:
    """True iff ``name`` is covered by the contract (exact or prefix)."""
    if name in _ENV_REGISTRY:
        return True
    return any(name.startswith(p) for p in _ENV_PREFIXES)


def env_contract_markdown() -> str:
    """The docs/observability.md env-contract table, generated from the
    registry (regenerate with
    ``python -c "import paddle_tpu.flags as f; print(f.env_contract_markdown())"``)."""
    rows = sorted(env_registry().values(), key=lambda v: v.name)
    lines = ["| variable | default | consumed by | meaning |",
             "|---|---|---|---|"]
    for v in rows:
        default = "—" if v.default is None else f"`{v.default}`"
        owner = f"`{v.owner}`" if v.owner else "—"
        lines.append(f"| `{v.name}` | {default} | {owner} | {v.help} |")
    return "\n".join(lines)


@dataclass
class _Flag:
    name: str
    default: Any
    type: type
    help: str
    validator: Optional[Callable[[Any], bool]] = None
    value: Any = None


_REGISTRY: Dict[str, _Flag] = {}


def _coerce(ftype: type, raw: Any) -> Any:
    if ftype is bool and isinstance(raw, str):
        return raw.lower() in ("1", "true", "yes", "on")
    return ftype(raw)


def define_flag(name: str, default: Any, help: str = "",
                ftype: Optional[type] = None,
                validator: Optional[Callable[[Any], bool]] = None) -> None:
    """Register a flag. Environment ``PT_FLAGS_<NAME>`` overrides the default."""
    ftype = ftype or type(default)
    env = os.environ.get("PT_FLAGS_" + name.upper())
    value = _coerce(ftype, env) if env is not None else default
    with _lock:
        _REGISTRY[name] = _Flag(name, default, ftype, help, validator, value)


def get_flags(names=None) -> Dict[str, Any]:
    """Read flag values. ``names`` may be a str, list of str, or None (=all)."""
    if names is None:
        names = list(_REGISTRY)
    if isinstance(names, str):
        names = [names]
    out = {}
    for n in names:
        if n not in _REGISTRY:
            raise KeyError(f"unknown flag {n!r}")
        out[n] = _REGISTRY[n].value
    return out


def get_flag(name: str) -> Any:
    return get_flags(name)[name]


def set_flags(flags: Dict[str, Any]) -> None:
    with _lock:
        for name, val in flags.items():
            if name not in _REGISTRY:
                raise KeyError(f"unknown flag {name!r}")
            f = _REGISTRY[name]
            val = _coerce(f.type, val)
            if f.validator is not None and not f.validator(val):
                raise ValueError(f"invalid value {val!r} for flag {name!r}")
            f.value = val


def describe_flags() -> str:
    lines = []
    for f in sorted(_REGISTRY.values(), key=lambda f: f.name):
        lines.append(f"{f.name} (={f.value!r}, default {f.default!r}): {f.help}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Core framework flags (analogs of paddle/fluid/platform/flags.cc entries).
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False,
            "Sweep op outputs for NaN/Inf during training "
            "(ref: FLAGS_check_nan_inf, framework/details/nan_inf_utils_detail.cc).")
define_flag("benchmark", False, "Synchronize and time each step.")
define_flag("matmul_precision", "default",
            "Precision for matmul/conv on TPU: default|high|highest "
            "(maps to jax.lax.Precision).")
define_flag("default_dtype", "float32", "Default floating dtype for creation ops.")
define_flag("conv_workspace_limit_mb", 512,
            "Kept for API parity; XLA manages conv scratch itself.")
define_flag("use_pallas_kernels", True,
            "Use Pallas TPU kernels for fused ops (flash attention etc.) "
            "when running on TPU; falls back to XLA-fused reference impls.")
define_flag("decode_kernel_min_t", 1024,
            "Cache length at/above which the decode engine's one-token "
            "step routes attention through the flash-decode kernel "
            "(reads only valid prefix blocks) instead of the dense "
            "einsum over the whole cache. Short caches stay on the "
            "einsum — the kernel's per-program overhead beats the "
            "bandwidth saving there.")
define_flag("scan_layers", True,
            "Run homogeneous transformer stacks as lax.scan over stacked "
            "block weights (one compiled layer body instead of L unrolled "
            "copies — L-fold faster XLA compiles). Off restores the "
            "unrolled Python loop.")
define_flag("log_level", "warning", "Framework log level.")
define_flag("stats_at_exit", False,
            "Dump the StatRegistry table to stderr at process exit "
            "(operator scrape path for launch/elastic CLI processes).")
define_flag("allocator_strategy", "xla",
            "Kept for API parity (ref auto_growth/naive_best_fit); on TPU the "
            "XLA/PJRT runtime owns HBM allocation.")


# ---------------------------------------------------------------------------
# The PT_* env contract (ptlint PT005 checks every read against this;
# the table in docs/observability.md is generated from it).
# ---------------------------------------------------------------------------

# -- multi-process topology (launch CLI → workers) --
declare_env("PT_COORDINATOR", "jax.distributed coordinator 'host:port'.",
            owner="distributed/env.py")
declare_env("PT_NUM_PROCESSES", "Total worker processes across nodes.",
            default="1", owner="distributed/env.py")
declare_env("PT_PROCESS_ID", "Global rank of this worker.", default="0",
            owner="distributed/env.py")
declare_env("PT_LOCAL_RANK", "Rank within this node.", default="0",
            owner="distributed/launch.py")
declare_env("PT_NNODES", "Node count.", default="1",
            owner="distributed/launch.py")
declare_env("PT_RANK", "RPC agent rank (rpc.init_rpc fallback).",
            default="0", owner="distributed/rpc.py")
declare_env("PT_WORLD_SIZE", "RPC / fleet world size fallback.",
            default="1", owner="distributed/rpc.py")
declare_env("PT_TRAINER_ENDPOINTS", "Comma-separated worker endpoints "
            "(fleet API parity).", owner="distributed/fleet")
declare_env("PT_MASTER", "Elastic store endpoint 'host:port'.",
            owner="distributed/elastic.py")
declare_env("PT_ELASTIC_VERSION", "Elastic job generation counter "
            "(set by the elastic manager on re-launch).",
            owner="distributed/elastic.py")
declare_env("PT_INIT_DEADLINE", "Seconds init_parallel_env may spend in "
            "rendezvous before CollectiveWatchdog raises.", default="120",
            owner="distributed/env.py")
declare_env("PT_RESTART_ATTEMPT", "Which auto-restart attempt this worker "
            "is (launch --max_restarts exports it; 0 = first run).",
            default="0", owner="distributed/launch.py")
declare_env("PT_ELASTIC_RESHAPE", "1 turns the --max_restarts relaunch "
            "into a local RESHAPE: the group relaunches at the "
            "surviving worker count and workers see the NEW world size "
            "/ membership via PT_NUM_PROCESSES / PT_PROCESS_ID "
            "(fleet/elastic_train re-plans its mesh and "
            "restore_resharded-resumes onto it). 0 keeps same-size "
            "restarts.", default="0", owner="distributed/launch.py")

# -- elastic fleet controller --
declare_env("PT_FLEET_MIN_REPLICAS", "Per-tier serving replica floor: "
            "the fleet controller heals back up to this many alive "
            "replicas (bypassing policy and cooldown) when deaths or "
            "drains drop a tier below it.", default="1",
            owner="fleet/controller.py")
declare_env("PT_FLEET_MAX_REPLICAS", "Per-tier serving replica "
            "ceiling: scale-up clamps here no matter how hard the SLO "
            "burns.", default="8", owner="fleet/controller.py")
declare_env("PT_FLEET_COOLDOWN_S", "Seconds after any policy-driven "
            "scale action before the controller takes the next one "
            "(actuation latency must not read as an unanswered "
            "signal). Healing below the floor ignores it.",
            default="5", owner="fleet/controller.py")
declare_env("PT_FLEET_DRAIN_GRACE_S", "How long a draining replica "
            "may take to finish its in-flight requests before the "
            "controller SIGKILLs it and the router's death sweep "
            "redistributes the remainder.", default="10",
            owner="fleet/controller.py")
declare_env("PT_RESHARD_INPLACE", "1 (default) lets elastic reshape "
            "events move live train state between (mesh, layout) "
            "pairs in HBM via distributed/redistribute.py — "
            "O(collective) instead of a checkpoint round trip. 0 "
            "forces the save + load_resharded fallback path (also "
            "taken automatically, loudly, when planning or transfer "
            "fails).", default="1", owner="fleet/elastic_train.py")
declare_env("PT_RESHARD_VERIFY", "1 (default) digests every leaf "
            "before and after an in-HBM redistribute — a mismatch "
            "(in-transit corruption) raises RedistributeError and the "
            "reshape degrades to the checkpoint fallback, counted "
            "under fleet/reshard_fallbacks, instead of training on "
            "corrupted state. 0 trades the host round trip for speed "
            "on trusted fabrics.", default="1",
            owner="distributed/redistribute.py")
declare_env("PT_DRAIN_MIGRATE", "1 (default) makes a draining serve "
            "replica MIGRATE its in-flight decode requests to "
            "survivors mid-decode (KV rows + token history over the "
            "fp32 wire, byte-identical streams) instead of finishing "
            "them in place; per-request failures fall back to "
            "finish-in-place. 0 restores drain-by-completion.",
            default="1", owner="serving/router.py")

# -- observability --
declare_env("PT_TRACE_DIR", "Enable tracing; rank traces land here as "
            "trace_rank{N}.json and the launcher merges them.",
            owner="observability/trace.py")
declare_env("PT_TRACE_FILE", "Exact trace output path (wins over "
            "PT_TRACE_DIR).", owner="observability/trace.py")
declare_env("PT_TRACE_RING", "Trace ring-buffer capacity in events.",
            default="65536", owner="observability/trace.py")
declare_env("PT_STATSZ_PORT", "Serve live /statsz snapshots on this port "
            "(launcher hands rank r port base+1+r).",
            owner="observability/statsz.py")
declare_env("PT_TRACE_FLUSH_S", "Seconds between periodic atomic "
            "rewrites of the (partial) trace file when tracing is "
            "env-enabled — a SIGKILLed replica still leaves its last "
            "flush on disk for stitching. 0 disables (atexit export "
            "only).", default="5", owner="observability/trace.py")
declare_env("PT_FLIGHT_RING", "Per-request flight recorder bound: how "
            "many requests' event timelines stay resident (FIFO "
            "eviction; each request keeps at most 64 events). 0 "
            "disables recording entirely.", default="256",
            owner="observability/flight.py")
declare_env("PT_FLIGHT_DIR", "Directory terminal-failure flight "
            "records dump into as flight_<rid>.json (falls back to "
            "PT_TRACE_DIR; with neither set the record is one "
            "structured stderr line).", owner="observability/flight.py")
declare_env("PT_NUMERICS_EVERY", "Training-numerics capture cadence: "
            "compute the in-graph tensor-stat pack every N optimizer "
            "steps (one packed device vector per sampled step). 0 "
            "(default) builds the step without the stats subgraph "
            "entirely.", default="0",
            owner="observability/numerics.py")
declare_env("PT_NUMERICS_RING", "Numerics flight-recorder bound: how "
            "many decoded snapshots stay resident for the "
            "detector-triggered dump.", default="64",
            owner="observability/numerics.py")
declare_env("PT_NUMERICS_DIR", "Directory numerics alert dumps land "
            "in as numerics_<step>.<pid>.json (falls back to "
            "PT_FLIGHT_DIR then PT_TRACE_DIR; with none set the dump "
            "is one structured stderr line).",
            owner="observability/numerics.py")
declare_env("PT_NUMERICS_WINDOW", "Numerics watch history window "
            "(samples) for the median/MAD spike detectors.",
            default="32", owner="observability/numerics.py")
declare_env("PT_NUMERICS_Z", "Numerics watch robust z-score "
            "threshold: loss/grad-norm spikes fire when the value "
            "exceeds median + z*(1.4826*MAD) over the window.",
            default="6.0", owner="observability/numerics.py")
declare_env("PT_NUMERICS_OVERFLOW", "Numerics watch overflow "
            "threshold: alert when any family's dtype-overflow "
            "fraction (|x| above 90% of finfo.max) exceeds this.",
            default="0.01", owner="observability/numerics.py")
declare_env("PT_NUMERICS_EF", "Numerics watch error-feedback runaway "
            "threshold: alert when any bucket's EF-to-grad magnitude "
            "ratio exceeds this.", default="8.0",
            owner="observability/numerics.py")
declare_env("PT_SLO_TTFT_P99_MS", "Fleet SLO target: merged p99 TTFT "
            "in milliseconds. The fleet watch publishes the "
            "fleet/slo_ttft_burn gauge (p99/target) and fires "
            "fleet/alert_slo_ttft on the burn>1 edge. Unset disables.",
            owner="observability/fleet.py")
declare_env("PT_SLO_GOODPUT", "Fleet SLO target: goodput floor in "
            "tokens/s summed over replicas (token-progress rate from "
            "the heartbeat load gauges). fleet/alert_slo_goodput "
            "fires while a busy fleet runs below it. Unset disables.",
            owner="observability/fleet.py")
declare_env("PT_SLO_QUEUE_AGE_S", "Runaway-queue detector threshold: "
            "a replica whose oldest waiting request exceeds this age "
            "raises fleet/alert_queue_age.", default="30",
            owner="observability/fleet.py")
declare_env("PT_PROF_PEAK_FLOPS", "Device-profiler roofline override: "
            "peak FLOP/s the prof/roofline_frac denominator uses "
            "instead of the detected per-generation table entry.",
            owner="observability/devprof.py")
declare_env("PT_PROF_PEAK_HBM_GBPS", "Device-profiler roofline "
            "override: peak HBM bandwidth in GB/s (detected table "
            "entry otherwise).", owner="observability/devprof.py")
declare_env("PT_PROF_LAUNCH_ITERS", "No-op launches timed by the "
            "once-per-process launch-tax calibration (the median is "
            "the per-dispatch overhead estimate).", default="64",
            owner="observability/devprof.py")

# -- serving --
declare_env("PT_SERVE_INFLIGHT", "Decode-engine pipeline depth: how many "
            "dispatches may be in flight before the oldest is harvested "
            "(1 = synchronous).", default="2",
            owner="inference/decode_engine.py")
declare_env("PT_SERVE_PREFILL_TOKENS", "Per-step prompt-token budget for "
            "interleaved chunked prefill (0 = largest bucket).",
            default="0", owner="inference/decode_engine.py")
declare_env("PT_SERVE_QUEUE_DEPTH", "Serving front-end admission-queue "
            "bound: submissions beyond this many waiting requests are "
            "rejected (serve/queue_rejects) instead of queued.",
            default="256", owner="serving/scheduler.py")
declare_env("PT_SERVE_ADMISSION", "Front-end admission-queue ordering "
            "policy: fifo (arrival), priority (higher priority= first), "
            "edf (earliest absolute deadline first).",
            default="priority", owner="serving/scheduler.py")
declare_env("PT_SERVE_ROUTER_PORT", "TCPStore port for the multi-"
            "replica router's control plane (membership, mailboxes, "
            "results).", default="8997", owner="serving/router.py")
declare_env("PT_SERVE_LOADGEN_SEED", "Deterministic load-generator "
            "seed — one knob pinning the exact SLO-bench/CI workload.",
            default="0", owner="serving/loadgen.py")
declare_env("PT_KV_WIRE", "KV-page transfer wire format for "
            "disaggregated prefill/decode serving and the fleet prefix "
            "directory: int8 (default, block-scaled ~3.9x compression), "
            "fp8, or fp32 (bit-identity opt-out — disaggregated decode "
            "exactly matches same-replica serving).", default="int8",
            owner="serving/kv_transfer.py")
declare_env("PT_SERVE_ROLE", "This serving replica's role in a "
            "disaggregated fleet: both (symmetric, default), prefill "
            "(big-bucket prefill only, KV handed off over the wire), "
            "decode (installs handoffs, deep decode occupancy).",
            default="both", owner="serving/disagg.py")
declare_env("PT_STORE_RETRY_S", "Per-op retry budget (seconds) for the "
            "guarded control-plane store client (GuardedStore): a store "
            "op failing for longer than this raises StorePartitioned "
            "and the replica degrades to partition mode — buffered "
            "results, missed heartbeats, decode keeps stepping.",
            default="2.0", owner="distributed/resilience.py")
declare_env("PT_KV_TRANSPORT", "Data plane for KV handoff/migration "
            "blobs in disaggregated serving: socket (default, direct "
            "replica-to-replica P2P — the TCPStore carries only "
            "membership/directory/results) or store (PR 16 TCPStore "
            "chunked-blob path, the fallback when the native P2P "
            "endpoint is unavailable).", default="socket",
            owner="serving/kv_transfer.py")
declare_env("PT_SERVE_HOST", "Host address replicas advertise for "
            "their socket KV-transport endpoint (kv_ep locators).",
            default="127.0.0.1", owner="serving/kv_transfer.py")
declare_env("PT_ROUTER_ENDPOINT_FILE", "Path of the router endpoint "
            "file ({host, port, gen, pid} JSON, atomically replaced): "
            "each router generation writes gen+1 here and replica "
            "RouterLinks watch it to dial the successor store after a "
            "router death. Unset disables cross-generation failover "
            "(single-generation PR 10 behavior).",
            owner="serving/router.py")
declare_env("PT_ROUTER_STANDBY", "1 makes the RouterSupervisor keep a "
            "warm standby router process (imports paid, waiting on a "
            "promotion token file) so failover costs store-bind + "
            "journal-replay only; 0 (default) cold-spawns the "
            "successor on death.", default="0",
            owner="fleet/controller.py")
declare_env("PT_FLEET_PREFIX", "0 disables the fleet-wide prefix-cache "
            "directory (publication, lookup, and the router's "
            "pre-placement consult) — replicas fall back to local "
            "radix caches only.", default="1", owner="serving/disagg.py")
declare_env("PT_PAGED_FUSED", "0 disables the fused append+attend paged "
            "decode kernel, restoring the read-only-pool + one-scatter-"
            "per-token formulation (the parity reference).", default="1",
            owner="inference/paged_engine.py")
declare_env("PT_PAGED_PREFIX", "0 disables prefix (radix) caching over "
            "the page pool — every prompt prefills cold and retirement "
            "frees pages instead of keeping them warm.", default="1",
            owner="inference/paged_engine.py")
declare_env("PT_PAGED_TUNE", "1 runs paged-kernel autotuning "
            "(pages_per_program, head_block) from the engine "
            "constructor, before any trace picks up the config.",
            default="0", owner="inference/paged_engine.py")
declare_env("PT_PAGED_MEGA", "0 disables the single-dispatch decode "
            "megakernel (layer-folded layers + fused sampling "
            "epilogue, 2 launches/step), falling back to the per-layer "
            "fused path (one paged launch per layer — the bit-parity "
            "reference).", default="1",
            owner="inference/paged_engine.py")
declare_env("PT_SERVE_ENGINE", "Default serving engine for the "
            "front-end/bench ladder: 'paged' (default) or 'contiguous' "
            "(the slot-contiguous DecodeEngine kept behind this flag).",
            default="paged", owner="inference/factory.py")

# -- cross-chip communication --
declare_env("PT_COMM_QUANT", "Wire format for the quantized gradient/"
            "weight collectives: none/bf16/int8/fp8/auto. auto asks "
            "planner._axis_tier per axis — DCN-crossing axes quantize "
            "to int8, ICI axes stay full precision.", default="auto",
            owner="distributed/compression.py")
declare_env("PT_COMM_BLOCK", "Block size for block-scaled quantization "
            "on the collective wire (one fp32 scale per block).",
            default="256", owner="distributed/compression.py")
declare_env("PT_COMM_QUANT_PSUM", "1 selects the legacy psum wire for "
            "compressed dp sync (int8 payloads upcast to int32 on the "
            "wire — the tested parity reference, NOT a volume win).",
            default="0", owner="distributed/compression.py")
declare_env("PT_COMM_BUCKET_MB", "Gradient-sync bucket budget in MB for "
            "the overlap scheduler: backward partitions grad leaves "
            "into ~this-many-MB buckets in reverse-layer order, one "
            "quantized reduce-scatter per bucket launched as the layer's "
            "grads appear.", default="4", owner="distributed/overlap.py")
declare_env("PT_COMM_OVERLAP", "0 disables overlap scheduling in the "
            "bucketed train step: collectives hoist to a tail sync "
            "after the full backward (same math, bit-identical params "
            "— the A/B baseline the train_overlap bench measures "
            "against).", default="1", owner="distributed/overlap.py")
declare_env("PT_COMM_STRIPE", "Link striping for large bucket payloads: "
            "0 off; auto/1 splits per planner.stripe_plan into a "
            "full-precision ICI stripe plus a quantized DCN stripe "
            "launched concurrently; a float in (0,1) forces that DCN "
            "fraction.", default="0", owner="distributed/overlap.py")

# -- bench / probe drivers (bench.py + tools/probe_bench.py) --
declare_env("PT_DEVICE_TIMEOUT_S", "bench.py device-acquisition "
            "watchdog: a wedged tunnel emits a bench_failed JSON line "
            "after this long instead of hanging the driver.",
            default="900", owner="bench.py")
declare_env("PT_BENCH_BUDGET_S", "bench.py wall budget: sub-benches "
            "past it are skipped with <name>_skipped rows (headline "
            "metric secured first).", default="7200", owner="bench.py")
declare_env("PT_BENCH_ONLY", "Comma-set of sub-benches to re-capture "
            "(e.g. bert,decode) without paying the flagship compile.",
            owner="bench.py")
declare_env("PT_DECODE_SECTIONS", "Comma-set of bench_decode sections "
            "(generate,int8,engine,engine_longctx,engine_paged,"
            "engine_paged_prefix,engine_int8,spec,spec_paged).",
            owner="bench.py")
declare_env("PT_PROBE_TIMEOUT_S", "Opportunistic-capture prober: "
            "per-probe subprocess kill timeout.", default="150",
            owner="tools/probe_bench.py")
declare_env("PT_PROBE_INTERVAL_S", "Prober poll interval while the "
            "device tunnel is down.", default="1200",
            owner="tools/probe_bench.py")
declare_env("PT_REBENCH_INTERVAL_S", "Prober re-bench cadence while "
            "the tunnel stays up (full rows refresh this often).",
            default="4800", owner="tools/probe_bench.py")

# -- compilation / data / testing --
declare_env("PT_COMPILE_CACHE_GUARD", "0 disables the persistent-compile-"
            "cache failure guard (compile_cache.guard).", default="1",
            owner="compile_cache.py")
declare_env("PT_XLA_CACHE_DIR", "Persistent XLA compilation cache "
            "directory (compile_cache.enable).", owner="compile_cache.py")
declare_env("PT_AUTOTUNE_CACHE", "Kernel autotuner cache file path.",
            owner="ops/autotune.py")
declare_env("PT_VMEM_BUDGET_MB", "Static per-core VMEM budget (MiB) "
            "the ptgeom PT006 rule and autotune's geometry guard "
            "check pallas launches against; a fixed 0.5 MiB compiler "
            "reserve is subtracted.", default="16",
            owner="analysis/kernelmodel.py")
declare_env("PT_DATA_DIR", "Root directory for bundled datasets.",
            owner="vision/datasets.py")
declare_env("PT_FAULTS", "Fault-injection plan: ';'-separated "
            "site:action[:k=v,...] rules (testing/faults.py).",
            owner="testing/faults.py")
declare_env_prefix("PT_FLAGS_", "Per-flag override of any define_flag "
                   "entry, e.g. PT_FLAGS_SCAN_LAYERS=0.", owner="flags.py")

# ---------------------------------------------------------------------------
# Tool env namespaces (ISSUE 15 satellite): report/smoke knobs the
# tools/ scripts read. declare_tool_prefix brings the NAMESPACE under
# the PT005 contract (tools/ is linted like paddle_tpu/ is); each knob
# still needs its own declare_env row below.
# ---------------------------------------------------------------------------
declare_tool_prefix("PD_", "profile_decode.py report knobs.",
                    owner="tools/profile_decode.py")
declare_tool_prefix("FLEETOBS_", "fleet-observability smoke/test "
                    "worker handshake.", owner="tests/_fleetobs.py")
declare_tool_prefix("PTGEOM_", "ptgeom.py kernel-geometry sweep "
                    "knobs.", owner="tools/ptgeom.py")

declare_env("PD_SIZE", "profile_decode model size: 1p3b (default), "
            "350m, or tiny (the CPU smoke).", default="1p3b",
            owner="tools/profile_decode.py")
declare_env("PD_SECTIONS", "Comma-set of profile_decode report "
            "sections: engine, paged, prof, mega (launches/step "
            "accounting for the single-dispatch megakernel).",
            default="engine,paged", owner="tools/profile_decode.py")
declare_env("PD_INFLIGHT", "Comma-list of pipeline depths to sweep "
            "(e.g. 1,2,4); unset uses the engine default.",
            owner="tools/profile_decode.py")
declare_env("PD_SPEC", "1 adds the chunked speculative run on "
            "repetitive prompts to the engine section.", default="0",
            owner="tools/profile_decode.py")
declare_env("PD_PREFIX", "1 adds the repeated-system-prompt cold/warm "
            "radix-cache sweep (the ci.sh paged gate).", default="0",
            owner="tools/profile_decode.py")
declare_env("PD_LENGTHS", "Comma-list of prompt lengths the prof "
            "section sweeps per decode path (default by model size; "
            ">=3 lengths make the launch-tax-vs-length curve).",
            owner="tools/profile_decode.py")
declare_env("PTGEOM_GEOMS", "Comma-set of ladder geometries the "
            "ptgeom sweep drives (tiny,350m,r06); unset sweeps all "
            "(tools/ptgeom.py --geoms overrides).",
            owner="tools/ptgeom.py")
declare_env("FLEETOBS_TRACE_FILE", "Per-replica trace path handed to "
            "launch-spawned fleet workers; translated to PT_TRACE_FILE "
            "at worker startup so the launcher's own atexit export "
            "cannot clobber replica traces.", owner="tests/_fleetobs.py")
