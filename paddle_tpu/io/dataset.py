"""Datasets (ref: python/paddle/fluid/dataloader/dataset.py)."""

import bisect

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not indexable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = [np.asarray(t) for t in tensors]
        assert all(t.shape[0] == self.tensors[0].shape[0]
                   for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            sample = ds[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        di = bisect.bisect_right(self.cum, idx)
        prev = 0 if di == 0 else self.cum[di - 1]
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    n = len(dataset)
    assert sum(lengths) == n
    rng = np.random.RandomState(generator)
    perm = rng.permutation(n)
    out = []
    offset = 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l].tolist()))
        offset += l
    return out
