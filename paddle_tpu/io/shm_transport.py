"""Worker→loader batch transport over the native shared-memory ring.

Reference analog: _use_shared_memory in
fluid/dataloader/dataloader_iter.py:114,611 — batches cross the process
boundary through shared memory instead of being pickled through a
multiprocessing.Queue pipe. Arrays ride as pickle-5 out-of-band buffers, so
encode is one memcpy into the ring slot and decode is zero-copy views over
the popped bytes.
"""

import pickle
import struct

__all__ = ["encode_msg", "decode_msg"]

_HDR = struct.Struct("<qI")  # batch_id, n_buffers


def encode_msg(batch_id: int, payload, error: str = None) -> bytes:
    buffers = []
    body = pickle.dumps((payload, error), protocol=5,
                        buffer_callback=buffers.append)
    parts = [_HDR.pack(batch_id, len(buffers)),
             struct.pack("<Q", len(body)), body]
    for b in buffers:
        raw = b.raw()
        parts.append(struct.pack("<Q", raw.nbytes))
        parts.append(raw)
    return b"".join(parts)


def decode_msg(data: bytes):
    mv = memoryview(data)
    batch_id, n_buffers = _HDR.unpack_from(mv, 0)
    off = _HDR.size
    (body_len,) = struct.unpack_from("<Q", mv, off)
    off += 8
    body = mv[off:off + body_len]
    off += body_len
    buffers = []
    for _ in range(n_buffers):
        (blen,) = struct.unpack_from("<Q", mv, off)
        off += 8
        # bytearray copy: arrays rebuilt over immutable bytes would be
        # read-only, diverging from the (writable) mp.Queue path
        buffers.append(bytearray(mv[off:off + blen]))
        off += blen
    payload, error = pickle.loads(body, buffers=buffers)
    return batch_id, payload, error
