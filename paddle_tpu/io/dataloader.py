"""DataLoader (ref: python/paddle/fluid/dataloader/dataloader_iter.py —
single-process iter :164, multi-process :381 with worker subprocesses
(worker.py:266) and shared-memory tensors).

TPU-native shape: workers produce numpy batches; the loader keeps a prefetch
depth ahead and (optionally) transfers to device asynchronously so host input
processing overlaps device compute — the role the reference's
blocking-queue reader ops play. A C++ shared-memory transport
(paddle_tpu/runtime_native) replaces pickle for large batches when built.
"""

import atexit
import itertools
import multiprocessing as mp
import os
import queue
import threading
from typing import Any, Callable, Optional

import numpy as np

from paddle_tpu.io.dataset import IterableDataset
from paddle_tpu.io.sampler import BatchSampler

__all__ = ["DataLoader", "get_worker_info", "default_collate_fn"]

_worker_info = threading.local()
_RING_SEQ = 0


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    """Stack a list of samples into batched numpy arrays (ref:
    fluid/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    return np.stack([np.asarray(s) for s in batch])


def _worker_loop(dataset, index_queue, result_queue, collate_fn, wid,
                 num_workers, seed, ring_name=None):
    """ref: fluid/dataloader/worker.py:266 _worker_loop. ``seed`` already
    incorporates the epoch so re-forked workers draw fresh augmentation
    randomness each epoch (ref derives a per-epoch base seed the same way).
    With ``ring_name``, results go through the native shared-memory ring
    (≙ _use_shared_memory) instead of the mp.Queue pipe."""
    _worker_info.info = WorkerInfo(wid, num_workers, dataset, seed)
    np.random.seed((seed + wid) % (2**32))
    _parent_pid = os.getppid()  # the consumer process that forked us
    ring = None
    if ring_name is not None:
        from paddle_tpu import native
        from paddle_tpu.io.shm_transport import encode_msg
        ring = native.ShmRingBuffer(ring_name, create=False)

    def emit(batch_id, data, err):
        if ring is None:
            result_queue.put((batch_id, data, err))
            return
        msg = encode_msg(batch_id, data, err)
        if len(msg) > ring.slot_size:
            msg = encode_msg(
                batch_id, None,
                f"batch of {len(msg)} bytes exceeds shm slot "
                f"({ring.slot_size}); raise DataLoader(shm_slot_bytes=...) "
                f"or pass use_shared_memory=False")
        # retry while the consumer stalls (first-step jit compilation can
        # exceed any single timeout); BrokenPipeError = consumer closed the
        # ring. If the consumer was SIGKILLed its atexit/finally never ran
        # and we are reparented — unlink the shm segment and die rather than
        # spin forever leaking /dev/shm until reboot (ADVICE r1).
        while True:
            try:
                ring.push(msg, timeout=60.0)
                return
            except TimeoutError:
                if os.getppid() != _parent_pid:  # consumer died (SIGKILL)
                    ring.close(unlink=True)
                    os._exit(1)
                continue
            except BrokenPipeError:
                return

    while True:
        # bounded get + reparent check: an idle worker whose consumer was
        # SIGKILLed must notice (fork-inherited queue write-ends keep the
        # blocking get alive forever) and release the shm ring.
        try:
            item = index_queue.get(timeout=5.0)
        except queue.Empty:
            if os.getppid() != _parent_pid:
                if ring is not None:
                    ring.close(unlink=True)
                os._exit(1)
            continue
        if item is None:
            break
        batch_id, indices = item
        try:
            samples = [dataset[i] for i in indices]
            emit(batch_id, collate_fn(samples), None)
        except Exception as e:  # propagate worker errors to the main proc
            emit(batch_id, None, repr(e))


class DataLoader:
    """ref: paddle.io.DataLoader."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, seed=0, shm_slot_bytes=16 << 20):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.use_shared_memory = use_shared_memory
        self.shm_slot_bytes = shm_slot_bytes
        self.prefetch_factor = max(prefetch_factor, 2)
        self.seed = seed
        self._epoch = 0
        self.worker_init_fn = worker_init_fn
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_size = batch_size
            self.drop_last = drop_last
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _iter_iterable(self):
        it = iter(self.dataset)
        while True:
            batch = list(itertools.islice(it, self.batch_size))
            if not batch:
                return
            if len(batch) < self.batch_size and self.drop_last:
                return
            yield self.collate_fn(batch)

    def _iter_single(self):
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def _iter_multiprocess(self):
        """ref: _DataLoaderIterMultiProcess (dataloader_iter.py:381).
        Results cross back via the native shared-memory ring when available
        (≙ _use_shared_memory), else the mp.Queue pipe."""
        ctx = mp.get_context("fork")
        index_queues = [ctx.Queue() for _ in range(self.num_workers)]
        result_queue = ctx.Queue()
        depth = self.num_workers * self.prefetch_factor

        ring = None
        ring_name = None
        if self.use_shared_memory:
            from paddle_tpu import native
            if native.is_available():
                global _RING_SEQ
                _RING_SEQ += 1
                # unique per iterator instance — two live iterators of one
                # loader must not collide (create unlinks the old name)
                ring_name = f"/ptdl_{os.getpid()}_{_RING_SEQ}"
                ring = native.ShmRingBuffer(
                    ring_name, nslots=max(4, min(depth, 8)),
                    slot_size=self.shm_slot_bytes)

        workers = []
        for wid in range(self.num_workers):
            w = ctx.Process(
                target=_worker_loop,
                args=(self.dataset, index_queues[wid], result_queue,
                      self.collate_fn, wid, self.num_workers,
                      self.seed + self._epoch * 7919, ring_name),
                daemon=True)
            w.start()
            workers.append(w)

        def recv():
            if ring is None:
                return result_queue.get()
            from paddle_tpu.io.shm_transport import decode_msg
            return decode_msg(ring.pop(timeout=300.0))

        def shutdown():
            for q in index_queues:
                try:
                    q.put(None)
                except Exception:
                    pass
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()
            if ring is not None:
                ring.close()

        atexit.register(shutdown)
        try:
            batches = list(self.batch_sampler)
            n = len(batches)
            next_send = 0
            reorder = {}
            next_yield = 0
            while next_send < min(depth, n):
                index_queues[next_send % self.num_workers].put(
                    (next_send, batches[next_send]))
                next_send += 1
            while next_yield < n:
                bid, data, err = recv()
                if err is not None:
                    raise RuntimeError(f"DataLoader worker failed: {err}")
                reorder[bid] = data
                if next_send < n:
                    index_queues[next_send % self.num_workers].put(
                        (next_send, batches[next_send]))
                    next_send += 1
                while next_yield in reorder:
                    yield reorder.pop(next_yield)
                    next_yield += 1
        finally:
            atexit.unregister(shutdown)
            shutdown()

    def __iter__(self):
        self._epoch += 1
        if self._iterable:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_single()
        return self._iter_multiprocess()
