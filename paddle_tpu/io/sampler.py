"""Samplers (ref: python/paddle/fluid/dataloader/{sampler,batch_sampler}.py).
DistributedBatchSampler mirrors the reference's rank-sharded sampler used by
data parallelism."""

import math

import numpy as np

__all__ = ["Sampler", "SequenceSampler", "RandomSampler",
           "WeightedRandomSampler", "BatchSampler",
           "DistributedBatchSampler"]


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)
        self.generator = generator
        self._epoch_seed = 0

    def __iter__(self):
        rng = np.random.RandomState(self.generator or self._epoch_seed)
        self._epoch_seed += 1
        n = len(self.data_source)
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(p), self.num_samples, self.replacement, p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__(dataset)
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else \
                SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """ref: python/paddle/fluid/dataloader/dist_batch_sampler — shards the
    index space over data-parallel ranks, padding so every rank sees the
    same number of batches (required: SPMD ranks must agree on step count)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from paddle_tpu.distributed import env as dist_env
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else \
            dist_env.get_world_size()
        self.local_rank = rank if rank is not None else dist_env.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[:(self.total_size - len(indices))]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size
