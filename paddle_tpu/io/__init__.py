from paddle_tpu.io.dataset import (Dataset, IterableDataset, TensorDataset,
                                   ComposeDataset, ChainDataset, Subset,
                                   random_split)
from paddle_tpu.io.sampler import (Sampler, SequenceSampler, RandomSampler,
                                   WeightedRandomSampler, BatchSampler,
                                   DistributedBatchSampler)
from paddle_tpu.io.dataloader import DataLoader, get_worker_info

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "Subset", "random_split", "Sampler",
           "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
           "BatchSampler", "DistributedBatchSampler", "DataLoader",
           "get_worker_info"]
