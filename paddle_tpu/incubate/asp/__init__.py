"""2:4 structured sparsity (ref: python/paddle/incubate/asp/ — ASP pruning
masks). Functional mask computation; TPU kernels consume the dense masked
weights (XLA has no sparse MMA like Ampere; the mask still gives model-size
and regularization parity)."""

import numpy as np
import jax.numpy as jnp

__all__ = ["calculate_density", "create_mask", "check_mask_2d",
           "prune_model", "decorate", "set_excluded_layers",
           "reset_excluded_layers"]


def calculate_density(x):
    x = np.asarray(x)
    return float((x != 0).sum() / x.size)


def create_mask(tensor, func_name="mask_2d_best", n=2, m=4):
    """2:4 mask along the last axis groups of m."""
    t = np.asarray(tensor)
    shape = t.shape
    flat = t.reshape(-1, m)
    idx = np.argsort(-np.abs(flat), axis=1)[:, :n]
    mask = np.zeros_like(flat, dtype=bool)
    np.put_along_axis(mask, idx, True, axis=1)
    return jnp.asarray(mask.reshape(shape))


def check_mask_2d(mask, n=2, m=4):
    mk = np.asarray(mask).reshape(-1, m)
    return bool((mk.sum(1) <= n).all())


def prune_model(model, n=2, m=4, mask_algo="mask_2d_best", with_mask=True):
    """Apply 2:4 masks to all 2-D+ params of a Module."""
    new_state = {}
    for name, p in model.state_dict().items():
        if _prunable(name, p):
            mask = create_mask(p, mask_algo, n, m)
            new_state[name] = jnp.asarray(p) * mask
    return model.merge_params(new_state)


_EXCLUDED = set()


def set_excluded_layers(param_names, main_program=None):
    """ref: sparsity set_excluded_layers — params whose masks ASP must not
    touch (embeddings, heads)."""
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def _prunable(name, p):
    # exact name or dotted-path prefix — substring matching would make
    # excluding 'fc1' also exclude 'fc10.weight'
    excluded = any(name == ex or name.startswith(ex + ".")
                   for ex in _EXCLUDED)
    return hasattr(p, "ndim") and p.ndim >= 2 and not excluded


class OptimizerWithSparsityGuarantee:
    """ref: asp ASPHelper.decorate → OptimizerWithSparsityGuarantee —
    re-applies the 2:4 masks after every optimizer update so training
    cannot regrow pruned weights. Masks are captured from the params of
    the FIRST update (run prune_model first) and stay fixed."""

    def __init__(self, optimizer, n=2, m=4):
        self._inner = optimizer
        self._n, self._m = n, m
        self._masks = None

    def __getattr__(self, name):  # delegate the full optimizer surface
        return getattr(self._inner, name)

    def _ensure_masks(self, params):
        if self._masks is None:
            self._masks = {
                name: create_mask(p, n=self._n, m=self._m)
                if _prunable(name, p) else None
                for name, p in params.items()}

    def _apply_masks(self, params):
        return {name: (p * self._masks[name]
                       if self._masks.get(name) is not None else p)
                for name, p in params.items()}

    def init(self, params):
        return self._inner.init(params)

    def update(self, grads, state, params):
        self._ensure_masks(params)
        new_params, new_state = self._inner.update(grads, state, params)
        return self._apply_masks(new_params), new_state

    def step(self, grads):
        self._inner._ensure_bound()
        self._ensure_masks(self._inner._params)
        new_p = self._inner.step(grads)
        masked = self._apply_masks(new_p)
        self._inner._params = masked
        return masked


def decorate(optimizer, n=2, m=4):
    """ref: asp decorate — wrap an optimizer with the sparsity
    guarantee."""
    return OptimizerWithSparsityGuarantee(optimizer, n=n, m=m)
