"""2:4 structured sparsity (ref: python/paddle/incubate/asp/ — ASP pruning
masks). Functional mask computation; TPU kernels consume the dense masked
weights (XLA has no sparse MMA like Ampere; the mask still gives model-size
and regularization parity)."""

import numpy as np
import jax.numpy as jnp

__all__ = ["calculate_density", "create_mask", "check_mask_2d",
           "prune_model"]


def calculate_density(x):
    x = np.asarray(x)
    return float((x != 0).sum() / x.size)


def create_mask(tensor, func_name="mask_2d_best", n=2, m=4):
    """2:4 mask along the last axis groups of m."""
    t = np.asarray(tensor)
    shape = t.shape
    flat = t.reshape(-1, m)
    idx = np.argsort(-np.abs(flat), axis=1)[:, :n]
    mask = np.zeros_like(flat, dtype=bool)
    np.put_along_axis(mask, idx, True, axis=1)
    return jnp.asarray(mask.reshape(shape))


def check_mask_2d(mask, n=2, m=4):
    mk = np.asarray(mask).reshape(-1, m)
    return bool((mk.sum(1) <= n).all())


def prune_model(model, n=2, m=4, mask_algo="mask_2d_best", with_mask=True):
    """Apply 2:4 masks to all 2-D+ params of a Module."""
    new_state = {}
    for name, p in model.state_dict().items():
        if hasattr(p, "ndim") and p.ndim >= 2:
            mask = create_mask(p, mask_algo, n, m)
            new_state[name] = jnp.asarray(p) * mask
    return model.merge_params(new_state)
