"""Mixture-of-Experts with expert parallelism over the 'ep' mesh axis.

Reference analog: python/paddle/incubate/distributed/models/moe/
moe_layer.py:259 (MoELayer), gates gshard_gate.py / switch_gate.py, and the
C++ all-to-all dispatch ops operators/collective/global_scatter_op.cc /
global_gather_op.cc (SURVEY §2.2 "EP").

TPU-native design (GShard/mesh-tensorflow style): token routing is expressed
as dense dispatch/combine einsums against a (tokens, experts, capacity)
one-hot tensor; the expert dimension of the stacked FFN weights and of the
dispatched activations is sharded over 'ep', so XLA lowers the dispatch
einsum to exactly the all-to-all that global_scatter implements by hand —
no ownership bookkeeping, and the backward all-to-all comes from jax.grad.
Capacity overflow drops tokens (their combine weight is zero → residual
passthrough in the caller), matching the reference's capacity semantics.
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.nn.module import Module, Parameter

__all__ = ["MoELayer", "top_k_gating", "EXPERT_PARTITION_RULES"]

# regex → spec; the single source of truth for expert-weight sharding
# (models.gpt composes these into its PARTITION_RULES)
from paddle_tpu.distributed.mesh import LAYOUT as _LAYOUT

EXPERT_PARTITION_RULES = (
    (r"moe_w1$", _LAYOUT.expert_column()),
    (r"moe_b1$", _LAYOUT.expert_column_bias()),
    (r"moe_w2$", _LAYOUT.expert_row()),
    (r"moe_b2$", _LAYOUT.expert_row_bias()),
    (r"gate_w$", P(None, None)),
)


def _one_hot(x, n, dtype=jnp.float32):
    return jax.nn.one_hot(x, n, dtype=dtype)


def top_k_gating(logits, k: int, capacity: int, rng_key=None,
                 jitter_eps: float = 0.0):
    """GShard top-k gating with capacity. logits: (N, E).

    Returns (combine (N,E,C), dispatch bool (N,E,C), aux_loss scalar).
    aux = E * Σ_e mean_n(probs_e) * mean_n(top1_mask_e)  (GShard eq. (4),
    the same form the reference's gshard_gate computes).
    """
    n, e = logits.shape
    if jitter_eps > 0.0 and rng_key is not None:
        logits = logits + jitter_eps * jax.random.uniform(
            rng_key, logits.shape, logits.dtype, -1.0, 1.0)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    combine = jnp.zeros((n, e, capacity), jnp.float32)
    masked_probs = probs
    position_base = jnp.zeros((e,), jnp.int32)  # tokens already in expert
    aux = jnp.zeros((), jnp.float32)
    gates_sum = jnp.zeros((n,), jnp.float32)
    chosen = []
    for i in range(k):
        idx = jnp.argmax(masked_probs, axis=-1)                  # (N,)
        mask = _one_hot(idx, e)                                  # (N,E)
        if i == 0:
            # load-balance aux uses the top-1 assignment only
            aux = e * jnp.sum(jnp.mean(probs, axis=0)
                              * jnp.mean(mask, axis=0))
        pos = (jnp.cumsum(mask, axis=0) - 1.0) + position_base   # (N,E)
        keep = (pos < capacity) & (mask > 0)
        position_base = position_base + jnp.sum(
            mask, axis=0).astype(jnp.int32)
        gate_i = jnp.sum(probs * mask, axis=-1)                  # (N,)
        in_cap = jnp.any(keep, axis=-1)
        gates_sum = gates_sum + gate_i * in_cap
        slot = jnp.sum(jnp.where(keep, pos, 0.0),
                       axis=-1).astype(jnp.int32)                # (N,)
        oh_slot = _one_hot(slot, capacity)                       # (N,C)
        contrib = (mask * jnp.any(keep, -1, keepdims=True))[..., None] \
            * oh_slot[:, None, :] * gate_i[:, None, None]
        combine = combine + contrib
        chosen.append((idx, gate_i))
        masked_probs = masked_probs * (1.0 - mask)
    if k > 1:
        # renormalize the selected gates to sum to 1 per token (gshard);
        # top-1 (switch) keeps the raw gate probability as the scale
        combine = combine / jnp.maximum(gates_sum, 1e-9)[:, None, None]
    dispatch = combine > 0.0
    return combine, dispatch, aux


class MoELayer(Module):
    """Expert-parallel FFN MoE (≙ MoELayer moe_layer.py:259).

    gate: "gshard" (top-2) or "switch" (top-1, jittered). The expert axis of
    the stacked weights is sharded over 'ep' when a global mesh is
    installed. forward returns (output, aux_loss); add
    ``aux_weight * aux_loss`` to the train loss (the reference folds it in
    via the gate object)."""

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 gate: str = "gshard", k: Optional[int] = None,
                 capacity_factor: float = 1.25, jitter_eps: float = 0.01,
                 tokens_per_group: int = 1024,
                 dtype=jnp.float32, seed: int = 0):
        super().__init__()
        if gate not in ("gshard", "switch"):
            raise ValueError(f"unknown gate {gate!r}")
        self.num_experts = num_experts
        self.k = k if k is not None else (2 if gate == "gshard" else 1)
        self.gate_type = gate
        self.capacity_factor = capacity_factor
        self.jitter_eps = jitter_eps if gate == "switch" else 0.0
        self.tokens_per_group = tokens_per_group
        key = jax.random.PRNGKey(seed)
        k1, k2, kg = jax.random.split(key, 3)
        E, d, h = num_experts, d_model, d_hidden
        std = 0.02
        self.gate_w = Parameter(
            (std * jax.random.normal(kg, (d, E))).astype(jnp.float32))
        self.moe_w1 = Parameter(
            (std * jax.random.normal(k1, (E, d, h))).astype(dtype))
        self.moe_b1 = Parameter(jnp.zeros((E, 1, h), dtype))
        self.moe_w2 = Parameter(
            (std * jax.random.normal(k2, (E, h, d))).astype(dtype))
        self.moe_b2 = Parameter(jnp.zeros((E, 1, d), dtype))

    def _shard(self, x, spec):
        from paddle_tpu.distributed.mesh import get_mesh
        mesh = get_mesh()
        if mesh is None or mesh.size == 1 or \
                dict(mesh.shape).get("ep", 1) == 1:
            return x
        try:
            return lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
        except Exception:
            return x

    def _shard_groups(self, xg, g):
        """Shard the group axis over the data axes (largest divisible
        prefix of dp×fsdp), keeping shard_map-free GSPMD dispatch."""
        from paddle_tpu.distributed.mesh import get_mesh
        mesh = get_mesh()
        if mesh is None or mesh.size == 1 or \
                dict(mesh.shape).get("ep", 1) == 1:
            return xg
        shape = dict(mesh.shape)
        axes = []
        div = 1
        for ax in ("dp", "fsdp"):
            if shape.get(ax, 1) > 1 and g % (div * shape[ax]) == 0:
                axes.append(ax)
                div *= shape[ax]
        if not axes:
            return xg
        return self._shard(xg, P(tuple(axes), None, None))

    def capacity(self, n_tokens: int) -> int:
        return max(4, int(math.ceil(
            self.k * n_tokens * self.capacity_factor / self.num_experts)))

    def forward(self, x, rng_key=None):
        """Tokens are routed within fixed-size groups (GShard-style: the
        dispatch tensor is (G, T, E, C) with C ∝ T/E, so total routing
        memory stays LINEAR in token count — a single global group would be
        quadratic). The trailing partial group is zero-padded; padded slots
        carry zero combine weight into the output, which is sliced off."""
        orig_shape = x.shape
        d = orig_shape[-1]
        xt = x.reshape(-1, d)
        n = xt.shape[0]
        t = min(self.tokens_per_group, n)
        g = -(-n // t)
        pad = g * t - n
        if pad:
            xt = jnp.pad(xt, ((0, pad), (0, 0)))
        xg = xt.reshape(g, t, d)
        # pin the group axis to the data axes BEFORE the dispatch einsum:
        # without this the partitioner has no registered transition from the
        # upstream batch sharding to the ep-sharded dispatch output and
        # falls back to "involuntary full rematerialization" (full
        # replication) — MULTICHIP_r02 phase D warning
        xg = self._shard_groups(xg, g)
        cap = self.capacity(t)
        logits = xg.astype(jnp.float32) @ self.gate_w        # (G,T,E)
        gate_keys = (jax.random.split(rng_key, g)
                     if (rng_key is not None and self.jitter_eps > 0.0)
                     else None)
        gating = lambda lg, kk: top_k_gating(
            lg, self.k, cap, rng_key=kk, jitter_eps=self.jitter_eps)
        if gate_keys is None:
            combine, dispatch, aux = jax.vmap(
                lambda lg: gating(lg, None))(logits)
        else:
            combine, dispatch, aux = jax.vmap(gating)(logits, gate_keys)
        aux = jnp.mean(aux)
        # dispatch: (G,T,E,C) x (G,T,D) -> (G,E,C,D); sharded over 'ep'
        # this is the global_scatter all-to-all
        expert_in = jnp.einsum("gtec,gtd->gecd",
                               dispatch.astype(xt.dtype), xg)
        expert_in = self._shard(expert_in, P(None, "ep", None, None))
        h = jnp.einsum("gecd,edh->gech", expert_in, self.moe_w1) \
            + self.moe_b1[None]
        h = jax.nn.gelu(h)
        out = jnp.einsum("gech,ehd->gecd", h, self.moe_w2) \
            + self.moe_b2[None]
        out = self._shard(out, P(None, "ep", None, None))
        # combine: back to tokens — the global_gather direction
        y = jnp.einsum("gtec,gecd->gtd", combine.astype(xt.dtype), out)
        y = y.reshape(g * t, d)
        if pad:
            y = y[:n]
        return y.reshape(orig_shape), aux
