"""incubate.optimizer (ref: python/paddle/incubate/optimizer/ —
lookahead.py LookAhead:25, modelaverage.py ModelAverage;
distributed_fused_lamb is CUDA-only fusion, dissolved into the plain
optimizer + GSPMD).

Functional design like the core optimizers: state is an explicit pytree
through ``update``, so both compose with jit/pjit and checkpointing."""

import jax
import jax.numpy as jnp

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """slow/fast two-timescale wrapper (≙ lookahead.py:25):
    every k inner steps, slow += alpha * (fast - slow); fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner = inner_optimizer
        self.alpha = alpha
        self.k = k

    def init(self, params):
        return {"inner": self.inner.init(params),
                "slow": jax.tree_util.tree_map(jnp.asarray, params),
                "step": jnp.zeros((), jnp.int32)}

    def update(self, grads, state, params):
        fast, inner_state = self.inner.update(grads, state["inner"], params)
        step = state["step"] + 1
        sync = (step % self.k) == 0

        def blend(slow, f):
            new_slow = jnp.where(sync, slow + self.alpha * (f - slow), slow)
            new_fast = jnp.where(sync, new_slow, f)
            return new_slow, new_fast

        pairs = jax.tree_util.tree_map(blend, state["slow"], fast)
        is_pair = (lambda x: isinstance(x, tuple) and len(x) == 2
                   and isinstance(x[0], jax.Array))
        new_slow = jax.tree_util.tree_map(lambda p: p[0], pairs,
                                          is_leaf=is_pair)
        new_fast = jax.tree_util.tree_map(lambda p: p[1], pairs,
                                          is_leaf=is_pair)
        return new_fast, {"inner": inner_state, "slow": new_slow,
                          "step": step}

    def __getattr__(self, name):
        return getattr(self.inner, name)


class ModelAverage:
    """Parameter averaging over a sliding window (≙ modelaverage.py):
    accumulate parameter sums each step; ``apply`` swaps in the average
    for evaluation, ``restore`` hands back the live weights.

    Window semantics follow the reference: the effective window is
    ``clip(rate * num_updates, min_average_window, max_average_window)``,
    realized with the reference's block-rotation trick — a current block
    plus the previous block, rotated when the current block fills, so
    ``apply`` always averages over between W and 2W recent steps."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=2, max_average_window=10000):
        self.rate = average_window_rate
        self.min_w = min_average_window
        self.max_w = max_average_window

    def init(self, params):
        z = jax.tree_util.tree_map(jnp.zeros_like, params)
        return {"sum_cur": z,
                "sum_prev": jax.tree_util.tree_map(jnp.zeros_like, params),
                "n_cur": jnp.zeros((), jnp.int32),
                "n_prev": jnp.zeros((), jnp.int32),
                "total": jnp.zeros((), jnp.int32)}

    def _window(self, total):
        w = (self.rate * total.astype(jnp.float32)).astype(jnp.int32)
        return jnp.clip(w, self.min_w, self.max_w)

    def accumulate(self, state, params):
        total = state["total"] + 1
        w = self._window(total)
        rotate = state["n_cur"] >= w

        def cur(s, p):
            return jnp.where(rotate, p, s + p)

        def prev(sp, sc):
            return jnp.where(rotate, sc, sp)

        new_prev = jax.tree_util.tree_map(prev, state["sum_prev"],
                                          state["sum_cur"])
        new_cur = jax.tree_util.tree_map(cur, state["sum_cur"], params)
        return {"sum_cur": new_cur, "sum_prev": new_prev,
                "n_cur": jnp.where(rotate, 1, state["n_cur"] + 1),
                "n_prev": jnp.where(rotate, state["n_cur"],
                                    state["n_prev"]),
                "total": total}

    def apply(self, state, params):
        """Averaged params for eval (live params returned by restore)."""
        n = jnp.maximum(state["n_cur"] + state["n_prev"], 1
                        ).astype(jnp.float32)
        return jax.tree_util.tree_map(
            lambda a, b: (a + b) / n, state["sum_cur"], state["sum_prev"])

    def restore(self, params):
        return params
