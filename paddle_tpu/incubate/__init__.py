"""Incubate (ref: python/paddle/incubate/ — MoE, fused transformer layers,
ASP sparsity, LookAhead/ModelAverage, DistributedFusedLamb).

MoE lives in paddle_tpu.distributed.moe (first-class, not incubating, on
TPU); fused layers in incubate.nn map onto the Pallas kernel inventory."""

from paddle_tpu.incubate import nn
from paddle_tpu.incubate import asp

__all__ = ["nn", "asp"]
