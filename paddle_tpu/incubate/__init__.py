"""Incubate (ref: python/paddle/incubate/ — MoE, fused transformer layers,
ASP sparsity, LookAhead/ModelAverage, DistributedFusedLamb).

MoE: paddle_tpu.incubate.moe (≙ incubate/distributed/models/moe) — GShard
dispatch einsums sharded over the 'ep' mesh axis instead of
global_scatter/global_gather NCCL ops; fused layers in incubate.nn map onto
the Pallas kernel inventory."""

from paddle_tpu.incubate import nn
from paddle_tpu.incubate import asp
from paddle_tpu.incubate import moe
from paddle_tpu.incubate.moe import MoELayer

__all__ = ["nn", "asp", "moe", "MoELayer", "optimizer"]
from paddle_tpu.incubate import optimizer  # noqa: E402
