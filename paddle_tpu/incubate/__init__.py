"""Incubate (ref: python/paddle/incubate/ — MoE, fused transformer layers,
ASP sparsity, LookAhead/ModelAverage, DistributedFusedLamb).

MoE: paddle_tpu.incubate.moe (≙ incubate/distributed/models/moe) — GShard
dispatch einsums sharded over the 'ep' mesh axis instead of
global_scatter/global_gather NCCL ops; fused layers in incubate.nn map onto
the Pallas kernel inventory."""

from paddle_tpu.incubate import nn
from paddle_tpu.incubate import asp
from paddle_tpu.incubate import moe
from paddle_tpu.incubate.moe import MoELayer

__all__ = ["nn", "asp", "moe", "MoELayer", "optimizer"]
from paddle_tpu.incubate import optimizer  # noqa: E402

# reference re-exports (paddle.incubate.__init__ surfaces these at top
# level; the implementations live with their subject areas here)
from paddle_tpu.incubate.optimizer import LookAhead, ModelAverage  # noqa: E402
from paddle_tpu.geometric import (  # noqa: E402
    segment_sum, segment_mean, segment_max, segment_min)
from paddle_tpu.geometric import (  # noqa: E402
    send_u_recv as graph_send_recv, reindex_graph as graph_reindex,
    sample_neighbors as graph_sample_neighbors,
    khop_sampler as graph_khop_sampler)


def softmax_mask_fuse(x, mask):
    """ref: incubate.softmax_mask_fuse (fused_softmax_mask_op.cu) — on
    TPU XLA fuses the additive mask into the softmax; one expression."""
    import jax
    import jax.numpy as jnp
    return jax.nn.softmax(jnp.asarray(x) + jnp.asarray(mask), axis=-1)


def softmax_mask_fuse_upper_triangle(x):
    """ref: incubate.softmax_mask_fuse_upper_triangle — causal-masked
    softmax over the last two dims (the GPT attention mask)."""
    import jax
    import jax.numpy as jnp
    x = jnp.asarray(x)
    s = x.shape[-1]
    causal = jnp.tril(jnp.ones((x.shape[-2], s), bool))
    return jax.nn.softmax(jnp.where(causal, x, -1e9), axis=-1)


def identity_loss(x, reduction="none"):
    """ref: incubate.identity_loss — marks a tensor as the loss with an
    optional reduction (used by custom-loss pipelines)."""
    import jax.numpy as jnp
    x = jnp.asarray(x)
    if reduction in ("none", 2):
        return x
    if reduction in ("mean", 1):
        return jnp.mean(x)
    if reduction in ("sum", 0):
        return jnp.sum(x)
    raise ValueError(f"unknown reduction {reduction!r}")


__all__ += ["LookAhead", "ModelAverage", "segment_sum", "segment_mean",
            "segment_max", "segment_min", "graph_send_recv",
            "graph_reindex", "graph_sample_neighbors",
            "graph_khop_sampler", "softmax_mask_fuse",
            "softmax_mask_fuse_upper_triangle", "identity_loss"]
