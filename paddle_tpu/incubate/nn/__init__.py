"""Fused layers (ref: python/paddle/incubate/nn/layer/fused_transformer.py —
FusedMultiHeadAttention:191, FusedFeedForward:478,
FusedTransformerEncoderLayer:706, FusedMultiTransformer:997; CUDA kernels
operators/fused/). On TPU "fused" means: flash-attention Pallas kernel +
XLA-fused epilogues; these classes provide the reference API shape."""

from paddle_tpu.nn.layer.transformer import (MultiHeadAttention,
                                             TransformerEncoderLayer)
from paddle_tpu.nn.module import Module, LayerList

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedMultiTransformer",
           "FusedBiasDropoutResidualLayerNorm"]


class FusedBiasDropoutResidualLayerNorm(Module):
    """``ln(residual + dropout(x + bias))`` in one fused Pallas pass
    (ref: incubate FusedBiasDropoutResidualLayerNorm, fused_transformer.py:81
    → fused_bias_dropout_residual_layer_norm_op.cu). Unlike the API shells
    above, this one carries its own fused kernel:
    paddle_tpu.ops.pallas.layer_norm.fused_layer_norm."""

    def __init__(self, embed_dim, dropout_rate=0.5, bias_attr=None,
                 epsilon=1e-5):
        super().__init__()
        import jax.numpy as jnp
        from paddle_tpu.nn.module import Parameter
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.weight = Parameter(jnp.ones((embed_dim,)))
        self.norm_bias = Parameter(jnp.zeros((embed_dim,)))
        self.bias = (None if bias_attr is False
                     else Parameter(jnp.zeros((embed_dim,))))

    def forward(self, x, residual, dropout_seed=None):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.nn.functional.common import fold_ctx_key
        from paddle_tpu.ops.pallas.layer_norm import fused_layer_norm
        p = self.dropout_rate if self.training else 0.0
        if p > 0.0 and dropout_seed is None:
            # context-threaded RNG like F.dropout; PRF wants a scalar seed
            dropout_seed = jax.random.bits(fold_ctx_key(), (),
                                           jnp.uint32).astype(jnp.int32)
        y, _ = fused_layer_norm(
            x, self.weight, self.norm_bias, residual=residual,
            bias=self.bias, dropout_p=p, dropout_seed=dropout_seed,
            epsilon=self.epsilon)
        return y


class FusedMultiHeadAttention(MultiHeadAttention):
    """ref: incubate FusedMultiHeadAttention:191 → fused_attention_op.cu.
    Same math; the TPU fusion is the Pallas flash-attention path inside
    scaled_dot_product_attention."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 **kwargs):
        super().__init__(embed_dim, num_heads, dropout=attn_dropout_rate,
                         kdim=kdim, vdim=vdim, need_weights=need_weights)


class FusedFeedForward(Module):
    """ref: incubate FusedFeedForward:478 → fused_feedforward_op.cu."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 activation="relu", act_dropout_rate=None,
                 normalize_before=False, **kwargs):
        super().__init__()
        from paddle_tpu.nn.layer.common import Linear, Dropout
        from paddle_tpu.nn.layer.norm import LayerNorm
        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm = LayerNorm(d_model)
        self.dropout = Dropout(dropout_rate)
        self.act_dropout = Dropout(act_dropout_rate if act_dropout_rate
                                   is not None else dropout_rate)
        self.activation = activation

    def forward(self, src):
        from paddle_tpu.nn import functional as F
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        act = getattr(F, self.activation)
        out = self.linear2(self.act_dropout(act(self.linear1(src))))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(TransformerEncoderLayer):
    """ref: incubate FusedTransformerEncoderLayer:706."""


class FusedMultiTransformer(Module):
    """ref: incubate FusedMultiTransformer:997 → fused_multi_transformer_op.cu
    (the inference hot path). Stacked pre-LN decoder blocks sharing one
    weight layout, compiled as one XLA program — including the CacheKV
    incremental-decode path the CUDA kernel exists for: pass
    ``caches=self.gen_cache(batch)`` and feed one token at a time."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, num_layers=1,
                 **kwargs):
        super().__init__()
        self.blocks = LayerList([
            TransformerEncoderLayer(embed_dim, num_heads, dim_feedforward,
                                    dropout_rate, activation,
                                    normalize_before=normalize_before)
            for _ in range(num_layers)])

    def gen_cache(self, x):
        """Per-layer EMPTY KV caches for a (B, *, D) prototype
        (≙ FusedMultiTransformer.gen_cache / CacheKV allocation). Prime
        them by running the prompt through ``forward(prompt, caches=...)``
        — each layer's cache must hold that layer's OWN K/V, so it cannot
        be precomputed from the input embedding."""
        import jax.numpy as jnp
        b = x.shape[0]
        out = []
        for blk in self.blocks:
            a = blk.self_attn
            shape = (b, 0, a.num_heads, a.head_dim)
            out.append((jnp.zeros(shape, x.dtype),
                        jnp.zeros(shape, x.dtype)))
        return out

    def forward(self, src, attn_mask=None, caches=None):
        out = src
        if caches is None:
            for blk in self.blocks:
                out = blk(out, src_mask=attn_mask)
            return out
        if len(caches) != len(self.blocks):
            raise ValueError(
                f"caches has {len(caches)} entries for "
                f"{len(self.blocks)} layers (build with gen_cache)")
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            out, cache = blk(out, src_mask=attn_mask, cache=cache)
            new_caches.append(cache)
        return out, new_caches
