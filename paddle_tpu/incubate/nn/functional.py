"""incubate.nn.functional (ref: python/paddle/incubate/nn/functional/
__init__.py — fused_multi_head_attention, fused_feedforward,
fused_matmul_bias/fused_linear, fused_bias_dropout_residual_layer_norm).

On TPU, "fused" is either a Pallas kernel (attention, bias-dropout-
residual-LN) or an XLA fusion guarantee (matmul+bias epilogues fuse under
jit unconditionally — no cublasLt version gate to re-create)."""

import jax.numpy as jnp

__all__ = ["fused_matmul_bias", "fused_linear",
           "fused_bias_dropout_residual_layer_norm",
           "fused_multi_head_attention", "fused_feedforward"]


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """ref: incubate fused_matmul_bias (fused_gemm_epilogue op). XLA fuses
    the bias add into the matmul epilogue under jit; int8 QuantTensor
    weights route the Pallas int8 kernel via __rmatmul__."""
    x = jnp.asarray(x)
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = y.T if hasattr(y, "dequantize") else jnp.swapaxes(
            jnp.asarray(y), -1, -2)
    out = x @ y if hasattr(y, "dequantize") else x @ jnp.asarray(y)
    if bias is not None:
        out = out + jnp.asarray(bias)
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        dropout_seed=None, name=None):
    """ref: incubate fused_bias_dropout_residual_layer_norm →
    fused_bias_dropout_residual_layer_norm_op.cu; here the Pallas fused-LN
    kernel (ops/pallas/layer_norm.py)."""
    x = jnp.asarray(x)
    d = x.shape[-1]
    from paddle_tpu.ops.pallas.layer_norm import fused_layer_norm
    gamma = jnp.ones((d,), x.dtype) if ln_scale is None else ln_scale
    beta = jnp.zeros((d,), x.dtype) if ln_bias is None else ln_bias
    p = dropout_rate if training else 0.0
    if p > 0.0 and dropout_seed is None:
        import jax
        from paddle_tpu.nn.functional.common import fold_ctx_key
        dropout_seed = jax.random.bits(fold_ctx_key(), (),
                                       jnp.uint32).astype(jnp.int32)
    y, _ = fused_layer_norm(x, gamma, beta, residual=residual, bias=bias,
                            dropout_p=p, dropout_seed=dropout_seed,
                            epsilon=ln_epsilon)
    return y


def _ln(x, scale, bias, eps):
    import jax
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        y = y * scale
    if bias is not None:
        y = y + bias
    return y.astype(x.dtype)


def _dropout(x, rate, training, key, mode="upscale_in_train"):
    # one dropout implementation for the whole package (incl. the
    # downscale_in_infer mode the reference supports)
    from paddle_tpu.nn.functional.common import dropout
    return dropout(x, rate, training=training, mode=mode, key=key)


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None,
        cache_kv=None, attn_mask=None, dropout_rate=0.5,
        attn_dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", ring_id=-1, add_residual=True,
        rng_key=None, name=None):
    """ref: incubate fused_multi_head_attention
    (fused_transformer.py:462, fused_attention_op.cu) — the whole
    [pre-LN →] packed-QKV projection → attention → out-proj → dropout →
    residual [→ post-LN] block. qkv_weight: (3, H, dh, D). On TPU the
    attention core routes the Pallas flash kernel via
    F.scaled_dot_product_attention; everything around it is one traced
    expression XLA fuses."""
    import jax

    from paddle_tpu.nn import functional as F
    from paddle_tpu.nn.functional.common import fold_ctx_key

    x = jnp.asarray(x)
    qkv_w = jnp.asarray(qkv_weight)
    assert qkv_w.ndim == 4 and qkv_w.shape[0] == 3, qkv_w.shape
    _, h, dh, d = qkv_w.shape
    assert d == x.shape[-1], (qkv_w.shape, x.shape)
    residual = x
    if pre_layer_norm:
        x = _ln(x, pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    b, sq = x.shape[0], x.shape[1]
    qkv = jnp.einsum("bsd,thed->bsthe", x, qkv_w)      # (B,S,3,H,dh)
    if qkv_bias is not None:
        qkv = qkv + jnp.asarray(qkv_bias)[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if rng_key is None:
        rng_key = fold_ctx_key(salt=101)  # context RNG, like the sibling
    k1, k2 = jax.random.split(rng_key)
    cache_out = None
    if cache_kv is not None:
        # incremental decode (≙ fused_attention_op CacheKV): cache_kv
        # (2, B, H, T_past, dh) holds the past; the step's K/V append
        # and the query attends to past + self (causal within the new
        # block). Reference-parity concat semantics — the growing shape
        # recompiles per length, so production serving uses the
        # static-slot DecodeEngine; this form exists for porting
        # parity with fused_transformer.py:462.
        ck = jnp.asarray(cache_kv)
        assert ck.ndim == 5 and ck.shape[0] == 2, ck.shape
        t_past = ck.shape[3]
        k_hmaj = jnp.swapaxes(k, 1, 2)       # (B, H, Sq, dh)
        v_hmaj = jnp.swapaxes(v, 1, 2)
        k_full = jnp.concatenate([ck[0], k_hmaj.astype(ck.dtype)], 2)
        v_full = jnp.concatenate([ck[1], v_hmaj.astype(ck.dtype)], 2)
        cache_out = jnp.stack([k_full, v_full])
        causal = (t_past + jnp.arange(sq)[:, None]
                  >= jnp.arange(t_past + sq)[None, :])
        bias = jnp.where(causal, 0.0, -jnp.inf)[None, None]
        if attn_mask is not None:
            bias = bias + jnp.asarray(attn_mask)
        attn = F.scaled_dot_product_attention(
            q, jnp.swapaxes(k_full, 1, 2).astype(q.dtype),
            jnp.swapaxes(v_full, 1, 2).astype(q.dtype),
            attn_mask=bias, is_causal=False,
            dropout_p=attn_dropout_rate if training else 0.0,
            training=training, rng_key=k1)
    else:
        attn = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=False,
            dropout_p=attn_dropout_rate if training else 0.0,
            training=training, rng_key=k1)
    out = attn.reshape(b, sq, h * dh) @ jnp.asarray(linear_weight)
    if not pre_layer_norm and add_residual:
        # the whole tail IS the sibling fused op → Pallas fused-LN kernel
        import jax as _jax
        seed = _jax.random.bits(k2, (), jnp.uint32).astype(jnp.int32)
        out = fused_bias_dropout_residual_layer_norm(
            out, residual, bias=linear_bias, ln_scale=ln_scale,
            ln_bias=ln_bias, dropout_rate=dropout_rate,
            ln_epsilon=ln_epsilon, training=training, dropout_seed=seed)
    else:
        if linear_bias is not None:
            out = out + jnp.asarray(linear_bias)
        out = _dropout(out, dropout_rate, training, k2, mode)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            out = _ln(out, ln_scale, ln_bias, ln_epsilon)
    # reference parity: (out, cache_kv_out) in the incremental form
    return out if cache_out is None else (out, cache_out)


def fused_feedforward(
        x, linear1_weight, linear2_weight, linear1_bias=None,
        linear2_bias=None, ln1_scale=None, ln1_bias=None, ln2_scale=None,
        ln2_bias=None, dropout1_rate=0.5, dropout2_rate=0.5,
        activation="relu", ln1_epsilon=1e-5, ln2_epsilon=1e-5,
        pre_layer_norm=False, training=True, mode="upscale_in_train",
        ring_id=-1, add_residual=True, rng_key=None, name=None):
    """ref: incubate fused_feedforward (fused_transformer.py:31,
    fused_feedforward_op.cu) — [pre-LN →] linear1 → act → dropout1 →
    linear2 → dropout2 → residual [→ post-LN], one traced expression."""
    import jax  # noqa: F401 (split below)

    from paddle_tpu.nn import functional as F
    from paddle_tpu.nn.functional.common import fold_ctx_key

    x = jnp.asarray(x)
    residual = x
    if pre_layer_norm:
        x = _ln(x, ln1_scale, ln1_bias, ln1_epsilon)
    if rng_key is None:
        rng_key = fold_ctx_key(salt=102)  # context RNG, like the sibling
    k1, k2 = jax.random.split(rng_key)
    h = fused_matmul_bias(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    h = _dropout(h, dropout1_rate, training, k1, mode)
    h = fused_matmul_bias(h, linear2_weight, linear2_bias)
    h = _dropout(h, dropout2_rate, training, k2, mode)
    out = residual + h if add_residual else h
    if not pre_layer_norm:
        out = _ln(out, ln2_scale, ln2_bias, ln2_epsilon)
    return out
