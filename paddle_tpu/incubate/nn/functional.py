"""incubate.nn.functional (ref: python/paddle/incubate/nn/functional/
__init__.py — fused_multi_head_attention, fused_feedforward,
fused_matmul_bias/fused_linear, fused_bias_dropout_residual_layer_norm).

On TPU, "fused" is either a Pallas kernel (attention, bias-dropout-
residual-LN) or an XLA fusion guarantee (matmul+bias epilogues fuse under
jit unconditionally — no cublasLt version gate to re-create)."""

import jax.numpy as jnp

__all__ = ["fused_matmul_bias", "fused_linear",
           "fused_bias_dropout_residual_layer_norm",
           "fused_multi_head_attention", "fused_feedforward"]


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """ref: incubate fused_matmul_bias (fused_gemm_epilogue op). XLA fuses
    the bias add into the matmul epilogue under jit; int8 QuantTensor
    weights route the Pallas int8 kernel via __rmatmul__."""
    x = jnp.asarray(x)
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = y.T if hasattr(y, "dequantize") else jnp.swapaxes(
            jnp.asarray(y), -1, -2)
    out = x @ y if hasattr(y, "dequantize") else x @ jnp.asarray(y)
    if bias is not None:
        out = out + jnp.asarray(bias)
    return out


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        dropout_seed=None, name=None):
    """ref: incubate fused_bias_dropout_residual_layer_norm →
    fused_bias_dropout_residual_layer_norm_op.cu; here the Pallas fused-LN
    kernel (ops/pallas/layer_norm.py)."""
    x = jnp.asarray(x)
    d = x.shape[-1]
    from paddle_tpu.ops.pallas.layer_norm import fused_layer_norm
    gamma = jnp.ones((d,), x.dtype) if ln_scale is None else ln_scale
    beta = jnp.zeros((d,), x.dtype) if ln_bias is None else ln_bias
    p = dropout_rate if training else 0.0
    if p > 0.0 and dropout_seed is None:
        import jax
        from paddle_tpu.nn.functional.common import fold_ctx_key
        dropout_seed = jax.random.bits(fold_ctx_key(), (),
                                       jnp.uint32).astype(jnp.int32)
    y, _ = fused_layer_norm(x, gamma, beta, residual=residual, bias=bias,
                            dropout_p=p, dropout_seed=dropout_seed,
                            epsilon=ln_epsilon)
    return y


def fused_multi_head_attention(x, qkv_weight, linear_weight, *args, **kwargs):
    """ref: incubate fused_multi_head_attention. Provided at layer level
    (FusedMultiHeadAttention → Pallas flash attention); the raw-weight
    functional form is intentionally a thin composition."""
    raise NotImplementedError(
        "use incubate.nn.FusedMultiHeadAttention (the layer form); the "
        "raw-weight functional depends on the reference's packed qkv "
        "layout which paddle_tpu does not use")


def fused_feedforward(x, linear1_weight, linear2_weight, *args, **kwargs):
    """ref: incubate fused_feedforward. See fused_multi_head_attention."""
    raise NotImplementedError(
        "use incubate.nn.FusedFeedForward (the layer form)")
