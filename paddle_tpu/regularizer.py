"""Weight-decay regularizers (ref: python/paddle/regularizer.py —
L1Decay, L2Decay; fluid/regularizer.py append_regularization_ops).

Functional form: a regularizer is ``grad' = grad + d(penalty)/d(param)``,
applied by the optimizer before the update (the reference appends the
same ops to the backward program). Pass as ``weight_decay=`` to any
optimizer; a float keeps meaning plain L2 (the common case)."""

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay"]


class _Regularizer:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, grad, param):
        raise NotImplementedError


class L1Decay(_Regularizer):
    """grad += coeff * sign(param) (≙ L1DecayRegularizer)."""

    def __call__(self, grad, param):
        return grad + self.coeff * jnp.sign(param)


class L2Decay(_Regularizer):
    """grad += coeff * param (≙ L2DecayRegularizer)."""

    def __call__(self, grad, param):
        return grad + self.coeff * param
