"""Native (C++) runtime components, loaded via ctypes.

Reference analog: the reference implements its data transport and
rendezvous natively (framework/data_feed.cc, distributed/store/tcp_store.cc)
— so does this framework: native/src/*.cc builds libptnative.so (CMake or
direct g++; no pybind11 — pure C ABI).

Components:
  ShmRingBuffer  process-shared ring for DataLoader worker batches
  TCPStore       rendezvous KV store (server + client)

The library auto-builds on first import when a toolchain is present;
`is_available()` gates callers so pure-Python fallbacks keep working.
"""

import os
import subprocess

_LIB = None
_BUILD_ERR = None


def _lib_path():
    return os.path.join(os.path.dirname(__file__), "libptnative.so")


def _build():
    root = os.path.join(os.path.dirname(__file__), "..", "..", "native")
    root = os.path.abspath(root)
    out = _lib_path()
    srcs = [os.path.join(root, "src", f)
            for f in ("ringbuffer.cc", "tcp_store.cc", "p2p.cc")]
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-Wall",
           *srcs, "-o", out, "-lpthread", "-lrt"]
    subprocess.run(cmd, check=True, capture_output=True)


def _load():
    global _LIB, _BUILD_ERR
    if _LIB is not None or _BUILD_ERR is not None:
        return _LIB
    import ctypes
    path = _lib_path()
    try:
        srcs_dir = os.path.join(os.path.dirname(__file__), "..", "..",
                                "native", "src")
        if not os.path.exists(path) or any(
                os.path.getmtime(os.path.join(srcs_dir, f)) >
                os.path.getmtime(path)
                for f in os.listdir(srcs_dir)):
            _build()
        _LIB = ctypes.CDLL(path)
        _configure(_LIB, ctypes)
    except Exception as e:  # no toolchain / unsupported platform
        _BUILD_ERR = e
        _LIB = None
    return _LIB


def _configure(lib, ctypes):
    c = ctypes
    lib.ptrb_create.restype = c.c_void_p
    lib.ptrb_create.argtypes = [c.c_char_p, c.c_uint32, c.c_uint64]
    lib.ptrb_open.restype = c.c_void_p
    lib.ptrb_open.argtypes = [c.c_char_p]
    lib.ptrb_slot_size.restype = c.c_uint64
    lib.ptrb_slot_size.argtypes = [c.c_void_p]
    lib.ptrb_push.restype = c.c_int
    lib.ptrb_push.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64, c.c_double]
    lib.ptrb_pop.restype = c.c_int64
    lib.ptrb_pop.argtypes = [c.c_void_p, c.c_void_p, c.c_uint64, c.c_double]
    lib.ptrb_close_producer.restype = None
    lib.ptrb_close_producer.argtypes = [c.c_void_p]
    lib.ptrb_size.restype = c.c_int
    lib.ptrb_size.argtypes = [c.c_void_p]
    lib.ptrb_close.restype = None
    lib.ptrb_close.argtypes = [c.c_void_p, c.c_int]

    lib.ptts_server_start.restype = c.c_void_p
    lib.ptts_server_start.argtypes = [c.c_int]
    lib.ptts_server_port.restype = c.c_int
    lib.ptts_server_port.argtypes = [c.c_void_p]
    lib.ptts_server_stop.restype = None
    lib.ptts_server_stop.argtypes = [c.c_void_p]
    lib.ptts_connect.restype = c.c_void_p
    lib.ptts_connect.argtypes = [c.c_char_p, c.c_int, c.c_double]
    lib.ptts_set.restype = c.c_int
    lib.ptts_set.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p, c.c_uint64]
    lib.ptts_get.restype = c.c_int64
    lib.ptts_get.argtypes = [c.c_void_p, c.c_char_p, c.c_void_p, c.c_uint64,
                             c.c_double]
    lib.ptts_add.restype = c.c_int64
    lib.ptts_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.ptts_del.restype = c.c_int
    lib.ptts_del.argtypes = [c.c_void_p, c.c_char_p]
    lib.ptts_close.restype = None
    lib.ptts_close.argtypes = [c.c_void_p]

    lib.ptpp_create.restype = c.c_void_p
    lib.ptpp_create.argtypes = [c.c_int]
    lib.ptpp_port.restype = c.c_int
    lib.ptpp_port.argtypes = [c.c_void_p]
    lib.ptpp_probe.restype = c.c_int64
    lib.ptpp_probe.argtypes = [c.c_void_p, c.c_uint64, c.c_double]
    lib.ptpp_recv.restype = c.c_int64
    lib.ptpp_recv.argtypes = [c.c_void_p, c.c_uint64, c.c_void_p,
                              c.c_uint64, c.c_double]
    lib.ptpp_send.restype = c.c_int
    lib.ptpp_send.argtypes = [c.c_void_p, c.c_char_p, c.c_int, c.c_uint64,
                              c.c_char_p, c.c_uint64]
    lib.ptpp_destroy.restype = None
    lib.ptpp_destroy.argtypes = [c.c_void_p]


def decode_counter(raw) -> int:
    """Decode a TCPStore counter value: ``add()`` keeps counters as raw
    little-endian int64 bytes; a ``set()`` writes ascii. One decoder for
    every consumer (elastic heartbeats, launch re-form watch)."""
    if isinstance(raw, (bytes, bytearray)) and len(raw) == 8:
        return int.from_bytes(raw, "little", signed=True)
    return int(raw)


def is_available() -> bool:
    return _load() is not None


def get_lib():
    lib = _load()
    if lib is None:
        raise RuntimeError(
            f"native library unavailable: {_BUILD_ERR!r}")
    return lib


class ShmRingBuffer:
    """Process-shared MPMC ring of fixed-size slots (see ringbuffer.cc)."""

    def __init__(self, name: str, nslots: int = 8,
                 slot_size: int = 8 << 20, create: bool = True):
        import ctypes
        self._ct = ctypes
        self._lib = get_lib()
        self.name = name
        if create:
            self._h = self._lib.ptrb_create(name.encode(), nslots, slot_size)
        else:
            self._h = self._lib.ptrb_open(name.encode())
        if not self._h:
            raise RuntimeError(f"shm ring {name!r} "
                               f"{'create' if create else 'open'} failed")
        self._owner = create
        self.slot_size = self._lib.ptrb_slot_size(self._h)
        self._popbuf = ctypes.create_string_buffer(self.slot_size)

    def push(self, data: bytes, timeout: float = 30.0):
        rc = self._lib.ptrb_push(self._h, data, len(data), timeout)
        if rc == -1:
            raise TimeoutError(f"push timed out after {timeout}s")
        if rc == -2:
            raise ValueError(f"payload {len(data)} > slot {self.slot_size}")
        if rc == -3:
            raise BrokenPipeError("ring closed")
        if rc != 0:
            raise RuntimeError(f"push failed rc={rc}")

    def pop(self, timeout: float = 30.0) -> bytes:
        n = self._lib.ptrb_pop(self._h, self._popbuf, self.slot_size,
                               timeout)
        if n == -1:
            raise TimeoutError(f"pop timed out after {timeout}s")
        if n == -3:
            raise EOFError("ring closed and drained")
        if n < 0:
            raise RuntimeError(f"pop failed rc={n}")
        # string_at copies exactly n bytes (.raw[:n] would materialize the
        # whole slot and then slice — 2x slot_size churn per batch)
        return self._ct.string_at(self._popbuf, n)

    def close_producer(self):
        self._lib.ptrb_close_producer(self._h)

    def __len__(self):
        return self._lib.ptrb_size(self._h)

    def close(self, unlink: bool = None):
        if self._h:
            self._lib.ptrb_close(
                self._h, 1 if (unlink if unlink is not None else
                               self._owner) else 0)
            self._h = None


class TCPStore:
    """Rendezvous KV store ≙ paddle TCPStore (tcp_store.cc).

    is_master=True also runs the server in-process (rank 0)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, timeout: float = 30.0):
        self._lib = get_lib()
        self._srv = None
        if is_master:
            self._srv = self._lib.ptts_server_start(port)
            if not self._srv:
                raise RuntimeError(f"TCPStore server failed on port {port}")
            port = self._lib.ptts_server_port(self._srv)
        self.host, self.port = host, port
        self._cli = self._lib.ptts_connect(host.encode(), port, timeout)
        if not self._cli:
            if self._srv:
                self._lib.ptts_server_stop(self._srv)
            raise ConnectionError(f"TCPStore connect {host}:{port} failed")

    def set(self, key: str, value: bytes):
        if isinstance(value, str):
            value = value.encode()
        rc = self._lib.ptts_set(self._cli, key.encode(), value, len(value))
        if rc != 0:
            raise RuntimeError(f"set({key!r}) failed rc={rc}")

    def get(self, key: str, timeout: float = 30.0) -> bytes:
        import ctypes
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.ptts_get(self._cli, key.encode(), buf, cap, timeout)
        if n == -1:
            raise TimeoutError(f"get({key!r}) timed out")
        if n < 0:
            raise RuntimeError(f"get({key!r}) failed rc={n}")
        return buf.raw[:n]

    def add(self, key: str, amount: int = 1) -> int:
        v = self._lib.ptts_add(self._cli, key.encode(), amount)
        if v == -(2 ** 63):
            raise RuntimeError(f"add({key!r}) failed")
        return v

    def delete_key(self, key: str):
        self._lib.ptts_del(self._cli, key.encode())

    def wait(self, keys, timeout: float = 30.0):
        for k in (keys if isinstance(keys, (list, tuple)) else [keys]):
            self.get(k, timeout=timeout)

    def close(self):
        if self._cli:
            self._lib.ptts_close(self._cli)
            self._cli = None
        if self._srv:
            self._lib.ptts_server_stop(self._srv)
            self._srv = None


class P2PEndpoint:
    """Tag-addressed point-to-point message endpoint (see p2p.cc;
    ≙ fleet_executor/message_bus.cc + interceptor.cc mailboxes). One per
    rank: ``send(host, port, tag, payload)`` is fire-and-forget on a
    cached connection; ``recv(tag)`` blocks on the local mailbox."""

    def __init__(self, port: int = 0):
        import ctypes
        self._ct = ctypes
        self._lib = get_lib()
        self._h = self._lib.ptpp_create(port)
        if not self._h:
            raise RuntimeError(f"P2PEndpoint failed to listen on {port}")
        self.port = self._lib.ptpp_port(self._h)

    def send(self, host: str, port: int, tag: int, payload: bytes):
        rc = self._lib.ptpp_send(self._h, host.encode(), port, tag,
                                 payload, len(payload))
        if rc == -1:
            raise ConnectionError(f"p2p connect {host}:{port} failed")
        if rc != 0:
            raise BrokenPipeError(f"p2p send to {host}:{port} failed")

    def recv(self, tag: int, timeout: float = 60.0) -> bytes:
        n = self._lib.ptpp_probe(self._h, tag, timeout)
        if n == -1:
            raise TimeoutError(f"p2p recv(tag={tag}) timed out "
                               f"after {timeout}s")
        buf = self._ct.create_string_buffer(max(int(n), 1))
        m = self._lib.ptpp_recv(self._h, tag, buf, n, 0.0)
        if m < 0:
            raise RuntimeError(f"p2p recv(tag={tag}) failed rc={m}")
        return self._ct.string_at(buf, m)

    def close(self):
        if self._h:
            self._lib.ptpp_destroy(self._h)
            self._h = None


__all__ = ["is_available", "get_lib", "decode_counter",
           "ShmRingBuffer", "TCPStore",
           "P2PEndpoint"]
