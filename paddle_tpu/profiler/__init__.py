"""Profiler (ref: paddle/fluid/platform/profiler/ two-generation tracer +
python/paddle/profiler/profiler.py:339 Profiler with scheduler states and
chrome-trace export).

TPU equivalent: jax.profiler (XLA/TPU trace → TensorBoard/Perfetto) plus
RecordEvent-style host annotations and the IPS/MFU benchmark timer
(≙ python/paddle/profiler/timer.py).
"""

import contextlib
import time
from typing import Callable, Optional

import jax

from paddle_tpu.profiler.timer import Benchmark, benchmark
from paddle_tpu.profiler import statistic
from paddle_tpu.profiler.statistic import (SpanCollector, StatRegistry,
                                           stat_registry, stat_add,
                                           stat_get, format_table)

__all__ = ["Profiler", "RecordEvent", "ProfilerTarget", "ProfilerState",
           "export_chrome_tracing", "Benchmark", "benchmark",
           "start_server", "SpanCollector", "StatRegistry", "stat_registry",
           "device_memory_stats", "memory_allocated",
           "max_memory_allocated", "record_memory_stats", "memory_summary",
           "stat_add", "stat_get", "format_table"]


class ProfilerTarget:
    CPU = "cpu"
    TPU = "tpu"
    GPU = "tpu"  # parity alias


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class RecordEvent:
    """Host-side named range (ref: platform/profiler/event_tracing.h
    RecordEvent) — shows up in the XLA trace via jax.profiler.TraceAnnotation."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        if self._t0 is not None:
            # feeds the host-side span table (≙ profiler_statistic.py)
            statistic.record_span(self.name, time.perf_counter() - self._t0)
            self._t0 = None


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """Returns an on_trace_ready callback (ref: profiler.py:210)."""
    def handle(prof):
        pass  # trace already written by jax.profiler to dir_name
    handle.dir_name = dir_name
    return handle


class Profiler:
    """ref: paddle.profiler.Profiler (profiler.py:339). Wraps
    jax.profiler.start_trace/stop_trace; scheduler(state machine) reduced to
    explicit start/stop + step marks."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, log_dir="./profiler_log"):
        self.log_dir = getattr(on_trace_ready, "dir_name", None) or log_dir
        self.timer_only = timer_only
        self._running = False
        self._step = 0
        self._step_times = []
        self._last = None
        self._collector = None

    def start(self):
        if not self.timer_only:
            jax.profiler.start_trace(self.log_dir)
        self._collector = statistic.SpanCollector()
        statistic._set_active(self._collector)
        self._running = True
        self._last = time.perf_counter()

    def stop(self):
        if self._running and not self.timer_only:
            jax.profiler.stop_trace()
        statistic._set_active(None)
        self._running = False

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last is not None:
            self._step_times.append(now - self._last)
        self._last = now
        self._step += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return "no steps recorded"
        avg = sum(self._step_times[-10:]) / len(self._step_times[-10:])
        return f"avg step {avg * 1000:.2f} ms ({1.0 / avg:.2f} steps/s)"

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", memory=True):
        """Host-span table + step-time breakdown (≙ the reference's
        profiler_statistic.py tables printed after Profiler.stop), plus
        the device-memory watermark block (≙ mem_tracing.h surface;
        disable with memory=False)."""
        if self._collector is None:
            return self.step_info()
        table = statistic.format_table(
            self._collector, step_times=self._step_times,
            sorted_by=sorted_by or "total", time_unit=time_unit)
        if memory:
            from paddle_tpu.profiler.memory import memory_summary
            try:
                table += "\n" + memory_summary()
            except Exception:
                pass
        return table

    def export(self, path=None, format=None):  # noqa: A002
        pass  # jax.profiler already wrote the trace to log_dir


def start_server(port: int = 9012):
    """On-demand profiling server (≙ the reference's remote profiler)."""
    return jax.profiler.start_server(port)


from paddle_tpu.profiler.memory import (  # noqa: E402
    device_memory_stats, memory_allocated, max_memory_allocated,
    record_memory_stats, memory_summary)
