"""Device-memory observability (VERDICT r2 missing 10).

Reference analog: paddle/fluid/memory/stats.h (DEVICE_MEMORY_STAT
allocated/peak counters), platform/profiler mem_tracing.h (per-op memory
events), and paddle.device.cuda.{memory,max_memory}_allocated. On TPU the
allocator is PJRT's: the numbers come from ``device.memory_stats()``
(bytes_in_use / peak_bytes_in_use straight from the runtime); the CPU
backend has no such API, so the fallback sums the process's live jax
arrays — the framework-visible working set.
"""

from typing import Optional

import jax

from paddle_tpu.profiler.statistic import stat_registry

__all__ = ["device_memory_stats", "memory_allocated",
           "max_memory_allocated", "record_memory_stats",
           "memory_summary"]


def _live_bytes(device) -> int:
    total = 0
    for arr in jax.live_arrays():
        try:
            if device is None or device in arr.devices():
                total += arr.nbytes
        except Exception:  # deleted/donated arrays
            continue
    return total


def device_memory_stats(device=None) -> dict:
    """Raw runtime memory stats for one device (first device default).

    Keys (PJRT): ``bytes_in_use``, ``peak_bytes_in_use``,
    ``largest_alloc_size``... On backends without allocator stats (CPU)
    returns ``{"bytes_in_use": <live array bytes>, "source": "live_arrays"}``.
    """
    device = device or jax.devices()[0]
    stats = None
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if stats:
        out = dict(stats)
        out["source"] = "pjrt"
        return out
    return {"bytes_in_use": _live_bytes(device), "source": "live_arrays"}


def memory_allocated(device=None) -> int:
    """ref: paddle.device.cuda.memory_allocated."""
    return int(device_memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """ref: paddle.device.cuda.max_memory_allocated. Falls back to the
    current allocation where the runtime keeps no peak counter."""
    s = device_memory_stats(device)
    return int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))


def record_memory_stats(device=None, prefix: str = "mem"):
    """Push the current device-memory numbers into the StatRegistry
    (≙ DEVICE_MEMORY_STAT_UPDATE feeding monitor.h counters), so they
    appear alongside profiler span tables and custom counters."""
    s = device_memory_stats(device)
    stat_registry.set(f"{prefix}/bytes_in_use",
                      int(s.get("bytes_in_use", 0)))
    if "peak_bytes_in_use" in s:
        stat_registry.set(f"{prefix}/peak_bytes_in_use",
                          int(s["peak_bytes_in_use"]))
    if "largest_alloc_size" in s:
        stat_registry.set(f"{prefix}/largest_alloc_size",
                          int(s["largest_alloc_size"]))
    return s


def memory_summary(device=None) -> str:
    """Human-readable HBM watermark block (appended to Profiler.summary)."""
    s = device_memory_stats(device)
    gib = 1024.0 ** 3
    lines = ["Device memory:"]
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size"):
        if key in s:
            lines.append(f"  {key:<22} {s[key] / gib:8.3f} GiB")
    lines.append(f"  source                 {s.get('source', '?')}")
    return "\n".join(lines)
