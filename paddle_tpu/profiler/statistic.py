"""Profiler statistics + monitor counters.

Reference analogs:
- python/paddle/profiler/profiler_statistic.py — per-op time tables
  (calls / total / avg / max / min / ratio) rendered after a profiling
  session;
- paddle/fluid/platform/monitor.h:35-139 — StatRegistry + STAT_ADD named
  int64 counters exported to Python.

Host-side spans come from RecordEvent (device-side timing lives in the XLA
trace; these tables cover the host orchestration the reference's host
tracer covers). Collection is active while a Profiler is running.
"""

import threading
import time
from typing import Dict, List, Optional

__all__ = ["SpanStats", "SpanCollector", "StatRegistry", "stat_registry",
           "stat_add", "stat_get", "format_table"]


class SpanStats:
    __slots__ = ("name", "count", "total", "min", "max")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def add(self, dt: float):
        self.count += 1
        self.total += dt
        self.min = min(self.min, dt)
        self.max = max(self.max, dt)

    @property
    def avg(self):
        return self.total / self.count if self.count else 0.0


class SpanCollector:
    """Aggregates RecordEvent spans by name (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._spans: Dict[str, SpanStats] = {}
        self._t0 = time.perf_counter()

    def add(self, name: str, dt: float):
        with self._lock:
            s = self._spans.get(name)
            if s is None:
                s = self._spans[name] = SpanStats(name)
            s.add(dt)

    def spans(self) -> List[SpanStats]:
        with self._lock:
            return list(self._spans.values())

    @property
    def wall(self) -> float:
        return time.perf_counter() - self._t0


_active_lock = threading.Lock()
_active: Optional[SpanCollector] = None


def _set_active(c: Optional[SpanCollector]):
    global _active
    with _active_lock:
        _active = c


def _get_active() -> Optional[SpanCollector]:
    return _active


def record_span(name: str, dt: float):
    c = _active
    if c is not None:
        c.add(name, dt)


def format_table(collector: SpanCollector, step_times=None,
                 sorted_by: str = "total", time_unit: str = "ms") -> str:
    """Render the profiler_statistic.py-style table."""
    unit = {"s": 1.0, "ms": 1e3, "us": 1e6}[time_unit]
    spans = collector.spans()
    key = {"total": lambda s: -s.total, "avg": lambda s: -s.avg,
           "count": lambda s: -s.count, "name": lambda s: s.name,
           "max": lambda s: -s.max}[sorted_by]
    spans.sort(key=key)
    wall = max(collector.wall, 1e-12)
    lines = []
    hdr = (f"{'Name':<32} {'Calls':>7} {'Total(' + time_unit + ')':>12} "
           f"{'Avg(' + time_unit + ')':>12} {'Max(' + time_unit + ')':>12} "
           f"{'Min(' + time_unit + ')':>12} {'Ratio%':>7}")
    lines.append("-" * len(hdr))
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for s in spans:
        lines.append(
            f"{s.name[:32]:<32} {s.count:>7} {s.total * unit:>12.3f} "
            f"{s.avg * unit:>12.3f} {s.max * unit:>12.3f} "
            f"{(0.0 if s.count == 0 else s.min) * unit:>12.3f} "
            f"{100.0 * s.total / wall:>7.2f}")
    if step_times:
        import numpy as np
        st = np.asarray(step_times)
        lines.append("-" * len(hdr))
        lines.append(
            f"steps: {len(st)}  avg {st.mean() * unit:.3f}{time_unit}  "
            f"p50 {np.percentile(st, 50) * unit:.3f}  "
            f"p95 {np.percentile(st, 95) * unit:.3f}  "
            f"max {st.max() * unit:.3f}")
    lines.append("-" * len(hdr))
    return "\n".join(lines)


# -- named counters: ONE registry process-wide ------------------------------
# Historically this module carried its own StatRegistry shadowing
# paddle_tpu.stats — profiler counters (mem/* gauges) and the runtime's
# resilience/serving counters then lived in two places no tool could see
# together. Now a thin re-export: stat_registry IS stats.default_registry(),
# so STAT_ADD-style bumps from anywhere land in the same scrape surface
# (stats.snapshot / statsz / launch-side merge).
#
# Semantics that changed with the merge — reset() now follows
# stats.StatRegistry: no-arg reset() clears EVERY subsystem's metrics
# (it is the process-wide registry now), and reset(name) is a PREFIX
# match (incl. derived .total_s/.p99 names), not an exact-key pop.
# Scope clears with a prefix: stat_registry.reset("mem/").

from paddle_tpu import stats as _stats  # noqa: E402

StatRegistry = _stats.StatRegistry
stat_registry = _stats.default_registry()


def stat_add(name: str, value: int = 1) -> int:
    """≙ STAT_ADD(name, value) (monitor.h:121)."""
    return int(stat_registry.add(name, int(value)))


def stat_get(name: str) -> int:
    return stat_registry.get(name, 0)
