"""IPS / MFU benchmark timer (ref: python/paddle/profiler/timer.py —
``benchmark()`` hooks reporting ips during training; extended here with MFU
as BASELINE.md requires: MFU = model_flops / (chips × peak_flops))."""

import time

__all__ = ["Benchmark", "benchmark"]

# Peak dense BF16 FLOP/s per chip by TPU generation (public figures).
PEAK_BF16_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def detect_peak_flops(default=197e12):
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
        for name, peak in PEAK_BF16_FLOPS.items():
            if name in kind:
                return peak
    except Exception:
        pass
    return default


class Benchmark:
    """Step timer with ips/MFU reporting."""

    def __init__(self, flops_per_step=None, num_chips=1, peak_flops=None):
        self.flops_per_step = flops_per_step
        self.num_chips = num_chips
        # lazy: detect_peak_flops() calls jax.devices(), which INITIALIZES
        # the backend — constructing a Benchmark (the module-level default
        # below runs at `import paddle_tpu`!) must never do that.
        # Falsy values (0/None) defer to detection, like the old
        # `peak_flops or detect_peak_flops()`.
        self._peak_flops = peak_flops or None
        self.reset()

    def reset(self):
        self.times = []
        self._last = None

    def begin(self):
        self._last = time.perf_counter()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            dt = now - self._last
            self.times.append((dt, num_samples))
            # per-step latency histogram: table() then shows train-loop
            # p50/p99 alongside the serving ones (docs/observability.md)
            from paddle_tpu import stats
            stats.observe("train/step_s", dt)
        self._last = now

    def end(self):
        self._last = None

    @property
    def avg_step_time(self):
        if not self.times:
            return float("nan")
        # skip warmup step
        ts = [t for t, _ in self.times[1:]] or [self.times[0][0]]
        return sum(ts) / len(ts)

    def ips(self):
        ts = self.times[1:] or self.times
        total_t = sum(t for t, _ in ts)
        total_n = sum(n or 0 for _, n in ts)
        return total_n / total_t if total_t > 0 else float("nan")

    @property
    def peak_flops(self):
        if self._peak_flops is None:
            self._peak_flops = detect_peak_flops()
        return self._peak_flops

    def mfu(self):
        if self.flops_per_step is None:
            return float("nan")
        return self.flops_per_step / (
            self.avg_step_time * self.num_chips * self.peak_flops)

    def report(self):
        out = {"step_time_s": self.avg_step_time, "ips": self.ips(),
               "mfu": self.mfu()}
        # publish into the named-stat registry (≙ monitor.h STAT_ADD
        # consumers scraping the benchmark numbers)
        from paddle_tpu import stats
        for k, v in out.items():
            # NaN publishes too: gauges are last-value-wins, and a stale
            # number from a previous run is worse than an honest NaN
            stats.set_value(f"benchmark/{k}", v)
        # train-loop gauges under the observability namespace: when
        # num_samples counts tokens, ips IS tokens/s (the LM-training
        # convention BENCH uses); MFU rides along for the capacity view
        stats.set_value("train/tokens_per_s", out["ips"])
        stats.set_value("train/mfu", out["mfu"])
        return out


_global_benchmark = Benchmark()


def benchmark():
    return _global_benchmark
