"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

This capability is ABSENT in the reference (SURVEY §5.7: no
sequence_parallel / ring_attention / context_parallel / ulysses anywhere in
the tree) — it is designed fresh for TPU:

- **Ring attention** (Liu et al. 2023): the sequence axis is sharded over a
  mesh axis; each step computes blockwise attention of the local Q shard
  against the currently-held KV shard, accumulates online-softmax state,
  and rotates KV one hop around the ring with `lax.ppermute` (ICI
  collective-permute). Peak memory per chip is O(S_local²) and the KV
  transfer overlaps compute under XLA's scheduler.
- **Ulysses** (DeepSpeed-Ulysses 2023): `lax.all_to_all` re-shards
  (seq-sharded, all heads) → (all seq, head-sharded), runs ordinary local
  attention, and all-to-alls back. Cheaper than a ring when
  n_heads % sp == 0 and S fits a chip.

Both are written for `jax.shard_map` bodies and are differentiable (scan +
ppermute/all_to_all have transpose rules), so `jax.grad` through a train
step produces the reversed ring the reference would have had to hand-code.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention", "ulysses_attention",
           "sequence_parallel_attention"]


def _chunk_attn(q, k, v, m, l, acc, q_off, kv_off, scale, causal, sk_valid):
    """One online-softmax accumulation of local Q against one KV chunk.

    q: (B,H,Sq,D) k/v: (B,H,Sk,D); m/l: (B,H,Sq); acc: (B,H,Sq,D) fp32.
    Positions are global: q rows start at q_off, kv cols at kv_off.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    sk = k.shape[2]
    col = kv_off + lax.broadcasted_iota(jnp.int32, s.shape, 3)
    mask = col < (kv_off + sk_valid)
    if causal:
        row = q_off + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        mask = jnp.logical_and(mask, row >= col)
    s = jnp.where(mask, s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None):
    """Ring attention over a sharded sequence axis; call inside shard_map.

    q/k/v: LOCAL shards (B, S_local, H, D) — the global sequence is
    S_local * axis_size(axis_name), shard i holding rows
    [i*S_local, (i+1)*S_local). Returns the local output shard.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    # (B,H,S,D) internally
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))

    # derive from qt so the carry is device-varying under shard_map's VMA
    # tracking (plain constants would be 'unvarying' and reject the scan)
    zero = qt[..., 0].astype(jnp.float32) * 0.0
    m0 = zero - jnp.inf
    l0 = zero
    acc0 = qt.astype(jnp.float32) * 0.0
    q_off = my * s_loc
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        kc, vc, m, l, acc = carry
        src = (my - t) % n  # who originally owned the chunk we now hold
        kv_off = src * s_loc

        def compute(args):
            m, l, acc = args
            return _chunk_attn(qt, kc, vc, m, l, acc, q_off, kv_off,
                               scale, causal, s_loc)

        if causal:
            # chunks entirely in the future contribute nothing; skip the
            # matmuls (every chip computes ~half the chunks — the same
            # total work as single-chip causal attention)
            fully_masked = kv_off > q_off + s_loc - 1
            m, l, acc = lax.cond(fully_masked, lambda a: a, compute,
                                 (m, l, acc))
        else:
            m, l, acc = compute((m, l, acc))
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (kc, vc, m, l, acc), None

    (kt, vt, m, l, acc), _ = lax.scan(
        step, (kt, vt, m0, l0, acc0), jnp.arange(n))
    out = acc / l[..., None]
    return jnp.transpose(out.astype(q.dtype), (0, 2, 1, 3))


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None, attn_fn=None):
    """Ulysses sequence parallelism: all-to-all seq-shard → head-shard, run
    full-sequence local attention on n_heads/sp heads, all-to-all back.

    q/k/v: LOCAL shards (B, S_local, H, D) with H % axis_size == 0.
    """
    n = lax.axis_size(axis_name)
    b, s_loc, h, d = q.shape
    if h % n != 0:
        raise ValueError(f"n_heads={h} not divisible by axis size {n}")
    if attn_fn is None:
        from paddle_tpu.nn.functional.attention import attention_reference
        attn_fn = functools.partial(attention_reference, is_causal=causal,
                                    scale=scale)

    def a2a(x, split, concat):
        return lax.all_to_all(x, axis_name, split_axis=split,
                              concat_axis=concat, tiled=True)

    # (B, S_loc, H, D) -> (B, S, H/n, D): split heads, gather sequence
    qg, kg, vg = (a2a(x, 2, 1) for x in (q, k, v))
    og = attn_fn(qg, kg, vg)
    # back: split sequence, gather heads
    return a2a(og, 1, 2)


def sequence_parallel_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                                causal: bool = True,
                                scale: Optional[float] = None,
                                mode: str = "ring",
                                batch_axes=("dp", "fsdp"),
                                head_axis: str = "tp"):
    """Global-view wrapper: shard_map ring/ulysses attention over `axis`.

    q/k/v: GLOBAL (B, S, H, D) arrays inside (or outside) a pjit program
    over `mesh`; the sequence axis is (re)sharded over `axis`, batch over
    `batch_axes`, heads over `head_axis`.
    """
    fn = {"ring": ring_attention, "ulysses": ulysses_attention}[mode]
    spec = P(batch_axes, axis, head_axis, None)
    body = functools.partial(fn, axis_name=axis, causal=causal, scale=scale)
    # check_vma=False: jax 0.4.37's replication checker rejects the
    # causal ring's lax.cond ("mismatched replication types") — a false
    # positive, every branch output is device-varying (both branches
    # return the per-shard online-softmax carry). The grad path hits it
    # too, so with the check on, training through ring attention fails
    # on this build. Replication is asserted EXPLICITLY instead:
    # tests/test_ring_attention.py::test_replication_explicit checks
    # every device's copy of a replicated (out_specs P()) reduction is
    # bit-identical — the property the checker would have proven.
    mapped = jax.shard_map(
        lambda q, k, v: body(q, k, v),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return mapped(q, k, v)
