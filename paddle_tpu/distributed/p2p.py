"""Eager cross-process point-to-point sends (send/recv/isend/irecv) and
object collectives.

Reference analog: paddle.distributed.{send, recv, isend, irecv, wait,
all_gather_object} over ProcessGroupNCCL p2p
(collective/ProcessGroupNCCL.cc Send/Recv + distributed/communication/).

TPU-native stance: INSIDE a jitted program, point-to-point is
``lax.ppermute`` (see collective.send_recv_ring) and XLA schedules it on
ICI. These functions are the EAGER, host-side path the reference also
has — moving a tensor between controller processes over DCN — riding the
framework's native tag-addressed P2P endpoint (native/src/p2p.cc), with
the TCPStore for rendezvous. isend/irecv return Task objects with
``wait()`` (the endpoint's reader threads make isend genuinely async;
irecv completes on first wait)."""

import io
import os
import threading
from typing import Optional

import numpy as np

__all__ = ["init_p2p", "send", "recv", "isend", "irecv", "wait",
           "all_gather_object", "destroy_process_group"]

_state = None
_lock = threading.Lock()
_TAG_KIND = 3 << 60  # distinct from fleet-executor/rpc tag spaces


class _P2PState:
    def __init__(self, rank, world, store, endpoint, peers):
        self.rank = rank
        self.world = world
        self.store = store
        self.endpoint = endpoint
        self.peers = peers
        self.send_seq = {}
        self.recv_seq = {}
        self.ago_round = 0


def init_p2p(rank: Optional[int] = None, world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None,
             host: str = "127.0.0.1", rendezvous_deadline: float = 60.0):
    """Rendezvous the eager p2p group (rank 0 hosts the store).

    Every store read runs deadline-guarded with retry/backoff
    (`resilience.store_get`): a peer that never publishes its address
    fails the rendezvous with a `DeadlineExceeded` naming its key within
    ``rendezvous_deadline`` seconds, instead of wedging the whole group
    behind one raw 60s get."""
    global _state
    from paddle_tpu import native
    from paddle_tpu.distributed import resilience

    rank = int(os.environ.get("PT_PROCESS_ID", 0)) if rank is None else rank
    world_size = int(os.environ.get("PT_NUM_PROCESSES", 1)) \
        if world_size is None else world_size
    master_endpoint = master_endpoint or os.environ.get(
        "PT_COORDINATOR", "127.0.0.1:23900")
    mhost, mport = master_endpoint.rsplit(":", 1)
    # offset: don't collide with the jax.distributed coordinator itself
    port = int(mport) + 7
    store = native.TCPStore(mhost if rank else "127.0.0.1", port,
                            is_master=(rank == 0), timeout=60.0)
    endpoint = native.P2PEndpoint()
    resilience.store_set(store, f"p2p/addr/{rank}",
                         f"{host}:{endpoint.port}".encode(),
                         op="p2p_rendezvous_set")
    peers = []
    dl = resilience.Deadline(rendezvous_deadline)
    for r in range(world_size):
        raw = resilience.store_get(
            store, f"p2p/addr/{r}",
            deadline=dl.budget(rendezvous_deadline),
            op="p2p_rendezvous_get").decode()
        h, p = raw.rsplit(":", 1)
        peers.append((h, int(p)))
    with _lock:
        _state = _P2PState(rank, world_size, store, endpoint, peers)
    return _state


def _require():
    if _state is None:
        raise RuntimeError("call distributed.init_p2p() first (or run "
                           "under the launch CLI and call init_p2p())")
    return _state


def _tag(src, dst, seq):
    return _TAG_KIND | (src << 44) | (dst << 28) | (seq & 0xFFFFFFF)


def _pack(value) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.asarray(value), allow_pickle=False)
    return buf.getvalue()


def _unpack(payload: bytes):
    return np.load(io.BytesIO(payload), allow_pickle=False)


class Task:
    """≙ the reference's distributed Task future (ProcessGroup::Task)."""

    def __init__(self, fn):
        self._result = None
        self._exc = None
        self._done = threading.Event()

        def run():
            try:
                self._result = fn()
            except BaseException as e:  # surfaced on wait()
                self._exc = e
            finally:
                self._done.set()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def wait(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("p2p task did not complete in time")
        if self._exc is not None:
            raise self._exc
        return self._result

    def is_completed(self):
        return self._done.is_set()


def _next_send_seq(st, dst):
    with _lock:
        st.send_seq[dst] = st.send_seq.get(dst, 0) + 1
        return st.send_seq[dst]


def send(tensor, dst: int, _seq=None, deadline: Optional[float] = 30.0):
    """ref: paddle.distributed.send — blocking eager send to rank dst.

    Transient connection failures (peer restarting, listen backlog full)
    retry with backoff under ``deadline``; a dropped-message fault
    (site ``p2p.send``) skips the wire write so receiver-side timeout
    recovery can be exercised deterministically."""
    import time as _time
    from paddle_tpu import stats
    from paddle_tpu.distributed import resilience
    from paddle_tpu.observability import trace
    from paddle_tpu.testing import faults
    st = _require()
    # the drop check must precede the seq claim: a dropped send that
    # consumed a sequence number would permanently desync the stream
    # (the receiver's rolled-back recv retries seq N forever while the
    # sender only ever sends N+1) — dropping BEFORE the claim models a
    # send that never happened, which the recv-timeout path can recover
    if faults.enabled() and faults.fire("p2p.send") == "drop":
        stats.add("p2p/dropped_sends")
        return
    seq = _next_send_seq(st, dst) if _seq is None else _seq
    h, p = st.peers[dst]
    with trace.span("p2p/send", dst=dst, seq=seq) as sp:
        payload = _pack(tensor)
        sp.attrs["bytes"] = len(payload)
        t0 = _time.perf_counter()
        resilience.DEFAULT_POLICY.run(
            lambda: st.endpoint.send(h, p, _tag(st.rank, dst, seq),
                                     payload),
            op="p2p_send",
            retry_on=(ConnectionError,),
            deadline=resilience.Deadline(deadline))
        stats.observe("p2p/send_s", _time.perf_counter() - t0)
    stats.add("p2p/send_msgs")              # §5.5 (≙ monitor.h STAT_ADD)
    stats.add("p2p/send_bytes", len(payload))


def recv(tensor=None, src: int = 0, timeout: float = 120.0):
    """ref: paddle.distributed.recv — blocking receive from rank src.
    Returns the received array (also copied into ``tensor`` when a numpy
    array is passed, matching the reference's out-param style)."""
    from paddle_tpu.testing import faults
    st = _require()
    if faults.enabled():
        faults.fire("p2p.recv")  # delay/raise BEFORE the seq claim
    # claim a DISTINCT seq per call (concurrent irecvs must not share a
    # tag); on timeout, roll the claim back if no later recv claimed past
    # us, so a retry still matches the sender's sequence
    import time as _time
    from paddle_tpu.observability import trace
    with _lock:
        seq = st.recv_seq.get(src, 0) + 1
        st.recv_seq[src] = seq
    t0 = _time.perf_counter()
    try:
        with trace.span("p2p/recv", src=src, seq=seq) as sp:
            payload = st.endpoint.recv(_tag(src, st.rank, seq), timeout)
            sp.attrs["bytes"] = len(payload)
    except TimeoutError:
        with _lock:
            if st.recv_seq.get(src) == seq:
                st.recv_seq[src] = seq - 1
        from paddle_tpu import stats
        stats.add("p2p/recv_timeouts")
        raise
    from paddle_tpu import stats
    stats.observe("p2p/recv_s", _time.perf_counter() - t0)
    stats.add("p2p/recv_msgs")
    stats.add("p2p/recv_bytes", len(payload))
    out = _unpack(payload)
    if tensor is not None and isinstance(tensor, np.ndarray):
        tensor[...] = out
    return out


def isend(tensor, dst: int) -> Task:
    """ref: paddle.distributed.isend — async send; wait() for completion.
    The sequence number is claimed in CALL order (not worker-thread
    order), so interleaved isend/send to one destination stay FIFO."""
    st = _require()
    value = np.asarray(tensor)  # snapshot before returning
    seq = _next_send_seq(st, dst)
    return Task(lambda: send(value, dst, _seq=seq))


def irecv(tensor=None, src: int = 0, timeout: float = 120.0) -> Task:
    """ref: paddle.distributed.irecv."""
    return Task(lambda: recv(tensor, src, timeout))


def wait(task_or_tensor, group=None, use_calc_stream=True):
    """ref: paddle.distributed.wait. For a Task, block on it. For a
    tensor, a no-op returning it: XLA's data dependencies ARE the stream
    ordering the reference's c_sync_* ops enforce by hand."""
    if isinstance(task_or_tensor, Task):
        return task_or_tensor.wait()
    return task_or_tensor


def all_gather_object(obj_list, obj, timeout: float = 120.0):
    """ref: paddle.distributed.all_gather_object — gather arbitrary
    (json-serializable) python objects from every rank through the store.
    The reference pickles over NCCL; a control-plane store exchange is
    the honest transport for objects."""
    import json

    from paddle_tpu.observability import trace

    st = _require()
    # per-call round id: a LOCAL counter — every rank calls
    # all_gather_object collectively, so local counts agree
    st.ago_round += 1
    key = st.ago_round
    with trace.span("p2p/all_gather_object", round=key):
        st.store.set(f"p2p/ago/{key}/{st.rank}",
                     json.dumps(obj).encode())
        del obj_list[:]
        for r in range(st.world):
            raw = st.store.get(f"p2p/ago/{key}/{r}", timeout=timeout)
            obj_list.append(json.loads(raw.decode()))
    return obj_list


def destroy_process_group(group=None, timeout: float = 60.0):
    """ref: paddle.distributed.destroy_process_group. Rank 0 hosts the
    store SERVER: it must not tear it down while peers are mid-request,
    so every rank posts a departure key and rank 0 waits for all of them
    (bounded) before closing — the shutdown barrier the reference gets
    from NCCL comm destruction semantics."""
    global _state
    with _lock:
        st = _state
        _state = None
    if st is None:
        return
    try:
        st.store.set(f"p2p/bye/{st.rank}", b"1")
        if st.rank == 0:
            for r in range(st.world):
                try:
                    st.store.get(f"p2p/bye/{r}", timeout=timeout)
                except TimeoutError:
                    continue  # a dead peer must not wedge shutdown,
                    # but LIVE higher ranks still deserve the barrier
    except Exception:
        pass
    try:
        st.endpoint.close()
        st.store.close()
    except Exception:
        pass
