"""Parameter server (ref: paddle/fluid/distributed/ps/ — table.h dense/
sparse tables, brpc_ps_server.cc, the fleet PS mode's push/pull protocol;
python/paddle/distributed/fleet/runtime/parameter_server_runtime.py).

TPU-native stance: synchronous data-parallel training on TPU uses mesh
collectives, not a PS — but the reference's PS also serves the workload
collectives can't: huge sparse embedding tables (recommender models) that
live CPU-side, rows pulled/pushed by id. That is what this module keeps:

- dense tables: whole-array pull / gradient push with a server-side
  optimizer (async-SGD semantics — no global barrier, ≙ the reference's
  async mode).
- sparse tables: rows partitioned across servers by ``id % n_servers``
  (≙ the reference's hash sharding), lazily initialized on first touch,
  per-row adagrad or sgd.

Transport is the RPC layer (distributed/rpc.py): handlers are module-level
functions executed in the server's rpc pool; table state lives in the
server process's ``_TABLES`` registry.

**Scale envelope (deliberate non-parity).** This is an in-memory,
single-socket-per-peer PS: tables live in server RAM, the wire is the
framework RPC over TCP, and sharding is id-hash only. The reference's
production machinery — brpc services with rpc compression, SSD-backed
tables (ssd_sparse_table), geo-async sync, heterogeneous PS
(cpu+gpu, heter_ps/), GPUPS HBM embedding caches — is out of scope
here: those exist to serve trillion-row embeddings at datacenter QPS,
which is not a TPU-training bottleneck this framework targets. The API
surface (push/pull dense+sparse, server-side optimizers) matches, so
models port; the capacity ceiling (≈ server RAM, ≈ thousands of QPS)
does not.
"""

import threading

import numpy as np

from paddle_tpu.distributed import rpc

__all__ = ["PSClient", "init_server_tables", "DenseTable", "SparseTable"]

_TABLES = {}
_TLOCK = threading.Lock()


class DenseTable:
    def __init__(self, shape, lr=0.1, optimizer="sgd", seed=0):
        rs = np.random.RandomState(seed)
        self.w = (rs.normal(size=shape) * 0.01).astype(np.float32)
        self.lr = lr
        self.optimizer = optimizer
        self.acc = np.zeros(shape, np.float32)  # adagrad accumulator
        self.lock = threading.Lock()

    def pull(self):
        with self.lock:
            return self.w.copy()

    def push(self, grad):
        grad = np.asarray(grad, np.float32)
        with self.lock:
            if self.optimizer == "adagrad":
                self.acc += grad * grad
                self.w -= self.lr * grad / (np.sqrt(self.acc) + 1e-8)
            else:
                self.w -= self.lr * grad


class SparseTable:
    """id → row, lazily initialized (≙ memory_sparse_table.cc)."""

    def __init__(self, dim, lr=0.1, optimizer="adagrad", init_std=0.01,
                 seed=0):
        self.dim = dim
        self.lr = lr
        self.optimizer = optimizer
        self.init_std = init_std
        self.seed = seed
        self.rows = {}
        self.acc = {}
        self.lock = threading.Lock()

    def _row(self, i):
        r = self.rows.get(i)
        if r is None:
            rs = np.random.RandomState((self.seed * 1000003 + i) & 0x7FFFFFFF)
            r = (rs.normal(size=(self.dim,)) * self.init_std).astype(
                np.float32)
            self.rows[i] = r
            self.acc[i] = np.zeros((self.dim,), np.float32)
        return r

    def pull(self, ids):
        with self.lock:
            return np.stack([self._row(int(i)) for i in ids])

    def push(self, ids, grads):
        grads = np.asarray(grads, np.float32)
        with self.lock:
            for i, g in zip(ids, grads):
                i = int(i)
                row = self._row(i)
                if self.optimizer == "adagrad":
                    self.acc[i] += g * g
                    row -= self.lr * g / (np.sqrt(self.acc[i]) + 1e-8)
                else:
                    row -= self.lr * g

    def size(self):
        with self.lock:
            return len(self.rows)


# -- server-side rpc handlers (module-level → picklable by reference) -------

def init_server_tables(specs):
    """Run ON the server via rpc: create tables from
    ``{name: ("dense", shape, kwargs) | ("sparse", dim, kwargs)}``."""
    with _TLOCK:
        for name, (kind, arg, kwargs) in specs.items():
            if name in _TABLES:
                continue  # idempotent: several workers declare the same job
            if kind == "dense":
                _TABLES[name] = DenseTable(arg, **kwargs)
            elif kind == "sparse":
                _TABLES[name] = SparseTable(arg, **kwargs)
            else:
                raise ValueError(kind)
    return sorted(_TABLES)


def _pull_dense(name):
    return _TABLES[name].pull()


def _push_dense(name, grad):
    _TABLES[name].push(grad)
    return True


def _pull_sparse(name, ids):
    return _TABLES[name].pull(ids)


def _push_sparse(name, ids, grads):
    _TABLES[name].push(ids, grads)
    return True


def _sparse_size(name):
    return _TABLES[name].size()


class PSClient:
    """Worker-side handle (≙ fleet PS worker's pull/push API).

    ``servers``: rpc worker names acting as parameter servers. Dense
    tables live on ``servers[hash(name) % n]``; sparse rows are
    partitioned ``id % n`` across all servers.
    """

    def __init__(self, servers):
        self.servers = list(servers)

    def _dense_home(self, name):
        # stable across processes (builtin hash is PYTHONHASHSEED-random:
        # two workers would route the same table to different servers)
        import zlib
        return self.servers[zlib.crc32(name.encode()) % len(self.servers)]

    def create_tables(self, specs):
        """specs: {name: ("dense", shape, kwargs)|("sparse", dim, kwargs)}.
        Dense specs go to their home server; sparse specs to every server
        (each holds its id-partition)."""
        per_server = {s: {} for s in self.servers}
        for name, spec in specs.items():
            if spec[0] == "dense":
                per_server[self._dense_home(name)][name] = spec
            else:
                for s in self.servers:
                    per_server[s][name] = spec
        for s, sub in per_server.items():
            if sub:
                rpc.rpc_sync(s, init_server_tables, args=(sub,))

    def pull_dense(self, name):
        from paddle_tpu import stats
        out = rpc.rpc_sync(self._dense_home(name), _pull_dense,
                           args=(name,))
        stats.add("ps/pulls")                  # §5.5 (≙ monitor.h)
        stats.add("ps/pull_bytes", np.asarray(out).nbytes)
        return out

    def push_dense(self, name, grad, block=True):
        from paddle_tpu import stats
        grad = np.asarray(grad)
        stats.add("ps/pushes")
        stats.add("ps/push_bytes", grad.nbytes)
        fut = rpc.rpc_async(self._dense_home(name), _push_dense,
                            args=(name, grad))
        return fut.wait() if block else fut

    def pull_sparse(self, name, ids):
        from paddle_tpu import stats
        stats.add("ps/pulls")
        ids = np.asarray(ids, np.int64)
        n = len(self.servers)
        out = np.empty((len(ids), 0), np.float32)
        parts = {}
        for s_idx in range(n):
            mask = (ids % n) == s_idx
            if mask.any():
                parts[s_idx] = (mask, rpc.rpc_async(
                    self.servers[s_idx], _pull_sparse,
                    args=(name, ids[mask])))
        rows = None
        for s_idx, (mask, fut) in parts.items():
            got = fut.wait(120.0)
            if rows is None:
                rows = np.zeros((len(ids), got.shape[1]), np.float32)
            rows[mask] = got
        if rows is not None:
            stats.add("ps/pull_bytes", rows.nbytes)
        return rows

    def push_sparse(self, name, ids, grads, block=True):
        from paddle_tpu import stats
        stats.add("ps/pushes")
        ids = np.asarray(ids, np.int64)
        grads = np.asarray(grads, np.float32)
        stats.add("ps/push_bytes", grads.nbytes)
        n = len(self.servers)
        futs = []
        for s_idx in range(n):
            mask = (ids % n) == s_idx
            if mask.any():
                futs.append(rpc.rpc_async(
                    self.servers[s_idx], _push_sparse,
                    args=(name, ids[mask], grads[mask])))
        if block:
            for f in futs:
                f.wait(120.0)
        return futs

    def sparse_size(self, name):
        return sum(rpc.rpc_sync(s, _sparse_size, args=(name,))
                   for s in self.servers)
