"""Parameter server (ref: paddle/fluid/distributed/ps/ — table.h dense/
sparse tables, brpc_ps_server.cc, the fleet PS mode's push/pull protocol;
python/paddle/distributed/fleet/runtime/parameter_server_runtime.py).

TPU-native stance: synchronous data-parallel training on TPU uses mesh
collectives, not a PS — but the reference's PS also serves the workload
collectives can't: huge sparse embedding tables (recommender models) that
live CPU-side, rows pulled/pushed by id. That is what this module keeps:

- dense tables: whole-array pull / gradient push with a server-side
  optimizer (async-SGD semantics — no global barrier, ≙ the reference's
  async mode).
- sparse tables: rows partitioned across servers by ``id % n_servers``
  (≙ the reference's hash sharding), lazily initialized on first touch,
  per-row adagrad or sgd.

Transport is the RPC layer (distributed/rpc.py): handlers are module-level
functions executed in the server's rpc pool; table state lives in the
server process's ``_TABLES`` registry.

Production features carried over (beyond the in-memory core):

- **Disk-backed sparse tables** (``SSDSparseTable`` ≙
  distributed/ps/table/ssd_sparse_table.cc): a bounded hot-row LRU in
  RAM, cold rows spilled to a per-table sqlite file — table capacity is
  disk, not server RAM, and the file doubles as crash persistence.
- **Geo-async SGD** (``GeoSGDClient`` ≙ fleet geo mode,
  distributed/ps/service/communicator.cc GeoCommunicator): workers train
  on local replicas and exchange accumulated parameter DELTAS every
  ``geo_step`` steps; the server sums deltas from all workers, so sync
  traffic is O(params/geo_step) and workers never block each other.
- **Table save/load** (``PSClient.save/load`` ≙ fleet
  save_persistables in PS mode): server-side snapshot of every table to
  npz, reloadable into a fresh job.

**Scale envelope (deliberate non-parity).** The wire is the framework
RPC over TCP and sharding is id-hash only. The reference's remaining
production machinery — brpc services with rpc compression,
heterogeneous PS (cpu+gpu, heter_ps/), GPUPS HBM embedding caches —
is out of scope: those serve trillion-row embeddings at datacenter QPS,
which is not a TPU-training bottleneck this framework targets. The API
surface matches, so models port; the QPS ceiling does not.
"""

import collections
import os
import sqlite3
import threading

import numpy as np

from paddle_tpu.distributed import rpc

__all__ = ["PSClient", "GeoSGDClient", "init_server_tables", "DenseTable",
           "SparseTable", "SSDSparseTable"]

_TABLES = {}
_TLOCK = threading.Lock()


class DenseTable:
    def __init__(self, shape, lr=0.1, optimizer="sgd", seed=0):
        rs = np.random.RandomState(seed)
        self.w = (rs.normal(size=shape) * 0.01).astype(np.float32)
        self.lr = lr
        self.optimizer = optimizer
        self.acc = np.zeros(shape, np.float32)  # adagrad accumulator
        self.lock = threading.Lock()

    def pull(self):
        with self.lock:
            return self.w.copy()

    def push(self, grad):
        grad = np.asarray(grad, np.float32)
        with self.lock:
            if self.optimizer == "adagrad":
                self.acc += grad * grad
                self.w -= self.lr * grad / (np.sqrt(self.acc) + 1e-8)
            else:
                self.w -= self.lr * grad

    def apply_delta(self, delta):
        """Geo-async: ``w += delta`` (worker-side optimizer already ran)."""
        with self.lock:
            self.w += np.asarray(delta, np.float32)

    def state(self):
        with self.lock:
            return {"w": self.w.copy(), "acc": self.acc.copy()}

    def load_state(self, st):
        with self.lock:
            self.w[...] = st["w"]
            self.acc[...] = st["acc"]


class SparseTable:
    """id → row, lazily initialized (≙ memory_sparse_table.cc)."""

    def __init__(self, dim, lr=0.1, optimizer="adagrad", init_std=0.01,
                 seed=0):
        self.dim = dim
        self.lr = lr
        self.optimizer = optimizer
        self.init_std = init_std
        self.seed = seed
        self.rows = {}
        self.acc = {}
        self.lock = threading.Lock()

    def _row(self, i):
        r = self.rows.get(i)
        if r is None:
            rs = np.random.RandomState((self.seed * 1000003 + i) & 0x7FFFFFFF)
            r = (rs.normal(size=(self.dim,)) * self.init_std).astype(
                np.float32)
            self.rows[i] = r
            self.acc[i] = np.zeros((self.dim,), np.float32)
        return r

    def pull(self, ids):
        with self.lock:
            return np.stack([self._row(int(i)) for i in ids])

    def push(self, ids, grads):
        grads = np.asarray(grads, np.float32)
        with self.lock:
            for i, g in zip(ids, grads):
                i = int(i)
                row = self._row(i)
                if self.optimizer == "adagrad":
                    self.acc[i] += g * g
                    row -= self.lr * g / (np.sqrt(self.acc[i]) + 1e-8)
                else:
                    row -= self.lr * g

    def size(self):
        with self.lock:
            return len(self.rows)

    def apply_delta(self, ids, deltas):
        """Geo-async: server-side ``row += delta`` (no optimizer — the
        worker already applied its optimizer locally)."""
        deltas = np.asarray(deltas, np.float32)
        with self.lock:
            for i, d in zip(ids, deltas):
                self._row(int(i))[...] += d

    def state(self):
        """Snapshot → dict of arrays (save_persistables analog)."""
        with self.lock:
            ids = np.asarray(sorted(self.rows), np.int64)
            return {"ids": ids,
                    "rows": np.stack([self.rows[int(i)] for i in ids])
                    if len(ids) else np.zeros((0, self.dim), np.float32),
                    "acc": np.stack([self.acc[int(i)] for i in ids])
                    if len(ids) else np.zeros((0, self.dim), np.float32)}

    def load_state(self, st):
        with self.lock:
            # rows absent from the snapshot reset to lazy init
            self.rows.clear()
            self.acc.clear()
            for i, r, a in zip(st["ids"], st["rows"], st["acc"]):
                self.rows[int(i)] = np.array(r, np.float32)
                self.acc[int(i)] = np.array(a, np.float32)


class SSDSparseTable(SparseTable):
    """Disk-backed sparse table: hot rows in a bounded RAM LRU, cold rows
    in a per-table sqlite file (≙ ssd_sparse_table.cc — RocksDB there;
    sqlite here for a zero-dependency store with the same contract:
    capacity = disk, RAM = cache, file = persistence).

    Same pull/push/optimizer semantics as SparseTable — eviction and
    fault-in are invisible to the protocol."""

    def __init__(self, dim, path, cache_rows=4096, **kwargs):
        super().__init__(dim, **kwargs)
        self.rows = collections.OrderedDict()   # hot LRU (id → row)
        self.cache_rows = int(cache_rows)
        self.path = path
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("CREATE TABLE IF NOT EXISTS rows ("
                         "id INTEGER PRIMARY KEY, w BLOB, acc BLOB)")
        self._db.commit()

    def _row(self, i):
        r = self.rows.get(i)
        if r is not None:
            self.rows.move_to_end(i)
            return r
        got = self._db.execute(
            "SELECT w, acc FROM rows WHERE id=?", (i,)).fetchone()
        if got is not None:  # fault the cold row in
            r = np.frombuffer(got[0], np.float32).copy()
            a = np.frombuffer(got[1], np.float32).copy()
        else:                # first touch: deterministic lazy init
            rs = np.random.RandomState(
                (self.seed * 1000003 + i) & 0x7FFFFFFF)
            r = (rs.normal(size=(self.dim,)) * self.init_std).astype(
                np.float32)
            a = np.zeros((self.dim,), np.float32)
        self.rows[i] = r
        self.acc[i] = a
        evicted = False
        while len(self.rows) > self.cache_rows:
            old, w = self.rows.popitem(last=False)
            self._write_db(old, w, self.acc.pop(old))
            evicted = True
        if evicted:
            # commit immediately: evicted rows must survive a crash (the
            # file IS the persistence story), and an open implicit
            # transaction would also lock out other connections
            self._db.commit()
        return r

    def _write_db(self, i, w, a):
        self._db.execute(
            "INSERT OR REPLACE INTO rows (id, w, acc) VALUES (?, ?, ?)",
            (i, w.tobytes(), a.tobytes()))

    def flush(self):
        """Write every hot row to disk (kept hot). Called by save and at
        any point durability is wanted."""
        with self.lock:
            for i, w in self.rows.items():
                self._write_db(i, w, self.acc[i])
            self._db.commit()

    def size(self):
        with self.lock:
            hot = set(self.rows)
            n_db = self._db.execute(
                "SELECT COUNT(*) FROM rows").fetchone()[0]
            n_db_hot = self._db.execute(
                "SELECT COUNT(*) FROM rows WHERE id IN (%s)" %
                ",".join(map(str, hot))).fetchone()[0] if hot else 0
            return n_db + len(hot) - n_db_hot

    def state(self):
        self.flush()
        with self.lock:
            got = self._db.execute(
                "SELECT id, w, acc FROM rows ORDER BY id").fetchall()
        ids = np.asarray([g[0] for g in got], np.int64)
        return {"ids": ids,
                "rows": np.stack([np.frombuffer(g[1], np.float32)
                                  for g in got])
                if len(got) else np.zeros((0, self.dim), np.float32),
                "acc": np.stack([np.frombuffer(g[2], np.float32)
                                 for g in got])
                if len(got) else np.zeros((0, self.dim), np.float32)}

    def load_state(self, st):
        with self.lock:
            # exact restore: rows absent from the snapshot reset to lazy
            # init, and the hot cache (which shadows the db on fault-in)
            # may hold rows newer than the snapshot — drop both
            self._db.execute("DELETE FROM rows")
            for i, r, a in zip(st["ids"], st["rows"], st["acc"]):
                self._write_db(int(i), np.asarray(r, np.float32),
                               np.asarray(a, np.float32))
            self._db.commit()
            self.rows.clear()
            self.acc.clear()


# -- server-side rpc handlers (module-level → picklable by reference) -------

def init_server_tables(specs):
    """Run ON the server via rpc: create tables from
    ``{name: ("dense", shape, kwargs) | ("sparse", dim, kwargs)}``."""
    with _TLOCK:
        for name, (kind, arg, kwargs) in specs.items():
            if name in _TABLES:
                continue  # idempotent: several workers declare the same job
            if kind == "dense":
                _TABLES[name] = DenseTable(arg, **kwargs)
            elif kind == "sparse":
                _TABLES[name] = SparseTable(arg, **kwargs)
            elif kind == "ssd_sparse":
                kw = dict(kwargs)
                # per-server file: several servers may share a filesystem
                kw["path"] = os.path.join(
                    kw.pop("dir"), f"{name}.{rpc.get_worker_info().name}"
                    ".sqlite")
                _TABLES[name] = SSDSparseTable(arg, **kw)
            else:
                raise ValueError(kind)
    return sorted(_TABLES)


def _pull_dense(name):
    return _TABLES[name].pull()


def _push_dense(name, grad):
    _TABLES[name].push(grad)
    return True


def _pull_sparse(name, ids):
    return _TABLES[name].pull(ids)


def _push_sparse(name, ids, grads):
    _TABLES[name].push(ids, grads)
    return True


def _sparse_size(name):
    return _TABLES[name].size()


def _apply_dense_delta(name, delta):
    _TABLES[name].apply_delta(delta)
    return True


def _apply_sparse_delta(name, ids, deltas):
    _TABLES[name].apply_delta(ids, deltas)
    return True


def _save_tables(path):
    """Snapshot every local table to ``path/<table>.<server>.npz``
    (≙ fleet.save_persistables in PS mode)."""
    me = rpc.get_worker_info().name
    os.makedirs(path, exist_ok=True)
    saved = []
    for name, t in sorted(_TABLES.items()):
        f = os.path.join(path, f"{name}.{me}.npz")
        np.savez(f, **t.state())
        saved.append(f)
    return saved


def _load_tables(path):
    me = rpc.get_worker_info().name
    loaded = []
    for name, t in sorted(_TABLES.items()):
        f = os.path.join(path, f"{name}.{me}.npz")
        if os.path.exists(f):
            t.load_state(dict(np.load(f)))
            loaded.append(f)
    return loaded


class PSClient:
    """Worker-side handle (≙ fleet PS worker's pull/push API).

    ``servers``: rpc worker names acting as parameter servers. Dense
    tables live on ``servers[hash(name) % n]``; sparse rows are
    partitioned ``id % n`` across all servers.
    """

    def __init__(self, servers):
        self.servers = list(servers)

    def _dense_home(self, name):
        # stable across processes (builtin hash is PYTHONHASHSEED-random:
        # two workers would route the same table to different servers)
        import zlib
        return self.servers[zlib.crc32(name.encode()) % len(self.servers)]

    def create_tables(self, specs):
        """specs: {name: ("dense", shape, kwargs)|("sparse", dim, kwargs)}.
        Dense specs go to their home server; sparse specs to every server
        (each holds its id-partition)."""
        per_server = {s: {} for s in self.servers}
        for name, spec in specs.items():
            if spec[0] == "dense":
                per_server[self._dense_home(name)][name] = spec
            else:
                for s in self.servers:
                    per_server[s][name] = spec
        for s, sub in per_server.items():
            if sub:
                rpc.rpc_sync(s, init_server_tables, args=(sub,))

    def pull_dense(self, name):
        from paddle_tpu import stats
        out = rpc.rpc_sync(self._dense_home(name), _pull_dense,
                           args=(name,))
        stats.add("ps/pulls")                  # §5.5 (≙ monitor.h)
        stats.add("ps/pull_bytes", np.asarray(out).nbytes)
        return out

    def push_dense(self, name, grad, block=True):
        from paddle_tpu import stats
        grad = np.asarray(grad)
        stats.add("ps/pushes")
        stats.add("ps/push_bytes", grad.nbytes)
        fut = rpc.rpc_async(self._dense_home(name), _push_dense,
                            args=(name, grad))
        return fut.wait() if block else fut

    def pull_sparse(self, name, ids):
        from paddle_tpu import stats
        stats.add("ps/pulls")
        ids = np.asarray(ids, np.int64)
        n = len(self.servers)
        out = np.empty((len(ids), 0), np.float32)
        parts = {}
        for s_idx in range(n):
            mask = (ids % n) == s_idx
            if mask.any():
                parts[s_idx] = (mask, rpc.rpc_async(
                    self.servers[s_idx], _pull_sparse,
                    args=(name, ids[mask])))
        rows = None
        for s_idx, (mask, fut) in parts.items():
            got = fut.wait(120.0)
            if rows is None:
                rows = np.zeros((len(ids), got.shape[1]), np.float32)
            rows[mask] = got
        if rows is not None:
            stats.add("ps/pull_bytes", rows.nbytes)
        return rows

    def push_sparse(self, name, ids, grads, block=True):
        from paddle_tpu import stats
        stats.add("ps/pushes")
        ids = np.asarray(ids, np.int64)
        grads = np.asarray(grads, np.float32)
        stats.add("ps/push_bytes", grads.nbytes)
        n = len(self.servers)
        futs = []
        for s_idx in range(n):
            mask = (ids % n) == s_idx
            if mask.any():
                futs.append(rpc.rpc_async(
                    self.servers[s_idx], _push_sparse,
                    args=(name, ids[mask], grads[mask])))
        if block:
            for f in futs:
                f.wait(120.0)
        return futs

    def sparse_size(self, name):
        return sum(rpc.rpc_sync(s, _sparse_size, args=(name,))
                   for s in self.servers)

    def save(self, path):
        """Snapshot every table on every server (save_persistables)."""
        return [f for s in self.servers
                for f in rpc.rpc_sync(s, _save_tables, args=(path,))]

    def load(self, path):
        return [f for s in self.servers
                for f in rpc.rpc_sync(s, _load_tables, args=(path,))]

    def apply_dense_delta(self, name, delta):
        """Geo-mode wire op: server-side ``w += delta``."""
        from paddle_tpu import stats
        delta = np.asarray(delta, np.float32)
        stats.add("ps/pushes")
        stats.add("ps/push_bytes", delta.nbytes)
        return rpc.rpc_sync(self._dense_home(name), _apply_dense_delta,
                            args=(name, delta))

    def apply_sparse_delta(self, name, ids, deltas):
        """Geo-mode wire op: ``row[id] += delta``, id-sharded like
        push_sparse (same fan-out, same counters)."""
        from paddle_tpu import stats
        ids = np.asarray(ids, np.int64)
        deltas = np.asarray(deltas, np.float32)
        stats.add("ps/pushes")
        stats.add("ps/push_bytes", deltas.nbytes)
        n = len(self.servers)
        futs = []
        for s_idx in range(n):
            mask = (ids % n) == s_idx
            if mask.any():
                futs.append(rpc.rpc_async(
                    self.servers[s_idx], _apply_sparse_delta,
                    args=(name, ids[mask], deltas[mask])))
        for f in futs:
            f.wait(120.0)


class GeoSGDClient:
    """Geo-async worker replica (≙ fleet geo mode / GeoCommunicator).

    The worker trains against LOCAL replicas; every ``geo_step`` local
    updates it ships the accumulated parameter delta to the servers
    (which just sum deltas — each worker already ran its optimizer) and
    refreshes its replica, picking up the other workers' progress.
    Between syncs nothing blocks and nothing crosses the wire.

        geo = GeoSGDClient(PSClient(servers), geo_step=16)
        w = geo.register_dense("w")          # local replica (np array)
        for step ...:
            w -= lr * grad                    # any local optimizer
            geo.step()                        # counts; syncs every 16

    Sparse replicas track touched rows only (pull-on-touch, delta-push).
    """

    def __init__(self, client: "PSClient", geo_step: int = 16):
        self.client = client
        self.geo_step = int(geo_step)
        self._dense = {}    # name → local replica
        self._dense_base = {}
        self._sparse = {}   # name → {id: row}
        self._sparse_base = {}
        self._dirty = {}    # name → ids updated since the last sync
        self._steps = 0

    def register_dense(self, name):
        w = np.asarray(self.client.pull_dense(name), np.float32)
        self._dense[name] = w
        self._dense_base[name] = w.copy()
        return w

    def pull_sparse(self, name, ids):
        """Rows from the local replica, faulting unseen ids from the
        servers."""
        cache = self._sparse.setdefault(name, {})
        base = self._sparse_base.setdefault(name, {})
        missing = [int(i) for i in np.asarray(ids).reshape(-1)
                   if int(i) not in cache]
        if missing:
            rows = self.client.pull_sparse(name, np.asarray(missing))
            for i, r in zip(missing, rows):
                cache[i] = np.array(r, np.float32)
                base[i] = cache[i].copy()
        return np.stack([cache[int(i)]
                         for i in np.asarray(ids).reshape(-1)])

    def update_sparse(self, name, ids, new_rows):
        cache = self._sparse[name]
        dirty = self._dirty.setdefault(name, set())
        for i, r in zip(np.asarray(ids).reshape(-1),
                        np.asarray(new_rows, np.float32)):
            cache[int(i)][...] = r
            dirty.add(int(i))

    def step(self):
        self._steps += 1
        if self._steps % self.geo_step == 0:
            self.sync()

    def sync(self):
        """Push deltas for rows dirtied since the last sync, then refresh
        those replicas (other workers' deltas land here). Sync traffic is
        O(dirty rows), not O(ever-touched rows); rows not touched since
        their pull stay stale until re-dirtied — standard geo staleness."""
        for name, w in self._dense.items():
            delta = w - self._dense_base[name]
            self.client.apply_dense_delta(name, delta)
            fresh = np.asarray(self.client.pull_dense(name), np.float32)
            w[...] = fresh
            self._dense_base[name] = fresh.copy()
        for name, cache in self._sparse.items():
            base = self._sparse_base[name]
            ids = np.asarray(sorted(self._dirty.get(name, ())), np.int64)
            if not len(ids):
                continue
            deltas = np.stack([cache[int(i)] - base[int(i)] for i in ids])
            self.client.apply_sparse_delta(name, ids, deltas)
            fresh = self.client.pull_sparse(name, ids)
            for i, r in zip(ids, fresh):
                cache[int(i)][...] = r
                base[int(i)] = cache[int(i)].copy()
            self._dirty[name].clear()
