"""Device-mesh topology.

Reference analog: ``CommunicateTopology``/``HybridCommunicateGroup``
(python/paddle/distributed/fleet/base/topology.py:50/:136) — the 4-D hybrid
order ["data", "pipe", "sharding", "model"] (fleet/fleet.py:406) with one
NCCL communicator per axis-group.

TPU-native: a single ``jax.sharding.Mesh`` with named axes. Collectives are
compiler-inserted from sharding annotations; axis groups need no explicit
communicators. Axis order is outermost-first so the innermost axes (tp, sp)
land on the fastest ICI links, mirroring the reference placing "model"
innermost for NVLink locality.
"""

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["SpecLayout", "LAYOUT", "mesh_safe_spec", "HybridTopology",
           "init_mesh", "get_topology", "get_mesh", "set_topology",
           "AXIS_DP", "AXIS_FSDP", "AXIS_TP", "AXIS_PP", "AXIS_SP",
           "AXIS_EP"]

# Canonical axis names (superset of the reference's 4: + sp for
# sequence/context parallelism and ep for expert parallelism, SURVEY §5.7)
AXIS_DP = "dp"          # data parallel (pure replication of params)
AXIS_FSDP = "fsdp"      # sharding axis ≙ reference "sharding" (ZeRO)
AXIS_TP = "tp"          # tensor/model parallel ≙ "model"
AXIS_PP = "pp"          # pipeline parallel ≙ "pipe"
AXIS_SP = "sp"          # sequence/context parallel (new capability)
AXIS_EP = "ep"          # expert parallel

# Outermost → innermost. pp LEADS: on a multi-host device list (host-major
# order) the outermost axis is the one that spans hosts, and pipeline
# stage boundaries move orders of magnitude fewer bytes than dp gradient
# all-reduce — so pp is the axis that can afford DCN (the reference's
# hybrid topology order pp→dp→sharding→mp, fleet/base/topology.py; the
# planner's _axis_tier DCN assignment assumes exactly this order).
_ORDER = ("pp", "dp", "fsdp", "sp", "ep", "tp")

_global_topology = None


@dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpec vocabulary over the hybrid mesh axes — the
    ONE place the repo's sharding conventions are written down (SNIPPETS
    [2] shape, extended with this repo's pp/sp/ep axes). models.gpt and
    models.bert build their PARTITION_RULES from these methods, the
    planner proposes them structurally, and auto_parallel places batches
    with them — so "column parallel" or "vocab embedding" means the same
    spec everywhere, and renaming a mesh axis is a one-line change here.

    Parameter-role methods follow the Megatron TP × ZeRO-3 convention:
    ``column()`` for expanding (d → k·d) weights, ``row()`` for
    contracting ones, with ``fsdp`` always on the non-tp dim so every
    weight is additionally ZeRO-sharded.
    """

    data_axis: str = AXIS_DP
    fsdp_axis: str = AXIS_FSDP
    tp_axis: str = AXIS_TP
    pp_axis: str = AXIS_PP
    sp_axis: str = AXIS_SP
    ep_axis: str = AXIS_EP

    # -- activations --------------------------------------------------------
    @property
    def batch_axes(self) -> Tuple[str, str]:
        """Axes the batch dim splits over (fsdp is ZeRO *data* parallel)."""
        return (self.data_axis, self.fsdp_axis)

    def activation(self, *trailing) -> P:
        """Batch-sharded activation: leading dim over (dp, fsdp), then
        the caller's trailing axes (e.g. ``activation('sp', None)``)."""
        return P(self.batch_axes, *trailing)

    # -- parameter roles ----------------------------------------------------
    def vocab_embedding(self) -> P:      # (V, d) lookup table
        return P(self.tp_axis, self.fsdp_axis)

    def vocab_head(self) -> P:           # (d, V) untied LM head
        return P(self.fsdp_axis, self.tp_axis)

    def vocab_bias(self) -> P:           # (V,) per-vocab bias
        return P(self.tp_axis)

    def position_table(self) -> P:       # (T, d) position/type tables
        return P(None, self.fsdp_axis)

    def column(self) -> P:               # expanding (d, k·d) ≙ megatron col
        return P(self.fsdp_axis, self.tp_axis)

    def column_bias(self) -> P:          # (k·d,) bias of a column layer
        return P(self.tp_axis)

    def row(self) -> P:                  # contracting (k·d, d) ≙ row
        return P(self.tp_axis, self.fsdp_axis)

    def row_bias(self) -> P:             # (d,) model-dim vector: replicate
        return P(None)

    norm = row_bias                      # LN scales/biases replicate too

    def root_linear(self) -> P:          # non-block (d, d') linear: ZeRO rows
        return P(self.fsdp_axis, None)

    def conv_filter(self) -> P:          # OIHW conv: ZeRO over out channels
        return P(self.fsdp_axis)

    def replicated(self) -> P:
        return P()

    # -- expert (MoE) roles -------------------------------------------------
    def expert_column(self) -> P:        # (E, d, k·d)
        return P(self.ep_axis, self.fsdp_axis, self.tp_axis)

    def expert_column_bias(self) -> P:   # (E, 1, k·d)
        return P(self.ep_axis, None, self.tp_axis)

    def expert_row(self) -> P:           # (E, k·d, d)
        return P(self.ep_axis, self.tp_axis, self.fsdp_axis)

    def expert_row_bias(self) -> P:      # (E, 1, d)
        return P(self.ep_axis, None, None)

    # -- derived layouts ----------------------------------------------------
    def stacked(self, spec: P, ndim: Optional[int] = None) -> P:
        """Scan-stacked variant of a per-block param spec: a leading
        REPLICATED layer axis ahead of the block rules, truncated when
        the leading axis consumed the rank budget (``ndim`` = rank of
        the stacked leaf). The layer axis itself never shards — scan
        slices it — so the per-layer fsdp/tp sharding is preserved
        verbatim on the trailing dims."""
        t = tuple(spec)
        if ndim is not None and len(t) >= ndim:
            t = t[:ndim - 1]
        return P(None, *t)

    def pipeline_stacked(self, spec: P, n_virtual: int = 1) -> P:
        """Pipeline-stacked param: (S, lps, ...) with the stage axis on
        'pp' — or (V, S, lpg, ...) interleaved, where only S shards."""
        lead = ((self.pp_axis, None) if n_virtual == 1
                else (None, self.pp_axis, None))
        return P(*(lead + tuple(spec)))


# The default layout instance every consumer shares. Axis names match the
# mesh built by init_mesh; a custom topology would install its own.
LAYOUT = SpecLayout()


def mesh_safe_spec(spec: P, mesh) -> P:
    """Drop axes the mesh does not define (e.g. 'fsdp' on a bare
    ('tp',) Mesh) — the spec then replicates over the missing axis
    instead of NamedSharding raising."""
    names = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in names)
            return kept if len(kept) > 1 else (kept[0] if kept else None)
        return entry if entry in names else None

    return P(*(keep(a) for a in tuple(spec)))


@dataclass
class HybridTopology:
    """≙ HybridCommunicateGroup: holds the Mesh plus per-axis degrees."""

    mesh: Mesh
    degrees: Dict[str, int]

    # -- reference-parity accessors (topology.py:136 surface) -----------------
    def get_data_parallel_world_size(self):
        return self.degrees.get("dp", 1) * self.degrees.get("fsdp", 1)

    def get_model_parallel_world_size(self):
        return self.degrees.get("tp", 1)

    def get_pipe_parallel_world_size(self):
        return self.degrees.get("pp", 1)

    def get_sharding_parallel_world_size(self):
        return self.degrees.get("fsdp", 1)

    def get_sequence_parallel_world_size(self):
        return self.degrees.get("sp", 1)

    def get_expert_parallel_world_size(self):
        return self.degrees.get("ep", 1)

    @property
    def axis_names(self):
        return self.mesh.axis_names

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


def init_mesh(dp: int = 1, tp: int = 1, pp: int = 1, fsdp: int = 1,
              sp: int = 1, ep: int = 1,
              devices: Optional[Sequence] = None,
              set_global: bool = True) -> HybridTopology:
    """Build the hybrid mesh (≙ fleet.init(strategy.hybrid_configs)).

    Degrees of 1 are kept as size-1 mesh axes so sharding specs can always
    name every axis; XLA elides trivial axes at compile time.
    """
    devices = list(devices if devices is not None else jax.devices())
    degrees = {"dp": dp, "pp": pp, "fsdp": fsdp, "sp": sp, "ep": ep,
               "tp": tp}
    total = int(np.prod(list(degrees.values())))
    if total != len(devices):
        raise ValueError(
            f"mesh degrees {degrees} (= {total}) != device count "
            f"{len(devices)}")
    shape = tuple(degrees[a] for a in _ORDER)
    arr = np.asarray(devices).reshape(shape)
    mesh = Mesh(arr, _ORDER)
    topo = HybridTopology(mesh=mesh, degrees=degrees)
    if set_global:
        global _global_topology
        _global_topology = topo
    return topo


def get_topology() -> Optional[HybridTopology]:
    return _global_topology


def get_mesh() -> Optional[Mesh]:
    return _global_topology.mesh if _global_topology else None


def set_topology(topo: HybridTopology):
    global _global_topology
    _global_topology = topo
