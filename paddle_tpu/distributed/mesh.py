"""Device-mesh topology.

Reference analog: ``CommunicateTopology``/``HybridCommunicateGroup``
(python/paddle/distributed/fleet/base/topology.py:50/:136) — the 4-D hybrid
order ["data", "pipe", "sharding", "model"] (fleet/fleet.py:406) with one
NCCL communicator per axis-group.

TPU-native: a single ``jax.sharding.Mesh`` with named axes. Collectives are
compiler-inserted from sharding annotations; axis groups need no explicit
communicators. Axis order is outermost-first so the innermost axes (tp, sp)
land on the fastest ICI links, mirroring the reference placing "model"
innermost for NVLink locality.
"""

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names (superset of the reference's 4: + sp for
# sequence/context parallelism and ep for expert parallelism, SURVEY §5.7)
AXIS_DP = "dp"          # data parallel (pure replication of params)
AXIS_FSDP = "fsdp"      # sharding axis ≙ reference "sharding" (ZeRO)
AXIS_TP = "tp"          # tensor/model parallel ≙ "model"
AXIS_PP = "pp"          # pipeline parallel ≙ "pipe"
AXIS_SP = "sp"          # sequence/context parallel (new capability)
AXIS_EP = "ep"          # expert parallel

# Outermost → innermost. pp LEADS: on a multi-host device list (host-major
# order) the outermost axis is the one that spans hosts, and pipeline
# stage boundaries move orders of magnitude fewer bytes than dp gradient
# all-reduce — so pp is the axis that can afford DCN (the reference's
# hybrid topology order pp→dp→sharding→mp, fleet/base/topology.py; the
# planner's _axis_tier DCN assignment assumes exactly this order).
_ORDER = ("pp", "dp", "fsdp", "sp", "ep", "tp")

_global_topology = None


@dataclass
class HybridTopology:
    """≙ HybridCommunicateGroup: holds the Mesh plus per-axis degrees."""

    mesh: Mesh
    degrees: Dict[str, int]

    # -- reference-parity accessors (topology.py:136 surface) -----------------
    def get_data_parallel_world_size(self):
        return self.degrees.get("dp", 1) * self.degrees.get("fsdp", 1)

    def get_model_parallel_world_size(self):
        return self.degrees.get("tp", 1)

    def get_pipe_parallel_world_size(self):
        return self.degrees.get("pp", 1)

    def get_sharding_parallel_world_size(self):
        return self.degrees.get("fsdp", 1)

    def get_sequence_parallel_world_size(self):
        return self.degrees.get("sp", 1)

    def get_expert_parallel_world_size(self):
        return self.degrees.get("ep", 1)

    @property
    def axis_names(self):
        return self.mesh.axis_names

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


def init_mesh(dp: int = 1, tp: int = 1, pp: int = 1, fsdp: int = 1,
              sp: int = 1, ep: int = 1,
              devices: Optional[Sequence] = None,
              set_global: bool = True) -> HybridTopology:
    """Build the hybrid mesh (≙ fleet.init(strategy.hybrid_configs)).

    Degrees of 1 are kept as size-1 mesh axes so sharding specs can always
    name every axis; XLA elides trivial axes at compile time.
    """
    devices = list(devices if devices is not None else jax.devices())
    degrees = {"dp": dp, "pp": pp, "fsdp": fsdp, "sp": sp, "ep": ep,
               "tp": tp}
    total = int(np.prod(list(degrees.values())))
    if total != len(devices):
        raise ValueError(
            f"mesh degrees {degrees} (= {total}) != device count "
            f"{len(devices)}")
    shape = tuple(degrees[a] for a in _ORDER)
    arr = np.asarray(devices).reshape(shape)
    mesh = Mesh(arr, _ORDER)
    topo = HybridTopology(mesh=mesh, degrees=degrees)
    if set_global:
        global _global_topology
        _global_topology = topo
    return topo


def get_topology() -> Optional[HybridTopology]:
    return _global_topology


def get_mesh() -> Optional[Mesh]:
    return _global_topology.mesh if _global_topology else None


def set_topology(topo: HybridTopology):
    global _global_topology
    _global_topology = topo
