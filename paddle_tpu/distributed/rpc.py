"""RPC framework (ref: python/paddle/distributed/rpc/rpc.py — init_rpc:73,
rpc_sync:141, rpc_async:179, shutdown:270, get_worker_info:299; C++ side
paddle/fluid/distributed/rpc/rpc_agent.cc over brpc).

TPU-native re-design: the transport is the same native P2P endpoint the
pipeline runtime uses (native/src/p2p.cc) instead of brpc; rendezvous goes
through the native TCPStore. Calls are pickled (fn, args, kwargs) — like
the reference, which ships cloudpickled callables between trusted trainer
processes — executed on a small server-side thread pool, results pickled
back. Mailboxes: one well-known request tag per rank, and ONE response
mailbox per rank whose payloads carry the sequence number — the response
loop does a single blocking recv and routes by seq, so latency does not
scale with the number of pending futures, and a timed-out call's late
reply (unknown seq) is simply dropped.
"""

import pickle
import threading
import traceback
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "WorkerInfo"]

_REQ_TAG = 1 << 60
_RESP_TAG = 1 << 61  # ONE response mailbox per rank; payload carries seq

_state = None
_lock = threading.Lock()


@dataclass
class WorkerInfo:
    name: str
    rank: int
    host: str
    port: int


class _Future:
    def __init__(self):
        self._ev = threading.Event()
        self._value = None
        self._exc = None
        self._on_timeout = None

    def _set(self, value=None, exc=None):
        self._value, self._exc = value, exc
        self._ev.set()

    def wait(self, timeout=None):
        if not self._ev.wait(timeout):
            if self._on_timeout is not None:
                self._on_timeout()  # unregister so the table can't leak
            raise TimeoutError("rpc future timed out")
        if self._exc is not None:
            raise self._exc
        return self._value

    def done(self):
        return self._ev.is_set()


class _RpcState:
    def __init__(self, name, rank, world_size, store, endpoint, workers):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.store = store
        self.endpoint = endpoint
        self.workers = workers            # rank -> WorkerInfo
        self.by_name = {w.name: w for w in workers.values()}
        self.seq = 0
        self.seq_lock = threading.Lock()
        self.futures = {}                 # seq -> _Future
        self.fut_lock = threading.Lock()
        self.stopping = threading.Event()
        self.pool = ThreadPoolExecutor(max_workers=4,
                                       thread_name_prefix="rpc-exec")
        self.req_thread = threading.Thread(target=self._serve_requests,
                                           daemon=True)
        self.resp_thread = threading.Thread(target=self._serve_responses,
                                            daemon=True)
        self.req_thread.start()
        self.resp_thread.start()

    # -- server side --------------------------------------------------------

    def _serve_requests(self):
        while not self.stopping.is_set():
            try:
                payload = self.endpoint.recv(_REQ_TAG, timeout=0.25)
            except TimeoutError:
                continue
            except Exception:
                if self.stopping.is_set():
                    return
                continue
            self.pool.submit(self._handle, payload)

    def _handle(self, payload):
        src, seq, fn, args, kwargs = pickle.loads(payload)
        try:
            result = (seq, True, fn(*args, **(kwargs or {})))
        except Exception as e:  # ship the failure back, not a hang
            result = (seq, False, (e, traceback.format_exc()))
        peer = self.workers[src]
        try:
            self.endpoint.send(peer.host, peer.port, _RESP_TAG,
                               pickle.dumps(result))
        except Exception:
            pass  # caller's timeout handles a dead peer

    # -- client side --------------------------------------------------------

    def _serve_responses(self):
        # single response mailbox: one blocking recv serves ALL pending
        # futures (no per-tag poll loop); a seq no longer in the table is
        # a timed-out call's late reply — dropped
        while not self.stopping.is_set():
            try:
                payload = self.endpoint.recv(_RESP_TAG, timeout=0.25)
            except TimeoutError:
                continue
            except Exception:
                if self.stopping.is_set():
                    return
                continue
            seq, ok, value = pickle.loads(payload)
            with self.fut_lock:
                fut = self.futures.pop(seq, None)
            if fut is None:
                continue
            if ok:
                fut._set(value=value)
            else:
                exc, tb = value
                exc.args = (f"{exc}\n[remote traceback]\n{tb}",)
                fut._set(exc=exc)

    def _discard(self, seq):
        with self.fut_lock:
            self.futures.pop(seq, None)

    def call(self, to, fn, args, kwargs, timeout):
        info = self.by_name.get(to)
        if info is None:
            raise ValueError(f"unknown worker {to!r}; known: "
                             f"{sorted(self.by_name)}")
        with self.seq_lock:
            seq = self.seq
            self.seq = (self.seq + 1) & 0xFFFFFF
        fut = _Future()
        # timed-out futures must not leak: wait() calls this back
        fut._on_timeout = lambda: self._discard(seq)
        with self.fut_lock:
            self.futures[seq] = fut
        self.endpoint.send(
            info.host, info.port, _REQ_TAG,
            pickle.dumps((self.rank, seq, fn, args or (), kwargs)))
        return fut

    def close(self):
        self.stopping.set()
        self.req_thread.join(timeout=2)
        self.resp_thread.join(timeout=2)
        self.pool.shutdown(wait=False)
        self.endpoint.close()


def init_rpc(name, rank=None, world_size=None, master_endpoint=None,
             host: str = "127.0.0.1"):
    """Join the RPC group (≙ rpc.init_rpc:73). ``master_endpoint`` is
    "host:port" of the TCPStore master (rank 0 starts it in-process)."""
    global _state
    import os
    from paddle_tpu import native
    rank = int(os.environ.get("PT_RANK", 0)) if rank is None else rank
    world_size = int(os.environ.get("PT_WORLD_SIZE", 1)) \
        if world_size is None else world_size
    master_endpoint = master_endpoint or os.environ.get(
        "PT_MASTER", "127.0.0.1:23750")
    mhost, mport = master_endpoint.rsplit(":", 1)
    store = native.TCPStore(mhost, int(mport), is_master=(rank == 0),
                            timeout=60.0)
    endpoint = native.P2PEndpoint()
    store.set(f"rpc/addr/{rank}", f"{name}|{host}:{endpoint.port}".encode())
    workers = {}
    for r in range(world_size):
        raw = store.get(f"rpc/addr/{r}", timeout=60.0).decode()
        wname, addr = raw.split("|", 1)
        whost, wport = addr.rsplit(":", 1)
        workers[r] = WorkerInfo(wname, r, whost, int(wport))
    names = [w.name for w in workers.values()]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate rpc worker names: {names}")
    with _lock:
        if _state is not None:
            raise RuntimeError("init_rpc called twice")
        _state = _RpcState(name, rank, world_size, store, endpoint, workers)
    return _state


def _require_state():
    if _state is None:
        raise RuntimeError("call init_rpc first")
    return _state


def rpc_sync(to, fn, args=None, kwargs=None, timeout=120.0):
    """Blocking remote call (≙ rpc_sync:141)."""
    return _require_state().call(to, fn, args, kwargs, timeout).wait(timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=120.0):
    """Returns a Future with ``.wait()`` / ``.done()`` (≙ rpc_async:179)."""
    return _require_state().call(to, fn, args, kwargs, timeout)


def get_worker_info(name=None):
    """(≙ get_worker_info:299) — by name, or this worker when None."""
    st = _require_state()
    return st.by_name[name] if name is not None else st.workers[st.rank]


def shutdown():
    """Barrier then teardown (≙ shutdown:270): every rank checks in; the
    group dissolves only when all have (so no rank drops requests still
    in flight from a slower peer)."""
    global _state
    st = _state
    if st is None:
        return
    n = st.store.add("rpc/shutdown", 1)
    deadline = 60.0
    import time
    waited = 0.0
    while n < st.world_size and waited < deadline:
        time.sleep(0.05)
        waited += 0.05
        n = st.store.add("rpc/shutdown", 0)
    st.close()
    st.store.close()
    _state = None
