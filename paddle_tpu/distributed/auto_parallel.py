"""One-call auto-parallel training: ``Engine(module, loss, opt).fit(data)``.

Reference analog: python/paddle/distributed/auto_parallel/engine.py:58 —
the Engine that wraps plan → parallelize → fit into one object (`_plan`
at :618 invokes the Planner/tuner, `_parallel` at :646 applies the
distributed passes, `fit` at :749 runs the loop). The TPU re-design is
thinner because the heavy machinery dissolved: planning is
`planner.suggest_mesh` (cost-ranked degree search), parallelization is
sharded `device_put` + GSPMD (no graph passes), and the train step is one
jitted SPMD program.

    eng = Engine(model, loss=my_loss, optimizer=AdamW(1e-3))
    eng.fit(loader, epochs=2)          # plans the mesh on first batch
    eng.evaluate(val_loader)

The plan can be pinned via ``DistributedStrategy.hybrid_configs`` (any
explicit degree > 1 skips the search — the reference's semi-auto mode).
"""

import time
from typing import Any, Callable, Dict, Iterable, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["Engine"]


def _as_xy(batch):
    if isinstance(batch, (tuple, list)):
        if len(batch) == 2:
            return batch[0], batch[1]
        if len(batch) == 1:
            return batch[0], batch[0]
    return batch, batch   # LM convention: labels are the inputs


class Engine:
    """Plan, parallelize, and train a Module in one object
    (≙ auto_parallel.engine.Engine:58).

    Args:
      module: a paddle_tpu Module; called as ``module(x)``.
      loss: ``loss(outputs, y) -> scalar``; defaults to
        ``models.gpt.lm_loss`` semantics when the module is a GPT.
      optimizer: a paddle_tpu optimizer (``init``/``update`` protocol).
      strategy: fleet.DistributedStrategy; explicit hybrid degrees > 1
        pin the plan, otherwise the planner searches (suggest_mesh).
      hbm_bytes / max_pp / n_hosts: forwarded to the planner search.
    """

    def __init__(self, module, loss: Optional[Callable] = None,
                 optimizer=None, strategy=None,
                 hbm_bytes: float = 16e9, max_pp: int = 1,
                 n_hosts: int = 1):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        self.module = module.tag_paths() if hasattr(module, "tag_paths") \
            else module
        self.loss = loss or self._default_loss()
        self.optimizer = optimizer
        self.strategy = strategy or DistributedStrategy()
        self.hbm_bytes = hbm_bytes
        self.max_pp = max_pp
        self.n_hosts = n_hosts
        self.degrees: Optional[Dict[str, int]] = None
        self.mesh = None
        self._params = None
        self._buffers = None
        self._opt_state = None
        self._step = None
        self._eval = None
        self.history = {"loss": []}

    def _default_loss(self):
        from paddle_tpu.models import gpt
        if isinstance(self.module, gpt.GPT):
            return lambda logits, y: gpt.lm_loss(logits, y)
        raise ValueError("Engine needs an explicit loss= for this module")

    # -- plan (≙ engine.py _plan:618) ---------------------------------------

    def _plan(self, sample_x) -> Dict[str, int]:
        hc = dict(self.strategy.hybrid_configs)
        explicit = {"dp": hc.get("dp_degree", 1),
                    "tp": hc.get("mp_degree", 1),
                    "pp": hc.get("pp_degree", 1),
                    "fsdp": hc.get("sharding_degree", 1)}
        if any(v and v > 1 for v in explicit.values()):
            degrees = {k: (v if v and v > 1 else 1)
                       for k, v in explicit.items()}
            # fleet.init semantics: dp absorbs the devices the explicit
            # degrees don't cover (a partial pin like mp_degree=4 on 8
            # devices means dp=2, not a mesh-size error mid-fit)
            world = 1
            for v in degrees.values():
                world *= v
            n = len(jax.devices())
            if n % world:
                raise ValueError(
                    f"pinned degrees {degrees} (= {world}) do not divide "
                    f"the device count {n}")
            degrees["dp"] *= n // world
            return degrees
        from paddle_tpu.distributed.planner import suggest_mesh
        # np.shape reads metadata without copying — the old
        # np.asarray(sample_x).shape forced a full device→host transfer
        # of the sample batch just to count its tokens (ptlint PT001)
        xshape = np.shape(sample_x)
        tokens = (int(np.prod(xshape[:2])) if len(xshape) >= 2
                  else int(np.prod(xshape)))
        # 6·N·tokens step-FLOPs estimate: without it the cost model sees
        # zero compute, which disables the grad-sync overlap credit and
        # skews the search toward needless tp
        n_params = sum(int(v.size)
                       for _, v in self.module.named_parameters())
        return suggest_mesh(self.module, len(jax.devices()),
                            hbm_bytes=self.hbm_bytes, max_pp=self.max_pp,
                            n_hosts=self.n_hosts, tokens_per_step=tokens,
                            flops_per_step=6.0 * n_params * tokens)

    # -- parallelize (≙ engine.py _parallel:646) ----------------------------

    def _parallel(self, degrees: Dict[str, int]):
        from paddle_tpu.distributed import mesh as mesh_lib
        from paddle_tpu.distributed.planner import plan_module
        if degrees.get("pp", 1) > 1:
            # honest failure beats silently replicating blocks across the
            # pp axis (which would also void the planner's 1/pp memory
            # credit): Engine v1 executes dp/fsdp/tp plans; pipeline runs
            # go through gpt.pipelined_apply / FleetExecutor
            raise NotImplementedError(
                f"plan {degrees} needs pipeline execution — Engine "
                "executes dp/fsdp/tp plans; use the GPT pipeline path or "
                "FleetExecutor for pp")
        axes = {k: degrees.get(k, 1) for k in ("dp", "tp", "pp", "fsdp")}
        topo = mesh_lib.init_mesh(**axes)
        self.mesh = topo.mesh
        self.degrees = degrees
        plan = plan_module(self.module, mesh=self.mesh)
        params, buffers = self.module.split_params()
        self._params = {
            n: jax.device_put(v, NamedSharding(self.mesh,
                                               plan.get(n, P())))
            for n, v in params.items()}
        self._buffers = {
            n: jax.device_put(v, NamedSharding(self.mesh, P()))
            for n, v in buffers.items()}
        if self.optimizer is not None:
            self._opt_state = self.optimizer.init(self._params)

        module, loss_fn, opt = self.module, self.loss, self.optimizer

        def loss_of(p, x, y, rng):
            m = module.merge_params({**self._buffers, **p})
            try:
                out = m(x, rng_key=rng)
            except TypeError:
                out = m(x)
            return loss_fn(out, y)

        def step(p, opt_state, x, y, rng):
            loss, grads = jax.value_and_grad(loss_of)(p, x, y, rng)
            new_p, new_state = opt.update(grads, opt_state, p)
            return new_p, new_state, loss

        self._step = jax.jit(step, donate_argnums=(0, 1))
        self._eval = jax.jit(loss_of)

    def prepare(self, sample_batch):
        """Plan the mesh from a sample batch and compile the hybrid step —
        fit() calls this lazily on its first batch."""
        x, _ = _as_xy(sample_batch)
        self._parallel(self._plan(x))
        return self.degrees

    # -- fit (≙ engine.py fit:749) ------------------------------------------

    def _place_batch(self, a):
        from paddle_tpu.distributed.mesh import LAYOUT
        a = jnp.asarray(a)
        shape = dict(self.mesh.shape)
        kept, prod = [], 1
        # the batch dim splits over the canonical data axes (dp, fsdp) —
        # the same SpecLayout vocabulary the models' activation
        # constraints use, so operand and constraint shardings agree
        for ax in LAYOUT.batch_axes:
            deg = shape.get(ax, 1)
            # divisibility is against the PRODUCT of kept axes — checking
            # each axis alone admits dp*fsdp > batch
            if deg > 1 and a.shape[0] % (prod * deg) == 0:
                kept.append(ax)
                prod *= deg
        spec = P(tuple(kept) if kept else None)
        return jax.device_put(a, NamedSharding(self.mesh, spec))

    def fit(self, train_data: Iterable, epochs: int = 1,
            log_freq: int = 0, rng_seed: int = 0) -> Dict[str, Any]:
        """Train over ``train_data`` (iterable of x or (x, y) batches);
        plans + parallelizes on the first batch. Returns the history."""
        if self.optimizer is None:
            raise ValueError("Engine.fit needs an optimizer")
        rng = jax.random.PRNGKey(rng_seed)
        for epoch in range(epochs):
            for i, batch in enumerate(train_data):
                x, y = _as_xy(batch)
                if self._step is None:
                    self.prepare(batch)
                rng, k = jax.random.split(rng)
                xb, yb = self._place_batch(x), self._place_batch(y)
                self._params, self._opt_state, loss = self._step(
                    self._params, self._opt_state, xb, yb, k)
                self.history["loss"].append(float(loss))
                if log_freq and i % log_freq == 0:
                    print(f"[engine] epoch {epoch} step {i} "
                          f"loss {float(loss):.4f} plan {self.degrees}",
                          flush=True)
        return self.history

    def evaluate(self, data: Iterable) -> float:
        """Mean loss over a dataset with the trained (sharded) params.
        Safe for one-shot iterators: the batch used for lazy prepare()
        still counts toward the mean."""
        import itertools
        it = iter(data)
        first = next(it, None)
        if first is None:
            return float("nan")
        if self._step is None:
            self.prepare(first)
        rng = jax.random.PRNGKey(0)
        losses = []
        for batch in itertools.chain([first], it):
            x, y = _as_xy(batch)
            losses.append(float(self._eval(
                self._params, self._place_batch(x), self._place_batch(y),
                rng)))
        return float(np.mean(losses))

    def state_dict(self):
        return dict(self._params or {})
