"""paddle_tpu.distributed (ref: python/paddle/distributed/ — 101.6k LoC).

Layer map (SURVEY §5.8 mapping):
  ProcessGroup/NCCL        → collective.py (jax.lax collectives over mesh axes)
  TCPStore/gen_comm_id     → env.init_parallel_env (jax coordination service)
  HybridCommunicateGroup   → mesh.init_mesh (named-axis jax Mesh)
  fleet meta_parallel      → fleet/ (TP layers, sharding, pipeline, MoE)
  launch CLI               → launch.py
  auto_parallel            → GSPMD itself; shard/reshard helpers in api.py
"""

from paddle_tpu.distributed import env
from paddle_tpu.distributed.env import (init_parallel_env, get_rank,
                                        get_world_size, ParallelEnv,
                                        is_initialized)
from paddle_tpu.distributed import mesh
from paddle_tpu.distributed.spawn import spawn, ProcessContext
from paddle_tpu.distributed.mesh import (init_mesh, get_mesh, get_topology,
                                         HybridTopology, SpecLayout,
                                         LAYOUT, mesh_safe_spec)
from paddle_tpu.distributed import collective
from paddle_tpu.distributed.collective import (
    Group, new_group, get_group, group_reduce, group_all_gather,
    ReduceOp, all_reduce, all_gather, all_to_all, reduce_scatter, broadcast,
    psum, pmean, pmax, pmin, ppermute, barrier, send_recv_ring,
    alltoall, alltoall_single, reduce, scatter, split, ParallelMode,
    stream)
from paddle_tpu.distributed.api import (shard_tensor, shard_module,
                                        reshard, replicate)
from paddle_tpu.distributed.ring_attention import (
    ring_attention, ulysses_attention, sequence_parallel_attention)
from paddle_tpu.distributed.sharding import (
    group_sharded_parallel, group_sharded_specs, build_group_sharded_step,
    init_group_sharded_state, GroupShardedSpecs)
from paddle_tpu.distributed.checkpoint import (
    save_state, load_state, load_resharded, verify_checkpoint,
    AutoCheckpoint)
from paddle_tpu.distributed import resilience
from paddle_tpu.distributed.resilience import (
    RetryPolicy, Deadline, DeadlineExceeded, CollectiveStallError,
    CollectiveWatchdog, with_deadline)
from paddle_tpu.distributed.mp_ops import (
    parallel_cross_entropy, vocab_parallel_embedding, axis_rng_key)
from paddle_tpu.distributed.recompute import (
    recompute, recompute_sequential, checkpoint_name)
from paddle_tpu.distributed.fleet_executor import (
    FleetExecutor, rendezvous_endpoints)
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import rpc
from paddle_tpu.distributed import ps
from paddle_tpu.distributed import p2p
from paddle_tpu.distributed.p2p import (
    init_p2p, send, recv, isend, irecv, wait, all_gather_object,
    destroy_process_group)
from paddle_tpu.distributed.dataset import (
    InMemoryDataset, QueueDataset, CountFilterEntry, ProbabilityEntry,
    ShowClickEntry)
from paddle_tpu.distributed import launch as launch_module
launch = launch_module  # ref: paddle.distributed.launch (module)
from paddle_tpu.distributed import auto_parallel
from paddle_tpu.distributed.auto_parallel import Engine
from paddle_tpu.distributed import compression
from paddle_tpu.distributed import overlap
from paddle_tpu.distributed.overlap import (
    overlap_parallel, build_overlap_step, overlap_group_specs,
    partition_buckets)
# gloo_* shims: the reference's CPU-barrier plane; the TCPStore covers it
def gloo_init_parallel_env(*a, **k):
    return None
def gloo_barrier(*a, **k):
    return None
def gloo_release(*a, **k):
    return None
from paddle_tpu.native import TCPStore  # ≙ fluid.core.TCPStore (C++)

__all__ = ["FleetExecutor", "rendezvous_endpoints", "rpc", "ps", "fleet",
           "env", "mesh", "collective", "init_parallel_env", "spawn", "ProcessContext", "get_rank",
           "get_world_size", "ParallelEnv", "is_initialized", "init_mesh",
           "get_mesh", "get_topology", "HybridTopology", "ReduceOp",
           "all_reduce", "all_gather", "all_to_all", "reduce_scatter",
           "broadcast", "psum", "pmean", "pmax", "pmin", "ppermute",
           "barrier", "send_recv_ring", "Group", "new_group", "get_group",
           "group_reduce", "group_all_gather", "shard_tensor", "shard_module",
           "reshard", "replicate", "ring_attention", "ulysses_attention",
           "sequence_parallel_attention", "group_sharded_parallel",
           "group_sharded_specs", "build_group_sharded_step",
           "init_group_sharded_state", "GroupShardedSpecs", "save_state",
           "load_state", "verify_checkpoint", "AutoCheckpoint", "TCPStore",
           "resilience", "RetryPolicy", "Deadline", "DeadlineExceeded",
           "CollectiveStallError", "CollectiveWatchdog", "with_deadline",
           "parallel_cross_entropy", "vocab_parallel_embedding",
           "axis_rng_key", "recompute", "recompute_sequential",
           "checkpoint_name", "alltoall", "alltoall_single", "reduce",
           "scatter", "split", "ParallelMode", "stream", "p2p", "init_p2p",
           "send", "recv", "isend", "irecv", "wait", "all_gather_object",
           "destroy_process_group", "InMemoryDataset", "QueueDataset",
           "CountFilterEntry", "ProbabilityEntry", "ShowClickEntry",
           "launch", "gloo_init_parallel_env", "gloo_barrier",
           "gloo_release", "overlap", "overlap_parallel",
           "build_overlap_step", "overlap_group_specs",
           "partition_buckets"]
