"""Multi-process / multi-host job launcher CLI.

Reference analog: ``python -m paddle.distributed.launch``
(launch/main.py:18 → CollectiveController controllers/collective.py:21):
build the env contract per rank, spawn workers, tail logs, watch children,
relaunch on failure (elastic, ≙ CollectiveElasticController :184 /
ElasticManager fleet/elastic/manager.py:128 — etcd replaced by the native
TCPStore).

Usage:
    python -m paddle_tpu.distributed.launch \
        --nproc_per_node 1 --nnodes 2 --node_rank 0 \
        --master 10.0.0.1:8765 train.py --lr 1e-4

Env contract written for each worker (read by env.init_parallel_env):
    PT_COORDINATOR     jax.distributed coordinator "host:port"
    PT_NUM_PROCESSES   total worker processes across nodes
    PT_PROCESS_ID      global rank of this worker
    PT_LOCAL_RANK      rank within this node
    PT_NNODES          node count

Observability contract (docs/observability.md): with PT_TRACE_DIR set
on the launcher, each worker gets PT_TRACE_FILE =
``$PT_TRACE_DIR/trace_rank{rank}.json`` and on exit the launcher merges
every rank file into ``trace_merged.json`` — one Perfetto timeline with
a lane per rank. With PT_STATSZ_PORT set, worker rank r serves its live
statsz on ``base + 1 + r`` (the launcher itself holds ``base``), so a
node's whole worker group is scrapeable from adjacent ports.
"""

import argparse
import os
import signal
import subprocess
import sys
import threading
import time

__all__ = ["launch", "main"]

ELASTIC_EXIT_CODE = 101  # ≙ fleet/elastic/manager.py:32


def _obs_env(rank):
    """Per-rank observability env (module docstring: per-rank trace
    file; statsz at base + 1 + rank so worker 0 never collides with the
    launcher's own server on base)."""
    out = {}
    tdir = os.environ.get("PT_TRACE_DIR")
    if tdir:
        out["PT_TRACE_FILE"] = os.path.join(
            tdir, f"trace_rank{rank}.json")
    base = os.environ.get("PT_STATSZ_PORT")
    if base:
        try:
            out["PT_STATSZ_PORT"] = str(int(base) + 1 + rank)
        except ValueError:
            pass
    return out


def _merge_traces_on_exit():
    """Fold every rank's trace file in PT_TRACE_DIR into ONE Perfetto
    timeline (trace_merged.json, rank → pid lane). Runs after the
    worker group exits; a worker that died before exporting simply
    contributes no lane — merging must never mask the job's own exit
    code, so failures only warn."""
    tdir = os.environ.get("PT_TRACE_DIR")
    if not tdir:
        return
    try:
        from paddle_tpu.observability import merge
        out = merge.merge_rank_traces(tdir)
        if out:
            print(f"[launch] merged rank traces -> {out}",
                  file=sys.stderr)
        # serving traces carry per-request trace contexts (args.rid):
        # also emit the stitched per-request timeline. A training job's
        # traces have no rids — stitch_rank_traces then writes nothing
        stitched = merge.stitch_rank_traces(tdir)
        if stitched:
            print(f"[launch] stitched request timeline -> {stitched}",
                  file=sys.stderr)
    except Exception as e:
        print(f"[launch] trace merge failed: {e}", file=sys.stderr)


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch a (multi-host) training job")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--nnodes", default="1",
                   help="node count, or MIN:MAX for an elastic range "
                        "(≙ the reference's --np 2:4): the job runs with "
                        "whatever node count inside the range announces "
                        "each membership round, so late nodes can JOIN")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--master", default="127.0.0.1:8765",
                   help="host:port of the jax.distributed coordinator "
                        "(process 0)")
    p.add_argument("--log_dir", default=None,
                   help="write per-rank workerlog.N files here")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="relaunch the local group this many times on "
                        "worker failure (elastic)")
    p.add_argument("--elastic", action="store_true",
                   help="membership-changing mode: on worker failure the "
                        "job RE-FORMS at the surviving world size (ranks "
                        "reassigned via the TCPStore registry) instead of "
                        "restarting at the same size")
    p.add_argument("--elastic_grace", type=float, default=1.0,
                   help="seconds the master waits for straggler nodes "
                        "when forming a membership round")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    lo, _, hi = str(args.nnodes).partition(":")
    try:
        args.nnodes_min = int(lo)
        args.nnodes_max = int(hi) if hi else args.nnodes_min
    except ValueError:
        p.error(f"--nnodes must be N or MIN:MAX, got {args.nnodes!r}")
    if not 1 <= args.nnodes_min <= args.nnodes_max:
        p.error(f"--nnodes range must satisfy 1 <= MIN <= MAX, "
                f"got {args.nnodes!r}")
    args.nnodes = args.nnodes_max
    return args


def _spawn(args, local_rank, rank=None, world=None, extra_env=None):
    if world is None:
        world = args.nnodes * args.nproc_per_node
    if rank is None:
        rank = args.node_rank * args.nproc_per_node + local_rank
    env = dict(os.environ)
    env.update({
        "PT_COORDINATOR": args.master,
        "PT_NUM_PROCESSES": str(world),
        "PT_PROCESS_ID": str(rank),
        "PT_LOCAL_RANK": str(local_rank),
        "PT_NNODES": str(args.nnodes),
    })
    env.update(_obs_env(rank))
    if extra_env:
        env.update(extra_env)
    cmd = [sys.executable, args.training_script,
           *args.training_script_args]
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        logf = open(os.path.join(args.log_dir, f"workerlog.{rank}"), "ab")
        stdout = stderr = logf
    elif local_rank == 0:
        logf = None
        stdout = stderr = None  # inherit: rank 0 streams to console
    else:
        logf = open(os.devnull, "wb")
        stdout = stderr = logf
    proc = subprocess.Popen(cmd, env=env, stdout=stdout, stderr=stderr,
                            start_new_session=True)
    proc._pt_logf = logf
    proc._pt_rank = rank
    return proc


def _kill_group(procs):
    for p in procs:
        if p.poll() is None:
            try:
                os.killpg(p.pid, signal.SIGTERM)
                # mark launcher-inflicted SIGTERMs the same way as the
                # SIGKILL escalation below: the reshape survivor count
                # must distinguish a healthy group-kill casualty from a
                # worker an EXTERNAL supervisor signaled (preemption)
                p._pt_launcher_terminated = True
            except ProcessLookupError:
                pass
    deadline = time.time() + 5
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
                # mark the escalation: a LAUNCHER-inflicted SIGKILL (a
                # healthy worker blocked past the SIGTERM grace, e.g.
                # mid-collective) must not read as an external
                # preemption to the reshape survivor count
                p._pt_launcher_killed = True
            except ProcessLookupError:
                pass
            # reap the escalated child: without this its returncode
            # stays None and the marker above is never consulted by
            # the reshape classification (and the child stays a
            # zombie until the Popen is collected)
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
    for p in procs:
        if p._pt_logf:
            p._pt_logf.close()


def _watch(procs, poll_s=0.2, should_abort=None, coalesce_s=0.0):
    """Block until all exit 0 (return 0) or any fails (kill rest, return
    its code). ≙ ControllerBase.watch (launch/controllers/controller.py:34).
    ``should_abort()`` (elastic): polled each tick; truthy → kill the
    group and return REFORM_RC (another node asked for a re-form).
    ``coalesce_s`` (reshape accounting): a preemption reclaims several
    workers near-simultaneously, so after the first failure wait this
    long for the co-failures to land before killing the group —
    otherwise the group SIGTERM races a sibling's own exit and the
    survivor count reads one casualty as healthy."""
    while True:
        alive = False
        rc_fail = None
        for p in procs:
            rc = p.poll()
            if rc is None:
                alive = True
            elif rc != 0 and rc_fail is None:
                rc_fail = rc
        if rc_fail is not None:
            if coalesce_s > 0 and alive:
                deadline = time.monotonic() + coalesce_s
                while (time.monotonic() < deadline
                       and any(p.poll() is None for p in procs)):
                    time.sleep(0.05)
            _kill_group(procs)
            return rc_fail
        if not alive:
            return 0
        if should_abort is not None and should_abort():
            _kill_group(procs)
            return REFORM_RC
        time.sleep(poll_s)


REFORM_RC = -1000  # internal: group killed because membership changed


def _launch_elastic(args):
    """Membership-changing controller (≙ CollectiveElasticController,
    launch/controllers/collective.py:184, with the etcd master replaced by
    ElasticRegistry on the native TCPStore).

    Round protocol: the master announces round v on ``elastic/round``;
    every node publishes its alive worker count for v; the master forms
    the rank table; every node (re)launches its local group with the
    assigned global ranks and the NEW world size. A worker failure on any
    node bumps ``elastic/reform``, which aborts every group and starts
    round v+1 with the failed workers removed — N→N−1 re-formation, not
    same-size restart (VERDICT r2 item 5)."""
    from paddle_tpu import native
    from paddle_tpu.distributed.elastic import ElasticRegistry

    host, port = args.master.rsplit(":", 1)
    reg_port = int(port) + 1
    is_master = args.node_rank == 0
    store = (native.TCPStore("127.0.0.1", reg_port, is_master=True)
             if is_master else native.TCPStore(host, reg_port))
    reg = ElasticRegistry(store, args.node_rank, is_master=is_master)
    n_local = args.nproc_per_node
    version = 0
    attempt = 0
    reform_seen = 0
    # pure reshape requests (every local worker exited ELASTIC_EXIT_CODE)
    # don't burn the restart budget, so a deterministically recurring
    # re-form (e.g. a peer that wedges the same way every generation)
    # needs its own bound or the launcher loops forever
    pure_reforms = 0
    join_attempts = 0
    try:
        while True:
            version += 1
            if is_master:
                store.set("elastic/round", str(version))
            else:
                while True:
                    v = int(store.get("elastic/round", timeout=60.0))
                    if v >= version:
                        version = v
                        break
                    time.sleep(0.1)
            reg.publish(version, n_local)
            try:
                if is_master:
                    reg.form_table(version, args.nnodes,
                                   grace=args.elastic_grace,
                                   nnodes_min=args.nnodes_min)
                table, world = reg.wait_table(version)
            except TimeoutError as e:
                # below-minimum membership (a node is late) is a WAIT
                # state, not a crash: announce the next round and keep
                # trying — the elastic semantics (≙ manager.py's watch
                # loop idling until min nodes register)
                print(f"[launch] round {version} incomplete ({e}); "
                      f"retrying", file=sys.stderr)
                time.sleep(1.0)
                continue
            if args.node_rank in table:
                join_attempts = 0  # an established member re-earns its
                # join budget for any later re-form race
            if args.node_rank not in table:
                if not is_master and n_local > 0:
                    # late JOINER (≙ manager.py:128 node-join watch): the
                    # round's table was formed before this node announced;
                    # ask the cluster to re-form and try the next round
                    join_attempts += 1
                    if join_attempts <= 3:
                        from paddle_tpu import stats
                        stats.add("launch/join_requests")
                        print(f"[launch] node {args.node_rank} joining: "
                              f"requesting re-form after round {version}",
                              file=sys.stderr)
                        reform_seen = store.add("elastic/reform", 1)
                        time.sleep(0.5)
                        continue
                    # the node never made it into a table: exiting 0 would
                    # read as success to the operator's orchestration
                    print(f"[launch] node {args.node_rank} failed to join "
                          f"after {join_attempts - 1} re-form requests",
                          file=sys.stderr)
                    return 1
                if not is_master:
                    store.set(f"elastic/done/{version}/{args.node_rank}", "1")
                    return 0  # dropped from membership; nothing to run
                # the master hosts the registry server: even with zero
                # local workers it must coordinate until the surviving
                # nodes finish (or drive the next re-form round)
                if not table:
                    return 1
                status, reform_seen = _master_wait_members(
                    store, table, version, reform_seen)
                if status == "reform":
                    continue
                return 0
            start, n = table[args.node_rank]
            from paddle_tpu import stats
            stats.add("launch/rounds")       # round 1 = form, 2+ = re-forms
            stats.add("launch/reforms", 1 if stats.get("launch/rounds") > 1
                      else 0)
            stats.set_value("launch/world_size", world)
            print(f"[launch] elastic round {version}: world={world} "
                  f"local={n} start_rank={start}", file=sys.stderr)
            procs = [_spawn(args, i, rank=start + i, world=world,
                            extra_env={"PT_ELASTIC_VERSION": str(version),
                                       "PT_RESTART_ATTEMPT": str(attempt)})
                     for i in range(n)]

            def reform_requested():
                nonlocal reform_seen
                try:
                    c = native.decode_counter(
                        store.get("elastic/reform", timeout=0.2))
                except (TimeoutError, ValueError):
                    return False
                if c > reform_seen:
                    reform_seen = c
                    return True
                return False

            rc = _watch(procs, should_abort=reform_requested)
            if rc == 0:
                store.set(f"elastic/done/{version}/{args.node_rank}", "1")
                if is_master:
                    # keep the registry alive for surviving members; if
                    # one of them asks for a re-form, keep coordinating
                    # with zero local workers
                    status, reform_seen = _master_wait_members(
                        store, table, version, reform_seen)
                    if status == "reform":
                        n_local = 0
                        continue
                return 0
            if rc != REFORM_RC:
                # local failure: shrink membership and ask the cluster to
                # re-form. Only LOCAL failures consume the restart budget;
                # a healthy node aborted by a peer's re-form request must
                # not burn its own budget (it did nothing wrong). A worker
                # exiting ELASTIC_EXIT_CODE is a SURVIVOR asking for a
                # re-form (ElasticManager saw a remote peer die) — it is
                # not a local failure and must not shrink the local count.
                n_failed = sum(1 for p in procs
                               if (p.returncode or 0) > 0
                               and p.returncode != ELASTIC_EXIT_CODE)
                n_reshape = sum(1 for p in procs
                                if p.returncode == ELASTIC_EXIT_CODE)
                if n_failed == 0 and n_reshape > 0:
                    pure_reforms += 1
                    if pure_reforms > max(8, 4 * args.max_restarts):
                        return rc
                    reform_seen = store.add("elastic/reform", 1)
                else:
                    attempt += 1
                    n_local = n - max(1, n_failed)
                    reform_seen = store.add("elastic/reform", 1)
                    if n_local <= 0 and args.nnodes == 1:
                        return rc
                    if attempt > args.max_restarts:
                        return rc
            print(f"[launch] re-forming after rc={rc}; attempt "
                  f"{attempt}/{args.max_restarts}", file=sys.stderr)
    finally:
        store.close()


def _master_wait_members(store, table, version, reform_seen,
                         timeout=600.0):
    """The master's launcher hosts the registry server in-process: it must
    outlive every member node's round, or survivors lose their control
    plane mid-job. Blocks until each member posts its done key — or a
    member requests a re-form (returns ("reform", counter) so the master
    loop can drive the next round even with zero local workers)."""
    from paddle_tpu import native

    deadline = time.time() + timeout
    pending = set(table)
    while pending and time.time() < deadline:
        for node in list(pending):
            try:
                store.get(f"elastic/done/{version}/{node}", timeout=0.2)
                pending.discard(node)
            except TimeoutError:
                pass
        try:
            c = native.decode_counter(
                        store.get("elastic/reform", timeout=0.2))
            if c > reform_seen:
                return ("reform", c)
        except (TimeoutError, ValueError):
            pass
    return ("done", reform_seen)


def launch(argv):
    args = _parse(argv)
    tdir = os.environ.get("PT_TRACE_DIR")
    if tdir:
        # the launcher itself has no PT_PROCESS_ID: its atexit export
        # would land on trace_rank0.json and clobber worker 0's file —
        # repoint it to a launcher-named lane file
        from paddle_tpu.observability import trace as _trace
        _trace._TRACER.out_path = os.path.join(tdir,
                                               "trace_launcher.json")
        # a reused trace dir must not leak a previous (possibly larger)
        # run's rank files into this run's merge as ghost lanes
        import glob as _glob
        for stale in _glob.glob(os.path.join(tdir, "trace_rank*.json")):
            try:
                os.remove(stale)
            except OSError:
                pass
    try:
        if args.elastic:
            return _launch_elastic(args)
        return _launch_static(args)
    finally:
        _merge_traces_on_exit()


def _launch_static(args):
    attempt = 0
    nproc = args.nproc_per_node
    # PT_ELASTIC_RESHAPE=1: the --max_restarts relaunch becomes a local
    # RESHAPE — the group relaunches at the SURVIVING worker count (the
    # failed workers removed) and every worker sees the NEW world size /
    # membership through the standard PT_NUM_PROCESSES / PT_PROCESS_ID
    # contract (until this knob, PT_RESTART_ATTEMPT was the relaunch
    # path's only contract and the world size silently stayed stale).
    # Training scripts built on fleet/elastic_train re-plan their mesh
    # from the new size and restore_resharded onto it. Multi-node
    # membership changes are the --elastic controller's job; this knob
    # covers the single-node preemption (a worker OOM-killed or
    # preempted) without a registry round.
    reshape = (os.environ.get("PT_ELASTIC_RESHAPE", "0") != "0"
               and args.nnodes == 1)
    pure_reforms = 0
    # relaunch generation, exported as PT_RESTART_ATTEMPT: EVERY
    # relaunch — budget-burning failure or pure reshape re-form — must
    # read as a resume to the workers ("attempt 1+ restores"), so this
    # is decoupled from `attempt`, which only counts failures against
    # --max_restarts
    gen = 0
    while True:
        # PT_RESTART_ATTEMPT is the auto-resume contract: workers (re)started
        # by the same launcher see which attempt they are, so training
        # scripts unconditionally AutoCheckpoint.restore() and attempt 1+
        # resumes from the last VERIFIED checkpoint with no operator action
        world = args.nnodes * nproc
        procs = [_spawn(args, i,
                        rank=args.node_rank * nproc + i, world=world,
                        extra_env={"PT_RESTART_ATTEMPT": str(gen)})
                 for i in range(nproc)]
        rc = _watch(procs, coalesce_s=1.0 if reshape else 0.0)
        if rc == 0:
            return 0
        gen += 1
        from paddle_tpu import stats
        if reshape:
            # shrink to the survivors: workers that exited on their own
            # (rc > 0) or were signaled from OUTSIDE (preemption via
            # SIGKILL or SIGTERM, crash via SIGSEGV/SIGABRT, ...) are
            # the failures; workers the launcher itself SIGTERMed —
            # or had to escalate to SIGKILL — during the group kill
            # (both marked in _kill_group) were healthy casualties
            # whatever code they exited with (a SIGTERM handler may
            # clean up and sys.exit(1)). ELASTIC_EXIT_CODE exits are
            # reshape REQUESTS, not failures (the requester rejoins
            # the relaunch).
            n_failed = sum(
                1 for p in procs
                if p.returncode != ELASTIC_EXIT_CODE
                and (p.returncode or 0) != 0
                and not getattr(p, "_pt_launcher_killed", False)
                and not getattr(p, "_pt_launcher_terminated", False))
            n_reshape = sum(1 for p in procs
                            if p.returncode == ELASTIC_EXIT_CODE)
            if n_failed == 0 and n_reshape > 0:
                # pure reshape request: relaunch at the SAME size (the
                # requesting survivors rejoin) and burn NO restart
                # budget — nothing actually failed, matching the
                # elastic path's accounting. Bounded separately so a
                # deterministically recurring request can't loop the
                # launcher forever.
                pure_reforms += 1
                if pure_reforms > max(8, 4 * args.max_restarts):
                    return rc
                print(f"[launch] re-forming same-size group after "
                      f"reshape request (rc={rc})", file=sys.stderr)
                stats.add("launch/restarts")
                continue
            new = max(1, nproc - max(1, n_failed))
            if new != nproc:
                stats.add("launch/reshapes")
                stats.set_value("launch/world_size", args.nnodes * new)
                print(f"[launch] reshaping local group {nproc}->{new} "
                      f"workers after rc={rc}", file=sys.stderr)
                nproc = new
        attempt += 1
        if attempt > args.max_restarts:
            return rc
        stats.add("launch/restarts")
        print(f"[launch] worker failed rc={rc}; restart "
              f"{attempt}/{args.max_restarts}", file=sys.stderr)


def main():
    sys.exit(launch(sys.argv[1:]))


if __name__ == "__main__":
    main()
