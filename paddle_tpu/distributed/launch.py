"""Multi-process / multi-host job launcher CLI.

Reference analog: ``python -m paddle.distributed.launch``
(launch/main.py:18 → CollectiveController controllers/collective.py:21):
build the env contract per rank, spawn workers, tail logs, watch children,
relaunch on failure (elastic, ≙ CollectiveElasticController :184 /
ElasticManager fleet/elastic/manager.py:128 — etcd replaced by the native
TCPStore).

Usage:
    python -m paddle_tpu.distributed.launch \
        --nproc_per_node 1 --nnodes 2 --node_rank 0 \
        --master 10.0.0.1:8765 train.py --lr 1e-4

Env contract written for each worker (read by env.init_parallel_env):
    PT_COORDINATOR     jax.distributed coordinator "host:port"
    PT_NUM_PROCESSES   total worker processes across nodes
    PT_PROCESS_ID      global rank of this worker
    PT_LOCAL_RANK      rank within this node
    PT_NNODES          node count
"""

import argparse
import os
import signal
import subprocess
import sys
import threading
import time

__all__ = ["launch", "main"]

ELASTIC_EXIT_CODE = 101  # ≙ fleet/elastic/manager.py:32


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch a (multi-host) training job")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--master", default="127.0.0.1:8765",
                   help="host:port of the jax.distributed coordinator "
                        "(process 0)")
    p.add_argument("--log_dir", default=None,
                   help="write per-rank workerlog.N files here")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="relaunch the local group this many times on "
                        "worker failure (elastic)")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _spawn(args, local_rank):
    world = args.nnodes * args.nproc_per_node
    rank = args.node_rank * args.nproc_per_node + local_rank
    env = dict(os.environ)
    env.update({
        "PT_COORDINATOR": args.master,
        "PT_NUM_PROCESSES": str(world),
        "PT_PROCESS_ID": str(rank),
        "PT_LOCAL_RANK": str(local_rank),
        "PT_NNODES": str(args.nnodes),
    })
    cmd = [sys.executable, args.training_script,
           *args.training_script_args]
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        logf = open(os.path.join(args.log_dir, f"workerlog.{rank}"), "ab")
        stdout = stderr = logf
    elif local_rank == 0:
        logf = None
        stdout = stderr = None  # inherit: rank 0 streams to console
    else:
        logf = open(os.devnull, "wb")
        stdout = stderr = logf
    proc = subprocess.Popen(cmd, env=env, stdout=stdout, stderr=stderr,
                            start_new_session=True)
    proc._pt_logf = logf
    proc._pt_rank = rank
    return proc


def _kill_group(procs):
    for p in procs:
        if p.poll() is None:
            try:
                os.killpg(p.pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
    deadline = time.time() + 5
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
    for p in procs:
        if p._pt_logf:
            p._pt_logf.close()


def _watch(procs, poll_s=0.2):
    """Block until all exit 0 (return 0) or any fails (kill rest, return
    its code). ≙ ControllerBase.watch (launch/controllers/controller.py:34)."""
    while True:
        alive = False
        for p in procs:
            rc = p.poll()
            if rc is None:
                alive = True
            elif rc != 0:
                _kill_group(procs)
                return rc
        if not alive:
            return 0
        time.sleep(poll_s)


def launch(argv):
    args = _parse(argv)
    attempt = 0
    while True:
        procs = [_spawn(args, i) for i in range(args.nproc_per_node)]
        rc = _watch(procs)
        if rc == 0:
            return 0
        attempt += 1
        if attempt > args.max_restarts:
            return rc
        print(f"[launch] worker failed rc={rc}; restart "
              f"{attempt}/{args.max_restarts}", file=sys.stderr)


def main():
    sys.exit(launch(sys.argv[1:]))


if __name__ == "__main__":
    main()
