"""Block-scaled quantized wire formats for the training collectives.

Reference analog: the DGC / local-SGD meta-optimizer family
(python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py,
paddle/fluid/operators/dgc_op.cc) — compress the gradient exchange when
the data-parallel axis rides a slow link — rebuilt EQuARX-style
(PAPERS: "EQuARX: Efficient Quantized AllReduce in XLA"): the wire
carries narrow dtypes END TO END, never a widened accumulator.

Wire formats (``method``):

- ``bf16``: payloads cross the axis as bfloat16 — 2x volume cut.
- ``int8``: block-scaled symmetric int8 — one fp32 scale per ``block``
  values (default 256), payload ``round(v / scale)`` clipped to ±127.
- ``fp8``: block-scaled float8-e4m3 (``dtypes.py``'s fp8 family),
  ``scale = amax / 448`` so the block maximum lands on e4m3's largest
  finite value.

At block 256 the int8/fp8 wire moves ``(256 + 4) / 1024`` of the fp32
bytes — a ~3.94x cut, metered by ``comm/bytes_wire`` vs
``comm/bytes_logical`` (collective.quantized_wire).

The collective itself is the part the legacy formulation got wrong: a
``psum`` of int8 payloads upcasts to int32 on the wire (XLA must widen
to accumulate), so its advertised 4x cut was really ~1x. The quantized
mean here is expressed as

- **all-gather of narrow payloads + local dequant-reduce** (small
  tensors): each replica quantizes with its OWN block scales, gathers
  payload+scales, and reduces in local fp32 registers; and
- **quantized all-to-all reduce-scatter → quantized all-gather** (large
  tensors, ``two_shot_min``): payload chunks ride all-to-all, each rank
  dequant-reduces its chunk, requantizes the result, and the reduced
  chunks ride a second narrow all-gather — the EQuARX two-shot shape,
  O(size/N) per-rank wire instead of O(size).

The legacy psum formulation is kept, unchanged, as the tested parity
reference behind ``PT_COMM_QUANT_PSUM=1`` (:func:`compressed_psum_mean`).

**Error feedback** (the residual accumulation DGC calls "momentum
correction"): each replica carries ``ef = (g + ef) - Q(g + ef)`` to the
next step, so quantization error accumulates into later updates instead
of biasing the trajectory — the property the convergence-parity test
pins down. The two-shot path additionally assigns each chunk's
second-stage (requantization) residual to the chunk's owner rank, so the
total compensation stays exact.

**Fail-loud wire guard**: every quantized exchange validates the
gathered scales and the dequantized result in-graph (finite, and inside
an envelope agreed via a pmax). A corrupted block SCALE — the fault
injector's ``collective.quant_payload`` site bitflips one in the
compiled program — poisons the synced gradients and the loss to NaN on
EVERY rank instead of silently steering the model, and the step wrapper
raises when fault injection is active. A flipped PAYLOAD byte stays a
valid in-envelope code the guard cannot distinguish from honest data;
its damage is bounded by the block's own scale (≤ ``amax`` per
element), which the payload-bitflip test pins — the guard's guarantee
is scale integrity, not payload integrity.

When to use: dp/fsdp axes that ride DCN (multi-host) — see
``planner.comm_quant_policy`` / :func:`resolve_comm_quant` and the
``PT_COMM_QUANT`` / ``PT_COMM_BLOCK`` knobs. On ICI the collectives are
rarely the bottleneck and full-precision sync is the default.
"""

import os
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

__all__ = ["compressed_psum_mean", "compressed_mean_allgather",
           "build_compressed_dp_step", "init_error_feedback",
           "quantize_blocks", "dequantize_blocks",
           "quantized_all_gather_dequant", "quantized_reduce_scatter_mean",
           "quantized_bucket_reduce_scatter",
           "resolve_comm_quant", "DEFAULT_BLOCK"]

_METHODS = ("bf16", "int8", "fp8")
_PSUM_METHODS = ("bf16", "int8")
DEFAULT_BLOCK = 256
_FAULT_SITE = "collective.quant_payload"


def _check_method(method: str, allowed=_METHODS):
    if method not in allowed:
        raise ValueError(f"grad_compression must be one of {allowed}, "
                         f"got {method!r}")


def _env_block(block: Optional[int]) -> int:
    if block is not None:
        # ptlint: disable=PT001 -- block is a static Python config knob
        return int(block)
    return int(os.environ.get("PT_COMM_BLOCK", str(DEFAULT_BLOCK)))


def _wire(method: str):
    """(wire dtype, qmax) of a block-scaled format (dtypes.py family)."""
    from paddle_tpu import dtypes
    if method == "int8":
        return dtypes.int8, 127.0
    if method == "fp8":
        # e4m3: widest mantissa; 448 = its largest finite value
        return dtypes.float8_e4m3, 448.0
    raise ValueError(f"no block-scaled wire format for {method!r}")


# ---------------------------------------------------------------------------
# Block-scaled quantization (the wire codec)
# ---------------------------------------------------------------------------

def quantize_blocks(v, method: str, block: int):
    """Flatten ``v`` (any shape, fp32) into ``block``-sized blocks and
    quantize each with its own symmetric scale. Returns
    ``(payload (nb, block) wire-dtype, scales (nb, 1) fp32, n)`` where
    ``n`` is the unpadded element count (the tail block zero-pads)."""
    dt, qmax = _wire(method)
    flat = v.astype(jnp.float32).reshape(-1)
    n = flat.size
    # a leaf smaller than one block must not pad UP to it (a 32-elem
    # bias at block 256 would put 8x fp32 volume on the wire): clamp to
    # the leaf — one scale for the whole tiny tensor
    block = max(1, min(block, n))
    nb = -(-n // block)
    flat = jnp.pad(flat, (0, nb * block - n))
    blocks = flat.reshape(nb, block)
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scales = amax / qmax + 1e-30
    if method == "int8":
        payload = jnp.clip(jnp.round(blocks / scales),
                           -qmax, qmax).astype(dt)
    else:
        payload = (blocks / scales).astype(dt)
    return payload, scales, n


def dequantize_blocks(payload, scales, n: int, shape):
    """Inverse of :func:`quantize_blocks` (fp32 out)."""
    deq = payload.astype(jnp.float32) * scales
    return deq.reshape(-1)[:n].reshape(shape)


def _inject_wire_fault(payload, scales):
    """Payload fault site ``collective.quant_payload``: a matching
    ``bitflip`` rule flips one bit of a block scale (``target=scale``,
    the default — ``bit`` of the fp32 word at flat ``offset``) or of a
    payload byte (``target=payload``) IN-GRAPH, between quantization and
    the wire. Consulted at trace time (see testing/faults.py): the
    corruption is baked into the compiled step, which is exactly the
    persistent-corruption scenario the wire guard must catch."""
    from paddle_tpu.testing import faults
    if not faults.enabled():
        return payload, scales
    for kw in faults.spec(_FAULT_SITE, actions=("bitflip",)):
        off = int(kw.get("offset", 0))
        bit = int(kw.get("bit", 30))
        if str(kw.get("target", "scale")) == "payload":
            bits = lax.bitcast_convert_type(
                payload.reshape(-1), jnp.uint8)
            word = bits[off % bits.size] ^ jnp.uint8(1 << (bit % 8))
            bits = bits.at[off % bits.size].set(word)
            payload = lax.bitcast_convert_type(
                bits, payload.dtype).reshape(payload.shape)
        else:
            flat = lax.bitcast_convert_type(
                scales.reshape(-1), jnp.uint32)
            word = flat[off % flat.size] ^ jnp.uint32(1 << (bit % 32))
            flat = flat.at[off % flat.size].set(word)
            scales = lax.bitcast_convert_type(
                flat, jnp.float32).reshape(scales.shape)
    return payload, scales


def _wire_ok(deq, scales_g, vmax_axis):
    """Fail-loud validation of one dequantized exchange: every gathered
    scale finite, every dequantized value finite and inside the envelope
    the pre-quantization maxima (pmax-agreed) allow. Identical on all
    ranks — computed from gathered data."""
    fin = jnp.all(jnp.isfinite(scales_g)) & jnp.all(jnp.isfinite(deq))
    bound = jnp.max(jnp.abs(deq)) <= 4.0 * vmax_axis + 1e-6
    return fin & bound


# ---------------------------------------------------------------------------
# The quantized collectives (must run inside shard_map over `axis`)
# ---------------------------------------------------------------------------

def _mean_one_shot(v, axis: str, method: str, block: int,
                   vmax_axis=None):
    """all-gather of narrow payload+scales, local dequant-reduce.
    Returns (mean fp32, own-dequant fp32, ok). ``vmax_axis`` is the
    pmax-agreed |v| maximum for the guard envelope — pass it when the
    caller batches the pmax over many leaves (one scalar collective per
    step instead of one per leaf); None issues a per-leaf pmax."""
    from paddle_tpu.distributed import collective as coll
    N = lax.axis_size(axis)
    if method == "bf16":
        q = v.astype(jnp.bfloat16)
        with coll.quantized_wire(4 * v.size):
            g = coll.all_gather(q.reshape(1, -1), axis, tiled_axis=0)
        mean = g.astype(jnp.float32).mean(0).reshape(v.shape)
        return mean, q.astype(jnp.float32), jnp.bool_(True)
    payload, scales, n = quantize_blocks(v, method, block)
    payload, scales = _inject_wire_fault(payload, scales)
    nb, blk = payload.shape            # blk: block clamped to tiny leaves
    with coll.quantized_wire(4 * v.size):
        pg = coll.all_gather(payload, axis, tiled_axis=0)   # (N*nb, blk)
        sg = coll.all_gather(scales, axis, tiled_axis=0)    # (N*nb, 1)
    deq = (pg.astype(jnp.float32) * sg).reshape(N, nb * blk)
    mean = deq.mean(0)[:n].reshape(v.shape)
    own = dequantize_blocks(payload, scales, n, v.shape)
    vmax = vmax_axis if vmax_axis is not None else \
        lax.pmax(jnp.max(jnp.abs(v)), axis)
    return mean, own, _wire_ok(mean, sg, vmax)


def _mean_two_shot(v, axis: str, method: str, block: int,
                   vmax_axis=None):
    """Quantized reduce-scatter (payload all-to-all + local dequant-
    reduce) then quantized all-gather of the reduced chunks — the EQuARX
    two-shot wire. Returns (mean fp32, ef-residual fp32, ok); unlike the
    one-shot, the residual includes the owner-assigned second-stage term,
    so callers use it directly instead of ``v - own``."""
    from paddle_tpu.distributed import collective as coll
    N = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    flat = v.astype(jnp.float32).reshape(-1)
    n = flat.size
    nbc = -(-n // (N * block))            # blocks per chunk
    padded = N * nbc * block
    flat = jnp.pad(flat, (0, padded - n))
    payload, scales, _ = quantize_blocks(flat, method, block)
    payload, scales = _inject_wire_fault(payload, scales)
    with coll.quantized_wire(4 * v.size + 4 * (padded // N)):
        # reduce-scatter leg: rank r receives every rank's chunk-r blocks
        pr = coll.all_to_all(payload, axis, split_axis=0, concat_axis=0)
        sr = coll.all_to_all(scales, axis, split_axis=0, concat_axis=0)
        deq = (pr.astype(jnp.float32) * sr).reshape(N, nbc * block)
        reduced = deq.sum(0)              # my chunk, sum over ranks
        p2, s2, _ = quantize_blocks(reduced, method, block)
        # all-gather leg: the narrow reduced chunks
        p2g = coll.all_gather(p2, axis, tiled_axis=0)   # (N*nbc, block)
        s2g = coll.all_gather(s2, axis, tiled_axis=0)
    total = (p2g.astype(jnp.float32) * s2g).reshape(-1)[:n]
    mean = (total / N).reshape(v.shape)
    # error feedback, exact in total: stage 1 (own quantization error,
    # everywhere) + stage 2 (requantization error of MY chunk, owner-
    # assigned in sum space — next step it re-enters the sum once)
    own = (payload.astype(jnp.float32) * scales).reshape(-1)
    resid = flat - own
    r2 = reduced - (p2.astype(jnp.float32) * s2).reshape(-1)
    resid = lax.dynamic_update_slice(
        resid, r2 + lax.dynamic_slice(resid, (idx * nbc * block,),
                                      (nbc * block,)),
        (idx * nbc * block,))
    ef = resid[:n].reshape(v.shape)
    vmax = vmax_axis if vmax_axis is not None else \
        lax.pmax(jnp.max(jnp.abs(v)), axis)
    ok = _wire_ok(mean, sr, vmax) & _wire_ok(mean, s2g, vmax)
    return mean, ef, ok


def compressed_mean_allgather(grads, ef, axis: str, method: str,
                              block: Optional[int] = None,
                              two_shot_min: int = 1 << 16):
    """Quantized mean over ``axis`` with a narrow wire end to end.

    Must be called INSIDE a shard_map/pmap context where ``axis`` is a
    bound mesh axis. ``grads`` are this replica's local gradients, ``ef``
    the replica's residual from the previous step (same pytree). Leaves
    with ``size >= two_shot_min`` take the two-shot (reduce-scatter →
    all-gather) wire; smaller leaves take the single gather.
    Returns ``(mean_grads fp32, new_ef, ok)`` — ``ok`` is the combined
    fail-loud wire-guard verdict, identical on every rank.
    """
    _check_method(method)
    block = _env_block(block)

    flat_g, tree = jax.tree_util.tree_flatten(grads)
    flat_e = jax.tree_util.tree_leaves(ef)
    vs = [g.astype(jnp.float32) + e for g, e in zip(flat_g, flat_e)]
    if method == "bf16" or not vs:
        vmaxes = [None] * len(vs)
    else:
        # ONE pmax for every leaf's guard envelope — per-leaf scalar
        # collectives would put hundreds of latency-bound round-trips on
        # exactly the slow link this module exists to relieve
        vmaxes = lax.pmax(
            jnp.stack([jnp.max(jnp.abs(v)) for v in vs]), axis)

    def one(v, vmax):
        if method != "bf16" and v.size >= two_shot_min:
            mean, new_e, ok = _mean_two_shot(v, axis, method, block,
                                             vmax_axis=vmax)
        else:
            mean, own, ok = _mean_one_shot(v, axis, method, block,
                                           vmax_axis=vmax)
            new_e = v - own
        return mean, new_e, ok

    out = [one(v, vmaxes[i]) for i, v in enumerate(vs)]
    synced = jax.tree_util.tree_unflatten(tree, [o[0] for o in out])
    new_ef = jax.tree_util.tree_unflatten(tree, [o[1] for o in out])
    ok = jnp.bool_(True)
    for o in out:
        ok = ok & o[2]
    return synced, new_ef, ok


def quantized_all_gather_dequant(shard, axis: str, method: str,
                                 block: Optional[int] = None,
                                 dim: int = 0, vmax_axis=None):
    """Stage-3 weight path: quantize the local param shard, all-gather
    the narrow payload (+ scales), dequantize, reassemble the full param
    along ``dim``. Stateless (no error feedback — each step re-gathers
    from the exact owner shards, so error cannot accumulate); the
    per-step tolerance is pinned by the parity test. Returns
    ``(full_param in shard dtype, ok)``."""
    from paddle_tpu.distributed import collective as coll
    block = _env_block(block)
    N = lax.axis_size(axis)
    x = jnp.moveaxis(shard, dim, 0)
    if method == "bf16":
        with coll.quantized_wire(4 * shard.size):
            g = coll.all_gather(x.astype(jnp.bfloat16), axis,
                                tiled_axis=0)
        full = g.astype(jnp.float32)
        ok = jnp.bool_(True)
    else:
        payload, scales, n = quantize_blocks(x, method, block)
        payload, scales = _inject_wire_fault(payload, scales)
        nb, blk = payload.shape
        with coll.quantized_wire(4 * shard.size):
            pg = coll.all_gather(payload, axis, tiled_axis=0)
            sg = coll.all_gather(scales, axis, tiled_axis=0)
        deq = (pg.astype(jnp.float32) * sg).reshape(N, nb * blk)
        full = deq[:, :n].reshape((N * x.shape[0],) + x.shape[1:])
        vmax = vmax_axis if vmax_axis is not None else \
            lax.pmax(jnp.max(jnp.abs(x)), axis)
        ok = _wire_ok(full, sg, vmax)
    return jnp.moveaxis(full, 0, dim).astype(shard.dtype), ok


def quantized_reduce_scatter_mean(g, e, axis: str, method: str,
                                  block: Optional[int] = None,
                                  dim: int = 0, vmax_axis=None):
    """Stage-2/3 gradient path: block-quantized mean-reduce-scatter along
    ``dim`` with error feedback, expressed as a payload all-to-all +
    local dequant-reduce (narrow wire; a psum_scatter of int8 would
    upcast exactly like the legacy psum). ``g``'s ``dim`` must divide by
    the axis size. Returns ``(my shard of mean(g) fp32, new_ef, ok)``;
    ``new_ef`` is full-``g``-shaped (each rank re-injects its own
    residual next step)."""
    from paddle_tpu.distributed import collective as coll
    block = _env_block(block)
    N = lax.axis_size(axis)
    v = g.astype(jnp.float32) + e
    x = jnp.moveaxis(v, dim, 0)
    d0 = x.shape[0]
    if d0 % N:
        raise ValueError(f"reduce-scatter dim {dim} (size {d0}) must "
                         f"divide by axis {axis!r} size {N}")
    shard_shape = (d0 // N,) + x.shape[1:]
    chunk = x.size // N
    chunks = x.reshape(N, chunk)
    if method == "bf16":
        with coll.quantized_wire(4 * v.size):
            recv = coll.all_to_all(chunks.astype(jnp.bfloat16), axis,
                                   split_axis=0, concat_axis=0)
        mine = recv.astype(jnp.float32).reshape(N, chunk).mean(0)
        own = chunks.astype(jnp.bfloat16).astype(jnp.float32)
        new_e = jnp.moveaxis((x - own.reshape(x.shape)), 0, dim)
        ok = jnp.bool_(True)
    else:
        nbc = -(-chunk // block)
        padded = jnp.pad(chunks, ((0, 0), (0, nbc * block - chunk)))
        payload, scales, _ = quantize_blocks(padded, method, block)
        payload, scales = _inject_wire_fault(payload, scales)
        with coll.quantized_wire(4 * v.size):
            pr = coll.all_to_all(payload, axis, split_axis=0,
                                 concat_axis=0)
            sr = coll.all_to_all(scales, axis, split_axis=0,
                                 concat_axis=0)
        deq = (pr.astype(jnp.float32) * sr).reshape(N, nbc * block)
        mine = deq.mean(0)[:chunk]
        own = (payload.astype(jnp.float32) * scales).reshape(
            N, nbc * block)[:, :chunk]
        new_e = jnp.moveaxis((x - own.reshape(x.shape)), 0, dim)
        vmax = vmax_axis if vmax_axis is not None else \
            lax.pmax(jnp.max(jnp.abs(v)), axis)
        ok = _wire_ok(mine, sr, vmax)
    shard = jnp.moveaxis(mine.reshape(shard_shape), 0, dim)
    return shard, new_e, ok


def quantized_bucket_reduce_scatter(grads, ef, axis: str, method,
                                    block: Optional[int] = None,
                                    dims=None, vmax_axis=None,
                                    stripe: Optional[float] = None,
                                    stripe_min: int = 1 << 16):
    """ONE wire exchange for a whole gradient BUCKET — the overlap
    scheduler's launch unit (distributed/overlap.py).

    Every leaf's rank-chunks are concatenated into a single
    ``(N, total)`` payload that rides one narrow all-to-all (plus its
    scales) instead of one collective per leaf: fewer launches on
    exactly the latency-bound link the bucket exists to feed, and the
    unit the FlexLink-style stripe splits. ``grads``/``ef`` are dicts of
    this replica's local gradients and residuals; ``dims[k]`` is the dim
    of leaf ``k`` carrying the comm axis (its size must divide the axis
    size). ``method`` ``None`` moves fp32 — the scheduling A/B baseline
    with an exactly-zero residual; "bf16"/"int8"/"fp8" as elsewhere.

    ``stripe`` in (0, 1) splits the payload columns into a leading
    full-precision ICI stripe and a trailing quantized DCN stripe of
    that fraction, launched concurrently and recombined on arrival
    (``planner.stripe_plan`` picks the fraction so both stripes finish
    together; ``comm/stripe_bytes_{ici,dcn}`` meter the split). Applied
    only when the bucket holds at least ``stripe_min`` elements — small
    buckets pay two launch latencies for no bandwidth win.

    Returns ``({k: my shard of mean(g)}, {k: new_ef}, ok)`` with the
    same error-feedback algebra as
    :func:`quantized_reduce_scatter_mean`; the fp32 stripe is exact, so
    its residual range is zero.
    """
    from paddle_tpu.distributed import collective as coll
    if method is not None:
        _check_method(method)
    block = _env_block(block)
    N = lax.axis_size(axis)
    keys = list(grads)
    dims = dims or {}
    xs, chunks, parts, logical = {}, {}, [], 0
    for k in keys:
        # ptlint: disable=PT001 -- dims holds static Python dim indices
        d = int(dims.get(k, 0))
        v = grads[k].astype(jnp.float32) + ef[k].astype(jnp.float32)
        x = jnp.moveaxis(v, d, 0)
        if x.shape[0] % N:
            raise ValueError(
                f"bucket leaf {k!r}: dim {d} (size {x.shape[0]}) must "
                f"divide by axis {axis!r} size {N}")
        xs[k] = x
        chunks[k] = x.size // N
        parts.append(x.reshape(N, -1))
        logical += 4 * x.size
    C = jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
    total = C.shape[1]
    # stripe / stripe_min are static Python config scalars, total a
    # static shape — the whole split decision happens at trace time
    # ptlint: disable=PT001 -- static config knobs, not device values
    do_stripe = (stripe is not None and 0.0 < float(stripe) < 1.0
                 # ptlint: disable=PT001 -- stripe_min is a static knob
                 and N * total >= int(stripe_min))
    if method is None:
        # fp32 wire: a stripe still splits into two CONCURRENT launches
        # ptlint: disable=PT001 -- static config knobs, not device values
        split = int(round(total * (1.0 - float(stripe)))) if do_stripe \
            else total
    else:
        # ptlint: disable=PT001 -- static config knobs, not device values
        split = int(round(total * (1.0 - float(stripe)))) if do_stripe \
            else 0
    split = min(max(split, 0), total)
    ok = jnp.bool_(True)
    mine_parts, own_parts, scales_seen = [], [], None
    with coll.quantized_wire(logical):
        if split:
            # leading stripe crosses at full precision (exact — its
            # error-feedback contribution is identically zero)
            ici = C[:, :split]
            if do_stripe:
                coll.stripe_bytes("ici", 4 * ici.size)
            r = coll.all_to_all(ici, axis, split_axis=0, concat_axis=0)
            mine_parts.append(r.reshape(N, split).mean(0))
            own_parts.append(ici)
        rest = total - split
        if rest:
            dcn = C[:, split:]
            if method is None:
                # fp32 wire with striping: the second stripe is another
                # concurrent full-precision launch (exact, zero residual)
                if do_stripe:
                    coll.stripe_bytes("dcn", 4 * dcn.size)
                r = coll.all_to_all(dcn, axis, split_axis=0,
                                    concat_axis=0)
                mine_parts.append(r.reshape(N, rest).mean(0))
                own_parts.append(dcn)
            elif method == "bf16":
                q = dcn.astype(jnp.bfloat16)
                if do_stripe:
                    coll.stripe_bytes("dcn", 2 * q.size)
                r = coll.all_to_all(q, axis, split_axis=0, concat_axis=0)
                mine_parts.append(
                    r.astype(jnp.float32).reshape(N, rest).mean(0))
                own_parts.append(q.astype(jnp.float32))
            else:
                nbc = -(-rest // block)
                padded = jnp.pad(dcn, ((0, 0), (0, nbc * block - rest)))
                payload, scales, _ = quantize_blocks(padded, method, block)
                payload, scales = _inject_wire_fault(payload, scales)
                if do_stripe:
                    coll.stripe_bytes(
                        "dcn", payload.size * payload.dtype.itemsize
                        + 4 * scales.size)
                pr = coll.all_to_all(payload, axis, split_axis=0,
                                     concat_axis=0)
                sr = coll.all_to_all(scales, axis, split_axis=0,
                                     concat_axis=0)
                deq = (pr.astype(jnp.float32) * sr).reshape(
                    N, nbc * block)
                mine_parts.append(deq.mean(0)[:rest])
                own_parts.append((payload.astype(jnp.float32)
                                  * scales).reshape(
                                      N, nbc * block)[:, :rest])
                scales_seen = sr
    mine = (jnp.concatenate(mine_parts) if len(mine_parts) > 1
            else mine_parts[0])
    own = (jnp.concatenate(own_parts, axis=1) if len(own_parts) > 1
           else own_parts[0])
    if scales_seen is not None:
        vmax = vmax_axis if vmax_axis is not None else \
            lax.pmax(jnp.max(jnp.abs(C)), axis)
        ok = _wire_ok(mine, scales_seen, vmax)
    shards, new_ef = {}, {}
    off = 0
    for k in keys:
        # ptlint: disable=PT001 -- dims holds static Python dim indices
        d = int(dims.get(k, 0))
        x, c = xs[k], chunks[k]
        shard_shape = (x.shape[0] // N,) + x.shape[1:]
        shards[k] = jnp.moveaxis(
            mine[off:off + c].reshape(shard_shape), 0, d)
        own_k = own[:, off:off + c].reshape(x.shape)
        new_ef[k] = jnp.moveaxis(x - own_k, 0, d)
        off += c
    return shards, new_ef, ok


# ---------------------------------------------------------------------------
# Legacy psum wire (the tested parity reference, PT_COMM_QUANT_PSUM=1)
# ---------------------------------------------------------------------------

def compressed_psum_mean(grads, ef, axis: str, method: str):
    """Quantized mean-all-reduce over ``axis`` with error feedback —
    the LEGACY psum formulation.

    Must be called INSIDE a shard_map/pmap context where ``axis`` is a
    bound mesh axis. ``grads`` are this replica's local gradients, ``ef``
    the replica's residual from the previous step (same pytree).
    Returns (mean_grads fp32, new_ef).

    Wire-volume caveat (the reason this is no longer the default): the
    int8 psum accumulates in int32 — XLA upcasts on the wire for the
    reduction — so the payload narrowing buys ~NOTHING in moved bytes
    (~1x, not 4x). It remains numerically exact as a parity oracle for
    the all-gather formulation (same pmax-agreed scale, same error-
    feedback algebra) and is selected by ``PT_COMM_QUANT_PSUM=1``.
    """
    _check_method(method, _PSUM_METHODS)
    n = lax.psum(jnp.ones((), jnp.float32), axis)

    def one(g, e):
        v = g.astype(jnp.float32) + e
        if method == "bf16":
            q = v.astype(jnp.bfloat16)            # the wire dtype
            deq = q.astype(jnp.float32)
            tot = lax.psum(q, axis).astype(jnp.float32)
        else:
            # scale agreed across replicas (pmax) so the int8 payloads are
            # summable; +tiny floor keeps all-zero grads finite
            s = lax.pmax(jnp.max(jnp.abs(v)), axis) / 127.0 + 1e-30
            q = jnp.clip(jnp.round(v / s), -127, 127).astype(jnp.int8)
            deq = q.astype(jnp.float32) * s
            tot = lax.psum(q.astype(jnp.int32), axis).astype(
                jnp.float32) * s
        return tot / n, v - deq

    flat_g, tree = jax.tree_util.tree_flatten(grads)
    flat_e = jax.tree_util.tree_leaves(ef)
    out = [one(g, e) for g, e in zip(flat_g, flat_e)]
    synced = jax.tree_util.tree_unflatten(tree, [o[0] for o in out])
    new_ef = jax.tree_util.tree_unflatten(tree, [o[1] for o in out])
    return synced, new_ef


# ---------------------------------------------------------------------------
# Policy + state plumbing
# ---------------------------------------------------------------------------

def resolve_comm_quant(axis: str = "dp", mesh: Optional[Mesh] = None,
                       degrees=None, n_hosts: Optional[int] = None
                       ) -> Optional[str]:
    """The per-axis wire-format decision: ``PT_COMM_QUANT`` forces a
    format (or ``none``); the ``auto`` default quantizes only axes whose
    collectives cross host boundaries (``planner._axis_tier`` says
    "dcn"), leaving ICI full-precision. Returns a method name or None."""
    env = os.environ.get("PT_COMM_QUANT", "auto").strip().lower()
    if env in ("none", "off", "0", "fp32"):
        return None
    if env in _METHODS:
        return env
    if env and env != "auto":
        raise ValueError(f"PT_COMM_QUANT must be one of "
                         f"{('auto', 'none') + _METHODS}, got {env!r}")
    from paddle_tpu.distributed import planner
    if degrees is None:
        degrees = dict(mesh.shape) if mesh is not None else {}
    if n_hosts is None:
        n_hosts = int(os.environ.get("PT_NNODES", "1"))
    return planner.comm_quant_policy(degrees, n_hosts).get(axis)


def init_error_feedback(params, mesh: Mesh, axis: str = "dp"):
    """Per-replica residual buffers: zeros with a leading ``axis`` dim,
    sharded over it (each replica owns its own residual)."""
    dp = dict(mesh.shape).get(axis, 1)

    def zeros(p):
        z = jnp.zeros((dp,) + p.shape, jnp.float32)
        return jax.device_put(z, NamedSharding(mesh, P(axis)))

    return jax.tree_util.tree_map(zeros, params)


def build_compressed_dp_step(loss_fn: Callable, optimizer, mesh: Mesh,
                             method: Optional[str], axis: str = "dp",
                             donate: bool = True,
                             block: Optional[int] = None,
                             two_shot_min: int = 1 << 16,
                             use_psum: Optional[bool] = None):
    """One jitted dp train step whose gradient exchange is compressed.

    ``loss_fn(params, batch) -> scalar`` is the per-replica loss on the
    replica's batch shard (batch leading dim splits over ``axis``).
    Returns ``step(params, opt_state, ef, batch) ->
    (params, opt_state, ef, loss)``; build ``ef`` with
    :func:`init_error_feedback` (pass ``()`` when ``method`` is None).

    ``method=None`` keeps the identical shard_map structure with a plain
    fp32 pmean — toggling compression on/off changes ONLY the wire
    format, never the batch-splitting or loss/grad semantics. The
    default wire is the block-scaled all-gather
    (:func:`compressed_mean_allgather`); ``use_psum`` (default: the
    ``PT_COMM_QUANT_PSUM`` env) selects the legacy psum parity reference.

    Fail-loud: when the wire guard trips (corrupted block scale, or any
    non-finite on the dequantized exchange — see
    ``collective.quant_payload``), the synced grads and the loss are
    NaN-poisoned in-graph on every rank, and the returned step RAISES
    RuntimeError while fault injection is active. A flipped payload
    byte is NOT detectable (it stays a valid code inside the scale
    envelope); its effect is bounded by the block's own scale — see the
    module docstring.

    ≙ dgc_optimizer.py's minimize(): grads compress before the dp
    all-reduce, the residual feeds back, the inner optimizer sees the
    dequantized mean.
    """
    if method is not None:
        _check_method(method)
    if use_psum is None:
        use_psum = os.environ.get("PT_COMM_QUANT_PSUM", "0") == "1"
    if use_psum and method == "fp8":
        raise ValueError("fp8 has no psum wire format (integer "
                         "accumulation only) — unset PT_COMM_QUANT_PSUM")
    block = _env_block(block)

    def per_replica(params, ef, batch):
        loss, g = jax.value_and_grad(loss_fn)(params, batch)
        ok = jnp.bool_(True)
        if method is None:
            g = jax.tree_util.tree_map(
                lambda x: lax.pmean(x.astype(jnp.float32), axis), g)
        else:
            # ef arrives (1, *shape) — this replica's slice
            e = jax.tree_util.tree_map(lambda x: x[0], ef)
            if use_psum:
                g, e = compressed_psum_mean(g, e, axis, method)
            else:
                g, e, ok = compressed_mean_allgather(
                    g, e, axis, method, block, two_shot_min)
                # a tripped guard must never reach the optimizer as a
                # plausible gradient: poison, don't steer
                g = jax.tree_util.tree_map(
                    lambda x: jnp.where(ok, x, jnp.nan), g)
            ef = jax.tree_util.tree_map(lambda x: x[None], e)
        loss = lax.pmean(loss, axis)
        loss = jnp.where(ok, loss, jnp.nan)
        return loss, g, ef, ok

    smapped = shard_map(
        per_replica, mesh=mesh,
        in_specs=(P(), P(axis), P(axis)),
        out_specs=(P(), P(), P(axis), P()),
        check_vma=False)

    def step(params, opt_state, ef, batch):
        loss, g, ef, ok = smapped(params, ef, batch)
        new_p, new_s = optimizer.update(g, opt_state, params)
        return new_p, new_s, ef, loss, ok

    kw = {"donate_argnums": (0, 1, 2)} if donate else {}
    jstep = jax.jit(step, **kw)
    guarded = method is not None and not use_psum

    def run(params, opt_state, ef, batch):
        new_p, new_s, ef, loss, ok = jstep(params, opt_state, ef, batch)
        if guarded:
            from paddle_tpu.testing import faults
            if faults.enabled() and not bool(ok):
                raise RuntimeError(
                    "quantized collective wire failed validation "
                    f"(fault site {_FAULT_SITE!r}): corrupted block "
                    "scale/payload — synced gradients NaN-poisoned on "
                    "every rank")
        return new_p, new_s, ef, loss

    return run
