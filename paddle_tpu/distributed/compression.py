"""Gradient-compressed data parallelism with error feedback.

Reference analog: the DGC / local-SGD meta-optimizer family
(python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py,
paddle/fluid/operators/dgc_op.cc) — compress the gradient exchange when
the data-parallel axis rides a slow link. The TPU re-design keeps the
part that matters on this stack (the wire format of the dp collective)
and drops what doesn't (DGC's top-k sparsification exists to cut NCCL
ring volume; on TPU the same 2-4x cut comes from dtype narrowing, which
stays dense and MXU/XLA-friendly):

- ``bf16``: gradients cross the dp axis as bfloat16 — 2x volume cut.
- ``int8``: symmetric per-tensor quantization with a pmax-agreed scale —
  4x cut. The psum accumulates in int32 (XLA upcasts on the wire for the
  reduction; a DCN deployment chasing the full 4x would all-gather int8
  and reduce locally — noted, not implemented).
- **Error feedback** (the residual accumulation DGC calls "momentum
  correction"): each replica carries ``ef = (g + ef) - Q(g + ef)`` to the
  next step, so quantization error accumulates into later updates instead
  of biasing the trajectory — the property the convergence-parity test
  pins down.

When to use: dp over DCN (multi-host data parallelism) where the gradient
all-reduce is the bottleneck — see ``planner._axis_tier``. On ICI the
collectives are rarely the bottleneck and full-precision sync is the
default.
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

__all__ = ["compressed_psum_mean", "build_compressed_dp_step",
           "init_error_feedback"]

_METHODS = ("bf16", "int8")


def _check_method(method: str):
    if method not in _METHODS:
        raise ValueError(f"grad_compression must be one of {_METHODS}, "
                         f"got {method!r}")


def compressed_psum_mean(grads, ef, axis: str, method: str):
    """Quantized mean-all-reduce over ``axis`` with error feedback.

    Must be called INSIDE a shard_map/pmap context where ``axis`` is a
    bound mesh axis. ``grads`` are this replica's local gradients, ``ef``
    the replica's residual from the previous step (same pytree).
    Returns (mean_grads fp32, new_ef).
    """
    _check_method(method)
    n = lax.psum(jnp.ones((), jnp.float32), axis)

    def one(g, e):
        v = g.astype(jnp.float32) + e
        if method == "bf16":
            q = v.astype(jnp.bfloat16)            # the wire dtype
            deq = q.astype(jnp.float32)
            tot = lax.psum(q, axis).astype(jnp.float32)
        else:
            # scale agreed across replicas (pmax) so the int8 payloads are
            # summable; +tiny floor keeps all-zero grads finite
            s = lax.pmax(jnp.max(jnp.abs(v)), axis) / 127.0 + 1e-30
            q = jnp.clip(jnp.round(v / s), -127, 127).astype(jnp.int8)
            deq = q.astype(jnp.float32) * s
            tot = lax.psum(q.astype(jnp.int32), axis).astype(
                jnp.float32) * s
        return tot / n, v - deq

    flat_g, tree = jax.tree_util.tree_flatten(grads)
    flat_e = jax.tree_util.tree_leaves(ef)
    out = [one(g, e) for g, e in zip(flat_g, flat_e)]
    synced = jax.tree_util.tree_unflatten(tree, [o[0] for o in out])
    new_ef = jax.tree_util.tree_unflatten(tree, [o[1] for o in out])
    return synced, new_ef


def init_error_feedback(params, mesh: Mesh, axis: str = "dp"):
    """Per-replica residual buffers: zeros with a leading ``axis`` dim,
    sharded over it (each replica owns its own residual)."""
    dp = dict(mesh.shape).get(axis, 1)

    def zeros(p):
        z = jnp.zeros((dp,) + p.shape, jnp.float32)
        return jax.device_put(z, NamedSharding(mesh, P(axis)))

    return jax.tree_util.tree_map(zeros, params)


def build_compressed_dp_step(loss_fn: Callable, optimizer, mesh: Mesh,
                             method: Optional[str], axis: str = "dp",
                             donate: bool = True):
    """One jitted dp train step whose gradient exchange is compressed.

    ``loss_fn(params, batch) -> scalar`` is the per-replica loss on the
    replica's batch shard (batch leading dim splits over ``axis``).
    Returns ``step(params, opt_state, ef, batch) ->
    (params, opt_state, ef, loss)``; build ``ef`` with
    :func:`init_error_feedback` (pass ``()`` when ``method`` is None).

    ``method=None`` keeps the identical shard_map structure with a plain
    fp32 pmean — toggling compression on/off changes ONLY the wire
    format, never the batch-splitting or loss/grad semantics.

    ≙ dgc_optimizer.py's minimize(): grads compress before the dp
    all-reduce, the residual feeds back, the inner optimizer sees the
    dequantized mean.
    """
    if method is not None:
        _check_method(method)

    def per_replica(params, ef, batch):
        loss, g = jax.value_and_grad(loss_fn)(params, batch)
        if method is None:
            g = jax.tree_util.tree_map(
                lambda x: lax.pmean(x.astype(jnp.float32), axis), g)
        else:
            # ef arrives (1, *shape) — this replica's slice
            e = jax.tree_util.tree_map(lambda x: x[0], ef)
            g, e = compressed_psum_mean(g, e, axis, method)
            ef = jax.tree_util.tree_map(lambda x: x[None], e)
        loss = lax.pmean(loss, axis)
        return loss, g, ef

    smapped = shard_map(
        per_replica, mesh=mesh,
        in_specs=(P(), P(axis), P(axis)),
        out_specs=(P(), P(), P(axis)),
        check_vma=False)

    def step(params, opt_state, ef, batch):
        loss, g, ef = smapped(params, ef, batch)
        new_p, new_s = optimizer.update(g, opt_state, params)
        return new_p, new_s, ef, loss

    kw = {"donate_argnums": (0, 1, 2)} if donate else {}
    return jax.jit(step, **kw)
