"""Tensor/model-parallel ops: vocab-parallel embedding and cross-entropy.

Reference analogs:
- parallel_cross_entropy ≙ c_softmax_with_cross_entropy
  (paddle/fluid/operators/collective/c_softmax_with_cross_entropy_op.cu:38,
  134-192; python surface mpu/mp_ops.py:405 _c_softmax_with_cross_entropy,
  mp_layers.py:491 ParallelCrossEntropy): per-rank row max → allreduce(max)
  → exp/sum → allreduce(sum) → masked pick of the label logit on the rank
  owning that vocab range → allreduce(pick). The full-vocab logit row is
  NEVER materialized on any device — the memory dominator at 1.3B+ scale.
- vocab_parallel_embedding ≙ VocabParallelEmbedding (mp_layers.py:37):
  each rank looks up tokens falling in its vocab range, zeros elsewhere,
  allreduce combines.

Here both are jax.shard_map bodies with explicit lax.psum over the 'tp'
mesh axis (SURVEY §5.8 mapping: NCCL allreduce → psum on ICI), fully
differentiable (shard_map AD; psum's VJP is psum).
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["parallel_cross_entropy", "vocab_parallel_embedding",
           "axis_rng_key"]


def _token_ce_local(logits, labels, axis, ignore_index):
    """shard_map body: per-token CE over a vocab axis sharded on `axis`.

    logits: (..., V_local) LOCAL shard, labels: (...) GLOBAL vocab ids.
    Returns per-token loss (...), 0 where label == ignore_index.
    """
    vloc = logits.shape[-1]
    start = lax.axis_index(axis) * vloc
    x = logits.astype(jnp.float32)
    # stop_gradient BEFORE pmax: the max term's gradient contribution
    # cancels (standard logsumexp stabilization) and pmax has no AD rule,
    # so tangents must never reach it
    mx = lax.pmax(jnp.max(lax.stop_gradient(x), axis=-1), axis)
    x = x - mx[..., None]
    denom = lax.psum(jnp.sum(jnp.exp(x), axis=-1), axis)
    valid = labels != ignore_index
    local = labels.astype(jnp.int32) - start
    in_range = (local >= 0) & (local < vloc) & valid
    safe = jnp.clip(local, 0, vloc - 1)
    pick = jnp.take_along_axis(x, safe[..., None], axis=-1)[..., 0]
    pick = lax.psum(jnp.where(in_range, pick, 0.0), axis)
    return jnp.where(valid, jnp.log(denom) - pick, 0.0)


def parallel_cross_entropy(logits, labels, mesh: Mesh = None,
                           axis: str = "tp", batch_axes=("dp", "fsdp"),
                           seq_axis: str = "sp", ignore_index: int = -100):
    """TP-sharded softmax cross-entropy over GLOBAL (B, S, V) logits whose
    vocab axis is sharded over mesh axis `axis`.

    Returns per-token loss (B, S) — 0 at ignore_index positions — sharded
    (batch_axes, seq_axis). Reduce it yourself (mean over valid tokens).
    Falls back to a dense computation when no mesh / axis degree 1.
    """
    if mesh is None:
        from paddle_tpu.distributed.mesh import get_mesh
        mesh = get_mesh()
    if mesh is None or dict(mesh.shape).get(axis, 1) == 1:
        x = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(x, axis=-1)
        valid = labels != ignore_index
        safe = jnp.where(valid, labels, 0).astype(jnp.int32)
        pick = jnp.take_along_axis(x, safe[..., None], axis=-1)[..., 0]
        return jnp.where(valid, logz - pick, 0.0)
    lspec = P(batch_axes, seq_axis, axis)
    yspec = P(batch_axes, seq_axis)
    body = lambda lg, lb: _token_ce_local(lg, lb, axis, ignore_index)
    return jax.shard_map(body, mesh=mesh, in_specs=(lspec, yspec),
                         out_specs=yspec)(logits, labels)


def vocab_parallel_embedding(table, tokens, mesh: Mesh = None,
                             axis: str = "tp", shard_axes=(),
                             batch_axes=("dp", "fsdp"),
                             seq_axis: str = "sp"):
    """Embedding lookup with the vocab (row) axis of `table` sharded over
    mesh axis `axis` — each rank looks up only tokens in its own vocab range
    and a psum combines, so the (V, d) table is never all-gathered over the
    VOCAB axis (≙ VocabParallelEmbedding, mp_layers.py:37). Any fsdp
    sharding of the d column is gathered at shard_map entry — exactly
    ZeRO-3's gather-param-at-use, and only V/tp × d per device.

    table: GLOBAL (V, d), rows sharded over `axis`; pass shard_axes=(ax,)
    to KEEP the d column sharded (only legal when ax is not in batch_axes).
    tokens: GLOBAL (B, S) int ids. Returns (B, S, d) sharded
    (batch_axes, seq_axis, shard_axes[0] or None).
    """
    if mesh is None:
        from paddle_tpu.distributed.mesh import get_mesh
        mesh = get_mesh()
    if mesh is None or dict(mesh.shape).get(axis, 1) == 1:
        return jnp.take(table, tokens, axis=0)
    col = shard_axes[0] if shard_axes else None

    def body(tbl, tok):
        vloc = tbl.shape[0]
        start = lax.axis_index(axis) * vloc
        local = tok.astype(jnp.int32) - start
        in_range = (local >= 0) & (local < vloc)
        safe = jnp.clip(local, 0, vloc - 1)
        out = jnp.take(tbl, safe, axis=0)
        out = jnp.where(in_range[..., None], out, 0)
        return lax.psum(out, axis)

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, col), P(batch_axes, seq_axis)),
        out_specs=P(batch_axes, seq_axis, col))(table, tokens)


def axis_rng_key(key, axis: str):
    """Per-mesh-axis-index PRNG key (≙ RNGStatesTracker, mpu/random.py:32:
    model-parallel regions need DIFFERENT dropout masks per tp rank for
    sharded activations; JAX's explicit keys make this a fold_in). Call
    inside shard_map."""
    return jax.random.fold_in(key, lax.axis_index(axis))
