"""Collective communication API.

Reference analog: ``paddle.distributed.{all_reduce, all_gather, …}`` backed
by ProcessGroupNCCL (paddle/fluid/distributed/collective/ProcessGroupNCCL.cc
— explicit comm streams, Task futures, c_sync_* ordering ops).

TPU-native: collectives are *program* constructs — jax.lax primitives over
named mesh axes inside jit/shard_map; XLA schedules them on ICI and the whole
stream-ordering layer (c_sync_calc_stream etc., SURVEY §5.8) has no
equivalent. These wrappers exist to (a) give reference users the same
vocabulary, (b) centralize axis-name defaults.

Inside shard_map-ed functions, `axis` accepts a mesh axis name or tuple.
"""

import contextlib
import threading

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ReduceOp", "all_reduce", "all_gather", "all_to_all",
           "reduce_scatter", "broadcast", "psum", "pmean", "pmax", "pmin",
           "ppermute", "axis_index", "axis_size", "send_recv_ring",
           "barrier", "Group", "new_group", "get_group", "group_reduce",
           "group_all_gather", "quantized_wire", "stripe_bytes"]


_wire_ctx = threading.local()


@contextlib.contextmanager
def quantized_wire(logical_bytes: int):
    """Byte-accounting scope for compressed wire formats (the EQuARX
    question: what actually crossed the link vs what the exchange is
    worth). Collectives issued inside record their REAL payload bytes
    (int8/fp8 blocks + scales) into ``comm/bytes_wire``, while
    ``comm/bytes_logical`` advances once by ``logical_bytes`` — the
    full-precision volume the same exchange would have moved. Outside any
    scope the wrappers tick both counters equally, so the two stay
    directly comparable and ``comm/compression_ratio`` (gauge,
    cumulative logical/wire) reads 1.0 for an uncompressed program.
    Like every ``_issue_span`` stat these tick at TRACE time — per
    compilation, not per step."""
    from paddle_tpu import stats
    prev = getattr(_wire_ctx, "active", False)
    _wire_ctx.active = True
    try:
        yield
    finally:
        _wire_ctx.active = prev
        # trace-time accounting BY DESIGN (see docstring): logical_bytes
        # is a static Python int computed from shapes, never a tracer
        # ptlint: disable=PT001,PT003 -- per-compilation counters, static arg
        stats.add("comm/bytes_logical", int(logical_bytes))
        wire = stats.get("comm/bytes_wire", 0)
        if wire:
            # ptlint: disable=PT003 -- per-compilation gauge, documented
            stats.set_value("comm/compression_ratio",
                            stats.get("comm/bytes_logical", 0) / wire)


def stripe_bytes(tier: str, nbytes: int):
    """FlexLink-style stripe accounting: wire bytes each stripe class of
    a striped collective moved (``comm/stripe_bytes_{ici,dcn}``) —
    per-compilation counters like every ``_issue_span`` stat, so the
    split a ``compression.quantized_bucket_reduce_scatter`` actually
    lowered is auditable against ``planner.stripe_plan``'s fraction."""
    from paddle_tpu import stats
    # ptlint: disable=PT003 -- per-compilation byte counters (see
    # quantized_wire: trace-time accounting is this module's contract)
    # ptlint: disable=PT001 -- nbytes is a static Python byte count
    stats.add(f"comm/stripe_bytes_{tier}", int(nbytes))


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _issue_span(name, x, axis):
    """Observability for the collective wrappers. These run at TRACE
    time (the op executes on-device inside the jitted program, where
    XLA owns the clock), so the span marks when the host issued the
    collective and tags its payload size — per-compilation, not
    per-step; on-device durations live in the XLA trace. Byte counters
    land in stats so an operator can attribute ICI traffic per op kind
    (the EQuARX-style question: which collective moves what)."""
    from paddle_tpu import stats
    from paddle_tpu.observability import trace
    try:
        nbytes = int(x.size) * int(jnp.dtype(x.dtype).itemsize)
    except Exception:
        nbytes = 0
    # ptlint: disable=PT003 -- trace-time recording is this helper's
    # documented contract (per-compilation, not per-step; see docstring)
    stats.add(f"collective/{name}_calls")
    if nbytes:
        # ptlint: disable=PT003 -- per-compilation byte counters
        stats.add(f"collective/{name}_bytes", nbytes)
        # ptlint: disable=PT003 -- per-compilation byte counters
        stats.add("comm/bytes_wire", nbytes)
        if not getattr(_wire_ctx, "active", False):
            # ptlint: disable=PT003 -- per-compilation byte counters
            stats.add("comm/bytes_logical", nbytes)
    if not trace.enabled():
        return contextlib.nullcontext()
    # ptlint: disable=PT003 -- issue-span semantics documented above
    return trace.span(f"collective/{name}", axis=str(axis),
                      bytes=nbytes)


def all_reduce(x, op=ReduceOp.SUM, axis="dp"):
    """ref: paddle.distributed.all_reduce → c_allreduce_{sum,max,min,prod}
    (operators/collective/c_allreduce_*). Must run inside shard_map/pjit."""
    with _issue_span("all_reduce", x, axis):
        if op == ReduceOp.SUM:
            return lax.psum(x, axis)
        if op == ReduceOp.MAX:
            return lax.pmax(x, axis)
        if op == ReduceOp.MIN:
            return lax.pmin(x, axis)
        if op == ReduceOp.AVG:
            return lax.pmean(x, axis)
        if op == ReduceOp.PROD:
            # gather-then-multiply: sign-correct for negatives/zeros (an
            # exp(psum(log)) trick would NaN on non-positive elements)
            return jnp.prod(lax.all_gather(x, axis), axis=0)
    raise ValueError(op)


psum = lax.psum
pmean = lax.pmean
pmax = lax.pmax
pmin = lax.pmin
ppermute = lax.ppermute


def all_gather(x, axis="dp", tiled_axis=0):
    """ref: c_allgather (operators/collective/c_allgather_op.cc)."""
    with _issue_span("all_gather", x, axis):
        return lax.all_gather(x, axis, axis=tiled_axis, tiled=True)


def reduce_scatter(x, axis="dp", scatter_axis=0):
    """ref: c_reducescatter."""
    with _issue_span("reduce_scatter", x, axis):
        return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                                tiled=True)


def all_to_all(x, axis="ep", split_axis=0, concat_axis=0):
    """ref: alltoall op / global_scatter+global_gather MoE dispatch
    (operators/collective/global_scatter_op.cc)."""
    with _issue_span("all_to_all", x, axis):
        return lax.all_to_all(x, axis, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def broadcast(x, src=0, axis="dp"):
    """ref: c_broadcast. Select src's shard and replicate."""
    with _issue_span("broadcast", x, axis):
        idx = lax.axis_index(axis)
        masked = jnp.where(idx == src, x, jnp.zeros_like(x))
        return lax.psum(masked, axis)


def axis_index(axis):
    return lax.axis_index(axis)


def axis_size(axis):
    return lax.axis_size(axis)


def send_recv_ring(x, axis="pp", shift=1):
    """Neighbor exchange on a ring (ref: send_v2/recv_v2 micro-batch P2P;
    on TPU a collective-permute rides ICI)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def barrier(axis=None):
    """ref: barrier op. Inside SPMD programs ordering is data-flow-driven;
    host-level barrier syncs all host processes. The span times the REAL
    host-side wait — a straggling rank shows up as one long barrier lane
    on every healthy rank's timeline."""
    if axis is None:
        import time as _time
        import jax.experimental.multihost_utils as mhu
        from paddle_tpu import stats
        from paddle_tpu.observability import trace
        with trace.span("collective/barrier"):
            t0 = _time.perf_counter()
            mhu.sync_global_devices("paddle_tpu_barrier")
            stats.observe("collective/barrier_s",
                          _time.perf_counter() - t0)


class Group:
    """Communicator subgroup (≙ paddle.distributed.collective.Group /
    new_group → ProcessGroup subsets). TPU-native: a subgroup is an
    ``axis_index_groups`` partition of a mesh axis — the XLA collective
    then runs independently inside each part, which is exactly what a
    sub-communicator does."""

    _next_id = 1

    def __init__(self, ranks, axis="dp", index_groups=None):
        self.ranks = list(ranks)
        self.axis = axis
        self.index_groups = index_groups
        self.id = Group._next_id
        Group._next_id += 1

    @property
    def nranks(self):
        return len(self.ranks)

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks}, axis={self.axis})"


_GROUPS = {}


def new_group(ranks=None, backend=None, axis="dp", world=None):
    """ref: paddle.distributed.new_group (collective.py:340). ``ranks``
    selects axis indices; the remaining indices are partitioned into
    equal-size groups when possible (the reference pattern — e.g. tp
    groups of 2 over 8 ranks) so every collective with
    ``axis_index_groups`` stays legal, else they form one complement
    group (psum-class reductions accept uneven parts)."""
    if world is None:
        from paddle_tpu.distributed.mesh import get_mesh
        m = get_mesh()
        world = dict(m.shape)[axis] if m is not None else len(jax.devices())
    all_idx = list(range(world))
    ranks = all_idx if ranks is None else sorted(int(r) for r in ranks)
    if len(set(ranks)) != len(ranks):
        raise ValueError(f"new_group: duplicate ranks {ranks}")
    bad = [r for r in ranks if not 0 <= r < world]
    if bad:
        raise ValueError(f"new_group: ranks {bad} out of range "
                         f"[0, {world})")
    rest = [i for i in all_idx if i not in ranks]
    groups = [ranks]
    if rest:
        n = len(ranks)
        if len(rest) % n == 0:
            groups += [rest[i:i + n] for i in range(0, len(rest), n)]
        else:
            groups.append(rest)
    g = Group(ranks, axis=axis, index_groups=groups)
    _GROUPS[g.id] = g
    return g


def get_group(gid):
    return _GROUPS.get(gid)


def _part_table(group):
    """part-id per axis index, as a static lookup array."""
    import numpy as np
    world = sum(len(p) for p in group.index_groups)
    table = np.zeros(world, np.int32)
    for pid, part in enumerate(group.index_groups):
        for r in part:
            table[r] = pid
    return jnp.asarray(table)


def group_reduce(x, op=ReduceOp.SUM, group: "Group" = None):
    """Collective restricted to ``group`` (inside shard_map): ranks in
    the group reduce among themselves; other ranks reduce within their
    own partition part. Mechanism: full-axis all_gather + a static
    part-membership mask (shard_map does not lower axis_index_groups),
    then a masked reduction — one gather instead of a sub-communicator,
    which on a TPU mesh is the same ICI traffic class."""
    if group is None:
        return all_reduce(x, op=op)
    table = _part_table(group)
    my_part = table[lax.axis_index(group.axis)]
    gathered = lax.all_gather(x, group.axis)  # (world, ...)
    mask = (table == my_part)
    mshape = (-1,) + (1,) * (gathered.ndim - 1)
    m = mask.reshape(mshape)
    dt = gathered.dtype
    # dtype-preserving identities (an inf mask would promote ints to f32)
    if jnp.issubdtype(dt, jnp.integer):
        lo, hi = jnp.iinfo(dt).min, jnp.iinfo(dt).max
    else:
        lo, hi = -jnp.inf, jnp.inf
    if op == ReduceOp.SUM:
        return jnp.sum(jnp.where(m, gathered, 0), axis=0)
    if op == ReduceOp.AVG:
        return (jnp.sum(jnp.where(m, gathered, 0), axis=0)
                / jnp.sum(mask))
    if op == ReduceOp.MAX:
        return jnp.max(jnp.where(m, gathered, lo), axis=0)
    if op == ReduceOp.MIN:
        return jnp.min(jnp.where(m, gathered, hi), axis=0)
    if op == ReduceOp.PROD:
        return jnp.prod(jnp.where(m, gathered, jnp.ones((), dt)), axis=0)
    raise ValueError(f"group_reduce: unsupported op {op}")


def group_all_gather(x, group: "Group", tiled_axis=0):
    """all_gather inside ``group`` — parts must be equal-size (so every
    rank's result has one static shape)."""
    sizes = {len(p) for p in group.index_groups}
    if len(sizes) != 1:
        raise ValueError("group_all_gather needs equal-size parts; "
                         f"got {group.index_groups}")
    import numpy as np
    table = _part_table(group)
    my_part = table[lax.axis_index(group.axis)]
    members = jnp.asarray(np.asarray(group.index_groups, np.int32))
    gathered = lax.all_gather(x, group.axis)       # (world, ...)
    rows = gathered[members[my_part]]              # (part_size, ...)
    part = rows.shape[0]
    # concatenate the per-member shards along tiled_axis (same contract
    # as all_gather(..., tiled=True))
    return jnp.concatenate([rows[i] for i in range(part)],
                           axis=tiled_axis)


# -- reference-name parity over the same lax machinery ----------------------

def alltoall(in_tensor_list, out_tensor_list=None, axis="ep"):
    """ref: paddle.distributed.alltoall (list-of-tensors form): rank r's
    i-th input lands as the r-th output of rank i. In-program form: stack
    → all_to_all → unstack."""
    x = jnp.stack([jnp.asarray(t) for t in in_tensor_list])
    out = lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=False)
    outs = [out[i] for i in range(out.shape[0])]
    if out_tensor_list is not None:
        del out_tensor_list[:]
        out_tensor_list.extend(outs)
    return outs


def alltoall_single(x, axis="ep", split_axis=0, concat_axis=0):
    """ref: paddle.distributed.alltoall_single (even splits)."""
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def reduce(x, dst=0, op=ReduceOp.SUM, axis="dp"):
    """ref: paddle.distributed.reduce — the reduced value lands on rank
    ``dst``; other ranks keep their input (the reference leaves their
    output buffer unspecified; keeping the input is deterministic)."""
    red = all_reduce(x, op=op, axis=axis)
    return jnp.where(lax.axis_index(axis) == dst, red, x)


def scatter(x, src=0, axis="dp"):
    """ref: paddle.distributed.scatter — rank ``src``'s input, split into
    axis-size chunks along dim 0; rank i receives chunk i."""
    full = broadcast(x, src=src, axis=axis)
    n = lax.axis_size(axis)
    if full.shape[0] % n:
        raise ValueError(f"scatter: dim 0 ({full.shape[0]}) must divide "
                         f"evenly over axis {axis!r} ({n} ranks)")
    chunk = full.shape[0] // n
    i = lax.axis_index(axis)
    return lax.dynamic_slice_in_dim(full, i * chunk, chunk, axis=0)


def split(x, weight=None, bias=None, operation="linear", axis=1,
          num_partitions=None, gather_out=True):
    """ref: paddle.distributed.split (fleet/layers/mpu) — the
    megatron-style model-parallel linear/embedding splitter; delegates to
    distributed/mp_ops.py's column/row helpers. ``axis``: 1 = column
    (output-dim) parallel, 0 = row (input-dim) parallel."""
    if operation == "embedding":
        # in-shard_map vocab-parallel lookup (split's linear branch also
        # assumes the caller's shard_map): each rank holds a contiguous
        # row range of the table; out-of-range ids read row 0 masked to
        # zero, psum over tp sums exactly one live contribution
        ids = jnp.asarray(x)
        per = weight.shape[0]
        start = lax.axis_index("tp") * per
        local = ids - start
        in_range = (local >= 0) & (local < per)
        rows = weight[jnp.clip(local, 0, per - 1)]
        rows = jnp.where(in_range[..., None], rows, 0.0)
        return lax.psum(rows, "tp")
    if operation != "linear":
        raise ValueError(f"split: unknown operation {operation!r}")
    if axis == 1:
        out = jnp.asarray(x) @ weight  # weight already column-sharded
        if bias is not None:
            out = out + bias
        if gather_out:
            out = lax.all_gather(out, "tp", axis=out.ndim - 1, tiled=True)
        return out
    out = jnp.asarray(x) @ weight      # row-parallel: partial sums
    out = lax.psum(out, "tp")
    if bias is not None:
        out = out + bias
    return out


class ParallelMode:
    """ref: paddle.distributed.ParallelMode enum."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class _StreamNamespace:
    """ref: paddle.distributed.stream.* — the stream-annotated collective
    variants. XLA owns stream scheduling, so each maps to the plain op."""

    def __getattr__(self, name):
        import sys
        mod = sys.modules[__name__]
        if hasattr(mod, name):
            return getattr(mod, name)
        raise AttributeError(f"stream has no collective {name!r}")


stream = _StreamNamespace()

__all__ += ["alltoall", "alltoall_single", "reduce", "scatter", "split",
            "ParallelMode", "stream"]
