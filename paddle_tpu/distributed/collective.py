"""Collective communication API.

Reference analog: ``paddle.distributed.{all_reduce, all_gather, …}`` backed
by ProcessGroupNCCL (paddle/fluid/distributed/collective/ProcessGroupNCCL.cc
— explicit comm streams, Task futures, c_sync_* ordering ops).

TPU-native: collectives are *program* constructs — jax.lax primitives over
named mesh axes inside jit/shard_map; XLA schedules them on ICI and the whole
stream-ordering layer (c_sync_calc_stream etc., SURVEY §5.8) has no
equivalent. These wrappers exist to (a) give reference users the same
vocabulary, (b) centralize axis-name defaults.

Inside shard_map-ed functions, `axis` accepts a mesh axis name or tuple.
"""

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ReduceOp", "all_reduce", "all_gather", "all_to_all",
           "reduce_scatter", "broadcast", "psum", "pmean", "pmax", "pmin",
           "ppermute", "axis_index", "axis_size", "send_recv_ring",
           "barrier"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def all_reduce(x, op=ReduceOp.SUM, axis="dp"):
    """ref: paddle.distributed.all_reduce → c_allreduce_{sum,max,min,prod}
    (operators/collective/c_allreduce_*). Must run inside shard_map/pjit."""
    if op == ReduceOp.SUM:
        return lax.psum(x, axis)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axis)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axis)
    if op == ReduceOp.AVG:
        return lax.pmean(x, axis)
    if op == ReduceOp.PROD:
        # gather-then-multiply: sign-correct for negatives/zeros (an
        # exp(psum(log)) trick would NaN on non-positive elements)
        return jnp.prod(lax.all_gather(x, axis), axis=0)
    raise ValueError(op)


psum = lax.psum
pmean = lax.pmean
pmax = lax.pmax
pmin = lax.pmin
ppermute = lax.ppermute


def all_gather(x, axis="dp", tiled_axis=0):
    """ref: c_allgather (operators/collective/c_allgather_op.cc)."""
    return lax.all_gather(x, axis, axis=tiled_axis, tiled=True)


def reduce_scatter(x, axis="dp", scatter_axis=0):
    """ref: c_reducescatter."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                            tiled=True)


def all_to_all(x, axis="ep", split_axis=0, concat_axis=0):
    """ref: alltoall op / global_scatter+global_gather MoE dispatch
    (operators/collective/global_scatter_op.cc)."""
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def broadcast(x, src=0, axis="dp"):
    """ref: c_broadcast. Select src's shard and replicate."""
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def axis_index(axis):
    return lax.axis_index(axis)


def axis_size(axis):
    return lax.axis_size(axis)


def send_recv_ring(x, axis="pp", shift=1):
    """Neighbor exchange on a ring (ref: send_v2/recv_v2 micro-batch P2P;
    on TPU a collective-permute rides ICI)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def barrier(axis=None):
    """ref: barrier op. Inside SPMD programs ordering is data-flow-driven;
    host-level barrier syncs all host processes."""
    if axis is None:
        import jax.experimental.multihost_utils as mhu
        mhu.sync_global_devices("paddle_tpu_barrier")
