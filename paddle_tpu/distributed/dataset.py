"""Fleet dataset surface (ref: python/paddle/distributed/fleet/dataset/ —
InMemoryDataset/QueueDataset feeding the PS trainers, plus the sparse
table accessor Entry configs of fleet/base/distributed_strategy.py).

TPU-native scope: the reference's datasets wrap a C++ DatasetFactory
feeding the trainer threads directly; here they are honest Python
iterables over in-memory samples (the TPU input path is io/dataloader's
shm-ring DataLoader — these classes exist for PS-workflow API parity)."""

import random
from typing import Callable, Iterable, List, Optional

import numpy as np

__all__ = ["InMemoryDataset", "QueueDataset", "CountFilterEntry",
           "ProbabilityEntry", "ShowClickEntry"]


class InMemoryDataset:
    """ref: fleet/dataset/InMemoryDataset — load files into memory,
    global-shuffle, then iterate batches."""

    def __init__(self):
        self._samples: List = []
        self._batch_size = 1
        self._parse_fn: Optional[Callable] = None
        self._drop_last = False
        self._seed = 0

    def init(self, batch_size=1, parse_fn=None, drop_last=False,
             **kwargs):
        self._batch_size = batch_size
        self._parse_fn = parse_fn
        self._drop_last = drop_last
        return self

    def set_batch_size(self, batch_size):
        self._batch_size = batch_size
        return self

    def set_filelist(self, filelist: Iterable[str]):
        self._filelist = list(filelist)

    def load_into_memory(self):
        """Parse every line of the filelist with parse_fn (default: float
        fields)."""
        self._samples = []
        for path in getattr(self, "_filelist", []):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    if self._parse_fn is not None:
                        self._samples.append(self._parse_fn(line))
                    else:
                        self._samples.append(
                            np.asarray([float(v) for v in line.split()],
                                       np.float32))

    def set_samples(self, samples: Iterable):
        """Direct in-memory feed (no file round-trip needed on TPU)."""
        self._samples = list(samples)

    def global_shuffle(self, fleet=None, thread_num=None, seed=None):
        rng = random.Random(self._seed if seed is None else seed)
        rng.shuffle(self._samples)
        self._seed += 1

    local_shuffle = global_shuffle

    def get_memory_data_size(self, fleet=None):
        return len(self._samples)

    def release_memory(self):
        self._samples = []

    def __iter__(self):
        bs = self._batch_size
        for i in range(0, len(self._samples), bs):
            batch = self._samples[i:i + bs]
            if len(batch) < bs and self._drop_last:
                break
            try:
                yield np.stack(batch)
            except Exception:
                yield batch

    def __len__(self):
        return max(0, len(self._samples) // self._batch_size)


class QueueDataset(InMemoryDataset):
    """ref: fleet/dataset/QueueDataset — streaming variant: iterates the
    filelist lazily instead of loading into memory."""

    def load_into_memory(self):
        raise RuntimeError("QueueDataset streams; use __iter__ directly")

    def __iter__(self):
        bs = self._batch_size
        batch = []
        for path in getattr(self, "_filelist", []):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    sample = (self._parse_fn(line) if self._parse_fn
                              else np.asarray([float(v) for v
                                               in line.split()],
                                              np.float32))
                    batch.append(sample)
                    if len(batch) == bs:
                        try:
                            yield np.stack(batch)
                        except Exception:
                            yield list(batch)
                        batch = []
        if batch and not self._drop_last:  # trailing partial batch
            try:
                yield np.stack(batch)
            except Exception:
                yield list(batch)


class _Entry:
    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)

    def __repr__(self):
        kv = ", ".join(f"{k}={v!r}" for k, v in self.__dict__.items())
        return f"{type(self).__name__}({kv})"


class CountFilterEntry(_Entry):
    """ref: sparse-table accessor config — admit a feature id into the
    table only after ``threshold`` occurrences."""

    def __init__(self, threshold: int = 0):
        super().__init__(threshold=threshold)

    def admit(self, count: int) -> bool:
        return count >= self.threshold


class ProbabilityEntry(_Entry):
    """ref: admit new ids with probability ``probability``."""

    def __init__(self, probability: float = 1.0):
        super().__init__(probability=probability)

    def admit(self, rng: random.Random) -> bool:
        return rng.random() < self.probability


class ShowClickEntry(_Entry):
    """ref: show/click-weighted accessor (CTR tables)."""

    def __init__(self, show_coeff: float = 1.0, click_coeff: float = 1.0):
        super().__init__(show_coeff=show_coeff, click_coeff=click_coeff)

    def score(self, shows: float, clicks: float) -> float:
        return self.show_coeff * shows + self.click_coeff * clicks
