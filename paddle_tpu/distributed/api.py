"""Sharding annotation API — the GSPMD face of the reference's auto_parallel
(``shard_tensor``/``DistAttr``, python/paddle/distributed/auto_parallel/;
C++ mirror paddle/fluid/distributed/auto_parallel/dist_attr.h).

The reference's Completer/Partitioner/Resharder pipeline (completion.py:964,
partitioner.py:66, reshard.py:926) is replaced wholesale by XLA's sharding
propagation: annotate a few tensors, the compiler completes the rest and
inserts collectives.
"""

from typing import Optional, Sequence, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.mesh import get_mesh


def _mesh_or_global(mesh):
    m = mesh if mesh is not None else get_mesh()
    if m is None:
        raise RuntimeError("no mesh: call distributed.init_mesh() first")
    return m


def shard_tensor(x, spec, mesh: Optional[Mesh] = None):
    """Place/annotate array with a PartitionSpec (≙ shard_tensor +
    dims_mapping in the reference's DistAttr).

    Inside jit: a sharding constraint. Outside: device_put.
    """
    m = _mesh_or_global(mesh)
    sharding = NamedSharding(m, P(*spec) if isinstance(spec, (list, tuple))
                             else spec)
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    return jax.device_put(x, sharding)


def replicate(x, mesh: Optional[Mesh] = None):
    m = _mesh_or_global(mesh)
    sharding = NamedSharding(m, P())
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    return jax.device_put(x, sharding)


def reshard(x, spec, mesh: Optional[Mesh] = None):
    """≙ Resharder (reshard.py:2503): move an array to a new sharding. XLA
    emits the minimal collective (all-gather / all-to-all / slice)."""
    return shard_tensor(x, spec, mesh)


def shard_module(module, rules=None, mesh: Optional[Mesh] = None,
                 auto: bool = False):
    """Apply {param-path-regex: PartitionSpec} rules to a Module's params
    (≙ the reference's per-op DistributedOperatorImpl sharding registry,
    auto_parallel/operators/common.py:54).

    ``auto=True`` derives the rules structurally instead (planner v0 ≙
    Completer, auto_parallel/completion.py:964): no annotations needed.
    """
    import re
    m = _mesh_or_global(mesh)
    state = module.state_dict()
    if auto:
        from paddle_tpu.distributed.planner import plan_module
        plan = plan_module(module, mesh=m)
        new_state = {
            name: jax.device_put(value, NamedSharding(m, plan.get(name,
                                                                  P())))
            for name, value in state.items()}
        return module.merge_params(new_state)
    if rules is None:
        raise ValueError("shard_module needs rules= or auto=True")
    new_state = {}
    for name, value in state.items():
        spec = P()
        for pattern, s in rules.items():
            if re.search(pattern, name):
                spec = P(*s) if isinstance(s, (list, tuple)) else s
                break
        new_state[name] = jax.device_put(value, NamedSharding(m, spec))
    return module.merge_params(new_state)
