"""Auto-parallel planner v0 — structural PartitionSpec completion.

Reference analog: the auto_parallel Completer + cost model
(python/paddle/distributed/auto_parallel/completion.py:964 —
``complete_forward_annotation`` fixed-point propagation over a per-op
registry of DistributedOperatorImpls; ``auto_parallel/cost/`` for the
memory/comm estimates; ``tuner/parallel_tuner.py:35`` for degree search).

The reference completes shardings over a *program graph*. Here there is no
graph before tracing, so v0 completes over *module structure* — which is
where the information actually lives for the Megatron/ZeRO family of
plans:

- 2-D weights inside repeated blocks alternate column/row parallel by
  dimension flow: expanding (d → k·d) = column P('fsdp','tp'),
  contracting (k·d → d) = row P('tp','fsdp'), square = row when an
  expanding sibling exists (the attention out-projection pattern).
- 1-D block params shard over 'tp' iff their dim is an expanded (column
  output) dim; model-dim vectors (biases of row layers, norms) replicate.
- Root-level tables: vocab-ratio tables get vocab parallel P('tp','fsdp');
  other tables (position/type embeddings) P(None,'fsdp'); root linears
  (identified by a paired bias) P('fsdp', None) — or P('fsdp','tp') when
  their output dim is vocab-like (an untied lm_head).
- 3-D weights are treated as expert-stacked: leading dim 'ep', then the
  column/row rule on the trailing two dims.

Known v0 limitation (documented, ≙ the reference needing dist-op impls
per op type): a block whose linears are ALL square (unfused q/k/v/o
projections) cannot be column/row-disambiguated structurally; all squares
become column-parallel there.
"""

import math
import re
from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.distributed.mesh import LAYOUT

__all__ = ["plan_module", "memory_report", "suggest_mesh",
           "enumerate_plans", "plan_cost", "rank_plans",
           "comm_quant_policy", "stripe_plan"]

_VOCAB_RATIO = 4       # dim0 >= ratio*dim1 → vocab-like table
_TINY_OUT = 8          # output dims below this are never sharded


def _model_dim(params) -> int:
    """The model's hidden size: the most frequent dim across all params."""
    from collections import Counter
    c = Counter()
    for _, v in params:
        for d in v.shape:
            if d > 1:
                c[d] += 1
    return c.most_common(1)[0][0] if c else 0


def _split_module(path: str) -> Tuple[str, str]:
    i = path.rfind(".")
    return ("", path) if i < 0 else (path[:i], path[i + 1:])


_REPEAT_RE = re.compile(r"\.(item_|)\d+(\.|$)")


def _in_repeated_block(path: str) -> bool:
    """Under a LayerList / numbered child ⇒ a repeated block param."""
    return bool(_REPEAT_RE.search("." + path))


def _bias_names(wname: str):
    """Candidate bias names paired with a weight name (wqkv→bqkv,
    pooler_w→pooler_b, weight→bias)."""
    out = []
    if wname.startswith("w"):
        out.append("b" + wname[1:])
    out.append(re.sub(r"_?w(eight)?$", lambda m: m.group(0).replace(
        "w", "b").replace("eight", "ias"), wname))
    return [o for o in out if o != wname]


def plan_module(module, mesh: Optional[Mesh] = None,
                mesh_shape: Optional[Dict[str, int]] = None) -> Dict[str, P]:
    """Propose a {param-path: PartitionSpec} plan for an un-annotated
    Module (``shard_module(model, auto=True)`` entry point). When ``mesh``
    (or a bare ``mesh_shape`` {axis: size} dict — the search path, no
    devices needed) is given, axes that do not divide the mapped dim are
    dropped from the proposed spec (shard_map-grade divisibility)."""
    params = list(module.named_parameters())
    names = {n for n, _ in params}
    d_model = _model_dim(params)
    vocab_dims = set()
    plan: Dict[str, P] = {}

    # pass 1: 2-D/3-D weights
    expanded_dims_by_mod: Dict[str, set] = {}
    for name, v in params:
        if v.ndim not in (2, 3) or not jnp.issubdtype(v.dtype, jnp.floating):
            continue
        mod, leaf = _split_module(name)
        in_block = _in_repeated_block(name)
        if v.ndim == 3:
            # expert-stacked (E, in, out): ep on experts + col/row rule
            e, din, dout = v.shape
            if din == 1:  # (E, 1, out) expert bias
                plan[name] = (LAYOUT.expert_column_bias()
                              if dout != d_model
                              else LAYOUT.expert_row_bias())
            elif dout >= din:
                plan[name] = LAYOUT.expert_column()
                expanded_dims_by_mod.setdefault(mod, set()).add(dout)
            else:
                plan[name] = LAYOUT.expert_row()
            continue
        d0, d1 = v.shape
        if d1 < _TINY_OUT:  # gating / tiny heads
            plan[name] = (P(None, None) if in_block
                          else LAYOUT.root_linear())
            continue
        if not in_block:
            if d0 >= _VOCAB_RATIO * d1 and d0 >= 256:
                plan[name] = LAYOUT.vocab_embedding()
                vocab_dims.add(d0)
                continue
            if d1 >= _VOCAB_RATIO * d0 and d1 >= 256:
                plan[name] = LAYOUT.vocab_head()    # untied head
                vocab_dims.add(d1)
                continue
            has_bias = any(b in names or f"{mod}.{b}" in names
                           for b in _bias_names(leaf))
            # linear (paired bias) vs table (no bias)
            plan[name] = (LAYOUT.root_linear() if has_bias
                          else LAYOUT.position_table())
            continue
        # in repeated block: dimension-flow column/row
        if d1 > d0:
            plan[name] = LAYOUT.column()
            expanded_dims_by_mod.setdefault(mod, set()).add(d1)
        elif d0 > d1:
            plan[name] = LAYOUT.row()
        else:
            # square: row iff an expanding sibling exists (attention
            # out-proj pattern); else column (v0 limitation, see docstring)
            mod_has_expand = any(
                w.shape[1] > w.shape[0]
                for n2, w in params
                if w.ndim == 2 and _split_module(n2)[0] == mod)
            plan[name] = (LAYOUT.row() if mod_has_expand
                          else LAYOUT.column())

    # pass 2: 1-D params
    for name, v in params:
        if v.ndim != 1:
            if v.ndim == 4:  # conv OIHW: ZeRO over output channels
                plan.setdefault(name, LAYOUT.conv_filter())
            elif v.ndim != 2 and v.ndim != 3:
                plan.setdefault(name, P())
            continue
        mod, leaf = _split_module(name)
        (dim,) = v.shape
        if dim in vocab_dims:
            plan[name] = LAYOUT.vocab_bias()
        elif _in_repeated_block(name) and \
                dim in expanded_dims_by_mod.get(mod, ()) and dim != d_model:
            plan[name] = LAYOUT.column_bias()
        else:
            plan[name] = LAYOUT.row_bias()

    if mesh_shape is None and mesh is not None:
        mesh_shape = dict(mesh.shape)
    if mesh_shape is not None:
        shapes = dict(params)
        plan = {n: _prune_indivisible(spec, shapes[n].shape, mesh_shape)
                for n, spec in plan.items()}
    return plan


def _prune_indivisible(spec: P, shape, mesh_shape) -> P:
    out = []
    for i, entry in enumerate(tuple(spec)):
        axes = entry if isinstance(entry, tuple) else (entry,)
        keep = []
        for ax in axes:
            if ax is None:
                continue
            deg = mesh_shape.get(ax, 1)
            if deg > 1 and i < len(shape) and shape[i] % deg == 0:
                keep.append(ax)
        out.append(tuple(keep) if len(keep) > 1
                   else (keep[0] if keep else None))
    return P(*out)


def _memory_with_plan(params, plan, degrees: Dict[str, int],
                      optimizer: str = "adamw",
                      moment_bytes: int = 4, pp: int = 1) -> Dict[str, float]:
    def shards(spec):
        n = 1
        for entry in tuple(spec):
            for ax in (entry if isinstance(entry, tuple) else (entry,)):
                if ax is not None:
                    n *= degrees.get(ax, 1)
        return n

    total = 0.0
    per_device = 0.0
    n_moments = {"sgd": 0, "momentum": 1}.get(optimizer, 2)
    for name, v in params:
        b = v.size * v.dtype.itemsize
        opt_b = v.size * moment_bytes * n_moments
        total += b + opt_b
        # pp splits the repeated blocks across stages (exact, per param);
        # embeddings/head stay whole on their stage
        stage = pp if pp > 1 and _in_repeated_block(name) else 1
        per_device += (b + opt_b) / shards(plan.get(name, P())) / stage
    return {"total_bytes": total, "per_device_bytes": per_device,
            "n_params": sum(v.size for _, v in params)}


def memory_report(module, mesh: Optional[Mesh] = None,
                  optimizer: str = "adamw",
                  moment_bytes: int = 4,
                  degrees: Optional[Dict[str, int]] = None
                  ) -> Dict[str, float]:
    """Per-device memory estimate for (params + optimizer state) under the
    proposed plan (≙ auto_parallel/cost/ estimate_cost's memory half).
    Activations are workload-dependent and excluded — treat the result as
    the static floor. Either a live ``mesh`` or a ``degrees`` dict (no
    devices needed — the search path) supplies the axis sizes."""
    params = list(module.named_parameters())
    if degrees is None:
        degrees = dict(mesh.shape) if mesh is not None else {}
        plan = plan_module(module, mesh)
    else:
        plan = plan_module(module, mesh_shape=degrees)
    return _memory_with_plan(params, plan, degrees, optimizer, moment_bytes)


# ---------------------------------------------------------------------------
# Plan search (≙ tuner/parallel_tuner.py:35 — enumerate degree assignments,
# score each with the cost model, return the argmin)
# ---------------------------------------------------------------------------

def _count_blocks(params) -> int:
    """Number of repeated blocks (distinct numbered-child prefixes)."""
    mods = set()
    for n, _ in params:
        m = _REPEAT_RE.search("." + n)
        if m:
            mods.add(("." + n)[:m.end()])
    return max(1, len(mods))


def _plan_ctx(module):
    """Candidate-independent inputs of the search, computed once: the
    param list, the unpruned structural plan, and the model stats."""
    params = list(module.named_parameters())
    return {"params": params, "base_plan": plan_module(module),
            "shapes": dict(params),
            "p_bytes": sum(v.size * v.dtype.itemsize for _, v in params),
            "n_blocks": _count_blocks(params),
            "d_model": _model_dim(params)}


def enumerate_plans(n_devices: int, max_tp: int = 8, max_pp: int = 1):
    """Power-of-two (dp, fsdp, tp[, pp]) factorizations of ``n_devices``
    with tp capped (tp beyond one chip's worth of ICI neighbors stops
    paying) and pp opt-in via ``max_pp`` (pipeline plans carry a "pp" key
    only when pp > 1, ≙ tuner/parallel_tuner.py:35's degree axes).
    Odd factors of a non-power-of-two device count land on dp — TPU
    slices are power-of-two shaped, and odd tp/fsdp degrees rarely divide
    any weight dim anyway."""
    out = []
    pp = 1
    while pp <= min(max_pp, n_devices):
        if n_devices % pp == 0:
            inner = n_devices // pp
            tp = 1
            while tp <= min(max_tp, inner):
                if inner % tp == 0:
                    rest = inner // tp
                    fsdp = 1
                    while fsdp <= rest:
                        if rest % fsdp == 0:
                            plan = {"dp": rest // fsdp, "fsdp": fsdp,
                                    "tp": tp}
                            if pp > 1:
                                plan["pp"] = pp
                            out.append(plan)
                        fsdp *= 2
                tp *= 2
        pp *= 2
    return out


def _axis_tier(degrees: Dict[str, int], axis: str, n_hosts: int) -> str:
    """"dcn" when the axis's collective crosses host boundaries, "ici"
    otherwise. Axis order outermost→innermost is (pp, dp, fsdp, tp) —
    matching ``mesh._ORDER``, so the tier the cost model charges is the
    tier the built mesh actually uses. An axis crosses hosts when its
    stride × degree exceeds the per-host device count
    (≙ comm_op_cost.py's cross-machine link selection)."""
    if n_hosts <= 1:
        return "ici"
    dp, fsdp, tp = (degrees.get("dp", 1), degrees.get("fsdp", 1),
                    degrees.get("tp", 1))
    pp = degrees.get("pp", 1)
    world = dp * fsdp * tp * pp
    per_host = max(1, world // n_hosts)
    stride = {"tp": 1, "fsdp": tp, "dp": tp * fsdp,
              "pp": tp * fsdp * dp}[axis]
    deg = degrees.get(axis, 1)
    return "dcn" if deg > 1 and stride * deg > per_host else "ici"


def comm_quant_policy(degrees: Dict[str, int], n_hosts: int = 1,
                      default_fmt: str = "int8") -> Dict[str, Optional[str]]:
    """Per-axis wire-format choice for the gradient-sync / weight-gather
    collectives (EQuARX deployment guidance: quantization pays where the
    link is slow): axes whose collectives cross host boundaries per
    :func:`_axis_tier` get ``default_fmt``, ICI-resident axes stay
    full-precision (None). Only the data axes are candidates — tp/pp
    move activations, whose quantization is a different (AMP) problem.
    Consumed by ``compression.resolve_comm_quant`` under
    ``PT_COMM_QUANT=auto``."""
    return {ax: (default_fmt
                 if _axis_tier(degrees, ax, n_hosts) == "dcn" else None)
            for ax in ("dp", "fsdp")}


def stripe_plan(degrees: Dict[str, int], n_hosts: int = 1,
                cost_model=None, quant_ratio: float = 3.94,
                axes=("dp", "fsdp")) -> Dict[str, Optional[float]]:
    """FlexLink-style stripe fractions for the striped bucket
    collectives (``compression.quantized_bucket_reduce_scatter``).

    For each data axis whose collective crosses hosts
    (:func:`_axis_tier` says "dcn"), the payload fraction routed on the
    QUANTIZED DCN stripe so both stripes finish together:
    ``f = q·B_dcn / (q·B_dcn + B_ici)`` — ``q`` the wire compression the
    DCN stripe enjoys (int8 block-256 ≈ 3.94x, PR 7's measured ratio),
    ``B_*`` the link bandwidths from the cost model. The remaining
    ``1-f`` crosses full-precision on the ICI stripe concurrently,
    recovering the intra-host links a pure-DCN collective leaves idle.
    ICI-resident axes get None — a single-tier axis has no second link
    class to stripe onto."""
    from paddle_tpu.cost_model import CostModel
    cm = cost_model or CostModel()
    out: Dict[str, Optional[float]] = {}
    for ax in axes:
        if _axis_tier(degrees, ax, n_hosts) == "dcn":
            eff = quant_ratio * cm.dcn_bw
            out[ax] = round(eff / (eff + cm.ici_bw), 4)
        else:
            out[ax] = None
    return out


def plan_cost(module, degrees: Dict[str, int], hbm_bytes: float = 16e9,
              budget: float = 0.6, optimizer: str = "adamw",
              flops_per_step: float = 0.0, tokens_per_step: int = 8192,
              act_bytes: int = 2, cost_model=None, n_hosts: int = 1,
              microbatches: Optional[int] = None,
              _ctx=None) -> Dict[str, float]:
    """Estimated step time + memory feasibility for one degree assignment.

    Cost terms (scaling-book comm recipe, ≙ auto_parallel/cost/
    estimate_cost.py's comm+memory halves, comm_op_cost.py's per-link
    tiers):
    - compute: flops_per_step spread over all devices at peak, inflated by
      the pipeline bubble (m + pp - 1)/m for m microbatches
    - dp: ring all-reduce of the local grad shard, 2(dp-1)/dp
    - fsdp: param all-gather fwd+bwd + grad reduce-scatter, 3(fsdp-1)/fsdp
    - tp: 4 activation all-reduces per block (2 fwd + 2 bwd), 2(tp-1)/tp
    - pp: fwd+bwd stage-boundary activation p2p, full microbatch volume
    Each axis is charged at its link tier: with ``n_hosts`` > 1, axes whose
    collectives cross hosts (outermost-first layout pp, dp, fsdp, tp) ride
    DCN instead of ICI — which is exactly why pp (low-volume boundary
    activations) wins the cross-host axis over dp (full-gradient volume).
    Memory: repeated-block params additionally divide by pp (stage split).
    """
    from paddle_tpu.cost_model import CostModel

    cm = cost_model or CostModel()
    dp, fsdp, tp = (degrees.get("dp", 1), degrees.get("fsdp", 1),
                    degrees.get("tp", 1))
    pp = degrees.get("pp", 1)
    world = dp * fsdp * tp * pp
    m = microbatches if microbatches else (4 * pp if pp > 1 else 1)
    ctx = _ctx or _plan_ctx(module)
    pruned = {n: _prune_indivisible(spec, ctx["shapes"][n].shape, degrees)
              for n, spec in ctx["base_plan"].items()}
    rep = _memory_with_plan(ctx["params"], pruned, degrees, optimizer,
                            pp=pp)
    p_bytes = ctx["p_bytes"]
    n_blocks = ctx["n_blocks"]
    d_model = ctx["d_model"]
    per_dev = rep["per_device_bytes"]
    # per-device activation bytes of one block's boundary tensor: the batch
    # dimension splits over BOTH data axes (fsdp is ZeRO data parallelism)
    act = tokens_per_step / (dp * fsdp) * d_model * act_bytes

    grad_sync_t = 0.0   # dp/fsdp grad collectives: overlappable with bwd
    critical_t = 0.0    # tp/pp activation comm: on the critical path
    comm = 0.0
    if dp > 1:
        b = 2 * (dp - 1) / dp * p_bytes / (pp * fsdp * tp)
        comm += b
        grad_sync_t += cm.collective_time(
            b, _axis_tier(degrees, "dp", n_hosts))
    if fsdp > 1:
        b = 3 * (fsdp - 1) / fsdp * p_bytes / (pp * tp)
        comm += b
        grad_sync_t += cm.collective_time(
            b, _axis_tier(degrees, "fsdp", n_hosts))
    if tp > 1:
        b = 4 * (n_blocks / pp) * 2 * (tp - 1) / tp * act
        comm += b
        critical_t += cm.collective_time(
            b, _axis_tier(degrees, "tp", n_hosts))
    pp_bytes = 0.0
    bubble = 1.0
    if pp > 1:
        # each stage boundary moves every microbatch's activation fwd+bwd;
        # per-device link load is the full per-replica token volume
        pp_bytes = 2.0 * act
        comm += pp_bytes
        critical_t += cm.collective_time(
            pp_bytes, _axis_tier(degrees, "pp", n_hosts))
        bubble = (m + pp - 1) / m
    compute_t = flops_per_step / (world * cm.peak_flops) * bubble
    # grad-sync overlaps the backward pass (~2/3 of compute) the way the
    # fleet optimizer actually schedules it; only the excess is exposed
    exposed_t = max(0.0, grad_sync_t - (2.0 / 3.0) * compute_t)
    time_s = compute_t + critical_t + exposed_t
    return {"time_s": time_s, "comm_bytes": comm,
            "compute_s": compute_t,
            "comm_s": grad_sync_t + critical_t,
            "exposed_comm_s": critical_t + exposed_t,
            "bubble_frac": bubble - 1.0, "pp_p2p_bytes": pp_bytes,
            "per_device_bytes": per_dev,
            "feasible": per_dev <= budget * hbm_bytes}


def rank_plans(module, n_devices: int, hbm_bytes: float = 16e9,
               max_tp: int = 8, budget: float = 0.6,
               optimizer: str = "adamw", flops_per_step: float = 0.0,
               tokens_per_step: int = 8192, measure_fn=None,
               measure_top_k: int = 3, max_pp: int = 1, n_hosts: int = 1,
               microbatches: Optional[int] = None, cost_model=None):
    """Score every candidate degree assignment; return
    ``[(cost_s, degrees, info), ...]`` best-first with infeasible plans
    (static memory floor over budget) ranked after all feasible ones.

    ``max_pp`` > 1 adds pipeline plans; ``n_hosts`` > 1 charges cross-host
    axes at DCN bandwidth (≙ comm_op_cost.py's link tiers).
    ``measure_fn(degrees) -> seconds`` optionally re-ranks the top
    ``measure_top_k`` feasible candidates by real measured step time
    (≙ tuner/optimization_tuner.py:188's trial runs).
    """
    from paddle_tpu.cost_model import CostModel

    cm = cost_model or CostModel()
    ctx = _plan_ctx(module)
    scored = []
    for degrees in enumerate_plans(n_devices, max_tp, max_pp):
        info = plan_cost(module, degrees, hbm_bytes, budget, optimizer,
                         flops_per_step, tokens_per_step, cost_model=cm,
                         n_hosts=n_hosts, microbatches=microbatches,
                         _ctx=ctx)
        scored.append((info["time_s"], degrees, info))
    scored.sort(key=lambda t: (not t[2]["feasible"], t[0]))
    if measure_fn is not None:
        head = [s for s in scored[:measure_top_k] if s[2]["feasible"]]
        tail = scored[len(head):]
        remeasured = []
        for _, degrees, info in head:
            t = measure_fn(degrees)
            info = dict(info, measured_s=t)
            remeasured.append((t, degrees, info))
        remeasured.sort(key=lambda t: t[0])
        scored = remeasured + tail
    return scored


def suggest_mesh(module, n_devices: int, hbm_bytes: float = 16e9,
                 max_tp: int = 8, budget: float = 0.6,
                 optimizer: str = "adamw", flops_per_step: float = 0.0,
                 tokens_per_step: int = 8192, measure_fn=None,
                 max_pp: int = 1, n_hosts: int = 1,
                 microbatches: Optional[int] = None,
                 cost_model=None) -> Dict[str, int]:
    """Pick (dp, fsdp, tp[, pp]) degrees for ``n_devices``: enumerate
    every factorization, reject those whose static memory floor exceeds
    ``budget``·HBM, and return the cost-model argmin
    (≙ tuner/parallel_tuner.py:35). With ``measure_fn`` the finalists are
    re-ranked by measured step time; ``max_pp``/``n_hosts`` unlock
    pipeline plans and the DCN link tier."""
    ranked = rank_plans(module, n_devices, hbm_bytes, max_tp, budget,
                        optimizer, flops_per_step, tokens_per_step,
                        measure_fn=measure_fn, max_pp=max_pp,
                        n_hosts=n_hosts, microbatches=microbatches,
                        cost_model=cost_model)
    for _, degrees, info in ranked:
        if info["feasible"]:
            return degrees
    # nothing fits the budget: return the min-memory plan so the caller
    # can at least try (matching the reference tuner's best-effort fall-through)
    return min(ranked, key=lambda t: t[2]["per_device_bytes"])[1]
