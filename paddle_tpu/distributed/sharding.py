"""GroupSharded / ZeRO stages 1-3 as GSPMD sharding policies.

Reference analog (SURVEY §2.2): the dygraph GroupSharded stack —
- stage 1 (os):     dygraph_sharding_optimizer.py:28 (optimizer-state shards)
- stage 2 (os_g):   group_sharded_stage2.py:45 + GroupShardedOptimizerStage2
  (grad buckets reduced to owner ranks, see Addendum E "ZeRO-2 grad path")
- stage 3 (p_g_os): group_sharded_stage3.py:59 (param gather/release hooks,
  TaskFlow prefetch) — user API group_sharded.py group_sharded_parallel().

TPU-native design: no hooks, buckets, or rank-ownership bookkeeping. Each
stage is a *sharding policy* over one mesh axis (default 'fsdp'):

  level    params        grads            optimizer slots
  os       replicated    replicated       sharded
  os_g     replicated    reduce-scattered sharded
  p_g_os   sharded       reduce-scattered sharded

The policy is expressed as PartitionSpecs; XLA then emits exactly the
collectives the reference hand-codes: stage-2's grad `reduce()` to owner
becomes a reduce-scatter, stage-3's pre-forward param gather becomes an
all-gather at first use (with XLA's scheduler prefetching it — the
reference's TaskFlow:838), and the post-update param broadcast becomes an
all-gather of the updated shard. The reference's HybridParallelClipGrad
(global-norm across all groups, hybrid_parallel_optimizer.py:45) needs no
special code at all: a ClipGradByGlobalNorm inside the jitted step reduces
over the full (sharded) grad tree and XLA produces the global norm.
"""

import dataclasses
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["GroupShardedSpecs", "group_sharded_specs",
           "init_group_sharded_state", "build_group_sharded_step",
           "group_sharded_parallel", "LEVELS"]

LEVELS = ("os", "os_g", "p_g_os")


def _spec_axes(spec: P):
    """Flatten a PartitionSpec into per-dim tuples of axis names."""
    out = []
    for e in spec:
        if e is None:
            out.append(())
        elif isinstance(e, (tuple, list)):
            out.append(tuple(e))
        else:
            out.append((e,))
    return out


def _strip_axis(spec: P, axis: str) -> P:
    """Remove `axis` from every dim of the spec (→ replicated over it)."""
    dims = []
    for axes in _spec_axes(spec):
        kept = tuple(a for a in axes if a != axis)
        dims.append(kept if len(kept) > 1 else (kept[0] if kept else None))
    return P(*dims)


def _ensure_axis(spec: P, shape, axis: str, axis_size: int) -> P:
    """Add `axis` to the largest divisible unsharded dim if absent (so small
    replicated params — ln scales, biases — still spread their optimizer
    state, like the reference's rank-assignment _trainable_param2rank)."""
    dims = _spec_axes(spec)
    if any(axis in axes for axes in dims):
        return spec
    best, best_len = None, 0
    for i, (axes, n) in enumerate(zip(dims, shape)):
        if not axes and n % axis_size == 0 and n >= axis_size and \
                n > best_len:
            best, best_len = i, n
    if best is None:
        return spec
    dims[best] = (axis,)
    return P(*[(d if len(d) > 1 else (d[0] if d else None)) for d in dims])


@dataclasses.dataclass
class GroupShardedSpecs:
    """Per-parameter PartitionSpecs for params, grads, optimizer slots."""
    param: Dict[str, P]
    grad: Dict[str, P]
    opt_slot: Dict[str, P]
    mesh: Mesh

    def param_shardings(self):
        return {k: NamedSharding(self.mesh, s)
                for k, s in self.param.items()}


def group_sharded_specs(params: Dict[str, jax.Array], mesh: Mesh,
                        level: str = "p_g_os", axis: str = "fsdp",
                        rules: Optional[Callable[[str], P]] = None
                        ) -> GroupShardedSpecs:
    """Derive the stage-1/2/3 spec sets from a base partition-rule function.

    `rules(path) -> P` gives the fully-sharded (stage-3 + TP) spec of each
    param — e.g. models.gpt.partition_spec. Stages 1/2 strip `axis` from the
    param (and stage 1 from the grad) spec; optimizer slots always keep it.
    """
    if level not in LEVELS:
        raise ValueError(f"level must be one of {LEVELS}, got {level!r}")
    if rules is None:
        rules = lambda path: P()
    mesh_shape = dict(mesh.shape)
    if axis not in mesh_shape:
        raise ValueError(
            f"sharding axis {axis!r} not in mesh axes "
            f"{tuple(mesh_shape)}; build the mesh with an {axis!r} "
            f"dimension (e.g. init_mesh({axis}=N))")
    axis_size = mesh_shape[axis]
    param, grad, opt_slot = {}, {}, {}
    for k, v in params.items():
        base = rules(k)
        if axis_size > 1:
            base = _ensure_axis(base, v.shape, axis, axis_size)
        param[k] = base if level == "p_g_os" else _strip_axis(base, axis)
        grad[k] = base if level in ("os_g", "p_g_os") else \
            _strip_axis(base, axis)
        opt_slot[k] = base
    return GroupShardedSpecs(param, grad, opt_slot, mesh)


def _constrain_tree(tree, specs: Dict[str, P], mesh: Mesh):
    """Apply with_sharding_constraint per named entry (leaves may be tuples
    of same-shaped slot arrays, e.g. Adam's (m, v))."""
    out = {}
    for k, v in tree.items():
        sh = NamedSharding(mesh, specs[k])
        out[k] = jax.tree_util.tree_map(
            lambda x: lax.with_sharding_constraint(x, sh), v)
    return out


def init_group_sharded_state(params, optimizer, specs: GroupShardedSpecs):
    """Place params per the level's param specs and build sharded optimizer
    state (slots land directly in their shards — no full-size materialize)."""
    mesh = specs.mesh
    shardings = specs.param_shardings()
    params = {k: jax.device_put(jnp.copy(v), shardings[k])
              for k, v in params.items()}

    def init(p):
        st = optimizer.init(p)
        return {"step": st["step"],
                "slots": _constrain_tree(st["slots"], specs.opt_slot, mesh)}

    return params, jax.jit(init)(params)


def build_group_sharded_step(loss_fn, optimizer, specs: GroupShardedSpecs,
                             donate: bool = True):
    """Jitted train step under the group-sharded policy.

    loss_fn(params, *batch) -> scalar. The grad constraint is what turns the
    backward's allreduce into reduce-scatter (stage 2+); the param/slot
    constraints keep the update math sharded so each device updates only its
    shard (≙ GroupShardedOptimizerStage2 updating owned shards then
    broadcasting — the broadcast being XLA's all-gather at next use).
    """
    mesh = specs.mesh

    def step(params, opt_state, *batch):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, *batch))(params)
        grads = _constrain_tree(grads, specs.grad, mesh)
        new_p, new_s = optimizer.update(grads, opt_state, params)
        new_p = _constrain_tree(new_p, specs.param, mesh)
        new_s = {"step": new_s["step"],
                 "slots": _constrain_tree(new_s["slots"], specs.opt_slot,
                                          mesh)}
        return new_p, new_s, loss

    kw = {"donate_argnums": (0, 1)} if donate else {}
    return jax.jit(step, **kw)


def group_sharded_parallel(params, optimizer, loss_fn, mesh: Mesh,
                           level: str = "p_g_os", axis: str = "fsdp",
                           rules: Optional[Callable[[str], P]] = None):
    """One-call API ≙ paddle.distributed.sharding.group_sharded_parallel
    (group_sharded.py: level "os" / "os_g" / "p_g_os").

    Returns (sharded_params, sharded_opt_state, jitted_train_step).
    """
    specs = group_sharded_specs(params, mesh, level=level, axis=axis,
                                rules=rules)
    params, opt_state = init_group_sharded_state(params, optimizer, specs)
    step = build_group_sharded_step(loss_fn, optimizer, specs)
    return params, opt_state, step
