"""GroupSharded / ZeRO stages 1-3 as GSPMD sharding policies.

Reference analog (SURVEY §2.2): the dygraph GroupSharded stack —
- stage 1 (os):     dygraph_sharding_optimizer.py:28 (optimizer-state shards)
- stage 2 (os_g):   group_sharded_stage2.py:45 + GroupShardedOptimizerStage2
  (grad buckets reduced to owner ranks, see Addendum E "ZeRO-2 grad path")
- stage 3 (p_g_os): group_sharded_stage3.py:59 (param gather/release hooks,
  TaskFlow prefetch) — user API group_sharded.py group_sharded_parallel().

TPU-native design: no hooks, buckets, or rank-ownership bookkeeping. Each
stage is a *sharding policy* over one mesh axis (default 'fsdp'):

  level    params        grads            optimizer slots
  os       replicated    replicated       sharded
  os_g     replicated    reduce-scattered sharded
  p_g_os   sharded       reduce-scattered sharded

The policy is expressed as PartitionSpecs; XLA then emits exactly the
collectives the reference hand-codes: stage-2's grad `reduce()` to owner
becomes a reduce-scatter, stage-3's pre-forward param gather becomes an
all-gather at first use (with XLA's scheduler prefetching it — the
reference's TaskFlow:838), and the post-update param broadcast becomes an
all-gather of the updated shard. The reference's HybridParallelClipGrad
(global-norm across all groups, hybrid_parallel_optimizer.py:45) needs no
special code at all: a ClipGradByGlobalNorm inside the jitted step reduces
over the full (sharded) grad tree and XLA produces the global norm.

**Quantized wire (``comm_quant``)**: when the comm axis rides DCN (or
``PT_COMM_QUANT`` forces a format — see
``compression.resolve_comm_quant``), the step is built as an EXPLICIT
shard_map over the axis instead of GSPMD constraints, so the two big
movers carry narrow dtypes end to end (distributed/compression.py):
stage-3's pre-forward param all-gather becomes quantize → int8/fp8
all-gather → dequant (stateless; the owner shards stay exact fp32, so
error cannot accumulate step over step), and stage-2/3's gradient
reduce-scatter becomes a block-quantized all-to-all + local
dequant-reduce with error feedback. The error-feedback residual rides
inside ``opt_state["comm_ef"]``, so the step signature is unchanged.
Stage-2's post-update param rebuild stays full-precision (it is the
authoritative state, not a per-step estimate).

**Overlap scheduling** (distributed/overlap.py): for models in block
form (PR 8's stacked-weights layout), :func:`build_overlap_sharded_step`
restructures this explicit step as scans over layer blocks — per-bucket
quantized reduce-scatters launched inside the backward scan as each
layer's grads appear, the stage-3 gather for layer l+1 issued inside
layer l's scan body (double-buffered carry), and large bucket payloads
striped across ICI and DCN concurrently. Same state layout
(``opt_state["comm_ef"]``), same step signature; the wire cost then
hides under compute instead of serializing after it.
"""

import dataclasses
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["GroupShardedSpecs", "group_sharded_specs",
           "init_group_sharded_state", "build_group_sharded_step",
           "build_overlap_sharded_step", "group_sharded_parallel",
           "attach_comm_ef", "LEVELS"]

LEVELS = ("os", "os_g", "p_g_os")


def _spec_axes(spec: P):
    """Flatten a PartitionSpec into per-dim tuples of axis names."""
    out = []
    for e in spec:
        if e is None:
            out.append(())
        elif isinstance(e, (tuple, list)):
            out.append(tuple(e))
        else:
            out.append((e,))
    return out


def _strip_axis(spec: P, axis: str) -> P:
    """Remove `axis` from every dim of the spec (→ replicated over it)."""
    dims = []
    for axes in _spec_axes(spec):
        kept = tuple(a for a in axes if a != axis)
        dims.append(kept if len(kept) > 1 else (kept[0] if kept else None))
    return P(*dims)


def _ensure_axis(spec: P, shape, axis: str, axis_size: int) -> P:
    """Add `axis` to the largest divisible unsharded dim if absent (so small
    replicated params — ln scales, biases — still spread their optimizer
    state, like the reference's rank-assignment _trainable_param2rank)."""
    dims = _spec_axes(spec)
    # a spec shorter than the param's rank (P() from the default rules)
    # leaves trailing dims unsharded — pad so they are candidates too
    dims += [()] * (len(shape) - len(dims))
    if any(axis in axes for axes in dims):
        return spec
    best, best_len = None, 0
    for i, (axes, n) in enumerate(zip(dims, shape)):
        if not axes and n % axis_size == 0 and n >= axis_size and \
                n > best_len:
            best, best_len = i, n
    if best is None:
        return spec
    dims[best] = (axis,)
    return P(*[(d if len(d) > 1 else (d[0] if d else None)) for d in dims])


@dataclasses.dataclass
class GroupShardedSpecs:
    """Per-parameter PartitionSpecs for params, grads, optimizer slots."""
    param: Dict[str, P]
    grad: Dict[str, P]
    opt_slot: Dict[str, P]
    mesh: Mesh
    axis: str = "fsdp"
    level: str = "p_g_os"

    def param_shardings(self):
        return {k: NamedSharding(self.mesh, s)
                for k, s in self.param.items()}


def group_sharded_specs(params: Dict[str, jax.Array], mesh: Mesh,
                        level: str = "p_g_os", axis: str = "fsdp",
                        rules: Optional[Callable[[str], P]] = None
                        ) -> GroupShardedSpecs:
    """Derive the stage-1/2/3 spec sets from a base partition-rule function.

    `rules(path) -> P` gives the fully-sharded (stage-3 + TP) spec of each
    param — e.g. models.gpt.partition_spec. Stages 1/2 strip `axis` from the
    param (and stage 1 from the grad) spec; optimizer slots always keep it.
    """
    if level not in LEVELS:
        raise ValueError(f"level must be one of {LEVELS}, got {level!r}")
    if rules is None:
        rules = lambda path: P()
    mesh_shape = dict(mesh.shape)
    if axis not in mesh_shape:
        raise ValueError(
            f"sharding axis {axis!r} not in mesh axes "
            f"{tuple(mesh_shape)}; build the mesh with an {axis!r} "
            f"dimension (e.g. init_mesh({axis}=N))")
    axis_size = mesh_shape[axis]
    param, grad, opt_slot = {}, {}, {}
    for k, v in params.items():
        base = rules(k)
        if axis_size > 1:
            base = _ensure_axis(base, v.shape, axis, axis_size)
        param[k] = base if level == "p_g_os" else _strip_axis(base, axis)
        grad[k] = base if level in ("os_g", "p_g_os") else \
            _strip_axis(base, axis)
        opt_slot[k] = base
    return GroupShardedSpecs(param, grad, opt_slot, mesh, axis=axis,
                             level=level)


def _constrain_tree(tree, specs: Dict[str, P], mesh: Mesh):
    """Apply with_sharding_constraint per named entry (leaves may be tuples
    of same-shaped slot arrays, e.g. Adam's (m, v))."""
    out = {}
    for k, v in tree.items():
        sh = NamedSharding(mesh, specs[k])
        out[k] = jax.tree_util.tree_map(
            lambda x: lax.with_sharding_constraint(x, sh), v)
    return out


def init_group_sharded_state(params, optimizer, specs: GroupShardedSpecs):
    """Place params per the level's param specs and build sharded optimizer
    state (slots land directly in their shards — no full-size materialize)."""
    mesh = specs.mesh
    shardings = specs.param_shardings()
    params = {k: jax.device_put(jnp.copy(v), shardings[k])
              for k, v in params.items()}

    def init(p):
        st = optimizer.init(p)
        return {"step": st["step"],
                "slots": _constrain_tree(st["slots"], specs.opt_slot, mesh)}

    return params, jax.jit(init)(params)


def build_group_sharded_step(loss_fn, optimizer, specs: GroupShardedSpecs,
                             donate: bool = True,
                             comm_quant: Optional[str] = None,
                             comm_block: Optional[int] = None,
                             stacked_keys=None):
    """Jitted train step under the group-sharded policy.

    loss_fn(params, *batch) -> scalar. The grad constraint is what turns the
    backward's allreduce into reduce-scatter (stage 2+); the param/slot
    constraints keep the update math sharded so each device updates only its
    shard (≙ GroupShardedOptimizerStage2 updating owned shards then
    broadcasting — the broadcast being XLA's all-gather at next use).

    ``comm_quant`` selects the quantized wire: "bf16"/"int8"/"fp8" swap
    this GSPMD formulation for the explicit shard_map step whose gradient
    reduce-scatter and stage-3 param all-gather move narrow blocks
    (module docstring); "auto" consults ``PT_COMM_QUANT`` / the planner's
    per-axis DCN tier and quietly keeps the GSPMD path for
    configurations the explicit step cannot run (grad_clip, level "os",
    multi-axis specs) — an explicit format raises for those instead.
    The default None (like "none") keeps the GSPMD
    path — quantization is OPT-IN here, because the quantized step needs
    the error-feedback residual in ``opt_state["comm_ef"]`` (build the
    state with :func:`init_group_sharded_state` + :func:`attach_comm_ef`,
    or use the one-call :func:`group_sharded_parallel`, whose own
    ``comm_quant=None`` default DOES auto-resolve and attaches it).

    ``stacked_keys`` (ISSUE 18) names the param entries carrying a
    leading layer axis so the numerics plane (PT_NUMERICS_EVERY > 0)
    attributes its per-layer grad stats / NaN provenance to layers;
    when numerics is enabled the step returns a 4th output — the
    packed stats vector.
    """
    from paddle_tpu.observability import numerics as _nm
    policy = _resolve_policy(comm_quant, specs, optimizer)
    if policy is not None:
        return _build_quantized_comm_step(loss_fn, optimizer, specs,
                                          policy, comm_block, donate,
                                          stacked_keys=stacked_keys)
    mesh = specs.mesh
    num_on = _nm.enabled()
    num_box = _nm.LayoutBox()

    def step(params, opt_state, *batch):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, *batch))(params)
        grads = _nm.poison_grads(grads, stacked_keys,
                                 step_count=opt_state["step"])
        grads = _constrain_tree(grads, specs.grad, mesh)
        new_p, new_s = optimizer.update(grads, opt_state, params)
        new_p = _constrain_tree(new_p, specs.param, mesh)
        new_s = {"step": new_s["step"],
                 "slots": _constrain_tree(new_s["slots"], specs.opt_slot,
                                          mesh)}
        if num_on:
            packed = _nm.capture_step(
                grads, loss=loss, step_count=opt_state["step"],
                stacked_keys=stacked_keys, box=num_box)
            return new_p, new_s, loss, packed
        return new_p, new_s, loss

    kw = {"donate_argnums": (0, 1)} if donate else {}
    fn = jax.jit(step, **kw)
    fn.numerics_layout = num_box
    return fn


def _resolve_policy(comm_quant: Optional[str], specs: GroupShardedSpecs,
                    optimizer=None) -> Optional[str]:
    """One normalization point for the comm_quant knob: "auto" asks
    ``compression.resolve_comm_quant`` (PT_COMM_QUANT / planner DCN
    tier); None/"none"/"fp32" mean full precision; anything else is a
    wire format passed through. Idempotent, so resolved values survive a
    second pass unchanged. An AUTO-resolved format additionally falls
    back to full precision when the explicit path cannot support the
    configuration (:func:`_quant_unsupported_reason`) — auto must never
    turn a previously-valid setup into a build-time error; an EXPLICIT
    format still raises loudly there."""
    was_auto = comm_quant == "auto"
    if was_auto:
        from paddle_tpu.distributed import compression
        comm_quant = compression.resolve_comm_quant(
            axis=specs.axis, mesh=specs.mesh)
    policy = None if comm_quant in (None, "none", "fp32") else comm_quant
    if policy is not None and was_auto and \
            _quant_unsupported_reason(optimizer, specs) is not None:
        return None
    return policy


def _quant_unsupported_reason(optimizer,
                              specs: GroupShardedSpecs) -> Optional[str]:
    """Why the explicit quantized step cannot run this configuration
    (None = supported). Shared by the loud path (explicit format →
    ValueError) and the quiet one (auto → GSPMD fallback)."""
    if specs.level not in ("os_g", "p_g_os"):
        return (f"level {specs.level!r} has no gradient reduce-scatter "
                f"to quantize (use os_g/p_g_os)")
    if getattr(optimizer, "grad_clip", None) is not None:
        return ("grad_clip computes a SHARD norm on the explicit path — "
                "clip inside loss_fn or use the GSPMD path")
    mesh_shape = dict(specs.mesh.shape)
    for name, tree in (("param", specs.param), ("grad", specs.grad),
                       ("opt_slot", specs.opt_slot)):
        for k, sp in tree.items():
            for axes in _spec_axes(sp):
                for ax in axes:
                    if ax != specs.axis and mesh_shape.get(ax, 1) > 1:
                        return (f"shards over {specs.axis!r} only; "
                                f"{name} spec of {k!r} also uses axis "
                                f"{ax!r} (size {mesh_shape[ax]})")
    return None


def _shard_dims(specs: GroupShardedSpecs) -> Dict[str, int]:
    """Per-param dim index carrying the comm axis (from the grad specs —
    stage 2 and 3 both reduce-scatter there); params the axis never
    reached (indivisible shapes) are absent and stay replicated."""
    out = {}
    for k, sp in specs.grad.items():
        for i, axes in enumerate(_spec_axes(sp)):
            if specs.axis in axes:
                out[k] = i
                break
    return out


def attach_comm_ef(params, opt_state, specs: GroupShardedSpecs):
    """Attach the quantized-wire error-feedback residual to the optimizer
    state (``opt_state["comm_ef"]``). ``params`` are the FULL (unsharded)
    shapes — call before/alongside :func:`init_group_sharded_state` with
    the same tree you pass there."""
    from paddle_tpu.distributed import compression
    out = dict(opt_state)
    out["comm_ef"] = compression.init_error_feedback(
        dict(params), specs.mesh, specs.axis)
    return out


def _sharded_update_tail(optimizer, opt_state, shard_p, shard_g, new_ef,
                         ok, loss, *, level, axis, sdim, dmean):
    """The shared owner-update tail of the explicit sharded steps (the
    PR 7 quantized step below and distributed/overlap.py's scheduler):
    sharded optimizer update, os_g's post-update rebuild of the
    replicated copy (the authoritative state crosses at full
    precision), the error-feedback rewrap into ``opt_state["comm_ef"]``,
    the dmean'd loss, and the fail-loud NaN poison on a tripped wire
    guard. One copy — a change to any of these semantics reaches both
    steps."""
    new_sp, new_state = optimizer.update(shard_g, opt_state, shard_p)
    out_p = {}
    for k in new_sp:
        if k in sdim and level == "os_g":
            out_p[k] = lax.all_gather(new_sp[k], axis, axis=sdim[k],
                                      tiled=True)
        else:
            out_p[k] = new_sp[k]
    new_state = dict(new_state)
    new_state["comm_ef"] = jax.tree_util.tree_map(
        lambda x: x[None], new_ef)
    loss = dmean(lax.pmean(loss, axis))
    # fail-loud: a tripped wire guard poisons state on EVERY rank
    out_p = jax.tree_util.tree_map(
        lambda x: jnp.where(ok, x, jnp.nan), out_p)
    loss = jnp.where(ok, loss, jnp.nan)
    return out_p, new_state, loss


def _build_quantized_comm_step(loss_fn, optimizer, specs: GroupShardedSpecs,
                               method: str, block: Optional[int],
                               donate: bool, stacked_keys=None):
    """The explicit shard_map formulation with a narrow wire: stage-3
    pre-forward param gather = quantize → all-gather → dequant; gradient
    reduce-scatter = block-quantized all-to-all + local dequant-mean with
    error feedback (distributed/compression.py). Supports levels os_g and
    p_g_os over a single comm axis. A dp axis alongside it splits the
    batch (leading dim, mean-style losses) and syncs grads with a plain
    fp32 pmean over dp BEFORE the quantized reduce-scatter — quantizing
    the dp leg itself is ``build_compressed_dp_step``'s job."""
    from paddle_tpu.distributed import compression
    from paddle_tpu.observability import numerics as _nm
    mesh, axis, level = specs.mesh, specs.axis, specs.level
    num_on = _nm.enabled()
    num_box = _nm.LayoutBox()
    reason = _quant_unsupported_reason(optimizer, specs)
    if reason is not None:
        raise ValueError(f"comm_quant={method!r}: {reason}")
    mesh_shape = dict(mesh.shape)
    n_shard = mesh_shape[axis]
    sdim = _shard_dims(specs)
    # a data axis alongside the comm axis splits the batch instead of
    # silently replicating the whole forward/backward per dp group
    data_axis = "dp" if axis != "dp" and mesh_shape.get("dp", 1) > 1 \
        else None

    def _dmean(x):
        return lax.pmean(x, data_axis) if data_axis else x

    def per_rank(params, opt_state, *batch):
        idx = lax.axis_index(axis)
        opt_state = dict(opt_state)
        ef = jax.tree_util.tree_map(lambda x: x[0],
                                    opt_state.pop("comm_ef"))
        ok = jnp.bool_(True)
        # guard envelopes agreed with ONE pmax per exchange family, not
        # one scalar collective per param (latency on the slow link)
        gather_keys = [k for k in params
                       if level == "p_g_os" and k in sdim]
        wmax = dict(zip(gather_keys, lax.pmax(jnp.stack(
            [jnp.max(jnp.abs(params[k])) for k in gather_keys]), axis))) \
            if gather_keys else {}
        full = {}
        for k, p in params.items():
            if k in wmax:
                f, okk = compression.quantized_all_gather_dequant(
                    p, axis, method, block, dim=sdim[k],
                    vmax_axis=wmax[k])
                ok = ok & okk
                full[k] = f
            else:
                full[k] = p
        loss, grads = jax.value_and_grad(
            lambda q: loss_fn(q, *batch))(full)
        grads = _nm.poison_grads(grads, stacked_keys,
                                 step_count=opt_state["step"])
        rs_keys = [k for k in grads if k in sdim]
        dmeaned = {k: _dmean(grads[k]) for k in rs_keys}
        gmax = dict(zip(rs_keys, lax.pmax(jnp.stack(
            [jnp.max(jnp.abs(dmeaned[k].astype(jnp.float32) + ef[k]))
             for k in rs_keys]), axis))) if rs_keys else {}
        shard_p, shard_g, new_ef = {}, {}, {}
        for k, gk in grads.items():
            if k in sdim:
                gs, ek, okk = compression.quantized_reduce_scatter_mean(
                    dmeaned[k], ef[k], axis, method, block, dim=sdim[k],
                    vmax_axis=gmax[k])
                ok = ok & okk
                shard_g[k], new_ef[k] = gs, ek
                if level == "p_g_os":
                    shard_p[k] = params[k]
                else:
                    d = params[k].shape[sdim[k]] // n_shard
                    shard_p[k] = lax.dynamic_slice_in_dim(
                        params[k], idx * d, d, axis=sdim[k])
            else:
                shard_g[k] = _dmean(lax.pmean(gk.astype(jnp.float32),
                                              axis))
                new_ef[k] = ef[k]
                shard_p[k] = params[k]
        step_count = opt_state["step"]
        out_p, out_s, out_loss = _sharded_update_tail(
            optimizer, opt_state, shard_p, shard_g, new_ef, ok, loss,
            level=level, axis=axis, sdim=sdim, dmean=_dmean)
        if not num_on:
            return out_p, out_s, out_loss

        # ISSUE 18 numerics capture: reads the dp-meaned grads and the
        # codec's residuals AFTER the wire already consumed them — the
        # update math above is dataflow-identical with capture on. The
        # final pmean replicates the small packed vector across the
        # mesh so it can leave the shard_map under P().
        def build():
            pk = _nm.Packer()
            gstats = {k: dmeaned[k] if k in dmeaned
                      else _dmean(grads[k]) for k in grads}
            _nm.add_grad_tree(pk, gstats, stacked_keys)
            if rs_keys:
                rows = jnp.stack([_nm.quant_raw(
                    [dmeaned[k]], [ef[k]], [new_ef[k]])
                    for k in rs_keys])
                rows = lax.psum(rows, axis)
                for i, k in enumerate(rs_keys):
                    pk.quant(f"rs/{k}", rows[i][None])
            packed = pk.pack(loss=out_loss, box=num_box)
            packed = lax.pmean(packed, axis)
            if data_axis:
                packed = lax.pmean(packed, data_axis)
            return packed

        packed = _nm.cond_every(step_count, max(1, _nm.every()), build)
        return out_p, out_s, out_loss, packed

    ef_spec = {k: P(axis) for k in specs.param}
    state_spec = {"step": P(), "slots": dict(specs.opt_slot),
                  "comm_ef": ef_spec}

    batch_spec = P(data_axis) if data_axis else P()
    out_tail = (P(), P()) if num_on else (P(),)

    def step(params, opt_state, *batch):
        # shard_map built per batch arity (jit retraces per arity anyway)
        smapped = shard_map(
            per_rank, mesh=mesh,
            in_specs=(dict(specs.param), state_spec)
            + (batch_spec,) * len(batch),
            out_specs=(dict(specs.param), state_spec) + out_tail,
            check_vma=False)
        return smapped(params, opt_state, *batch)

    kw = {"donate_argnums": (0, 1)} if donate else {}
    fn = jax.jit(step, **kw)
    fn.numerics_layout = num_box
    return fn


def build_overlap_sharded_step(*args, **kwargs):
    """Overlap-scheduled variant of :func:`build_group_sharded_step` for
    block-form models (module docstring "Overlap scheduling"): bucketed
    in-backward gradient sync, one-layer-ahead weight prefetch, ICI+DCN
    striping. Thin alias of :func:`distributed.overlap.build_overlap_step`
    (late import — overlap builds on this module's specs machinery)."""
    from paddle_tpu.distributed import overlap
    return overlap.build_overlap_step(*args, **kwargs)


def group_sharded_parallel(params, optimizer, loss_fn, mesh: Mesh,
                           level: str = "p_g_os", axis: str = "fsdp",
                           rules: Optional[Callable[[str], P]] = None,
                           comm_quant: Optional[str] = None,
                           comm_block: Optional[int] = None,
                           stacked_keys=None):
    """One-call API ≙ paddle.distributed.sharding.group_sharded_parallel
    (group_sharded.py: level "os" / "os_g" / "p_g_os").

    ``comm_quant``/``comm_block`` select the quantized collective wire
    (see :func:`build_group_sharded_step`); here ``comm_quant=None``
    means "auto" — the owner of the state can safely auto-resolve,
    because when a format results the error-feedback residual is
    attached to the optimizer state, keeping the step signature
    identical either way.

    Returns (sharded_params, sharded_opt_state, jitted_train_step).
    """
    specs = group_sharded_specs(params, mesh, level=level, axis=axis,
                                rules=rules)
    policy = _resolve_policy(
        "auto" if comm_quant is None else comm_quant, specs, optimizer)
    full_params = params
    params, opt_state = init_group_sharded_state(params, optimizer, specs)
    if policy is not None:
        opt_state = attach_comm_ef(full_params, opt_state, specs)
    step = build_group_sharded_step(loss_fn, optimizer, specs,
                                    comm_quant=policy,
                                    comm_block=comm_block,
                                    stacked_keys=stacked_keys)
    return params, opt_state, step
